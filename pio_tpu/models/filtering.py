"""Shared serve-time candidate filtering + ranking for the recommender
templates (similarproduct, ecommerce).

Parity target: the reference templates' isCandidateItem / whiteList /
blackList / categories filtering before their cosine/score loops
(examples/scala-parallel-ecommercerecommendation/train-with-rate-event/src/
main/scala/ALSAlgorithm.scala:148-341, examples/scala-parallel-similarproduct
ALSAlgorithm.scala). TPU-native: the candidate set is selected on host
(id-space work), then scored in ONE bucketed gather+matmul+top_k on device —
candidate counts are padded to powers of two so serving never recompiles per
query shape.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from pio_tpu.ops.similarity import normalize_rows
from pio_tpu.ops.bucketing import pow2_bucket


def invert_categories(item_categories: dict) -> dict:
    """item id -> categories  =>  category -> [item ids]. Built once per
    model (cached by callers) so category-filtered queries select candidates
    in O(matching items), not O(catalog)."""
    inv: dict = {}
    for iid, cats in item_categories.items():
        for c in cats:
            inv.setdefault(c, []).append(iid)
    return inv


def candidate_ids(
    items_index,
    item_categories: dict,
    white,
    categories,
    exclude,
    cat_index: dict | None = None,
):
    """The candidate id list to rank within when selective filters apply;
    None when no selective filter is present (callers then use the
    full-catalog top-k path).

    items_index: EntityIdIndex; white/categories: sets or None; exclude: set;
    cat_index: invert_categories() result, or a zero-arg callable returning
    it (resolved only when a category filter is actually present, so
    filterless queries never pay the O(catalog) inversion). Used when
    categories is set and white is not, making selection cost O(matching
    items) not O(catalog).
    """
    if white is None and categories is None:
        return None
    if white is not None:
        ids = white
    else:
        if callable(cat_index):
            cat_index = cat_index()
        if cat_index is None:
            cat_index = invert_categories(item_categories)
        ids = set()
        for c in categories:
            ids.update(cat_index.get(c, ()))
        categories = None  # already applied via the index
    out = []
    # sorted: candidate order (and so top-k tie-breaks) must not depend on
    # per-process string-hash order — evals and serving stay reproducible
    for i in sorted(ids):
        if i in exclude or i not in items_index:
            continue
        if categories is not None and not (
            set(item_categories.get(i, ())) & categories
        ):
            continue
        out.append(i)
    return out


@partial(jax.jit, static_argnames=("normalize", "k"))
def _rank_jit(item_factors, qv, cidx, valid, normalize: bool, k: int):
    vecs = item_factors[cidx]  # (C, d) gather
    q = qv.reshape(1, -1)
    if normalize:
        vecs = normalize_rows(vecs)
        q = normalize_rows(q)
    scores = (vecs @ q.T)[:, 0]
    scores = jnp.where(valid, scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


def rank_candidates(
    item_factors,
    qv,
    cidx: np.ndarray,
    num: int,
    normalize: bool = False,
):
    """Score candidate rows `cidx` of item_factors against query vector `qv`
    and return (positions_into_cidx, scores) for the top `num`, best first.

    The candidate count and k are padded/bucketed to powers of two before
    jit, so distinct per-query candidate sizes share a small, bounded set of
    compiled programs (same convention as ops.similarity.cosine_topk /
    ops.als.recommend_topk).
    """
    cidx = np.asarray(cidx, dtype=np.int32)
    n = len(cidx)
    if n == 0:
        return np.array([], np.int64), np.array([], np.float32)
    bucket = pow2_bucket(n)
    pad = bucket - n
    cidx_p = np.concatenate([cidx, np.zeros(pad, np.int32)])
    valid = np.concatenate([np.ones(n, bool), np.zeros(pad, bool)])
    k = min(num, n)
    kb = pow2_bucket(k, cap=bucket)
    scores, pos = _rank_jit(
        item_factors, jnp.asarray(qv), cidx_p, valid, normalize, kb
    )
    scores, pos = np.asarray(scores)[:k], np.asarray(pos)[:k]
    keep = pos < n  # drop any padding rows that slipped into top-k
    return pos[keep], scores[keep]
