"""Regression engine template — ridge (closed form) + linear SGD.

Parity target: the reference's regression examples,
examples/experimental/scala-parallel-regression/Run.scala:33-80 (PDataSource
reading "label f1 f2 ..." lines with MLUtils.kFold, MLlib
LinearRegressionWithSGD as a P2LAlgorithm, LAverageServing) and
examples/experimental/scala-local-regression/Run.scala:26-60 (LDataSource +
breeze normal-equations solve as an LAlgorithm).

TPU-native redesign: the local example's `inv(X^T X) X^T y` becomes a
batched ridge solve on the MXU — Gram matrix by one (D,N)x(N,D) matmul in
f32, `jax.scipy.linalg.cho_solve` for the weights — exact, one compile,
no SGD hyperparameters. The SGD algorithm is kept for MLlib signature
parity (numIterations/stepSize/miniBatchFraction) and runs its whole
iteration loop on-device under `lax.scan` with the MLlib GradientDescent
step-size schedule (stepSize / sqrt(t)); the host never sees an
intermediate iterate.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Sequence

import numpy as np

from pio_tpu.controller.base import (
    AverageServing,
    DataSource,
    IdentityPreparator,
    P2LAlgorithm,
    Params,
)
from pio_tpu.controller.engine import Engine, EngineFactory
from pio_tpu.e2.crossvalidation import split_data


@dataclass(frozen=True)
class DataSourceParams(Params):
    """Either a whitespace-separated text file ("label f1 f2 ...", the
    reference ParallelDataSource filepath contract) or event-store entity
    properties (numeric `attributes` + `label`, like the classification
    template)."""

    path_fields = ("filepath",)  # engine-dir-relative (CLI absolutizes)

    filepath: str = ""
    app_name: str = ""
    attributes: tuple[str, ...] = ()
    label: str = "label"
    entity_type: str = "point"
    eval_k: int = 0
    seed: int = 9527


@dataclass
class RegressionData:
    x: np.ndarray  # (N, D) float32
    y: np.ndarray  # (N,) float32

    def sanity_check(self):
        if len(self.y) == 0:
            raise ValueError(
                "RegressionData is empty; check filepath / event properties."
            )
        if not np.isfinite(self.x).all() or not np.isfinite(self.y).all():
            raise ValueError("RegressionData contains non-finite values.")


class RegressionDataSource(DataSource):
    """Reference ParallelDataSource (Run.scala:33-51): parse rows, k-fold
    for eval. Event-store mode mirrors ClassificationDataSource but with
    numeric attributes only."""

    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        self.params = params

    def _read(self, ctx) -> RegressionData:
        p = self.params
        if p.filepath:
            rows = []
            with open(p.filepath) as f:
                for line in f:
                    parts = line.split()
                    if parts:
                        rows.append([float(v) for v in parts])
            if not rows:
                return RegressionData(
                    np.zeros((0, 0), np.float32), np.zeros(0, np.float32)
                )
            arr = np.asarray(rows, np.float32)
            return RegressionData(x=arr[:, 1:], y=arr[:, 0])
        props = ctx.event_store.aggregate_properties(
            app_name=p.app_name,
            entity_type=p.entity_type,
            required=[p.label, *p.attributes],
        )
        xs, ys = [], []
        for _, pm in sorted(props.items()):
            xs.append([float(pm.get(a)) for a in p.attributes])
            ys.append(float(pm.get(p.label)))
        return RegressionData(
            x=np.asarray(xs, np.float32).reshape(len(ys), -1),
            y=np.asarray(ys, np.float32),
        )

    def read_training(self, ctx) -> RegressionData:
        return self._read(ctx)

    def read_eval(self, ctx):
        data = self._read(ctx)
        if self.params.eval_k <= 1:
            return []
        # seeded shuffle before the index-mod-k split: the reference's
        # MLUtils.kFold is seeded-random (Run.scala:45, seed 9527), and an
        # unshuffled file sorted by label would otherwise give skewed folds
        rows = list(np.random.default_rng(self.params.seed).permutation(
            len(data.y)))
        folds = []
        for train_rows, info, test_rows in split_data(rows, self.params.eval_k):
            tr = RegressionData(x=data.x[train_rows], y=data.y[train_rows])
            qa = [
                ({"features": data.x[i].tolist()}, float(data.y[i]))
                for i in test_rows
            ]
            folds.append((tr, info, qa))
        return folds


@dataclass
class LinearModel:
    """w·x + b. Weights live on host (few KB); prediction is a matvec."""

    weights: np.ndarray  # (D,)
    intercept: float

    def predict(self, x: np.ndarray) -> np.ndarray:
        return x @ self.weights + self.intercept


def _predict_query(model: LinearModel, query: dict) -> float:
    x = np.asarray(query["features"], np.float32)
    return float(x @ model.weights + model.intercept)


def _batch_predict(model: LinearModel, queries: Sequence[dict]) -> list:
    if not queries:
        return []
    x = np.stack([np.asarray(q["features"], np.float32) for q in queries])
    return [float(v) for v in model.predict(x)]


@dataclass(frozen=True)
class RidgeParams(Params):
    reg: float = 0.0          # L2 penalty (0 = ordinary least squares)
    fit_intercept: bool = True


class RidgeRegressionAlgorithm(P2LAlgorithm):
    """Closed-form ridge on the MXU — the TPU answer to both the local
    example's breeze normal equations (scala-local-regression/Run.scala:
    nak LinearRegression) and MLlib RidgeRegressionWithSGD."""

    params_class = RidgeParams

    def __init__(self, params: RidgeParams = RidgeParams()):
        self.params = params

    def train(self, ctx, data: RegressionData) -> LinearModel:
        import jax.numpy as jnp
        from jax.scipy.linalg import cho_factor, cho_solve

        data.sanity_check()
        x = jnp.asarray(data.x, jnp.float32)
        y = jnp.asarray(data.y, jnp.float32)
        if self.params.fit_intercept:
            x_mean = x.mean(axis=0)
            y_mean = y.mean()
            xc, yc = x - x_mean, y - y_mean
        else:
            xc, yc = x, y
        d = xc.shape[1]
        gram = xc.T @ xc + self.params.reg * jnp.eye(d, dtype=jnp.float32)
        rhs = xc.T @ yc
        w = cho_solve(cho_factor(gram), rhs)
        w_host = np.asarray(w, np.float64)
        if not np.isfinite(w_host).all():
            # singular Gram (collinear features / D > N) with reg == 0:
            # jax Cholesky yields NaNs rather than raising — fall back to
            # the min-norm least-squares solution
            w, *_ = jnp.linalg.lstsq(xc, yc)
            w_host = np.asarray(w, np.float64)
        if self.params.fit_intercept:
            b = float(y_mean - x_mean @ w)
        else:
            b = 0.0
        return LinearModel(weights=w_host, intercept=b)

    def predict(self, model: LinearModel, query: dict) -> float:
        return _predict_query(model, query)

    def batch_predict(self, model: LinearModel, queries) -> list:
        return _batch_predict(model, queries)


@dataclass(frozen=True)
class SGDParams(Params):
    """MLlib LinearRegressionWithSGD.train signature
    (scala-parallel-regression/Run.scala:55-63)."""

    num_iterations: int = 200
    step_size: float = 0.1
    mini_batch_fraction: float = 1.0
    seed: int = 0


class SGDRegressionAlgorithm(P2LAlgorithm):
    """LinearRegressionWithSGD parity. The full iteration loop runs
    on-device in one compiled `lax.scan`; mini-batches are drawn by
    pre-generated index matrix so shapes stay static."""

    params_class = SGDParams

    def __init__(self, params: SGDParams = SGDParams()):
        self.params = params

    def train(self, ctx, data: RegressionData) -> LinearModel:
        import jax
        import jax.numpy as jnp

        data.sanity_check()
        p = self.params
        n, d = data.x.shape
        batch = max(1, int(round(n * min(1.0, p.mini_batch_fraction))))
        rng = np.random.default_rng(p.seed)

        x = jnp.asarray(data.x, jnp.float32)
        y = jnp.asarray(data.y, jnp.float32)
        steps = p.step_size / jnp.sqrt(jnp.arange(1, p.num_iterations + 1, dtype=jnp.float32))

        def grad_step(carry, step, xb, yb, m):
            w, b = carry
            resid = xb @ w + b - yb           # (B,)
            gw = xb.T @ resid / m
            gb = resid.mean()
            return (w - step * gw, b - step * gb), None

        init = (jnp.zeros((d,), jnp.float32), jnp.float32(0.0))
        if batch >= n:
            # full-batch: no index matrix, no gather — scan over steps only
            def body(carry, step):
                return grad_step(carry, step, x, y, n)

            (w, b), _ = jax.lax.scan(body, init, steps)
        else:
            idx_dev = jnp.asarray(
                rng.integers(0, n, size=(p.num_iterations, batch))
            )

            def body(carry, it):
                rows, step = it
                return grad_step(carry, step, x[rows], y[rows], batch)

            (w, b), _ = jax.lax.scan(body, init, (idx_dev, steps))
        return LinearModel(
            weights=np.asarray(w, np.float64), intercept=float(b)
        )

    def predict(self, model: LinearModel, query: dict) -> float:
        return _predict_query(model, query)

    def batch_predict(self, model: LinearModel, queries) -> list:
        return _batch_predict(model, queries)


class RegressionEngine(EngineFactory):
    """Reference RegressionEngineFactory (scala-parallel-regression/
    Run.scala:72-80): datasource + identity preparator + SGD algo +
    LAverageServing; plus the exact ridge solver as a second algorithm."""

    @classmethod
    def apply(cls) -> Engine:
        return Engine(
            RegressionDataSource,
            IdentityPreparator,
            {"ridge": RidgeRegressionAlgorithm, "sgd": SGDRegressionAlgorithm},
            AverageServing,
        )
