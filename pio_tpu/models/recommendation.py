"""Recommendation engine template — ALS collaborative filtering.

Parity target: reference examples/scala-parallel-recommendation/* (DataSource
reads rate/buy events, MLlib ALS.trainImplicit/train, query {"user", "num"}
-> {"itemScores": [...]}; custom-query variant adds item whitelist filtering,
ALSAlgorithm.scala:56-67, ALSModel.scala:18-47). TPU-native: the ALS kernel
is pio_tpu.ops.als (batched normal equations on the MXU, sharded over the
mesh); the model keeps factors as jax arrays resident in HBM for serving.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import jax
import numpy as np

from pio_tpu.controller.base import (
    DataSource,
    FirstServing,
    IdentityPreparator,
    PAlgorithm,
    Params,
)
from pio_tpu.controller.engine import Engine, EngineFactory
from pio_tpu.data.bimap import EntityIdIndex
from pio_tpu.data.eventstore import Interactions
from pio_tpu.ops import als


@dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = ""
    channel_name: str | None = None
    event_names: tuple[str, ...] = ("rate", "buy")
    rating_event: str = "rate"      # events carrying an explicit rating
    implicit_value: float = 4.0     # value assigned to non-rating events
    eval_k: int = 0                 # >0 -> read_eval produces k folds
    eval_num: int = 10              # ranking depth of each fold query
    # fold queries blacklist the user's train-fold items (unseen-item
    # evaluation; see e2.crossvalidation.split_interactions)
    eval_exclude_seen: bool = True


class RecommendationDataSource(DataSource):
    """Reads rate/buy events into Interactions (reference
    custom-query/src/main/scala/DataSource.scala behavior: `rate` events use
    properties.rating, `buy` maps to a fixed implicit value)."""

    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        self.params = params

    def _read(self, ctx) -> Interactions:
        p = self.params
        # EventStore.interactions: one native C++ sweep on the eventlog
        # backend, find + to_interactions on the others — same semantics
        # (rate events carry properties.rating, everything else maps to the
        # fixed implicit value).
        return ctx.event_store.interactions(
            app_name=p.app_name,
            channel_name=p.channel_name,
            entity_type="user",
            target_entity_type="item",
            event_names=list(p.event_names),
            value_key="rating",
            default_value=p.implicit_value,
            value_event=p.rating_event,
            dedup="last",
        )

    def read_training(self, ctx) -> Interactions:
        return self._read(ctx)

    def read_eval(self, ctx):
        """Index-mod-k folds (reference e2 CrossValidation.splitData)."""
        from pio_tpu.e2.crossvalidation import split_interactions

        data = self._read(ctx)
        return split_interactions(
            data, self.params.eval_k, num=self.params.eval_num,
            exclude_seen=self.params.eval_exclude_seen,
        )


def _rank_candidates(cand: list, scores, num: int) -> dict:
    """Candidate ids + their scores -> top-`num` PredictedResult shape
    (shared by the single-query and batched whitelist paths so their
    ranking semantics cannot drift)."""
    order = np.argsort(-np.asarray(scores))[:num]
    return {
        "itemScores": [
            {"item": cand[i], "score": float(scores[i])} for i in order
        ]
    }


@dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 10
    num_iterations: int = 10
    lambda_: float = 0.01
    alpha: float = 1.0
    implicit_prefs: bool = False
    seed: int | None = None
    chunk: int = 65536
    # inner-solver knobs (ops/als.py): cg_iters -1 = auto per side;
    # warm-sweep schedule drops to cg_warm_iters after cg_warm_sweeps
    # full-strength sweeps (eval/ALS_ROOFLINE.md) — -1 disables
    cg_iters: int = -1
    # 6 = the ops-layer ALSParams default, so the engine path runs the
    # exact schedule the tuning grid (eval/CG_WARM_QUALITY.json) and the
    # bench measured; override per-engine in engine.json if needed
    cg_warm_iters: int = 6
    cg_warm_sweeps: int = 2
    # > 0: hold out this fraction of interactions, score heldout RMSE
    # after every sweep inside the training scan, and keep the BEST
    # sweep's factors instead of the last (ops/als.py ALSValidation —
    # measured on ML-20M the final sweep is ~4.6% worse than the curve
    # minimum). 0 disables (exact reference behavior: last sweep wins).
    validation_fraction: float = 0.0
    # two-stage retrieval (ops/retrieval.py; docs/serving.md): the
    # engine.json `retrieval` block. None/absent = exact mode — every
    # query rides the oracle einsum exactly as before. {"mode":
    # "clustered", ...} serves top-k via the quantized candidate scan +
    # exact re-rank; whiteList queries always stay on predict_pairs.
    retrieval: dict | None = None


@jax.tree_util.register_pytree_node_class
@dataclass
class RecommendationModel:
    """ALS factors + id indexes (reference ALSModel.scala:18-47).

    `validation` (aux, optional): the ALSValidation trajectory when the
    algorithm trained with validation_fraction > 0 — surfaces the
    per-sweep heldout curve + chosen sweep to eval artifacts and the
    dashboard."""

    factors: als.ALSModel
    users: EntityIdIndex
    items: EntityIdIndex
    validation: als.ALSValidation | None = None

    def tree_flatten(self):
        return (self.factors,), (self.users, self.items, self.validation)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], *aux)


class ALSAlgorithm(PAlgorithm):
    """Reference ALSAlgorithm.scala:56-67 (MLlib ALS.trainImplicit) — TPU
    re-design in ops/als.py. Device model: factors live in HBM."""

    params_class = ALSAlgorithmParams

    def __init__(self, params: ALSAlgorithmParams):
        self.params = params
        # parse the retrieval block NOW so a typo'd knob fails engine
        # construction (deploy/train time), never silently serves exact
        from pio_tpu.ops.retrieval import RetrievalParams

        self._rparams = RetrievalParams.from_config(params.retrieval)

    def _retrieval_index(self, model: RecommendationModel):
        """The (RetrievalIndex, DeviceRetrievalIndex) pair for this
        model's CURRENT item factors, cached on the model object (a
        plain attribute — pytree aux ignores it) and keyed by item-table
        identity, so a fold-in swap that replaces the factors rebuilds
        the sidecar while the hot path pays the k-means exactly once.
        The fold-in applier updates the cache in the SAME swap
        (workflow/serve.py), so this rebuild is the cold-start/fallback
        path, not the freshness contract."""
        from pio_tpu.ops import retrieval as rt

        itf = model.factors.item_factors
        cached = getattr(model, "_retrieval_cache", None)
        if cached is not None and cached[0] is itf:
            return cached[1]
        idx = rt.build_index(np.asarray(itf), self._rparams)
        pair = (idx, rt.build_device_index(idx))
        model._retrieval_cache = (itf, pair)
        return pair

    def _als_params(self) -> als.ALSParams:
        p = self.params
        return als.ALSParams(
            rank=p.rank,
            iterations=p.num_iterations,
            reg=p.lambda_,
            alpha=p.alpha,
            implicit=p.implicit_prefs,
            seed=p.seed if p.seed is not None else 3,
            chunk=p.chunk,
            cg_iters=p.cg_iters,
            cg_warm_iters=p.cg_warm_iters,
            cg_warm_sweeps=p.cg_warm_sweeps,
        )

    def train(self, ctx, data: Interactions) -> RecommendationModel:
        data.sanity_check()
        ap = self._als_params()
        vf = self.params.validation_fraction
        if ctx.mesh is not None and ctx.mesh.devices.size > 1:
            # sharded path: best-sweep selection not yet threaded through
            # shard_map (the curve would need a psum'd heldout metric);
            # last-sweep factors, as the reference always does
            factors = als.als_train_sharded(
                data.user_idx, data.item_idx, data.values,
                data.n_users, data.n_items, ap, ctx.mesh,
            )
            return RecommendationModel(factors, data.users, data.items)
        if vf > 0.0:
            nnz = len(data.values)
            n_val = max(1, int(nnz * vf))
            if nnz < 10:
                raise ValueError(
                    "validation_fraction needs >=10 interactions")
            rng = np.random.default_rng(ap.seed)
            perm = rng.permutation(nnz)
            va, tr = perm[:n_val], perm[n_val:]
            factors, validation = als.als_train_validated(
                data.user_idx[tr], data.item_idx[tr], data.values[tr],
                data.n_users, data.n_items, ap,
                data.user_idx[va], data.item_idx[va], data.values[va],
            )
            return RecommendationModel(
                factors, data.users, data.items, validation)
        factors = als.als_train(
            data.user_idx, data.item_idx, data.values,
            data.n_users, data.n_items, ap,
        )
        return RecommendationModel(factors, data.users, data.items)

    def predict(self, model: RecommendationModel, query: dict) -> dict:
        """query {"user": id, "num": k, "whiteList"?: [...], "blackList"?: [...]}
        -> {"itemScores": [{"item": id, "score": s}]} (reference Serving.scala
        PredictedResult shape; whitelist per custom-query variant)."""
        user = query["user"]
        num = int(query.get("num", 10))
        if user not in model.users:
            return {"itemScores": []}
        uidx = model.users.index_of(user)
        white = query.get("whiteList")
        black = set(query.get("blackList") or ())
        if white:
            # score the whitelist candidates directly (reference custom-query
            # variant restricts scoring to the candidate set, so a small
            # whitelist still fills `num` slots)
            cand = [i for i in white if i in model.items and i not in black]
            if not cand:
                return {"itemScores": []}
            cidx = model.items.encode(cand)
            scores = np.asarray(
                als.predict_pairs(
                    model.factors,
                    np.full(len(cidx), uidx, dtype=np.int32),
                    cidx,
                )
            )
            return _rank_candidates(cand, scores, num)
        n_items = model.factors.item_factors.shape[0]
        k = min(num + len(black), n_items)
        rp = self._rparams
        if rp.mode == "clustered" and not rp.is_exhaustive(n_items):
            # two-stage tier: quantized clustered scan picks candidates,
            # the exact oracle einsum re-scores them (ops/retrieval.py).
            # Exhaustive knobs (nprobe >= n_clusters) take the oracle
            # branch below instead — bit-parity by running the literal
            # same computation, the module's exactness contract.
            from pio_tpu.ops import retrieval as rt

            _, didx = self._retrieval_index(model)
            urow = np.asarray(model.factors.user_factors)[uidx]
            scores, idx = rt.candidate_topk(
                didx, model.factors.item_factors, urow, k)
            scores, idx = scores[0], idx[0]
            keep = idx >= 0   # fewer real survivors than k: drop pads
            scores, idx = scores[keep], idx[keep]
        else:
            scores, idx = als.recommend_topk(
                model.factors, np.array([uidx]), k
            )
            scores = np.asarray(scores)[0]
            idx = np.asarray(idx)[0]
        item_ids = model.items.decode(idx)
        out = []
        for item, score in zip(item_ids, scores):
            if item in black:
                continue
            out.append({"item": item, "score": float(score)})
            if len(out) >= num:
                break
        return {"itemScores": out}

    def batch_predict(self, model: RecommendationModel, queries) -> list:
        """Vectorized batch scoring (evaluation + the serving micro-batcher):
        ONE top-k matmul for all known-user queries — blackList queries
        included (over-fetch k = num + max blacklist, filter per row on
        host; unseen-item evaluation blacklists on every query, so routing
        them to the single-query path would collapse the batch API into
        thousands of single-row dispatches). whiteList queries batch too:
        their ragged candidate sets flatten into ONE predict_pairs call
        (user index repeated per candidate), ranked per query on host."""
        results: list[dict] = [{"itemScores": []} for _ in queries]
        known = []
        white_q = []   # (query_index, uidx, [candidate ids])
        for i, q in enumerate(queries):
            if q["user"] not in model.users:
                continue
            if q.get("whiteList"):
                black = set(q.get("blackList") or ())
                cand = [c for c in q["whiteList"]
                        if c in model.items and c not in black]
                if cand:
                    white_q.append(
                        (i, model.users.index_of(q["user"]), cand))
            else:
                known.append((i, model.users.index_of(q["user"])))
        if white_q:
            flat_u = np.concatenate([
                np.full(len(cand), u, np.int32)
                for _, u, cand in white_q
            ])
            flat_i = np.concatenate([
                model.items.encode(cand) for _, _, cand in white_q
            ]).astype(np.int32)
            flat_s = np.asarray(
                als.predict_pairs(model.factors, flat_u, flat_i))
            off = 0
            for qi, _, cand in white_q:
                s = flat_s[off:off + len(cand)]
                off += len(cand)
                results[qi] = _rank_candidates(
                    cand, s, int(queries[qi].get("num", 10)))
        if not known:
            return results
        n_items = model.factors.item_factors.shape[0]
        rows = np.array([u for _, u in known], dtype=np.int32)
        k = min(
            max(int(queries[qi].get("num", 10))
                + len(queries[qi].get("blackList") or ())
                for qi, _ in known),
            n_items,
        )
        rp = self._rparams
        if rp.mode == "clustered" and not rp.is_exhaustive(n_items):
            # batched two-stage tier (same branch contract as predict)
            from pio_tpu.ops import retrieval as rt

            _, didx = self._retrieval_index(model)
            urows = np.asarray(model.factors.user_factors)[rows]
            scores, idx = rt.candidate_topk(
                didx, model.factors.item_factors, urows, k)
        else:
            scores, idx = als.recommend_topk(model.factors, rows, k)
            scores, idx = np.asarray(scores), np.asarray(idx)
        for row, (qi, _) in enumerate(known):
            q = queries[qi]
            n = int(q.get("num", 10))
            black = set(q.get("blackList") or ())
            keep = idx[row] >= 0
            items = model.items.decode(idx[row][keep])
            out = []
            for it, s in zip(items, scores[row][keep]):
                if it in black:
                    continue
                out.append({"item": it, "score": float(s)})
                if len(out) >= n:
                    break
            results[qi] = {"itemScores": out}
        return results

    def prepare_model_for_deploy(self, ctx, model: RecommendationModel):
        """Re-hydrate factors into device HBM (replaces the reference's
        retrain-at-deploy for PAlgorithm, Engine.scala:208-230)."""
        factors = als.ALSModel(
            jax.device_put(model.factors.user_factors),
            jax.device_put(model.factors.item_factors),
        )
        return RecommendationModel(
            factors, model.users, model.items, model.validation)


class RecommendationEngine(EngineFactory):
    """engine.json engineFactory target (reference Engine.scala template
    object RecommendationEngine extends EngineFactory)."""

    @classmethod
    def apply(cls) -> Engine:
        return Engine(
            RecommendationDataSource,
            IdentityPreparator,
            {"als": ALSAlgorithm},
            FirstServing,
        )
