"""Stock backtesting engine template — indicator regression + walk-forward
backtest.

Parity target: reference examples/experimental/scala-stock: price frames
(YahooDataSource.scala / DataSource.scala), indicator feature pipelines
(Indicators.scala), per-ticker next-day-return linear regression
(RegressionStrategy.scala:38-53 nak LinearRegression per symbol), and the
backtesting evaluator with enter/exit thresholds, max positions, NAV /
return / volatility / Sharpe stats (BackTestingMetrics.scala:19-60).

TPU-first redesign: the reference regresses ONE TICKER AT A TIME on the
driver; here the whole universe is a single batched solve — indicator
features are (T, N, F) tensors (ops/indicators.py), the per-ticker normal
equations are one einsum pair, and the solve is a batched Cholesky on the
MXU (the same shape of work as the ALS kernel's per-row systems). The
walk-forward backtest retrains on a sliding window and simulates the
threshold strategy day by day on host (portfolio bookkeeping is branchy
and tiny — exactly the part that does NOT belong on the accelerator).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from pio_tpu.controller.base import (
    DataSource,
    FirstServing,
    IdentityPreparator,
    P2LAlgorithm,
    Params,
)
from pio_tpu.controller.engine import Engine, EngineFactory
from pio_tpu.ops.indicators import indicator_matrix, log_returns

DEFAULT_INDICATORS = (("return", 1), ("return", 5), ("rsi", 14))


@dataclass(frozen=True)
class DataSourceParams(Params):
    """Price series from `$set` events carrying a `price` property on
    ticker entities (one event per ticker per day), or a CSV file of
    `date,ticker,price` rows (the offline stand-in for the reference's
    YahooDataSource)."""

    path_fields = ("filepath",)

    filepath: str = ""
    app_name: str = ""
    entity_type: str = "ticker"
    price_key: str = "price"


@dataclass
class PriceFrame:
    """(T, N) price panel + labels (the reference's saddle Frame role)."""

    log_price: np.ndarray        # (T, N) float32 log prices
    tickers: list[str]
    dates: list                  # length T, sorted ascending

    def sanity_check(self):
        if self.log_price.size == 0:
            raise ValueError("PriceFrame is empty; check price events/file.")
        if not np.isfinite(self.log_price).all():
            raise ValueError("PriceFrame has non-finite log prices.")


def _frame_from_rows(rows: list[tuple]) -> PriceFrame:
    """rows: (date, ticker, price). Missing points forward-fill; leading
    gaps back-fill from the first seen price."""
    dates = sorted({d for d, _, _ in rows})
    tickers = sorted({t for _, t, _ in rows})
    d_ix = {d: i for i, d in enumerate(dates)}
    t_ix = {t: j for j, t in enumerate(tickers)}
    m = np.full((len(dates), len(tickers)), np.nan, np.float64)
    for d, t, p in rows:
        if p <= 0:
            raise ValueError(f"non-positive price {p} for {t} @ {d}")
        m[d_ix[d], t_ix[t]] = np.log(p)
    # forward-fill then back-fill per column
    for j in range(m.shape[1]):
        col = m[:, j]
        mask = np.isnan(col)
        if mask.all():
            raise ValueError(f"ticker {tickers[j]} has no prices")
        idx = np.where(~mask, np.arange(len(col)), 0)
        np.maximum.accumulate(idx, out=idx)
        col[:] = col[idx]
        first = np.flatnonzero(~mask)[0]
        col[:first] = col[first]
    return PriceFrame(m.astype(np.float32), tickers, dates)


class StockDataSource(DataSource):
    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        self.params = params

    def read_training(self, ctx) -> PriceFrame:
        p = self.params
        rows: list[tuple] = []
        if p.filepath:
            with open(p.filepath) as f:
                for line in f:
                    line = line.strip()
                    if not line or line.startswith("date,"):
                        continue
                    d, t, price = line.split(",")
                    rows.append((d, t, float(price)))
        else:
            # $set price events only; the panel row key is the DATE — one
            # row per calendar day regardless of intraday timestamps, the
            # latest event of a day winning (events arrive time-ordered,
            # and _frame_from_rows overwrites on duplicate (date, ticker))
            events = sorted(
                ctx.event_store.find(
                    app_name=p.app_name, entity_type=p.entity_type,
                    event_names=["$set"],
                ),
                key=lambda e: e.event_time,
            )
            for e in events:
                price = e.properties.get_or_else(p.price_key, None)
                if price is not None:
                    rows.append(
                        (e.event_time.date(), e.entity_id, float(price)))
        return _frame_from_rows(rows)


@dataclass(frozen=True)
class RegressionStrategyParams(Params):
    """Reference RegressionStrategyParams (indicators +
    maxTrainingWindowSize) merged with BacktestingParams (enter/exit
    thresholds, maxPositions)."""

    indicators: tuple = DEFAULT_INDICATORS
    max_training_window: int = 200
    enter_threshold: float = 0.001
    exit_threshold: float = 0.0
    max_positions: int = 3
    ridge: float = 1e-4


def score_with_weights(feats: np.ndarray, weights: np.ndarray) -> np.ndarray:
    """(N, F) features x (N, F+1) weights (bias last) -> (N,) scores —
    the ONE scoring implementation predict and backtest both use."""
    f1 = np.concatenate(
        [feats, np.ones((feats.shape[0], 1), np.float32)], axis=1)
    return np.einsum("nf,nf->n", f1, weights)


def select_positions(
    scores: np.ndarray,
    held: set[int],
    params: "RegressionStrategyParams",
) -> set[int]:
    """The threshold policy (reference BacktestingParams semantics): exit
    holdings below exit_threshold, then enter the top scorers above
    enter_threshold until max_positions are held. Shared by predict
    (held = empty: stateless advice) and backtest (persistent holdings)."""
    held = {i for i in held if scores[i] >= params.exit_threshold}
    for i in np.argsort(-scores):
        if len(held) >= params.max_positions:
            break
        if scores[i] > params.enter_threshold:
            held.add(int(i))
    return held


@dataclass
class StockModel:
    weights: np.ndarray          # (N, F+1) per-ticker regression weights
    latest_features: np.ndarray  # (N, F) indicator values at the last day
    tickers: list[str]
    params: RegressionStrategyParams

    def scores(self) -> np.ndarray:
        return score_with_weights(self.latest_features, self.weights)


def fit_ticker_regressions(
    feats: jax.Array, targets: jax.Array, ridge: float
) -> jax.Array:
    """Batched per-ticker least squares: feats (T, N, F), targets (T, N)
    -> weights (N, F+1) with a bias column — the reference's per-symbol
    nak regression (RegressionStrategy.scala:39-53) as ONE batched solve."""
    T, N, F = feats.shape
    ones = jnp.ones((T, N, 1), feats.dtype)
    X = jnp.concatenate([feats, ones], axis=-1)       # (T, N, F+1)
    A = jnp.einsum("tnf,tng->nfg", X, X)
    A = A + ridge * jnp.eye(F + 1, dtype=X.dtype)[None]
    b = jnp.einsum("tnf,tn->nf", X, targets)
    chol = jax.scipy.linalg.cho_factor(A)
    return jax.scipy.linalg.cho_solve(chol, b)


class RegressionStrategyAlgorithm(P2LAlgorithm):
    params_class = RegressionStrategyParams

    def __init__(self, params=RegressionStrategyParams()):
        self.params = params

    def _features_targets(self, frame: PriceFrame):
        lp = jnp.asarray(frame.log_price)
        feats = indicator_matrix(lp, tuple(self.params.indicators))
        target = log_returns(lp, 1)                   # realized 1d return
        # predict NEXT day's return from today's features
        return feats[:-1], target[1:], feats[-1]

    def train(self, ctx, frame: PriceFrame) -> StockModel:
        frame.sanity_check()
        p = self.params
        feats, targets, latest = self._features_targets(frame)
        w = p.max_training_window
        if feats.shape[0] > w:
            feats, targets = feats[-w:], targets[-w:]
        weights = fit_ticker_regressions(feats, targets, p.ridge)
        return StockModel(
            weights=np.asarray(weights),
            latest_features=np.asarray(latest),
            tickers=frame.tickers,
            params=p,
        )

    def predict(self, model: StockModel, query: dict) -> dict:
        """{"tickers"?: [...]} -> predicted next-day log returns + the
        threshold strategy's enter/exit calls (reference DailyResult)."""
        scores = model.scores()
        order = {t: i for i, t in enumerate(model.tickers)}
        asked = [t for t in (query.get("tickers") or model.tickers)
                 if t in order]
        idx = {order[t] for t in asked}
        # the SAME policy the backtest simulates, restricted to the asked
        # universe, from a flat (no holdings) position
        sub_scores = scores.copy()
        mask = np.full(len(scores), -np.inf)
        for i in idx:
            mask[i] = scores[i]
        enter_idx = select_positions(mask, set(), model.params)
        out = sorted(
            ({"ticker": t, "score": float(scores[order[t]])} for t in asked),
            key=lambda d: -d["score"],
        )
        enter = sorted((model.tickers[i] for i in enter_idx),
                       key=lambda t: -scores[order[t]])
        exit_ = [t for t in asked
                 if scores[order[t]] < model.params.exit_threshold]
        return {
            "tickerScores": out,
            "toEnter": enter,
            "toExit": exit_,
        }


# ---------------------------------------------------------------------------
# walk-forward backtest (reference BackTestingMetrics.scala)
# ---------------------------------------------------------------------------

@dataclass
class BacktestResult:
    nav: list[float]             # daily net asset value (starts at 1.0)
    daily_returns: list[float]
    total_return: float
    volatility: float            # stdev of daily returns
    sharpe: float                # annualized (sqrt(252))
    days: int

    def to_dict(self) -> dict:
        return {
            "nav": self.nav, "dailyReturns": self.daily_returns,
            "ret": self.total_return, "vol": self.volatility,
            "sharpe": self.sharpe, "days": self.days,
        }


def backtest(
    frame: PriceFrame,
    params: RegressionStrategyParams = RegressionStrategyParams(),
    train_window: int = 100,
    retrain_every: int = 5,
) -> BacktestResult:
    """Walk-forward: retrain the batched regression every `retrain_every`
    days on the trailing window, each day enter the top-scoring tickers
    above enter_threshold (up to max_positions, reference
    BacktestingParams), exit below exit_threshold, and realize the held
    tickers' next-day returns equal-weighted into NAV."""
    lp = frame.log_price
    T, N = lp.shape
    if T <= train_window + 2:
        raise ValueError(
            f"need more than {train_window + 2} days, have {T}"
        )
    feats_all = np.asarray(indicator_matrix(
        jnp.asarray(lp), tuple(params.indicators)))
    rets_all = np.asarray(log_returns(jnp.asarray(lp), 1))

    algo = RegressionStrategyAlgorithm(params)
    nav = [1.0]
    daily: list[float] = []
    held: set[int] = set()
    weights = None
    for t in range(train_window, T - 1):
        if weights is None or (t - train_window) % retrain_every == 0:
            f = jnp.asarray(feats_all[t - train_window:t - 1])
            y = jnp.asarray(rets_all[t - train_window + 1:t])
            weights = np.asarray(
                fit_ticker_regressions(f, y, params.ridge))
        scores = score_with_weights(feats_all[t], weights)
        held = select_positions(scores, held, params)
        day_ret = (
            float(np.mean([rets_all[t + 1, i] for i in held]))
            if held else 0.0
        )
        daily.append(day_ret)
        nav.append(nav[-1] * float(np.exp(day_ret)))
    arr = np.array(daily)
    vol = float(arr.std())
    mean = float(arr.mean())
    sharpe = float(mean / vol * np.sqrt(252)) if vol > 0 else 0.0
    return BacktestResult(
        nav=[float(v) for v in nav],
        daily_returns=[float(r) for r in daily],
        total_return=float(nav[-1] - 1.0),
        volatility=vol,
        sharpe=sharpe,
        days=len(daily),
    )


class StockEngine(EngineFactory):
    """Reference scala-stock Run.scala composition: DataSource +
    RegressionStrategy + (backtest via `backtest()` / the evaluation
    workflow)."""

    @classmethod
    def apply(cls) -> Engine:
        return Engine(
            StockDataSource,
            IdentityPreparator,
            {"regression": RegressionStrategyAlgorithm},
            FirstServing,
        )
