"""Classification engine template — Naive Bayes + Random Forest.

Parity target: reference examples/scala-parallel-classification/
{add-algorithm, custom-attributes}: DataSource aggregates user entity
properties into labeled feature vectors (custom-attributes/.../DataSource.scala:30-60
maps categorical attrs through value maps and requires a `plan` label);
algorithms are MLlib NaiveBayes (NaiveBayesAlgorithm.scala:15-27) and
RandomForest (add-algorithm/.../RandomForestAlgorithm.scala:28-43); query =
attribute dict -> {"label": ...}. TPU-native: NB scoring is a single matmul
(ops/naive_bayes.py); forest GROWTH is host-side histogram induction, its
batched inference runs on-device (ops/forest.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Sequence

import numpy as np

from pio_tpu.controller.base import (
    DataSource,
    FirstServing,
    IdentityPreparator,
    LAlgorithm,
    P2LAlgorithm,
    Params,
)
from pio_tpu.controller.engine import Engine, EngineFactory
from pio_tpu.e2.crossvalidation import split_data
from pio_tpu.e2.vectorizer import BinaryVectorizer
from pio_tpu.ops import forest as rf
from pio_tpu.ops import naive_bayes as nb


@dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = ""
    attributes: tuple[str, ...] = ("gender", "age", "education")
    label: str = "plan"
    eval_k: int = 0


@dataclass
class ClassificationData:
    """Feature rows (one-hot categorical + numeric passthrough) + labels."""

    x: np.ndarray                    # (N, D) float32
    y: np.ndarray                    # (N,) int labels
    vectorizer: BinaryVectorizer
    numeric_fields: tuple[str, ...]
    labels: "Any"                    # BiMap label-value -> index

    def sanity_check(self):
        if len(self.y) == 0:
            raise ValueError(
                "ClassificationData is empty; check that entities define the "
                "required label/attribute properties."
            )

    def encode_query(self, attrs: dict) -> np.ndarray:
        cat = {k: v for k, v in attrs.items() if isinstance(v, str)}
        row = self.vectorizer.transform(cat)
        nums = np.array(
            [float(attrs.get(f, 0.0)) for f in self.numeric_fields],
            np.float32,
        )
        return np.concatenate([row, nums])


class ClassificationDataSource(DataSource):
    """aggregateProperties(entityType='user', required=[label]+attrs) ->
    labeled vectors (reference DataSource.scala:30-60). Categorical string
    attributes one-hot encode; numeric attributes pass through."""

    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        self.params = params

    def _read(self, ctx) -> ClassificationData:
        from pio_tpu.data.bimap import BiMap

        p = self.params
        props = ctx.event_store.aggregate_properties(
            app_name=p.app_name,
            entity_type="user",
            required=[p.label, *p.attributes],
        )
        rows = []
        for entity_id, pm in sorted(props.items()):
            attrs = {a: pm.get(a) for a in p.attributes}
            rows.append((str(pm.get(p.label)), attrs))
        if not rows:
            return ClassificationData(
                x=np.zeros((0, 0), np.float32),
                y=np.zeros(0, np.int64),
                vectorizer=BinaryVectorizer.fit([], []),
                numeric_fields=(),
                labels=BiMap({}),
            )
        categorical = tuple(
            a for a in p.attributes
            if isinstance(rows[0][1][a], str)
        )
        numeric = tuple(a for a in p.attributes if a not in categorical)
        vec = BinaryVectorizer.fit(
            ({k: v for k, v in attrs.items() if k in categorical}
             for _, attrs in rows),
            categorical,
        )
        labels = BiMap.string_int(lbl for lbl, _ in rows)
        x = np.stack([
            np.concatenate([
                vec.transform({k: v for k, v in attrs.items()
                               if k in categorical}),
                np.array([float(attrs[f]) for f in numeric], np.float32),
            ])
            for _, attrs in rows
        ])
        y = np.array([labels[lbl] for lbl, _ in rows], np.int64)
        return ClassificationData(
            x=x, y=y, vectorizer=vec, numeric_fields=numeric, labels=labels
        )

    def read_training(self, ctx) -> ClassificationData:
        return self._read(ctx)

    def read_eval(self, ctx):
        data = self._read(ctx)
        if self.params.eval_k <= 1:
            return []
        rows = list(range(len(data.y)))
        folds = []
        for train_rows, info, test_rows in split_data(rows, self.params.eval_k):
            tr = ClassificationData(
                x=data.x[train_rows], y=data.y[train_rows],
                vectorizer=data.vectorizer,
                numeric_fields=data.numeric_fields, labels=data.labels,
            )
            qa = [
                ({"_vector": data.x[i].tolist()},
                 data.labels.inverse()[int(data.y[i])])
                for i in test_rows
            ]
            folds.append((tr, info, qa))
        return folds


@dataclass(frozen=True)
class NaiveBayesParams(Params):
    lambda_: float = 1.0  # reference NaiveBayesAlgorithm "lambda"


@dataclass
class NBClassifierModel:
    nb_model: nb.MultinomialNBModel
    data_schema: ClassificationData  # vectorizer/labels (x,y stripped)


def _schema_only(data: ClassificationData) -> ClassificationData:
    return ClassificationData(
        x=np.zeros((0, 0), np.float32), y=np.zeros(0, np.int64),
        vectorizer=data.vectorizer, numeric_fields=data.numeric_fields,
        labels=data.labels,
    )


def _query_vector(model_schema: ClassificationData, query: dict) -> np.ndarray:
    if "_vector" in query:  # eval path: pre-encoded
        return np.asarray(query["_vector"], np.float32)
    return model_schema.encode_query(query)


class NaiveBayesAlgorithm(P2LAlgorithm):
    """Reference NaiveBayesAlgorithm.scala:15-27 (MLlib NaiveBayes(lambda)).

    Note: multinomial NB treats numeric attributes as event counts, so
    threshold rules on raw numerics (e.g. age > 50) are poorly captured —
    same limitation as MLlib NB. Use the randomforest algorithm (the
    add-algorithm variant's point) when such rules matter."""

    params_class = NaiveBayesParams

    def __init__(self, params: NaiveBayesParams = NaiveBayesParams()):
        self.params = params

    def train(self, ctx, data: ClassificationData) -> NBClassifierModel:
        data.sanity_check()
        model = nb.multinomial_nb_train(
            data.x, data.y, n_classes=len(data.labels),
            smoothing=self.params.lambda_,
        )
        return NBClassifierModel(model, _schema_only(data))

    def predict(self, model: NBClassifierModel, query: dict) -> dict:
        v = _query_vector(model.data_schema, query)
        label_idx = int(nb.multinomial_nb_predict(model.nb_model, v[None, :])[0])
        return {"label": model.data_schema.labels.inverse()[label_idx]}

    def batch_predict(self, model: NBClassifierModel, queries) -> list:
        if not queries:
            return []
        x = np.stack([_query_vector(model.data_schema, q) for q in queries])
        preds = nb.multinomial_nb_predict(model.nb_model, x)
        inv = model.data_schema.labels.inverse()
        return [{"label": inv[int(i)]} for i in preds]


@dataclass(frozen=True)
class RandomForestParams(Params):
    num_trees: int = 10
    max_depth: int = 5
    feature_subset_strategy: str = "auto"
    max_bins: int = 32  # MLlib Strategy.maxBins; 0 = exact threshold search
    seed: int = 0


@dataclass
class RFClassifierModel:
    forest: rf.RandomForestModel
    data_schema: ClassificationData


class RandomForestAlgorithm(LAlgorithm):
    """Reference RandomForestAlgorithm.scala:28-43."""

    params_class = RandomForestParams

    def __init__(self, params: RandomForestParams = RandomForestParams()):
        self.params = params

    def train(self, ctx, data: ClassificationData) -> RFClassifierModel:
        data.sanity_check()
        model = rf.random_forest_train(
            data.x, data.y, n_classes=len(data.labels),
            num_trees=self.params.num_trees,
            max_depth=self.params.max_depth,
            feature_subset=self.params.feature_subset_strategy,
            max_bins=self.params.max_bins,
            seed=self.params.seed,
        )
        return RFClassifierModel(model, _schema_only(data))

    def predict(self, model: RFClassifierModel, query: dict) -> dict:
        v = _query_vector(model.data_schema, query)
        label_idx = int(model.forest.predict(v[None, :])[0])
        return {"label": model.data_schema.labels.inverse()[label_idx]}

    def batch_predict(self, model: RFClassifierModel, queries) -> list:
        if not queries:
            return []
        x = np.stack([_query_vector(model.data_schema, q) for q in queries])
        if len(x) >= 2048:  # big catalogs: jitted gather loop on device
            preds = np.asarray(model.forest.predict_device(x))
        else:
            preds = model.forest.predict(x)
        inv = model.data_schema.labels.inverse()
        return [{"label": inv[int(i)]} for i in preds]


class ClassificationEngine(EngineFactory):
    """Multi-algorithm engine (the add-algorithm variant's point: register
    both NB and RF, select via engine.json)."""

    @classmethod
    def apply(cls) -> Engine:
        return Engine(
            ClassificationDataSource,
            IdentityPreparator,
            {"naive": NaiveBayesAlgorithm, "randomforest": RandomForestAlgorithm},
            FirstServing,
        )
