"""Friend-recommendation engine template — SimRank over a social graph.

Parity target: reference examples/experimental/
scala-parallel-friend-recommendation: PDataSource variants reading an
edge-list file — full graph (DataSource.scala:29-41), node sampling and
forest-fire sampling (Sampling.scala) for graphs too large to score whole —
Delta-SimRank on GraphX (DeltaSimRankRDD.scala), and a pairwise Query
(item1, item2) -> score (Engine.scala:6-9, SimRankAlgorithm.scala:35-41).

TPU-native: SimRank is the dense matrix recurrence on the MXU
(ops/simrank.py). The query surface accepts both the reference's pairwise
shape {"item1", "item2"} -> {"score"} and the natural retrieval shape
{"user", "num"} -> {"friendScores": [...]} the template's name promises.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from pio_tpu.controller.base import (
    DataSource,
    FirstServing,
    IdentityPreparator,
    P2LAlgorithm,
    Params,
)
from pio_tpu.controller.engine import Engine, EngineFactory
from pio_tpu.data.bimap import EntityIdIndex
from pio_tpu.ops.simrank import simrank_scores, simrank_topk


@dataclass(frozen=True)
class DataSourceParams(Params):
    """graph_edgelist_path: whitespace-separated `src dst` lines (the
    reference GraphLoader.edgeListFile contract). Event mode instead reads
    user->user events (e.g. `follow`). Sampling mirrors the reference's
    NodeSamplingDataSource / ForestFireSamplingDataSource params."""

    path_fields = ("graph_edgelist_path",)

    graph_edgelist_path: str = ""
    app_name: str = ""
    event_names: tuple[str, ...] = ("follow",)
    sample_method: str = "none"       # none | node | forestfire
    sample_fraction: float = 1.0
    geo_param: float = 0.3            # forest-fire geometric(p) burst size
    seed: int = 9


@dataclass
class FriendGraph:
    src: np.ndarray                   # (E,) int node indices
    dst: np.ndarray
    nodes: EntityIdIndex

    def sanity_check(self):
        if len(self.src) == 0:
            raise ValueError("FriendGraph has no edges.")


def node_sample(src, dst, n_nodes: int, fraction: float, seed: int):
    """Uniform node sampling (reference Sampling.nodeSampling): keep a
    fraction of nodes, induce the subgraph."""
    rng = np.random.default_rng(seed)
    keep = rng.random(n_nodes) < fraction
    mask = keep[src] & keep[dst]
    return src[mask], dst[mask]


def forest_fire_sample(src, dst, n_nodes: int, fraction: float,
                       geo_param: float, seed: int):
    """Forest-fire sampling (reference Sampling.forestFireSamplingInduced):
    BFS burns from random seeds, burning a geometric(p) number of
    out-neighbors per node, until ~fraction of nodes are burned; the
    induced subgraph is returned."""
    rng = np.random.default_rng(seed)
    target = max(1, int(n_nodes * fraction))
    out_adj: dict[int, list[int]] = {}
    for s, d in zip(src, dst):
        out_adj.setdefault(int(s), []).append(int(d))
    burned: set[int] = set()
    frontier: list[int] = []
    while len(burned) < target:
        if not frontier:
            fresh = int(rng.integers(0, n_nodes))
            if fresh in burned:
                continue
            burned.add(fresh)
            frontier.append(fresh)
            continue
        node = frontier.pop(0)
        # geometric burst size (reference geometricSample)
        n_burn = 1
        while rng.random() <= geo_param:
            n_burn += 1
        nbrs = [x for x in out_adj.get(node, ()) if x not in burned]
        rng.shuffle(nbrs)
        for x in nbrs[:n_burn]:
            burned.add(x)
            frontier.append(x)
            if len(burned) >= target:
                break
    keep = np.zeros(n_nodes, bool)
    keep[list(burned)] = True
    mask = keep[src] & keep[dst]
    return src[mask], dst[mask]


class FriendGraphDataSource(DataSource):
    """All three reference datasource variants behind one params switch
    (the reference registers them as named datasources 'default'/'node'/
    'forest', Engine.scala:21-26)."""

    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        self.params = params

    def _edges(self, ctx) -> tuple[list[str], list[str]]:
        p = self.params
        if p.graph_edgelist_path:
            srcs, dsts = [], []
            with open(p.graph_edgelist_path) as f:
                for line in f:
                    parts = line.split()
                    if len(parts) >= 2 and not parts[0].startswith("#"):
                        srcs.append(parts[0])
                        dsts.append(parts[1])
            return srcs, dsts
        events = ctx.event_store.find(
            app_name=p.app_name, event_names=list(p.event_names)
        )
        pairs = [
            (e.entity_id, e.target_entity_id)
            for e in events if e.target_entity_id
        ]
        return [a for a, _ in pairs], [b for _, b in pairs]

    def read_training(self, ctx) -> FriendGraph:
        p = self.params
        srcs, dsts = self._edges(ctx)
        nodes = EntityIdIndex(list(srcs) + list(dsts))
        src = nodes.encode(srcs) if srcs else np.zeros(0, np.int64)
        dst = nodes.encode(dsts) if dsts else np.zeros(0, np.int64)
        n = len(nodes)
        sampled = False
        if p.sample_method == "node" and p.sample_fraction < 1.0:
            src, dst = node_sample(src, dst, n, p.sample_fraction, p.seed)
            sampled = True
        elif p.sample_method == "forestfire" and p.sample_fraction < 1.0:
            src, dst = forest_fire_sample(
                src, dst, n, p.sample_fraction, p.geo_param, p.seed
            )
            sampled = True
        if sampled:
            # re-index over the SURVIVING nodes: sampling exists so the
            # n^2 SimRank state fits the chip, which only works if the
            # dead nodes leave the index too
            ids = nodes.decode(np.concatenate([src, dst])) \
                if len(src) else []
            nodes = EntityIdIndex(ids)
            if len(src):
                src = nodes.encode(ids[: len(src)])
                dst = nodes.encode(ids[len(src):])
        return FriendGraph(src=src, dst=dst, nodes=nodes)


@dataclass(frozen=True)
class SimRankParams(Params):
    """Reference SimRankParams (SimRankAlgorithm.scala:10-12)."""

    num_iterations: int = 5
    decay: float = 0.8
    k_top: int = 50               # neighbor table width for retrieval


@dataclass
class SimRankModel:
    top_scores: np.ndarray        # (n, k_top)
    top_idx: np.ndarray           # (n, k_top)
    pair_scores: np.ndarray       # (n, n) full matrix (pairwise queries)
    nodes: EntityIdIndex


class SimRankAlgorithm(P2LAlgorithm):
    params_class = SimRankParams

    def __init__(self, params: SimRankParams = SimRankParams()):
        self.params = params

    def train(self, ctx, data: FriendGraph) -> SimRankModel:
        data.sanity_check()
        p = self.params
        S = simrank_scores(
            data.src, data.dst, len(data.nodes),
            decay=p.decay, iterations=p.num_iterations,
        )
        scores, idx = simrank_topk(S, p.k_top)
        return SimRankModel(scores, idx, S, data.nodes)

    def predict(self, model: SimRankModel, query: dict) -> dict:
        # pairwise shape (reference Query(item1, item2) -> Double)
        if "item1" in query and "item2" in query:
            a, b = str(query["item1"]), str(query["item2"])
            if a not in model.nodes or b not in model.nodes:
                return {"score": 0.0}
            ia = int(model.nodes.encode([a])[0])
            ib = int(model.nodes.encode([b])[0])
            return {"score": float(model.pair_scores[ia, ib])}
        # retrieval shape: top-num friends for a user
        user = str(query.get("user", ""))
        num = int(query.get("num", 10))
        if user not in model.nodes:
            return {"friendScores": []}
        iu = int(model.nodes.encode([user])[0])
        out = []
        for j, s in zip(model.top_idx[iu][:num], model.top_scores[iu][:num]):
            if s > 0:
                out.append({"friend": model.nodes.id_of(int(j)),
                            "score": float(s)})
        return {"friendScores": out}


class FriendRecommendationEngine(EngineFactory):
    """Reference PSimRankEngineFactory (Engine.scala:20-30)."""

    @classmethod
    def apply(cls) -> Engine:
        return Engine(
            FriendGraphDataSource,
            IdentityPreparator,
            {"simrank": SimRankAlgorithm},
            FirstServing,
        )
