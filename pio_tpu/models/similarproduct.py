"""Similar-product engine template — item-to-item similarity over ALS factors.

Parity target: reference examples/scala-parallel-similarproduct/* : DataSource
reads $set events for users/items plus view/like events; ALS.trainImplicit
learns item factors; query {"items": [...], "num": N, "categories"?,
"whiteList"?, "blackList"?} returns the most cosine-similar items to the
query set, excluding the query items themselves
(ALSAlgorithm.scala cosine loop; multi/LikeAlgorithm.scala:21-86). TPU-native:
the per-item cosine RDD map becomes one normalized matmul + top_k
(ops/similarity.py); category filtering reads item properties aggregated at
train time.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from pio_tpu.controller.base import (
    DataSource,
    FirstServing,
    IdentityPreparator,
    P2LAlgorithm,
    PAlgorithm,
    Params,
)
from pio_tpu.controller.engine import Engine, EngineFactory
from pio_tpu.data.bimap import EntityIdIndex
from pio_tpu.data.eventstore import Interactions
from pio_tpu.models.filtering import (
    candidate_ids,
    invert_categories,
    rank_candidates,
)
from pio_tpu.ops import als
from pio_tpu.ops.bucketing import pow2_bucket
from pio_tpu.ops.similarity import column_cosine_topk, cosine_topk, mean_vector


@dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = ""
    event_names: tuple[str, ...] = ("view", "like")


@dataclass
class SimilarProductData:
    interactions: Interactions
    item_categories: dict[str, list[str]]  # item id -> categories

    def sanity_check(self):
        self.interactions.sanity_check()


class SimilarProductDataSource(DataSource):
    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        self.params = params

    def read_training(self, ctx) -> SimilarProductData:
        p = self.params
        inter = ctx.event_store.interactions(
            app_name=p.app_name,
            entity_type="user",
            target_entity_type="item",
            event_names=list(p.event_names),
            value_key=None,
            default_value=1.0,
            dedup="sum",
        )
        item_props = ctx.event_store.aggregate_properties(
            app_name=p.app_name, entity_type="item"
        )
        cats = {
            iid: pm.get_or_else("categories", [])
            for iid, pm in item_props.items()
        }
        return SimilarProductData(inter, cats)


@dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 10
    num_iterations: int = 10
    lambda_: float = 0.01
    alpha: float = 1.0
    seed: int | None = None
    chunk: int = 65536


@jax.tree_util.register_pytree_node_class
@dataclass
class SimilarProductModel:
    """Item factors + id index + categories (reference ALSModel with
    productFeatures + items map)."""

    item_factors: jax.Array
    items: EntityIdIndex
    item_categories: dict

    def tree_flatten(self):
        return (self.item_factors,), (self.items, self.item_categories)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], *aux)

    def cat_index(self) -> dict:
        return _cached_cat_index(self)


def _parse_similar_query(items_index, query: dict):
    """Shared query parsing for the similarproduct algorithms (reference
    predict() preamble: item->index map, white/black lists, query items
    always excluded from results)."""
    items = query.get("items") or []
    num = int(query.get("num", 10))
    known = [i for i in items if i in items_index]
    exclude = set(items) | set(query.get("blackList") or ())
    white = set(query.get("whiteList") or ()) or None
    categories = set(query.get("categories") or ()) or None
    return num, known, exclude, white, categories


def _cached_cat_index(model) -> dict:
    """category -> [item ids], built lazily once per model instance."""
    if not hasattr(model, "_cat_index"):
        model._cat_index = invert_categories(model.item_categories)
    return model._cat_index


class ALSSimilarityAlgorithm(PAlgorithm):
    params_class = ALSAlgorithmParams

    def __init__(self, params: ALSAlgorithmParams):
        self.params = params

    def train(self, ctx, data: SimilarProductData) -> SimilarProductModel:
        data.sanity_check()
        inter = data.interactions
        p = self.params
        ap = als.ALSParams(
            rank=p.rank, iterations=p.num_iterations, reg=p.lambda_,
            alpha=p.alpha, implicit=True,
            seed=p.seed if p.seed is not None else 3, chunk=p.chunk,
        )
        if ctx.mesh is not None and ctx.mesh.devices.size > 1:
            factors = als.als_train_sharded(
                inter.user_idx, inter.item_idx, inter.values,
                inter.n_users, inter.n_items, ap, ctx.mesh,
            )
        else:
            factors = als.als_train(
                inter.user_idx, inter.item_idx, inter.values,
                inter.n_users, inter.n_items, ap,
            )
        return SimilarProductModel(
            factors.item_factors, inter.items, data.item_categories
        )

    def predict(self, model: SimilarProductModel, query: dict) -> dict:
        """Reference ALSAlgorithm.predict: average query-item vectors,
        cosine top-k over the catalog, filter query items / categories /
        white / black lists."""
        num, known, exclude, white, categories = \
            _parse_similar_query(model.items, query)
        if not known:
            return {"itemScores": []}
        q_idx = model.items.encode(known)
        qv = mean_vector(model.item_factors, q_idx)
        candidates = candidate_ids(
            model.items, model.item_categories, white, categories, exclude,
            cat_index=model.cat_index,
        )
        if candidates is not None:
            # selective filters: rank WITHIN the candidate set (reference
            # ALSAlgorithm.scala filters candidates before its cosine loop);
            # scoring is one bucketed gather+matmul+top_k on device
            if not candidates:
                return {"itemScores": []}
            cidx = model.items.encode(candidates)
            pos, scores = rank_candidates(
                model.item_factors, qv, cidx, num, normalize=True
            )
            return {"itemScores": [
                {"item": candidates[p], "score": float(s)}
                for p, s in zip(pos, scores)
            ]}
        k = min(num + len(exclude), model.item_factors.shape[0])
        scores, idx = cosine_topk(model.item_factors, qv, k)
        return self._format_topk(
            model, np.asarray(scores)[0], np.asarray(idx)[0], exclude, num)

    @staticmethod
    def _format_topk(model, scores, idx, exclude, num) -> dict:
        out = []
        for i, s in zip(model.items.decode(idx), scores):
            if i in exclude:
                continue
            out.append({"item": i, "score": float(s)})
            if len(out) >= num:
                break
        return {"itemScores": out}

    def batch_predict(self, model: SimilarProductModel, queries) -> list:
        """Vectorized batch scoring (the micro-batcher's path): plain
        queries (no whiteList/categories filters) share ONE gather of all
        query-item vectors, per-query means on host, and ONE cosine top-k
        over the bucketed batch (over-fetch k = num + max excluded, host
        filter). Selectively-filtered queries keep full candidate-set
        semantics via the single-query path."""
        results: list[dict] = [{"itemScores": []} for _ in queries]
        plain = []   # (query_index, q_idx array, exclude set, num)
        for i, q in enumerate(queries):
            num, known, exclude, white, categories = \
                _parse_similar_query(model.items, q)
            if not known:
                continue
            if white or categories:
                results[i] = self.predict(model, q)
            else:
                plain.append(
                    (i, model.items.encode(known), exclude, num))
        if not plain:
            return results
        # one device gather for every query's item vectors, means on host;
        # flat length bucketed (varying per-batch totals must not compile
        # one gather program per size)
        flat = np.concatenate([qi for _, qi, _, _ in plain])
        n_flat = len(flat)
        flat = np.concatenate(
            [flat, np.zeros(pow2_bucket(n_flat) - n_flat, flat.dtype)])
        rows = np.asarray(
            model.item_factors[jnp.asarray(flat)])[:n_flat]
        d = rows.shape[1]
        b = len(plain)
        qv = np.zeros((b, d), rows.dtype)
        off = 0
        for r, (_, qi, _, _) in enumerate(plain):
            qv[r] = rows[off:off + len(qi)].mean(axis=0)
            off += len(qi)
        k = min(
            max(num + len(exclude) for _, _, exclude, num in plain),
            model.item_factors.shape[0],
        )
        scores, idx = cosine_topk(model.item_factors, jnp.asarray(qv), k)
        scores, idx = np.asarray(scores), np.asarray(idx)
        for r, (qi_out, _, exclude, num) in enumerate(plain):
            results[qi_out] = self._format_topk(
                model, scores[r], idx[r], exclude, num)
        return results


@dataclass(frozen=True)
class DIMSUMParams(Params):
    """Reference DIMSUMAlgorithmParams(threshold)
    (examples/experimental/scala-parallel-similarproduct-dimsum/src/main/
    scala/DIMSUMAlgorithm.scala:22). `k_sim` bounds the neighbor table
    kept per item (the reference keeps full sparse similarity rows; a
    top-k table is the fixed-shape equivalent)."""

    threshold: float = 0.0
    k_sim: int = 50
    user_batch: int = 4096


@dataclass
class DIMSUMModel:
    """Top-k item-to-item cosine table over the RAW interaction matrix
    (reference DIMSUMModel.similarities sparse rows)."""

    sim_scores: np.ndarray      # (n_items, k_sim) cosine scores
    sim_idx: np.ndarray         # (n_items, k_sim) neighbor item indices
    items: EntityIdIndex
    item_categories: dict

    def cat_index(self) -> dict:
        return _cached_cat_index(self)


class DIMSUMAlgorithm(P2LAlgorithm):
    """Exact all-pairs column cosine (ops/similarity.column_cosine_topk) —
    the TPU redesign of MLlib RowMatrix.columnSimilarities(threshold)
    (DIMSUMAlgorithm.scala:125-132). Unlike the ALS algorithm this scores
    items by raw co-occurrence, no factorization. P2L: device-heavy train,
    small host model (the reference persists its RDD rows; the top-k table
    checkpoints whole)."""

    params_class = DIMSUMParams

    def __init__(self, params: DIMSUMParams = DIMSUMParams()):
        self.params = params

    def train(self, ctx, data: SimilarProductData) -> DIMSUMModel:
        data.sanity_check()
        inter = data.interactions
        p = self.params
        scores, idx = column_cosine_topk(
            inter.user_idx, inter.item_idx, inter.values,
            inter.n_users, inter.n_items,
            k=p.k_sim, threshold=p.threshold, user_batch=p.user_batch,
        )
        return DIMSUMModel(scores, idx, inter.items, data.item_categories)

    def predict(self, model: DIMSUMModel, query: dict) -> dict:
        """Reference DIMSUMAlgorithm.predict: union the query items'
        similarity rows, sum scores per candidate, filter query items /
        white / black lists, top num."""
        num, known, exclude, white, categories = \
            _parse_similar_query(model.items, query)
        if not known:
            return {"itemScores": []}
        q_idx = model.items.encode(known)
        agg: dict[int, float] = {}
        for qi in q_idx:
            for j, s in zip(model.sim_idx[qi], model.sim_scores[qi]):
                if s > 0:
                    agg[int(j)] = agg.get(int(j), 0.0) + float(s)
        # filter semantics shared with the ALS path (filtering.py): when a
        # selective filter is present, membership comes from candidate_ids
        allowed = candidate_ids(
            model.items, model.item_categories, white, categories, exclude,
            cat_index=model.cat_index,
        )
        allowed = None if allowed is None else set(allowed)
        out = []
        for j, s in sorted(agg.items(), key=lambda kv: (-kv[1], kv[0])):
            iid = model.items.id_of(j)
            if iid in exclude:
                continue
            if allowed is not None and iid not in allowed:
                continue
            out.append({"item": iid, "score": s})
            if len(out) >= num:
                break
        return {"itemScores": out}


class SimilarProductEngine(EngineFactory):
    @classmethod
    def apply(cls) -> Engine:
        return Engine(
            SimilarProductDataSource,
            IdentityPreparator,
            {"als": ALSSimilarityAlgorithm, "dimsum": DIMSUMAlgorithm},
            FirstServing,
        )
