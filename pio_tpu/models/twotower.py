"""Two-tower neural retrieval template — the flagship pjit model.

The new-capability template from BASELINE.json ("Two-tower neural recommender
template (new PAlgorithm, pjit data-parallel)"): user and item towers
(embedding + MLP) trained with in-batch sampled softmax over (user, item)
interaction pairs. This is where the mesh design shows its axes:

 * batch is sharded over the "data" axis (pure dp);
 * embedding tables and MLP kernels are sharded over the "model" axis
   (Megatron-style tp: vocab-sharded embeddings, alternating column/row
   sharded Dense kernels);
 * the in-batch softmax runs over the GLOBAL batch: XLA inserts the
   all_gather/psum for the (B, B) logits automatically from the sharding
   annotations — the "let GSPMD insert collectives" recipe.

Serving: item embeddings are precomputed into a matrix at train end; query =
user tower forward + the same top-k matmul path the ALS templates use.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pio_tpu.controller.base import (
    DataSource,
    FirstServing,
    IdentityPreparator,
    PAlgorithm,
    Params,
)
from pio_tpu.controller.engine import Engine, EngineFactory
from pio_tpu.data.eventstore import Interactions
from pio_tpu.ops.bucketing import pow2_bucket
from pio_tpu.ops.similarity import cosine_topk
from pio_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


class Tower(nn.Module):
    """Embedding + 2-layer MLP -> L2-normalized embedding."""

    vocab: int
    embed_dim: int
    hidden_dim: int
    out_dim: int

    @nn.compact
    def __call__(self, ids):  # (B,) int32
        # vocab-sharded table (tp): rows split over the model axis
        e = nn.Embed(
            self.vocab, self.embed_dim,
            embedding_init=nn.initializers.normal(0.02),
        )(ids)
        h = nn.Dense(self.hidden_dim)(e)       # column-sharded kernel
        h = nn.relu(h)
        z = nn.Dense(self.out_dim)(h)          # row-sharded kernel
        return z / (jnp.linalg.norm(z, axis=-1, keepdims=True) + 1e-8)


@dataclass(frozen=True)
class TwoTowerParams(Params):
    embed_dim: int = 64
    hidden_dim: int = 128
    out_dim: int = 32
    temperature: float = 0.05
    learning_rate: float = 1e-3
    batch_size: int = 1024
    steps: int = 200
    seed: int = 0
    # mid-train step checkpoints (workflow/orbax_ckpt.py); "" = off
    checkpoint_dir: str = ""
    checkpoint_every: int = 100


def param_shardings(params_tree, mesh: Mesh):
    """Sharding tree for the tower params: embeddings vocab-sharded, Dense
    kernels alternately column/row sharded over the model axis."""

    def spec_for(path, leaf):
        names = [getattr(p, "key", str(p)) for p in path]
        if leaf.ndim == 2:
            if any("Embed" in n or "embedding" in n for n in names):
                return P(MODEL_AXIS, None)      # vocab-sharded
            if "Dense_0" in names:
                return P(None, MODEL_AXIS)      # column parallel
            if "Dense_1" in names:
                return P(MODEL_AXIS, None)      # row parallel
        return P()

    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(mesh, spec_for(path, leaf)),
        params_tree,
    )


def make_towers(n_users: int, n_items: int, p: TwoTowerParams):
    user_tower = Tower(n_users, p.embed_dim, p.hidden_dim, p.out_dim)
    item_tower = Tower(n_items, p.embed_dim, p.hidden_dim, p.out_dim)
    return user_tower, item_tower


def init_params(n_users: int, n_items: int, p: TwoTowerParams):
    user_tower, item_tower = make_towers(n_users, n_items, p)
    ku, ki = jax.random.split(jax.random.PRNGKey(p.seed))
    dummy = jnp.zeros((1,), jnp.int32)
    return {
        "user": user_tower.init(ku, dummy)["params"],
        "item": item_tower.init(ki, dummy)["params"],
    }


def make_train_step(n_users: int, n_items: int, p: TwoTowerParams, optimizer):
    user_tower, item_tower = make_towers(n_users, n_items, p)

    def loss_fn(params, u_ids, i_ids):
        u = user_tower.apply({"params": params["user"]}, u_ids)   # (B, d)
        v = item_tower.apply({"params": params["item"]}, i_ids)   # (B, d)
        logits = (u @ v.T) / p.temperature                        # (B, B)
        labels = jnp.arange(u_ids.shape[0])
        # symmetric in-batch softmax (user->item and item->user)
        l1 = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        l2 = optax.softmax_cross_entropy_with_integer_labels(logits.T, labels)
        return (l1.mean() + l2.mean()) / 2

    def train_step(params, opt_state, u_ids, i_ids):
        loss, grads = jax.value_and_grad(loss_fn)(params, u_ids, i_ids)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step, (user_tower, item_tower)


def train_two_tower(
    inter: Interactions,
    p: TwoTowerParams,
    mesh: Mesh | None = None,
    checkpoint=None,
    lifecycle=None,
) -> tuple[dict, jax.Array, Any]:
    """-> (params, item_embeddings matrix, towers). Sharded over the mesh
    when given; single-device jit otherwise. `checkpoint` is a
    StepCheckpointer (or None): training saves every save_every steps and
    resumes from the latest saved step with an identical batch stream
    (sampling is keyed by (seed, step)). `lifecycle` is a
    workflow.lifecycle.TrainLifecycle (or None): heartbeats every span
    boundary, and a requested preemption force-saves the current step
    then raises TrainingPreempted."""
    optimizer = optax.adam(p.learning_rate)
    train_step, towers = make_train_step(
        inter.n_users, inter.n_items, p, optimizer
    )
    params = init_params(inter.n_users, inter.n_items, p)
    opt_state = optimizer.init(params)

    from pio_tpu.workflow.orbax_ckpt import resume_or_init

    params, opt_state, start_step = resume_or_init(checkpoint, params, opt_state)

    batch = min(p.batch_size, max(8, len(inter)))
    if mesh is not None:
        n_data = mesh.shape[DATA_AXIS]
        batch = max(n_data, batch - batch % n_data)  # divisible by dp
        p_shard = param_shardings(params, mesh)
        o_shard = param_shardings_for_opt(opt_state, params, p_shard, mesh)
        # step axis replicated, batch axis dp-sharded
        xs_sharding = NamedSharding(mesh, P(None, DATA_AXIS))
        params = jax.device_put(params, p_shard)
        opt_state = jax.device_put(opt_state, o_shard)
    else:
        p_shard = o_shard = xs_sharding = None

    def run_span(params, opt_state, uu, ii):
        """lax.scan over a span of steps — the whole span is ONE device
        program: no per-step host round trip (dispatch-bound on a
        remote/tunneled device) and no per-step transfer."""
        def body(carry, xs):
            params, opt_state = carry
            u, i = xs
            params, opt_state, _loss = train_step(params, opt_state, u, i)
            return (params, opt_state), None

        (params, opt_state), _ = jax.lax.scan(
            body, (params, opt_state), (uu, ii))
        return params, opt_state

    if mesh is not None:
        span = jax.jit(
            run_span,
            in_shardings=(p_shard, o_shard, xs_sharding, xs_sharding),
            out_shardings=(p_shard, o_shard),
        )
    else:
        span = jax.jit(run_span)

    # (seed, step)-keyed sampling: the stream is identical whether the run
    # is fresh or resumed from a checkpoint. Indices for a whole SPAN of
    # steps are built host-side and cross to the device once — a span is
    # one compiled program instead of span-many dispatches; boundaries
    # come from workflow/spans.py (bounded staging + checkpoint cadence).
    from pio_tpu.workflow.spans import span_bounds

    n = len(inter)

    def batches_for(lo: int, hi: int):
        idx = np.stack([
            np.random.default_rng((p.seed, s)).integers(0, n, size=batch)
            for s in range(lo, hi)
        ])
        uu = jnp.asarray(inter.user_idx[idx], jnp.int32)
        ii = jnp.asarray(inter.item_idx[idx], jnp.int32)
        if mesh is not None:
            uu = jax.device_put(uu, xs_sharding)
            ii = jax.device_put(ii, xs_sharding)
        return uu, ii

    every = (
        max(1, checkpoint.config.save_every) if checkpoint is not None
        else None
    )
    from pio_tpu.workflow.spans import after_span, step_chaos_active

    step_chaos = step_chaos_active()
    for lo, hi, save_after in span_bounds(
            start_step, p.steps, every, cap=1 if step_chaos else 512):
        uu, ii = batches_for(lo, hi)
        params, opt_state = span(params, opt_state, uu, ii)
        after_span(hi, p.steps, params, opt_state, checkpoint=checkpoint,
                   lifecycle=lifecycle, save_after=save_after,
                   step_chaos=step_chaos)

    # materialize all item embeddings for serving
    item_ids = jnp.arange(inter.n_items, dtype=jnp.int32)
    item_emb = towers[1].apply({"params": jax.device_get(params)["item"]}, item_ids)
    return jax.device_get(params), item_emb, towers


def param_shardings_for_opt(opt_state, params, p_shard, mesh: Mesh):
    """Optimizer state shardings: adam's mu/nu are pytrees with exactly the
    params' structure, so any subtree structurally identical to `params`
    gets the params' sharding tree verbatim; everything else (count and
    other scalars) is replicated. Structural matching avoids the shape-
    collision hazard of matching leaves by shape."""
    params_struct = jax.tree_util.tree_structure(params)
    replicated = NamedSharding(mesh, P())

    def is_params_like(node):
        if node is opt_state:
            return False
        try:
            return jax.tree_util.tree_structure(node) == params_struct
        except Exception:  # noqa: BLE001 - non-pytree leaves
            return False

    def handle(node):
        if is_params_like(node):
            return p_shard
        return jax.tree_util.tree_map(lambda _: replicated, node)

    return jax.tree_util.tree_map(handle, opt_state, is_leaf=is_params_like)


# ---------------------------------------------------------------------------
# DASE wrapper
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class TwoTowerDataSourceParams(Params):
    app_name: str = ""
    event_names: tuple[str, ...] = ("view", "buy", "rate")
    # >0 -> read_eval produces k index-mod-k folds: the tuning sweep's
    # sequential path (pio eval --sweep on this engine) scores the
    # two-tower grid through the SAME fold contract the ALS templates
    # use — what promotes this engine from demo to tuned second class
    eval_k: int = 0
    eval_num: int = 10              # ranking depth of each fold query
    eval_exclude_seen: bool = True


class TwoTowerDataSource(DataSource):
    params_class = TwoTowerDataSourceParams

    def __init__(self, params: TwoTowerDataSourceParams):
        self.params = params

    def read_training(self, ctx) -> Interactions:
        return ctx.event_store.interactions(
            app_name=self.params.app_name,
            entity_type="user",
            target_entity_type="item",
            event_names=list(self.params.event_names),
            value_key=None,
            default_value=1.0,
            dedup="sum",
        )

    def read_eval(self, ctx):
        """k folds of (train, info, [(query, heldout items)]) — the
        recommendation-template eval contract over the two-tower read."""
        from pio_tpu.e2.crossvalidation import split_interactions

        data = self.read_training(ctx)
        return split_interactions(
            data, self.params.eval_k, num=self.params.eval_num,
            exclude_seen=self.params.eval_exclude_seen,
        )


@jax.tree_util.register_pytree_node_class
@dataclass
class TwoTowerModel:
    params: dict           # tower params (host pytree after train)
    item_embeddings: jax.Array
    users: Any
    items: Any
    config: TwoTowerParams

    def tree_flatten(self):
        return (self.params, self.item_embeddings), (
            self.users, self.items, self.config,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], *aux)


class TwoTowerAlgorithm(PAlgorithm):
    params_class = TwoTowerParams

    def __init__(self, params: TwoTowerParams = TwoTowerParams()):
        self.params = params

    def train(self, ctx, inter: Interactions) -> TwoTowerModel:
        inter.sanity_check()
        mesh = ctx.mesh if ctx and ctx.mesh and ctx.mesh.devices.size > 1 else None
        lifecycle = getattr(ctx, "lifecycle", None)
        # explicit params win; otherwise run_train's per-instance dir
        # (lifecycle.checkpoint_dir) makes every supervised run resumable
        ckpt_dir = self.params.checkpoint_dir or (
            lifecycle.checkpoint_dir if lifecycle is not None else ""
        )
        ckpt = None
        if ckpt_dir:
            from pio_tpu.workflow.orbax_ckpt import (
                StepCheckpointConfig,
                StepCheckpointer,
            )

            ckpt = StepCheckpointer(StepCheckpointConfig(
                ckpt_dir,
                save_every=self.params.checkpoint_every,
            ))
        try:
            params, item_emb, _ = train_two_tower(
                inter, self.params, mesh, checkpoint=ckpt,
                lifecycle=lifecycle,
            )
        finally:
            if ckpt is not None:
                ckpt.close()
        return TwoTowerModel(
            params=params, item_embeddings=item_emb,
            users=inter.users, items=inter.items, config=self.params,
        )

    def predict(self, model: TwoTowerModel, query: dict) -> dict:
        return self.batch_predict(model, [query])[0]

    def batch_predict(self, model: TwoTowerModel, queries) -> list:
        """Vectorized retrieval (the micro-batcher's path): ONE user-tower
        forward + ONE cosine top-k for every known user in the batch
        (blackList handled by over-fetch + host filter, like the
        recommendation template's batched path)."""
        results: list[dict] = [{"itemScores": []} for _ in queries]
        known = [
            (i, model.users.index_of(q["user"]))
            for i, q in enumerate(queries)
            if q.get("user", "") in model.users
        ]
        if not known:
            return results
        tower = Tower(
            len(model.users), model.config.embed_dim,
            model.config.hidden_dim, model.config.out_dim,
        )
        # batch dim bucketed: the micro-batcher produces varying sizes and
        # each distinct B would otherwise compile a fresh tower forward +
        # top-k program
        b = len(known)
        uidx = np.zeros(pow2_bucket(b), np.int32)
        uidx[:b] = [u for _, u in known]
        uv = tower.apply(
            {"params": model.params["user"]}, jnp.asarray(uidx),
        )                                                   # (B', d)
        n_items = model.item_embeddings.shape[0]
        k = min(
            max(int(queries[qi].get("num", 10))
                + len(queries[qi].get("blackList") or ())
                for qi, _ in known),
            n_items,
        )
        scores, idx = cosine_topk(model.item_embeddings, uv, k)
        scores, idx = np.asarray(scores)[:b], np.asarray(idx)[:b]
        for row, (qi, _) in enumerate(known):
            q = queries[qi]
            num = int(q.get("num", 10))
            black = set(q.get("blackList") or ())
            out = []
            for item, s in zip(model.items.decode(idx[row]), scores[row]):
                if item in black:
                    continue
                out.append({"item": item, "score": float(s)})
                if len(out) >= num:
                    break
            results[qi] = {"itemScores": out}
        return results


class TwoTowerEngine(EngineFactory):
    @classmethod
    def apply(cls) -> Engine:
        return Engine(
            TwoTowerDataSource,
            IdentityPreparator,
            {"twotower": TwoTowerAlgorithm},
            FirstServing,
        )
