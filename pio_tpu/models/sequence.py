"""Sequential (session-based) recommendation template — self-attentive
next-item prediction over user event histories.

Net-new model family beyond the reference's capability set (the reference
has no sequence models: SURVEY.md section 5 "Long-context / sequence
parallelism: absent"); it is the framework's long-context showcase and the
engine that exercises ops/attention.py end to end:

 * training: causal transformer over time-ordered per-user item sequences
   (next-item cross-entropy, embedding-tied output head);
 * parallelism: one shard_map'd SPMD train step over the mesh — batch on
   the "data" axis, sequence on the "seq" axis with `ring_attention`
   rotating k/v shards over ICI, gradients psum'd across both axes.
   The same code path runs single-device (both axes size 1);
 * serving: encode the user's recent history (live event-store read, like
   the ecommerce template's cold-start path) with the Pallas
   `flash_attention` kernel, then the standard top-k matmul.

Event-data contract matches the other templates: user->item events with
event times (e.g. view/buy), sequences are the per-user time-ordered item
ids (same fold order as the reference's LEventAggregator time ordering).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pio_tpu.utils.jaxcompat import ensure_jax_compat

ensure_jax_compat()  # jax<0.5: install the jax.shard_map forwarding wrapper

from pio_tpu.controller.base import (
    DataSource,
    FirstServing,
    IdentityPreparator,
    PAlgorithm,
    Params,
)
from pio_tpu.controller.engine import Engine, EngineFactory
from pio_tpu.data.bimap import EntityIdIndex
from pio_tpu.ops.attention import (
    attention_reference,
    chunked_attention,
    flash_attention,
    flash_attention_trainable,
    ring_attention,
    ulysses_attention,
)
from pio_tpu.ops.bucketing import pow2_bucket
from pio_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS


PAD = 0  # item index 0 is reserved as padding; real items start at 1


@dataclass(frozen=True)
class SequenceParams(Params):
    max_len: int = 64          # sequence length (pad/truncate buckets)
    embed_dim: int = 64
    num_heads: int = 2
    num_layers: int = 2
    ffn_dim: int = 128
    dropout: float = 0.0       # kept 0 in-graph; eval-mode determinism
    learning_rate: float = 1e-3
    batch_size: int = 128
    steps: int = 300
    seed: int = 0
    # "auto" | "reference" | "chunked" | "flash" | "ring" | "ulysses" —
    # "flash" trains with the Pallas forward + chunked backward
    # (ops/attention.py flash_attention_trainable; fastest forward on
    # TPU-class backends). auto picks
    # ring when the mesh shards the sequence axis; on a single device it
    # picks chunked (memory-efficient online-softmax scan,
    # ops/attention.py chunked_attention — logits memory O(S*chunk), so
    # long contexts train single-chip) above chunked_threshold tokens and
    # the naive reference below it. ulysses = all-to-all head-sharded
    # sequence parallelism (ops/attention.py ulysses_attention): two
    # collectives per layer vs ring's n-1 hops; requires num_heads
    # divisible by the seq-axis size.
    attention: str = "auto"
    # single-device auto: sequences at/above this length train with
    # chunked attention (naive logits at 1024 tokens are already
    # B*H*1024^2*4 bytes)
    chunked_threshold: int = 1024
    # mixture-of-experts FFN: 0 = dense (default). With > 0 experts each
    # block's FFN becomes a Switch-style MoE (ops/moe.py) — one-hot-matmul
    # dispatch, capacity-dropped tokens ride the residual, and the
    # load-balance aux loss joins the objective with moe_aux_weight
    moe_experts: int = 0
    moe_capacity_factor: float = 2.0
    moe_aux_weight: float = 0.01
    unseen_only: bool = True   # serve-time: drop items already in history
    # serve-time live history read (empty app_name = training snapshot only)
    app_name: str = ""
    event_names: tuple[str, ...] = ("view", "buy")
    # mid-train step checkpoints (workflow/orbax_ckpt.py); "" = off
    checkpoint_dir: str = ""
    checkpoint_every: int = 100


class Block(nn.Module):
    """Pre-LN transformer block with a pluggable attention fn and an
    optional MoE FFN (moe_experts > 0; ops/moe.py)."""

    num_heads: int
    head_dim: int
    ffn_dim: int
    moe_experts: int = 0
    moe_capacity_factor: float = 2.0

    @nn.compact
    def __call__(self, x, attn_fn):
        from pio_tpu.ops.moe import MoEConfig, moe_ffn

        b, s, e = x.shape
        h, d = self.num_heads, self.head_dim
        y = nn.LayerNorm()(x)
        qkv = nn.Dense(3 * h * d, use_bias=False)(y).reshape(b, s, 3, h, d)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        o = attn_fn(q, k, v)                            # (b, s, h, d)
        x = x + nn.Dense(e, use_bias=False)(o.reshape(b, s, h * d))
        y = nn.LayerNorm()(x)
        if self.moe_experts > 0:
            E, f = self.moe_experts, self.ffn_dim
            init = nn.initializers.normal(1.0 / np.sqrt(e))
            init_out = nn.initializers.normal(1.0 / np.sqrt(f))
            moe_params = {
                "router": self.param("moe_router", init, (e, E)),
                "w_in": self.param("moe_w_in", init, (E, e, f)),
                "b_in": self.param("moe_b_in", nn.initializers.zeros, (E, f)),
                "w_out": self.param("moe_w_out", init_out, (E, f, e)),
                "b_out": self.param(
                    "moe_b_out", nn.initializers.zeros, (E, e)),
            }
            cfg = MoEConfig(E, e, f, self.moe_capacity_factor)
            y2, aux = moe_ffn(moe_params, y.reshape(b * s, e), cfg)
            # sow is a no-op unless the caller makes "moe_aux" mutable
            # (training does; serving never pays for it)
            self.sow("moe_aux", "aux", aux)
            x = x + y2.reshape(b, s, e)
        else:
            y = nn.Dense(self.ffn_dim)(y)
            y = nn.gelu(y)
            x = x + nn.Dense(e)(y)
        return x


class SeqEncoder(nn.Module):
    """Item-id sequence -> per-position hidden states; logits are tied to
    the item embedding table (SASRec-style)."""

    vocab: int                 # includes PAD at index 0
    max_len: int               # GLOBAL max sequence length (for positions)
    embed_dim: int
    num_heads: int
    num_layers: int
    ffn_dim: int
    moe_experts: int = 0
    moe_capacity_factor: float = 2.0

    @nn.compact
    def __call__(self, ids, attn_fn, pos_offset=0):
        emb = self.param(
            "item_emb", nn.initializers.normal(0.02),
            (self.vocab, self.embed_dim),
        )
        pos = self.param(
            "pos_emb", nn.initializers.normal(0.02),
            (self.max_len, self.embed_dim),
        )
        s = ids.shape[1]
        x = emb[ids] * np.sqrt(self.embed_dim)
        x = x + jax.lax.dynamic_slice_in_dim(pos, pos_offset, s, axis=0)[None]
        head_dim = self.embed_dim // self.num_heads
        for _ in range(self.num_layers):
            x = Block(self.num_heads, head_dim, self.ffn_dim,
                      self.moe_experts, self.moe_capacity_factor)(x, attn_fn)
        x = nn.LayerNorm()(x)
        logits = x @ emb.T                              # weight-tied head
        return x, logits


def user_histories(events):
    """-> ({user id: time-ordered item-id list}, items EntityIdIndex
    over EVERY item seen). The ONE event-grouping/ordering
    implementation behind read_training (build_sequences) and
    read_eval's rolling folds — so the two reads cannot drift on event
    filtering or ordering."""
    by_user: dict[str, list[tuple[Any, str]]] = {}
    item_ids: dict[str, None] = {}
    for e in events:
        if not e.target_entity_id:
            continue
        by_user.setdefault(e.entity_id, []).append(
            (e.event_time, e.target_entity_id)
        )
        item_ids.setdefault(e.target_entity_id, None)
    items = EntityIdIndex(item_ids.keys())
    hists = {}
    for uid, evs in by_user.items():
        evs.sort(key=lambda t: t[0])
        hists[uid] = [i for _, i in evs]
    return hists, items


def build_sequences(events, max_len: int):
    """Time-ordered per-user item sequences from user->item events.

    Returns (seqs int32 (N, max_len) right-aligned & PAD-left-padded,
    users EntityIdIndex over sequence owners, items EntityIdIndex with ids
    offset by 1 for PAD). Users with < 2 interactions are dropped (no
    next-item target exists)."""
    hists, items = user_histories(events)
    users, rows = [], []
    for uid, ids in hists.items():
        if len(ids) < 2:
            continue
        seq = [items.index_of(i) + 1 for i in ids][-max_len:]  # +1: PAD=0
        rows.append(np.pad(seq, (max_len - len(seq), 0)))
        users.append(uid)
    if not rows:
        raise ValueError("no user has >= 2 interactions; cannot train")
    return (
        np.stack(rows).astype(np.int32),
        EntityIdIndex(users),
        items,
    )


@dataclass
class SequenceData:
    seqs: np.ndarray            # (N, max_len) int32, PAD-left
    users: EntityIdIndex
    items: EntityIdIndex

    def sanity_check(self):
        assert self.seqs.ndim == 2 and self.seqs.shape[0] > 0


POS_HEADROOM = 16


def _apply_with_aux(encoder, params, inp, attn, pos_offset, p):
    """encoder.apply collecting the MoE load-balance aux loss (zero for
    dense models — the moe_aux collection is only populated by MoE
    blocks)."""
    if p.moe_experts > 0:
        out, aux_vars = encoder.apply(
            {"params": params}, inp, attn, pos_offset=pos_offset,
            mutable=["moe_aux"],
        )
        leaves = jax.tree_util.tree_leaves(aux_vars)
        aux = p.moe_aux_weight * sum(jnp.mean(a) for a in leaves) \
            / max(1, len(leaves))
        return out, aux
    out = encoder.apply(
        {"params": params}, inp, attn, pos_offset=pos_offset
    )
    return out, jnp.float32(0.0)


def make_encoder(n_items: int, p: SequenceParams) -> SeqEncoder:
    # Position-table headroom: the train step right-pads the sequence so it
    # splits evenly over the seq mesh axis (up to n_seq-1 extra positions).
    # The table size must be a pure function of the params — serving
    # re-creates the encoder without knowing the training mesh — so the
    # headroom is fixed and train_sequence_model validates the pad fits.
    return SeqEncoder(
        vocab=n_items + 1, max_len=p.max_len + POS_HEADROOM,
        embed_dim=p.embed_dim,
        num_heads=p.num_heads, num_layers=p.num_layers, ffn_dim=p.ffn_dim,
        moe_experts=p.moe_experts,
        moe_capacity_factor=p.moe_capacity_factor,
    )


def train_sequence_model(
    data: SequenceData, p: SequenceParams, mesh: Mesh | None = None,
    checkpoint=None, lifecycle=None,
):
    """SPMD train loop: dp x sp shard_map step (see module docstring).

    `checkpoint` is a StepCheckpointer (or None): saves every save_every
    steps, resumes from the latest step with an identical batch stream.
    `lifecycle` is a workflow.lifecycle.TrainLifecycle (or None):
    heartbeats at span boundaries; preemption force-saves then raises.
    Returns (params, encoder, final loss)."""
    encoder = make_encoder(len(data.items), p)
    optimizer = optax.adam(p.learning_rate)

    seqs = data.seqs
    inp_all, tgt_all = seqs[:, :-1], seqs[:, 1:]
    s_global = inp_all.shape[1]

    if p.attention not in ("auto", "reference", "chunked", "flash",
                           "ring", "ulysses"):
        raise ValueError(
            f"unknown attention mode {p.attention!r}: expected "
            "'auto' | 'reference' | 'chunked' | 'flash' | 'ring' | "
            "'ulysses'"
        )
    # once the sequence is sharded, attention MUST be sequence-parallel
    # (ring or ulysses) — a local-only attention would silently drop
    # cross-shard interactions
    use_sp = mesh is not None and mesh.shape.get(SEQ_AXIS, 1) > 1
    if use_sp and p.attention in ("reference", "chunked", "flash"):
        raise ValueError(
            f"attention={p.attention!r} is a local-only path and cannot "
            "run with the sequence sharded over the mesh seq axis; use "
            "'auto'/'ring'/'ulysses' or a seq=1 mesh"
        )
    if not use_sp and p.attention in ("ring", "ulysses"):
        raise ValueError(
            f"attention={p.attention!r} requires a mesh with a seq axis > 1"
        )
    if use_sp and p.attention == "ulysses":
        n_seq_axis = mesh.shape[SEQ_AXIS]
        if p.num_heads % n_seq_axis:
            raise ValueError(
                f"attention='ulysses' needs num_heads ({p.num_heads}) "
                f"divisible by the seq axis ({n_seq_axis})"
            )

    # local (non-sequence-parallel) attention: chunked at/above the
    # threshold (compared on max_len: the training inputs are one token
    # shorter), naive reference below it
    use_chunked_local = p.attention == "chunked" or (
        p.attention == "auto" and p.max_len >= p.chunked_threshold
    )
    if p.attention == "flash":
        # Pallas forward + chunked-XLA backward (custom_vjp): the fast
        # training-forward option on TPU-class backends; on CPU the
        # kernel runs in interpret mode, so prefer chunked/reference
        local_attn = partial(flash_attention_trainable, causal=True)
    else:
        local_attn = partial(
            chunked_attention if use_chunked_local else attention_reference,
            causal=True,
        )
    # init with the SAME local attention: a naive-attention init forward
    # would materialize the full (1,H,S,S) logits and OOM at exactly the
    # long contexts the chunked path exists for
    params = encoder.init(
        jax.random.PRNGKey(p.seed),
        jnp.zeros((1, s_global), jnp.int32),
        local_attn,
    )["params"]
    opt_state = optimizer.init(params)

    if mesh is not None:
        n_data = mesh.shape[DATA_AXIS]
        n_seq = mesh.shape.get(SEQ_AXIS, 1)
        # sequence length must split evenly over the seq axis
        if s_global % n_seq:
            pad = n_seq - s_global % n_seq
            if s_global + pad > p.max_len + POS_HEADROOM:
                raise ValueError(
                    f"seq-axis padding ({pad}) overflows the position table "
                    f"({p.max_len} + {POS_HEADROOM} headroom); raise max_len "
                    f"or use a smaller seq mesh axis (n_seq={n_seq})"
                )
            inp_all = np.pad(inp_all, ((0, 0), (0, pad)))
            tgt_all = np.pad(tgt_all, ((0, 0), (0, pad)))
            s_global += pad
        s_local = s_global // n_seq

        def local_loss(params, inp, tgt, pos_offset):
            if use_sp and p.attention == "ulysses":
                attn = partial(
                    ulysses_attention, axis_name=SEQ_AXIS, causal=True,
                )
            elif use_sp:
                attn = partial(
                    ring_attention, axis_name=SEQ_AXIS, causal=True,
                )
            else:
                attn = local_attn
            (_, logits), aux = _apply_with_aux(
                encoder, params, inp, attn, pos_offset, p
            )
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, tgt)
            mask = (tgt != PAD).astype(jnp.float32)
            loss_sum = jax.lax.psum(
                jnp.sum(ce * mask), (DATA_AXIS, SEQ_AXIS)
            )
            count = jax.lax.psum(jnp.sum(mask), (DATA_AXIS, SEQ_AXIS))
            aux = jax.lax.pmean(aux, (DATA_AXIS, SEQ_AXIS))
            return loss_sum / jnp.maximum(count, 1.0) + aux

        @partial(
            jax.shard_map, mesh=mesh,
            in_specs=(
                P(), P(),
                P(DATA_AXIS, SEQ_AXIS), P(DATA_AXIS, SEQ_AXIS),
            ),
            out_specs=(P(), P(), P()),
            check_vma=False,
        )
        def step(params, opt_state, inp, tgt):
            pos_offset = jax.lax.axis_index(SEQ_AXIS) * s_local
            loss, grads = jax.value_and_grad(local_loss)(
                params, inp, tgt, pos_offset
            )
            # local grads cover local tokens only; sum across dp and sp
            grads = jax.lax.psum(grads, (DATA_AXIS, SEQ_AXIS))
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        step = jax.jit(step)
        batch = max(n_data, p.batch_size - p.batch_size % n_data)
    else:
        n_data = 1
        attn = local_attn

        def loss_fn(params, inp, tgt):
            (_, logits), aux = _apply_with_aux(
                encoder, params, inp, attn, 0, p
            )
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, tgt)
            mask = (tgt != PAD).astype(jnp.float32)
            return jnp.sum(ce * mask) / jnp.maximum(jnp.sum(mask), 1.0) + aux

        @jax.jit
        def step(params, opt_state, inp, tgt):
            loss, grads = jax.value_and_grad(loss_fn)(params, inp, tgt)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        batch = p.batch_size

    from pio_tpu.workflow.orbax_ckpt import resume_or_init

    params, opt_state, start_step = resume_or_init(checkpoint, params, opt_state)

    n = inp_all.shape[0]
    # the sampled batch must split evenly over the data mesh axis
    size = min(batch, max(8, n))
    size = max(n_data, size - size % n_data)

    # spans of steps scanned on device: one dispatch + one batch transfer
    # per span instead of per step (workflow/spans.py owns the boundary
    # math — bounded staging, checkpoint cadence preserved step-for-step)
    from pio_tpu.workflow.spans import span_bounds

    def run_span(params, opt_state, inps, tgts):
        def body(carry, xs):
            params, opt_state = carry
            inp, tgt = xs
            params, opt_state, loss = step_fn(params, opt_state, inp, tgt)
            return (params, opt_state), loss

        (params, opt_state), losses = jax.lax.scan(
            body, (params, opt_state), (inps, tgts))
        return params, opt_state, losses[-1]

    step_fn = step  # the (possibly shard_mapped) single-step update
    span = jax.jit(run_span)

    def batches_for(lo: int, hi: int):
        idx = np.stack([
            np.random.default_rng((p.seed, s)).integers(0, n, size=size)
            for s in range(lo, hi)
        ])
        inps = jnp.asarray(inp_all[idx])
        tgts = jnp.asarray(tgt_all[idx])
        if mesh is not None:
            xs_sharding = NamedSharding(
                mesh, P(None, DATA_AXIS, SEQ_AXIS))
            inps = jax.device_put(inps, xs_sharding)
            tgts = jax.device_put(tgts, xs_sharding)
        return inps, tgts

    every = (
        max(1, checkpoint.config.save_every) if checkpoint is not None
        else None
    )
    # spans stage (span, batch, seq) token tensors: cap by BYTES so long
    # sequences shrink the span instead of blowing up staging memory
    # (2 arrays x cap x size x seq_len x 4B <= ~64 MB)
    seq_len = inp_all.shape[1]
    cap = max(1, min(512, (64 << 20) // max(1, 2 * size * seq_len * 4)))
    from pio_tpu.workflow.spans import after_span, step_chaos_active

    step_chaos = step_chaos_active()
    if step_chaos:
        cap = 1
    loss = None
    for lo, hi, save_after in span_bounds(start_step, p.steps, every,
                                          cap=cap):
        inps, tgts = batches_for(lo, hi)
        params, opt_state, loss = span(params, opt_state, inps, tgts)
        after_span(hi, p.steps, params, opt_state, checkpoint=checkpoint,
                   lifecycle=lifecycle, save_after=save_after,
                   step_chaos=step_chaos)
    if loss is None:
        # resumed a run whose final step is already checkpointed (or
        # steps == 0): report the loss AT the restored params on the last
        # step's batch — span's loss is pre-update, and the updated
        # params/opt_state are discarded
        inps, tgts = batches_for(max(start_step - 1, 0),
                                 max(start_step, 1))
        _, _, loss = span(params, opt_state, inps, tgts)
    return jax.device_get(params), encoder, float(loss)


# ---------------------------------------------------------------------------
# DASE wrapper
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class SequenceDataSourceParams(Params):
    app_name: str = ""
    event_names: tuple[str, ...] = ("view", "buy")
    max_len: int = 64
    # >0 -> read_eval produces k ROLLING next-item folds: fold f holds
    # out each user's (f+1)-th-from-last item and trains on the strict
    # prefix — the time-respecting split for sequence models, and what
    # lets `pio eval --sweep` tune this engine through the sequential
    # fallback like the two-tower grid
    eval_k: int = 0
    eval_num: int = 10              # ranking depth of each fold query


class SequenceDataSource(DataSource):
    params_class = SequenceDataSourceParams

    def __init__(self, params: SequenceDataSourceParams):
        self.params = params

    def _histories(self, ctx):
        """-> (per-user time-ordered item-id lists, full items index)
        via the SAME user_histories grouping read_training uses. The
        items index spans EVERY fold so vocab/embedding shapes stay
        identical across the sweep's candidates."""
        events = ctx.event_store.find(
            app_name=self.params.app_name,
            entity_type="user",
            target_entity_type="item",
            event_names=list(self.params.event_names),
        )
        return user_histories(events)

    def read_training(self, ctx) -> SequenceData:
        events = ctx.event_store.find(
            app_name=self.params.app_name,
            entity_type="user",
            target_entity_type="item",
            event_names=list(self.params.event_names),
        )
        seqs, users, items = build_sequences(events, self.params.max_len)
        return SequenceData(seqs, users, items)

    def read_eval(self, ctx):
        """k rolling next-item folds of (train, info, [(query, actual)]):
        fold f trains each user on their history minus the last f+1
        items and is scored on predicting the held-out item — strictly
        past-only, like the tuning subsystem's time split."""
        k = self.params.eval_k
        max_len = self.params.max_len
        hists, items = self._histories(ctx)
        folds = []
        for f in range(k):
            cut = f + 1
            users, rows, qa = [], [], []
            for uid, ids in hists.items():
                # >= 2 training items must remain (next-item training
                # needs a target inside the train split)
                if len(ids) < cut + 2:
                    continue
                train_ids = ids[:-cut]
                seq = [items.index_of(i) + 1
                       for i in train_ids][-max_len:]
                rows.append(np.pad(seq, (max_len - len(seq), 0)))
                users.append(uid)
                qa.append(({"user": uid, "num": self.params.eval_num},
                           [ids[-cut]]))
            if not rows:
                continue
            train = SequenceData(
                np.stack(rows).astype(np.int32),
                EntityIdIndex(users), items)
            folds.append((train, {"fold": f, "holdout": cut}, qa))
        return folds


@jax.tree_util.register_pytree_node_class
@dataclass
class SequenceModel:
    params: dict
    seqs: np.ndarray           # training-time sequences for serve lookup
    users: EntityIdIndex
    items: EntityIdIndex
    config: SequenceParams

    def tree_flatten(self):
        # seqs is a leaf (arrays in aux_data would make the treedef
        # unhashable and break jit/device_put over the model)
        return (self.params, self.seqs), (self.users, self.items, self.config)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], *aux)


class SequenceAlgorithm(PAlgorithm):
    params_class = SequenceParams

    def __init__(self, params: SequenceParams = SequenceParams()):
        self.params = params
        self._event_store = None

    def train(self, ctx, data: SequenceData) -> SequenceModel:
        data.sanity_check()
        # max_len lives in BOTH the datasource and the algorithm params
        # (the datasource builds sequences, the algorithm sizes its
        # position table); adapt rather than explode on a mismatch —
        # right-aligned truncate (keep the most recent items) or left-pad
        s = data.seqs
        if s.shape[1] != self.params.max_len:
            if s.shape[1] > self.params.max_len:
                s = s[:, -self.params.max_len:]
            else:
                s = np.pad(s, ((0, 0), (self.params.max_len - s.shape[1], 0)))
            data = SequenceData(
                seqs=np.ascontiguousarray(s), users=data.users,
                items=data.items,
            )
        mesh = (
            ctx.mesh
            if ctx and ctx.mesh is not None and ctx.mesh.devices.size > 1
            else None
        )
        lifecycle = getattr(ctx, "lifecycle", None)
        # explicit params win; otherwise run_train's per-instance dir
        ckpt_dir = self.params.checkpoint_dir or (
            lifecycle.checkpoint_dir if lifecycle is not None else ""
        )
        ckpt = None
        if ckpt_dir:
            from pio_tpu.workflow.orbax_ckpt import (
                StepCheckpointConfig,
                StepCheckpointer,
            )

            ckpt = StepCheckpointer(StepCheckpointConfig(
                ckpt_dir,
                save_every=self.params.checkpoint_every,
            ))
        try:
            params, _, _ = train_sequence_model(
                data, self.params, mesh, checkpoint=ckpt,
                lifecycle=lifecycle,
            )
        finally:
            if ckpt is not None:
                ckpt.close()
        if ctx is not None:
            self._event_store = getattr(ctx, "event_store", None)
        return SequenceModel(
            params=params, seqs=data.seqs, users=data.users,
            items=data.items, config=self.params,
        )

    def prepare_model_for_deploy(self, ctx, model: SequenceModel):
        self._event_store = ctx.event_store
        return model

    def _live_history(self, model: SequenceModel, user: str):
        """The user's recent item sequence from a live event-store read
        (the ecommerce template's serve-time pattern) — catches events that
        happened after training and users unseen at training time. Returns
        a PAD-left (max_len,) int32 row, or None when unavailable."""
        p = model.config
        if not p.app_name or self._event_store is None:
            return None
        try:
            events = self._event_store.find_by_entity(
                app_name=p.app_name,
                entity_type="user",
                entity_id=user,
                event_names=list(p.event_names),
                target_entity_type="item",
                limit=p.max_len,
                latest=True,
            )
        except Exception:  # noqa: BLE001 - storage outage must not kill serving
            return None
        seq = [
            model.items.index_of(e.target_entity_id) + 1
            for e in reversed(events)  # newest-first -> time order
            if e.target_entity_id in model.items
        ][-p.max_len:]
        if not seq:
            return None
        return np.pad(
            np.asarray(seq, np.int32), (p.max_len - len(seq), 0)
        )

    def _score_last_batch(self, model: SequenceModel, rows: np.ndarray):
        """Forward the last max_len-1 items of a (B, max_len) batch of
        history rows; return next-item scores (B, vocab) from the tied
        head at the final position. Training consumes inputs of length
        max_len-1 (positions 0..max_len-2), so serving must too — feeding
        all max_len items would read the never-trained last position row.
        The batch dim is bucketed to a power of two so the micro-batcher's
        varying sizes compile O(log) programs. Serving path: Pallas flash
        attention on TPU, reference on CPU."""
        p = model.config
        encoder = make_encoder(len(model.items), p)
        on_cpu = jax.devices()[0].platform == "cpu"
        attn = partial(
            attention_reference if on_cpu else flash_attention, causal=True,
        )
        b = rows.shape[0]
        bucket = pow2_bucket(b)
        inp = rows[:, -(p.max_len - 1):]
        if bucket != b:
            inp = np.concatenate(
                [inp, np.zeros((bucket - b, inp.shape[1]), inp.dtype)])
        _, logits = encoder.apply(
            {"params": model.params}, jnp.asarray(inp), attn,
        )
        return logits[:b, -1]

    def history_row(self, model: SequenceModel, query: dict):
        """The (max_len,) PAD-left row predict actually scores from: the
        live event-store history when app_name is configured (including
        post-training events), else the training snapshot; None for an
        unknown user with no live history. Public so user-code stages
        (e.g. a no-repeat Serving) reason about the SAME history the
        scores came from instead of re-deriving a stale one."""
        user = query.get("user", "")
        row = self._live_history(model, user)
        if row is None and user in model.users:
            row = model.seqs[model.users.index_of(user)]
        return row

    def predict(self, model: SequenceModel, query: dict) -> dict:
        return self.batch_predict(model, [query])[0]

    def batch_predict(self, model: SequenceModel, queries) -> list:
        """Vectorized serving (the micro-batcher's path): the history rows
        of every resolvable user in the batch encode in ONE transformer
        forward (batch bucketed to a power of two for compile-cache
        bounds); per-query seen/blackList masking and ranking happen on
        host over the (B, vocab) score matrix."""
        results: list[dict] = [{"itemScores": []} for _ in queries]
        resolved = []
        for i, q in enumerate(queries):
            row = self.history_row(model, q)
            if row is not None:
                resolved.append((i, row))
        if not resolved:
            return results
        rows = np.stack([r for _, r in resolved])
        all_scores = np.array(self._score_last_batch(model, rows))
        for b, (qi, row) in enumerate(resolved):
            q = queries[qi]
            num = int(q.get("num", 10))
            scores = all_scores[b]   # view into all_scores: masked IN
            # PLACE — each row is consumed exactly once, here
            scores[PAD] = -np.inf
            seen = (
                set(int(i) for i in row if i != PAD)
                if model.config.unseen_only else set()
            )
            black = {
                model.items.index_of(x) + 1
                for x in (q.get("blackList") or ())
                if x in model.items
            }
            for i in seen | black:
                scores[i] = -np.inf
            order = np.argsort(-scores)[:num]
            results[qi] = {"itemScores": [
                {"item": model.items.decode([i - 1])[0],
                 "score": float(scores[i])}
                for i in order if np.isfinite(scores[i])
            ]}
        return results


class SequenceEngine(EngineFactory):
    @classmethod
    def apply(cls) -> Engine:
        return Engine(
            SequenceDataSource,
            IdentityPreparator,
            {"sasrec": SequenceAlgorithm},
            FirstServing,
        )
