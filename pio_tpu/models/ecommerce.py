"""E-commerce recommendation template — ALS + serve-time business rules.

Parity target: reference examples/scala-parallel-ecommercerecommendation/
train-with-rate-event/src/main/scala/ALSAlgorithm.scala:148-341:
 * implicit ALS over view/buy events;
 * serve-time filtering: seen items (live LEventStore read of the user's
   view/buy events), "unavailableItems" constraint entity, whiteList /
   blackList, category filter;
 * cold start: unknown users are served from their recent view events —
   average the viewed items' factors and recommend by similarity.

TPU-native: scoring is the factor matmul + top_k; the serve-time storage
reads go through EventStore.find_by_entity (SURVEY.md section 7 flags this
as the "DB query inside the predict path" hazard — reads are bounded by
`limit` and hit the indexed entity columns).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from pio_tpu.controller.base import (
    DataSource,
    FirstServing,
    IdentityPreparator,
    PAlgorithm,
    Params,
)
from pio_tpu.controller.engine import Engine, EngineFactory
from pio_tpu.data.eventstore import Interactions, to_interactions
from pio_tpu.models.filtering import (
    candidate_ids,
    invert_categories,
    rank_candidates,
)
from pio_tpu.ops import als
from pio_tpu.ops.bucketing import pow2_bucket
from pio_tpu.ops.similarity import cosine_topk, mean_vector


@dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = ""
    event_names: tuple[str, ...] = ("view", "buy")


@dataclass
class ECommerceData:
    interactions: Interactions
    item_categories: dict[str, list[str]]

    def sanity_check(self):
        self.interactions.sanity_check()


class ECommerceDataSource(DataSource):
    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        self.params = params

    def read_training(self, ctx) -> ECommerceData:
        p = self.params
        events = ctx.event_store.find(
            app_name=p.app_name,
            entity_type="user",
            target_entity_type="item",
            event_names=list(p.event_names),
        )
        # buy weighs heavier than view (reference train-with-rate-event
        # maps buy to a stronger implicit signal)
        inter = to_interactions(
            events,
            value_fn=lambda e: 4.0 if e.event == "buy" else 1.0,
            dedup="sum",
        )
        item_props = ctx.event_store.aggregate_properties(
            app_name=p.app_name, entity_type="item"
        )
        cats = {
            iid: pm.get_or_else("categories", [])
            for iid, pm in item_props.items()
        }
        return ECommerceData(inter, cats)


@dataclass(frozen=True)
class ECommAlgorithmParams(Params):
    app_name: str = ""            # serve-time event reads
    rank: int = 10
    num_iterations: int = 10
    lambda_: float = 0.01
    alpha: float = 1.0
    seed: int | None = None
    chunk: int = 65536
    unseen_only: bool = True      # filter items the user has seen
    seen_events: tuple[str, ...] = ("view", "buy")
    recent_events: tuple[str, ...] = ("view",)   # cold-start signal
    recent_count: int = 10
    # TTL (seconds) for the serve-time "unavailableItems" constraint read
    # — a GLOBAL aggregate that otherwise runs once per query, the
    # "DB query inside the predict path" hazard SURVEY §7 flags. Default
    # 0 = live read per query (reference behavior,
    # ALSAlgorithm.scala:232-260 — except that on a storage outage the
    # last successfully-read set serves instead of the reference's
    # empty set, which would UN-filter unavailable items mid-outage);
    # production deployments set e.g. 1-5 s
    # to keep the hot predict path off storage, trading bounded
    # staleness of the unavailable-items set. The per-user seen-items
    # read stays live either way: a just-bought item must drop out of
    # the very next recommendation.
    constraint_cache_ttl_s: float = 0.0


@jax.tree_util.register_pytree_node_class
@dataclass
class ECommerceModel:
    factors: als.ALSModel
    users: Any
    items: Any
    item_categories: dict

    def tree_flatten(self):
        return (self.factors,), (self.users, self.items, self.item_categories)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], *aux)

    def cat_index(self) -> dict:
        """category -> [item ids], built lazily once per model."""
        if not hasattr(self, "_cat_index"):
            self._cat_index = invert_categories(self.item_categories)
        return self._cat_index


class ECommAlgorithm(PAlgorithm):
    params_class = ECommAlgorithmParams

    def __init__(self, params: ECommAlgorithmParams):
        self.params = params
        self._event_store = None  # bound at predict time via ctx-free reads
        # (expiry_monotonic, frozenset) for _unavailable_items
        self._constraint_cache: tuple[float, set[str]] | None = None

    def train(self, ctx, data: ECommerceData) -> ECommerceModel:
        data.sanity_check()
        inter = data.interactions
        p = self.params
        ap = als.ALSParams(
            rank=p.rank, iterations=p.num_iterations, reg=p.lambda_,
            alpha=p.alpha, implicit=True,
            seed=p.seed if p.seed is not None else 3, chunk=p.chunk,
        )
        if ctx.mesh is not None and ctx.mesh.devices.size > 1:
            factors = als.als_train_sharded(
                inter.user_idx, inter.item_idx, inter.values,
                inter.n_users, inter.n_items, ap, ctx.mesh,
            )
        else:
            factors = als.als_train(
                inter.user_idx, inter.item_idx, inter.values,
                inter.n_users, inter.n_items, ap,
            )
        self._event_store = ctx.event_store
        return ECommerceModel(
            factors, inter.users, inter.items, data.item_categories
        )

    # -- serve-time storage access ------------------------------------------
    def _bind_store(self):
        if self._event_store is None:
            from pio_tpu.data.eventstore import EventStore

            self._event_store = EventStore()

    def prepare_model_for_deploy(self, ctx, model: ECommerceModel):
        self._event_store = ctx.event_store
        return model

    def _seen_items(self, user: str) -> set[str]:
        """Live read of the user's seen items (reference
        LEventStore.findByEntity with seenEvents, ALSAlgorithm.scala:200-230)."""
        if not self.params.unseen_only or self._event_store is None:
            return set()
        try:
            events = self._event_store.find_by_entity(
                app_name=self.params.app_name,
                entity_type="user",
                entity_id=user,
                event_names=list(self.params.seen_events),
                limit=-1,
            )
            return {
                e.target_entity_id for e in events if e.target_entity_id
            }
        except Exception:  # noqa: BLE001 - storage outage must not kill serving
            return set()

    def _unavailable_items(self) -> set[str]:
        """Constraint entity 'unavailableItems' (reference
        ALSAlgorithm.scala:232-260: latest $set on constraint entity),
        TTL-cached per ECommAlgorithmParams.constraint_cache_ttl_s so the
        hot predict path is not gated on a storage aggregate per query."""
        if self._event_store is None:
            return set()
        ttl = self.params.constraint_cache_ttl_s
        now = time.monotonic()
        cached = self._constraint_cache
        if ttl > 0 and cached is not None and now < cached[0]:
            return cached[1]
        try:
            props = self._event_store.aggregate_properties(
                app_name=self.params.app_name, entity_type="constraint"
            )
            pm = props.get("unavailableItems")
            out = set(pm.get_or_else("items", [])) if pm else set()
        except Exception:  # noqa: BLE001
            # storage outage must not kill serving: serve the stale set
            # if we have one (bounded by the outage, not the TTL) and
            # RE-ARM a short expiry so a hanging backend gates one query
            # per second, not every query for the whole outage
            stale = cached[1] if cached is not None else set()
            if ttl > 0:
                self._constraint_cache = (now + min(ttl, 1.0), stale)
            return stale
        self._constraint_cache = (now + ttl, out)
        return out

    def _recent_item_vector(self, model: ECommerceModel, user: str):
        """Cold start: average factors of recently-viewed items (reference
        ALSAlgorithm.scala:262-300)."""
        if self._event_store is None:
            return None
        try:
            events = self._event_store.find_by_entity(
                app_name=self.params.app_name,
                entity_type="user",
                entity_id=user,
                event_names=list(self.params.recent_events),
                limit=self.params.recent_count,
                latest=True,
            )
        except Exception:  # noqa: BLE001
            return None
        idx = [
            model.items.index_of(e.target_entity_id)
            for e in events
            if e.target_entity_id and e.target_entity_id in model.items
        ]
        if not idx:
            return None
        return mean_vector(model.factors.item_factors, np.array(idx))

    def predict(self, model: ECommerceModel, query: dict) -> dict:
        self._bind_store()
        return self._predict_impl(model, query, self._unavailable_items())

    def _predict_impl(self, model: ECommerceModel, query: dict,
                      unavailable: set) -> dict:
        """predict with the query-independent unavailable-items read done
        by the caller (batch_predict reads it once per batch)."""
        user = query.get("user", "")
        num = int(query.get("num", 10))
        exclude = set(query.get("blackList") or ())
        exclude |= self._seen_items(user)
        exclude |= unavailable
        white = set(query.get("whiteList") or ()) or None
        categories = set(query.get("categories") or ()) or None
        candidates = candidate_ids(
            model.items, model.item_categories, white, categories, exclude,
            cat_index=model.cat_index,
        )
        n_items = model.factors.item_factors.shape[0]

        known_user = user in model.users
        if not known_user:
            qv = self._recent_item_vector(model, user)
            if qv is None:
                return {"itemScores": []}

        if candidates is not None:
            # selective filters: score the candidate set directly (reference
            # isCandidateItem filters before ranking, ALSAlgorithm.scala);
            # one bucketed gather+matmul+top_k — no per-size recompiles
            if not candidates:
                return {"itemScores": []}
            cidx = model.items.encode(candidates)
            if known_user:
                uidx = model.users.index_of(user)
                qv = model.factors.user_factors[uidx]
            pos, scores = rank_candidates(
                model.factors.item_factors, qv, cidx, num,
                normalize=not known_user,
            )
            return {"itemScores": [
                {"item": candidates[p], "score": float(s)}
                for p, s in zip(pos, scores)
            ]}

        k = min(num + len(exclude), n_items)
        if known_user:
            uidx = model.users.index_of(user)
            scores, idx = als.recommend_topk(
                model.factors, np.array([uidx]), k
            )
        else:
            scores, idx = cosine_topk(model.factors.item_factors, qv, k)
        return self._format_topk(
            model, np.asarray(scores)[0], np.asarray(idx)[0], exclude, num)

    @staticmethod
    def _format_topk(model, scores, idx, exclude, num) -> dict:
        out = []
        for item, s in zip(model.items.decode(idx), scores):
            if item in exclude:
                continue
            out.append({"item": item, "score": float(s)})
            if len(out) >= num:
                break
        return {"itemScores": out}

    def batch_predict(self, model: ECommerceModel, queries) -> list:
        """Vectorized batch scoring (the micro-batcher's path): the
        query-independent unavailable-items constraint is read ONCE per
        batch; plain known-user queries share one top-k matmul and plain
        cold-start queries one cosine top-k (per-user seen/recent reads
        stay live, as the reference's serve-time semantics require).
        whiteList/categories queries keep candidate-set semantics via the
        single-query path."""
        self._bind_store()
        unavailable = self._unavailable_items()
        results: list[dict] = [{"itemScores": []} for _ in queries]
        known_plain = []   # (i, uidx, exclude, num)
        cold_plain = []    # (i, qv, exclude, num)
        for i, q in enumerate(queries):
            white = set(q.get("whiteList") or ()) or None
            categories = set(q.get("categories") or ()) or None
            if white or categories:
                results[i] = self._predict_impl(model, q, unavailable)
                continue
            user = q.get("user", "")
            exclude = (
                set(q.get("blackList") or ())
                | self._seen_items(user) | unavailable
            )
            num = int(q.get("num", 10))
            if user in model.users:
                known_plain.append(
                    (i, model.users.index_of(user), exclude, num))
            else:
                qv = self._recent_item_vector(model, user)
                if qv is not None:
                    cold_plain.append(
                        (i, np.asarray(qv).reshape(-1), exclude, num))
        n_items = model.factors.item_factors.shape[0]
        if known_plain:
            k = min(
                max(num + len(ex) for _, _, ex, num in known_plain),
                n_items,
            )
            rows = np.array([u for _, u, _, _ in known_plain], np.int32)
            scores, idx = als.recommend_topk(model.factors, rows, k)
            scores, idx = np.asarray(scores), np.asarray(idx)
            for r, (qi, _, exclude, num) in enumerate(known_plain):
                results[qi] = self._format_topk(
                    model, scores[r], idx[r], exclude, num)
        if cold_plain:
            k = min(
                max(num + len(ex) for _, _, ex, num in cold_plain),
                n_items,
            )
            qv = np.stack([v for _, v, _, _ in cold_plain])
            scores, idx = cosine_topk(
                model.factors.item_factors, jnp.asarray(qv), k)
            scores, idx = np.asarray(scores), np.asarray(idx)
            for r, (qi, _, exclude, num) in enumerate(cold_plain):
                results[qi] = self._format_topk(
                    model, scores[r], idx[r], exclude, num)
        return results


class ECommerceEngine(EngineFactory):
    @classmethod
    def apply(cls) -> Engine:
        return Engine(
            ECommerceDataSource,
            IdentityPreparator,
            {"ecomm": ECommAlgorithm},
            FirstServing,
        )
