"""DASE controller API — the developer-facing pipeline contracts.

Mirrors the reference controller layer (core/.../controller/): DataSource,
Preparator, Algorithm, Serving, plus the `Doer` instantiation helper
(core/AbstractDoer.scala:43-65). The reference distinguishes execution shapes
L / P2L / P by where data lives (local object vs RDD); the TPU-native
equivalents are about where the *model* lives:

 * LAlgorithm   — host-object model (reference LAlgorithm.scala:12-57);
 * P2LAlgorithm — mesh-trained, host-serializable model
                  (reference P2LAlgorithm.scala:13-49);
 * PAlgorithm   — device-resident (sharded jax.Array pytree) model
                  (reference PAlgorithm.scala:10-47). Unlike the reference —
                  which persists Unit and *retrains at deploy*
                  (Engine.scala:208-230) — these checkpoint their sharded
                  arrays and restore straight into serving HBM.

Queries/predictions are JSON-compatible dicts (the reference's typed Q/P via
gson/json4s collapses to plain dicts + optional dataclass params).
"""

from __future__ import annotations

import abc
import dataclasses
from dataclasses import dataclass
from typing import Any, Callable, Sequence


class TrainingInterruption(Exception):
    """Controlled stop (reference WorkflowUtils.scala:379-384
    StopAfterReadInterruption / StopAfterPrepareInterruption)."""

    def __init__(self, stage: str):
        super().__init__(f"stopped after {stage}")
        self.stage = stage


@dataclass(frozen=True)
class Params:
    """Base for per-stage parameter dataclasses (reference controller
    Params). Subclass with @dataclass(frozen=True)."""


@dataclass(frozen=True)
class EmptyParams(Params):
    pass


def params_from_dict(params_class: type | None, d: dict | None) -> Any:
    if params_class is None:
        return EmptyParams() if not d else d
    if d is None:
        return params_class()
    field_names = {f.name for f in dataclasses.fields(params_class)}
    unknown = set(d) - field_names
    if unknown:
        raise ValueError(
            f"unknown params {sorted(unknown)} for {params_class.__name__} "
            f"(expected subset of {sorted(field_names)})"
        )
    return params_class(**d)


def params_to_dict(p: Any) -> dict:
    if p is None:
        return {}
    if dataclasses.is_dataclass(p):
        return dataclasses.asdict(p)
    if isinstance(p, dict):
        return dict(p)
    raise TypeError(f"cannot serialize params of type {type(p)}")


def Doer(cls: type, params: Any = None):
    """Instantiate a DASE class with its params (reference
    AbstractDoer.scala Doer.apply: params-ctor first, zero-arg fallback).
    Accepts params as a dataclass instance or a raw dict (converted via the
    class's `params_class`)."""
    params_class = getattr(cls, "params_class", None)
    if isinstance(params, dict):
        params = params_from_dict(params_class, params)
    if params is None or isinstance(params, EmptyParams):
        try:
            return cls()
        except TypeError:
            return cls(params or EmptyParams())
    return cls(params)


class DataSource(abc.ABC):
    """Reads training (and evaluation) data from the event store
    (reference core/BaseDataSource.scala:31-52, controller/PDataSource.scala).
    """

    params_class: type | None = None

    @abc.abstractmethod
    def read_training(self, ctx) -> Any:
        """-> training data (TD): typically host numpy / columnar arrays."""

    def read_eval(self, ctx) -> Sequence[tuple[Any, Any, list[tuple[dict, Any]]]]:
        """-> [(TD, evaluation-info, [(query, actual)])] — one element per
        fold (reference readEvalBase)."""
        return []


class Preparator(abc.ABC):
    """TD -> PD (reference core/BasePreparator.scala:30-42)."""

    params_class: type | None = None

    @abc.abstractmethod
    def prepare(self, ctx, training_data) -> Any: ...


class IdentityPreparator(Preparator):
    """Reference controller/IdentityPreparator."""

    def prepare(self, ctx, training_data):
        return training_data


class Algorithm(abc.ABC):
    """Train on prepared data; answer queries (reference
    core/BaseAlgorithm.scala:55-123)."""

    params_class: type | None = None
    #: "local"  -> model pickled whole (L / P2L);
    #: "device" -> model is a jax pytree checkpointed with shardings (P)
    model_kind: str = "local"

    @abc.abstractmethod
    def train(self, ctx, prepared_data) -> Any: ...

    @abc.abstractmethod
    def predict(self, model, query: dict) -> Any: ...

    def batch_predict(self, model, queries: Sequence[dict]) -> list:
        """Bulk prediction for evaluation (reference batchPredictBase).
        Algorithms override with a vectorized/jit path; default loops."""
        return [self.predict(model, q) for q in queries]

    def prepare_model_for_deploy(self, ctx, model) -> Any:
        """Hook run at deploy after restore (e.g. device_put to the serving
        mesh). Reference analogue: Engine.prepareDeploy re-hydration."""
        return model


class LAlgorithm(Algorithm):
    model_kind = "local"


class P2LAlgorithm(Algorithm):
    model_kind = "local"


class PAlgorithm(Algorithm):
    model_kind = "device"


class Serving(abc.ABC):
    """Query pre/post-processing around algorithms (reference
    core/BaseServing.scala:28-51, controller/LServing.scala)."""

    params_class: type | None = None

    def supplement(self, query: dict) -> dict:
        return query

    @abc.abstractmethod
    def serve(self, query: dict, predictions: Sequence[Any]) -> Any:
        """Combine per-algorithm predictions into the served result."""


class FirstServing(Serving):
    """Reference controller/LFirstServing."""

    def serve(self, query, predictions):
        return predictions[0]


class AverageServing(Serving):
    """Reference controller/LAverageServing: numeric mean of predictions."""

    def serve(self, query, predictions):
        return sum(predictions) / len(predictions)


def sanity_check(data: Any) -> None:
    """Run the data's own sanityCheck hook if present (reference
    SanityCheck trait, Engine.scala:649-661)."""
    hook: Callable | None = getattr(data, "sanity_check", None)
    if callable(hook):
        hook()
