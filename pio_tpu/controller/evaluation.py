"""Evaluation + metrics + tuning.

Mirrors the reference's metric workflow:
 * `Metric[EI,Q,P,A,R].calculate` over Seq[(EI, RDD[(Q,P,A)])]
   (core/.../controller/Metric.scala:13-134) — the RDD union+stats Spark
   reductions become numpy reductions over the flattened (q,p,a) triples;
 * helper shapes AverageMetric / OptionAverageMetric / StdevMetric /
   SumMetric / ZeroMetric;
 * `Evaluation` binding an engine to its metric(s)
   (controller/Evaluation.scala:10-64);
 * `EngineParamsGenerator` (controller/EngineParamsGenerator.scala);
 * `MetricEvaluator` scoring every EngineParams and picking the best
   (controller/MetricEvaluator.scala:76-260), incl. the best.json output.
"""

from __future__ import annotations

import abc
import html
import json
import math
from dataclasses import dataclass, field
from typing import Any, Generic, Sequence, TypeVar

import numpy as np

from pio_tpu.controller.engine import Engine, EngineParams

R = TypeVar("R")

# eval data set shape: [(eval_info, [(query, prediction, actual)])]
EvalDataSet = Sequence[tuple[Any, Sequence[tuple[dict, Any, Any]]]]


class Metric(abc.ABC, Generic[R]):
    """Reference Metric.scala: calculate + comparison semantics."""

    #: larger is better by default (reference Metric's Ordering)
    higher_is_better: bool = True

    @abc.abstractmethod
    def calculate(self, ctx, eval_data_set: EvalDataSet) -> R: ...

    @property
    def header(self) -> str:
        return type(self).__name__


class QPAMetric(Metric[float]):
    """Base for metrics defined per (q, p, a) triple.

    Non-Option metrics treat a None from calculate_one as a bug and raise
    (the reference's AverageMetric takes a plain Double); Option* variants
    set allow_none and exclude Nones."""

    allow_none = False

    @abc.abstractmethod
    def calculate_one(self, query: dict, prediction: Any, actual: Any) -> Any:
        ...

    def _scores(self, eval_data_set: EvalDataSet) -> np.ndarray:
        out = []
        for _, qpa in eval_data_set:
            for q, p, a in qpa:
                s = self.calculate_one(q, p, a)
                if s is None:
                    if not self.allow_none:
                        raise ValueError(
                            f"{type(self).__name__}.calculate_one returned "
                            "None; use an Option* metric to skip triples"
                        )
                    continue
                out.append(s)
        return np.array(out, dtype=np.float64)


class AverageMetric(QPAMetric):
    """Reference Metric.scala AverageMetric: mean over all triples."""

    def calculate(self, ctx, eval_data_set: EvalDataSet) -> float:
        scores = self._scores(eval_data_set)
        return float(np.mean(scores)) if scores.size else float("nan")


class OptionAverageMetric(AverageMetric):
    """calculate_one may return None; Nones are excluded from the mean
    (reference OptionAverageMetric)."""

    allow_none = True


class StdevMetric(QPAMetric):
    """Reference StdevMetric: population stdev of scores."""

    def calculate(self, ctx, eval_data_set: EvalDataSet) -> float:
        scores = self._scores(eval_data_set)
        return float(np.std(scores)) if scores.size else float("nan")


class OptionStdevMetric(StdevMetric):
    """Reference OptionStdevMetric."""

    allow_none = True


class SumMetric(QPAMetric):
    """Reference SumMetric."""

    def calculate(self, ctx, eval_data_set: EvalDataSet) -> float:
        scores = self._scores(eval_data_set)
        return float(np.sum(scores))


class MeanSquareError(AverageMetric):
    """Regression MSE over served numeric predictions — the metric the
    reference regression examples evaluate with
    (examples/experimental/scala-parallel-regression/Run.scala imports
    controller.MeanSquareError). Lower is better."""

    higher_is_better = False

    @property
    def header(self) -> str:
        return "MSE"

    def calculate_one(self, query, prediction, actual):
        return (float(prediction) - float(actual)) ** 2


class ZeroMetric(Metric[float]):
    """Reference ZeroMetric: always 0 (placeholder)."""

    def calculate(self, ctx, eval_data_set: EvalDataSet) -> float:
        return 0.0


class EngineParamsGenerator:
    """Tuning search space (reference EngineParamsGenerator.scala).
    Subclass and set engine_params_list (None default avoids a shared
    mutable class-level list across subclasses)."""

    engine_params_list: list[EngineParams] | None = None

    @classmethod
    def params_list(cls) -> list[EngineParams]:
        if not cls.engine_params_list:
            raise ValueError(
                f"{cls.__name__} must define engine_params_list"
            )
        return list(cls.engine_params_list)


class Evaluation:
    """Binds an engine with its metric(s) (reference Evaluation.scala).

    Subclass and set engine + metric (and optionally metrics for
    supplementary columns)."""

    engine: Engine = None
    metric: Metric = None
    metrics: list[Metric] | None = None

    @classmethod
    def other_metrics(cls) -> list[Metric]:
        return list(cls.metrics or [])

    @classmethod
    def engine_metric(cls) -> tuple[Engine, Metric]:
        if cls.engine is None or cls.metric is None:
            raise ValueError(
                f"{cls.__name__} must define both engine and metric"
            )
        return cls.engine, cls.metric


@dataclass
class MetricScores:
    score: Any
    other_scores: list[Any] = field(default_factory=list)


@dataclass
class MetricEvaluatorResult:
    best_score: MetricScores
    best_engine_params: EngineParams
    best_idx: int
    metric_header: str
    other_metric_headers: list[str]
    engine_params_scores: list[tuple[EngineParams, MetricScores]]

    def one_liner(self) -> str:
        return (
            f"[{self.best_score.score}] {self.best_engine_params.to_json()}"
        )

    def to_json(self) -> str:
        return json.dumps(
            {
                "metricHeader": self.metric_header,
                "otherMetricHeaders": self.other_metric_headers,
                "bestScore": _jsonable(self.best_score.score),
                "bestIndex": self.best_idx,
                "bestEngineParams": json.loads(self.best_engine_params.to_json()),
                "allScores": [
                    {
                        "engineParams": json.loads(ep.to_json()),
                        "score": _jsonable(ms.score),
                        "otherScores": [_jsonable(s) for s in ms.other_scores],
                    }
                    for ep, ms in self.engine_params_scores
                ],
            },
            indent=2,
        )

    def to_html(self) -> str:
        esc = html.escape
        rows = "".join(
            f"<tr><td>{i}</td><td>{esc(str(_jsonable(ms.score)))}</td>"
            f"<td><pre>{esc(ep.to_json())}</pre></td></tr>"
            for i, (ep, ms) in enumerate(self.engine_params_scores)
        )
        return (
            f"<h2>{esc(self.metric_header)}</h2>"
            f"<p>Best score: {esc(str(_jsonable(self.best_score.score)))} "
            f"(params #{self.best_idx})</p>"
            f"<table><tr><th>#</th><th>score</th><th>params</th></tr>"
            f"{rows}</table>"
        )


def _jsonable(x):
    if isinstance(x, float) and (math.isnan(x) or math.isinf(x)):
        return str(x)
    return x


def pick_best_index(scores: Sequence[tuple], metric: Metric) -> int:
    """Best-candidate index over [(engine_params, MetricScores)] with
    the evaluator's NaN rule: NaN is never best, for either comparison
    direction. The ONE selection policy — the classic evaluator and the
    tuning sweep both call it, so their winners cannot drift."""
    def sort_key(i: int):
        s = scores[i][1].score
        if isinstance(s, float) and math.isnan(s):
            return -math.inf  # NaN is never best, for either direction
        return s if metric.higher_is_better else -s

    return max(range(len(scores)), key=sort_key)


class MetricEvaluator:
    """Scores every EngineParams with the metric, picks the best
    (reference MetricEvaluator.scala evaluateBase:163, best selection +
    best.json at :138-161)."""

    def __init__(
        self,
        metric: Metric,
        other_metrics: Sequence[Metric] = (),
        output_path: str | None = None,
        workers: int = 1,
    ):
        self.metric = metric
        self.other_metrics = list(other_metrics)
        self.output_path = output_path
        # workers > 1 runs the params grid on a thread pool — the reference
        # runs it `.par` (MetricEvaluator.scala:169-178). Default sequential:
        # deterministic FastEval cache behavior, and single-device training
        # rarely overlaps anyway; tuning sweeps over many params opt in.
        self.workers = workers

    def _score_one(self, ctx, engine: Engine, ep: EngineParams) -> MetricScores:
        eval_data_set = engine.eval(ctx, ep)
        return MetricScores(
            score=self.metric.calculate(ctx, eval_data_set),
            other_scores=[
                m.calculate(ctx, eval_data_set) for m in self.other_metrics
            ],
        )

    def evaluate_base(
        self,
        ctx,
        engine: Engine,
        engine_params_list: Sequence[EngineParams],
    ) -> MetricEvaluatorResult:
        if not engine_params_list:
            raise ValueError("engine_params_list must not be empty")
        if self.workers > 1:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=self.workers) as pool:
                all_ms = list(pool.map(
                    lambda ep: self._score_one(ctx, engine, ep),
                    engine_params_list,
                ))
            scores = list(zip(engine_params_list, all_ms))
        else:
            scores = [
                (ep, self._score_one(ctx, engine, ep))
                for ep in engine_params_list
            ]

        best_idx = pick_best_index(scores, self.metric)
        result = MetricEvaluatorResult(
            best_score=scores[best_idx][1],
            best_engine_params=scores[best_idx][0],
            best_idx=best_idx,
            metric_header=self.metric.header,
            other_metric_headers=[m.header for m in self.other_metrics],
            engine_params_scores=scores,
        )
        if self.output_path:
            with open(self.output_path, "w") as f:
                f.write(result.best_engine_params.to_json())
        return result
