"""Engine — the concrete DASE orchestrator.

Mirrors reference controller/Engine.scala:80-829: named class-maps per stage,
the train loop (read -> sanity -> prepare -> per-algo train, Engine.scala:622-709),
the eval cross-product (per-fold train + batch-predict + per-query serve,
Engine.scala:727-817), and engine-variant JSON -> EngineParams extraction
(jValueToEngineParams, Engine.scala:354-417).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Sequence

from pio_tpu.controller.base import (
    Doer,
    TrainingInterruption,
    params_from_dict,
    params_to_dict,
    sanity_check,
)


@dataclass
class EngineParams:
    """Named (stage-name, params) per stage + a list for algorithms
    (reference EngineParams.scala:10-64). Params may be dataclasses or raw
    dicts (converted lazily by Doer)."""

    datasource: tuple[str, Any] = ("", None)
    preparator: tuple[str, Any] = ("", None)
    algorithms: list[tuple[str, Any]] = field(default_factory=list)
    serving: tuple[str, Any] = ("", None)

    def to_json(self) -> str:
        return json.dumps(
            {
                "dataSourceParams": {self.datasource[0]: params_to_dict(self.datasource[1])},
                "preparatorParams": {self.preparator[0]: params_to_dict(self.preparator[1])},
                "algorithmParamsList": [
                    {"name": n, "params": params_to_dict(p)}
                    for n, p in self.algorithms
                ],
                "servingParams": {self.serving[0]: params_to_dict(self.serving[1])},
            },
            sort_keys=True,
        )


def _single_class_map(x) -> dict[str, type]:
    """Engine ctor accepts a single class or a name->class dict per stage."""
    if isinstance(x, dict):
        return x
    return {"": x}


class Engine:
    """DASE engine (reference Engine.scala:80)."""

    def __init__(
        self,
        datasource_classes,
        preparator_classes,
        algorithm_classes,
        serving_classes,
    ):
        self.datasource_classes = _single_class_map(datasource_classes)
        self.preparator_classes = _single_class_map(preparator_classes)
        self.algorithm_classes = _single_class_map(algorithm_classes)
        self.serving_classes = _single_class_map(serving_classes)

    # -- stage instantiation ------------------------------------------------
    def _stage(self, class_map: dict[str, type], name: str, params, kind: str):
        if name not in class_map:
            raise ValueError(
                f"{kind} {name!r} is not defined; available: "
                f"{sorted(class_map)}"
            )
        return Doer(class_map[name], params)

    def _doers(self, engine_params: EngineParams):
        ds = self._stage(
            self.datasource_classes, *engine_params.datasource, "datasource"
        )
        prep = self._stage(
            self.preparator_classes, *engine_params.preparator, "preparator"
        )
        algo_list = engine_params.algorithms or [("", None)]
        algos = [
            self._stage(self.algorithm_classes, n, p, "algorithm")
            for n, p in algo_list
        ]
        serving = self._stage(
            self.serving_classes, *engine_params.serving, "serving"
        )
        return ds, prep, algos, serving

    # -- train (reference Engine.object.train, Engine.scala:622-709) --------
    def train(
        self,
        ctx,
        engine_params: EngineParams,
        stop_after_read: bool = False,
        stop_after_prepare: bool = False,
    ) -> list[Any]:
        ds, prep, algos, _ = self._doers(engine_params)
        td = ds.read_training(ctx)
        sanity_check(td)
        if stop_after_read:
            raise TrainingInterruption("read")
        pd = prep.prepare(ctx, td)
        sanity_check(pd)
        if stop_after_prepare:
            raise TrainingInterruption("prepare")
        models = [algo.train(ctx, pd) for algo in algos]
        for m in models:
            sanity_check(m)
        return models

    # -- eval (reference Engine.object.eval, Engine.scala:727-817) ----------
    def eval(
        self, ctx, engine_params: EngineParams
    ) -> list[tuple[Any, list[tuple[dict, Any, Any]]]]:
        """-> per eval-set: (eval-info, [(query, prediction, actual)])."""
        ds, prep, algos, serving = self._doers(engine_params)
        eval_sets = ds.read_eval(ctx)
        results = []
        for td, eval_info, qa_pairs in eval_sets:
            pd = prep.prepare(ctx, td)
            models = [algo.train(ctx, pd) for algo in algos]
            queries = [serving.supplement(q) for q, _ in qa_pairs]
            # per-algo bulk predict, then per-query serve combination
            # (reference union+groupByKey at Engine.scala:787-793 — here a
            # plain transpose, order-preserving)
            per_algo = [
                algo.batch_predict(model, queries)
                for algo, model in zip(algos, models)
            ]
            qpa = [
                (q, serving.serve(q, [preds[i] for preds in per_algo]), a)
                for i, (q, a) in enumerate(qa_pairs)
            ]
            results.append((eval_info, qpa))
        return results

    def algorithm_model_kinds(self, engine_params: EngineParams) -> list[str]:
        algo_list = engine_params.algorithms or [("", None)]
        return [
            getattr(self.algorithm_classes[n], "model_kind", "local")
            for n, _ in algo_list
        ]

    # -- engine.json extraction (reference jValueToEngineParams) ------------
    def engine_params_from_variant(self, variant: dict) -> EngineParams:
        return engine_params_from_variant(
            variant,
            self.datasource_classes,
            self.preparator_classes,
            self.algorithm_classes,
            self.serving_classes,
        )


class SimpleEngine(Engine):
    """1-datasource/identity-prep/1-algo sugar (reference Engine.scala:66-70)."""

    def __init__(self, datasource_class, algorithm_class, serving_class=None):
        from pio_tpu.controller.base import FirstServing, IdentityPreparator

        super().__init__(
            datasource_class,
            IdentityPreparator,
            algorithm_class,
            serving_class or FirstServing,
        )


class EngineFactory:
    """User entry point named in engine.json (reference EngineFactory.scala:8).
    Subclass and implement apply()."""

    @classmethod
    def apply(cls) -> Engine:
        raise NotImplementedError


def _stage_params(variant: dict, key: str, class_map: dict[str, type]):
    """Extract one stage's (name, params) from variant JSON. Accepts either
    {"params": {...}} (unnamed) or {"name": ..., "params": {...}}."""
    spec = variant.get(key) or {}
    name = spec.get("name", "")
    raw = spec.get("params", {})
    if name not in class_map and name == "" and len(class_map) == 1:
        name = next(iter(class_map))
    cls = class_map.get(name)
    params_class = getattr(cls, "params_class", None) if cls else None
    return name, params_from_dict(params_class, raw)


def engine_params_from_variant(
    variant: dict,
    datasource_classes,
    preparator_classes,
    algorithm_classes,
    serving_classes,
) -> EngineParams:
    """engine.json variant -> EngineParams (reference Engine.scala:354-417).

    Variant shape:
      {"id": ..., "engineFactory": "pkg.module.Factory",
       "datasource": {"params": {...}},
       "preparator": {"params": {...}},
       "algorithms": [{"name": "als", "params": {...}}, ...],
       "serving": {"params": {...}}}
    """
    ds = _stage_params(variant, "datasource", _single_class_map(datasource_classes))
    prep = _stage_params(variant, "preparator", _single_class_map(preparator_classes))
    serving = _stage_params(variant, "serving", _single_class_map(serving_classes))
    algo_map = _single_class_map(algorithm_classes)
    algos = []
    for spec in variant.get("algorithms", []):
        name = spec.get("name", "")
        if name not in algo_map and name == "" and len(algo_map) == 1:
            name = next(iter(algo_map))
        if name not in algo_map:
            raise ValueError(
                f"algorithm {name!r} not in engine (available: {sorted(algo_map)})"
            )
        params_class = getattr(algo_map[name], "params_class", None)
        algos.append((name, params_from_dict(params_class, spec.get("params", {}))))
    return EngineParams(
        datasource=ds, preparator=prep, algorithms=algos, serving=serving
    )
