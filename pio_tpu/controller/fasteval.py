"""FastEvalEngine — hyperparameter-search accelerator.

Mirrors reference controller/FastEvalEngine.scala:50-343: during tuning,
consecutive EngineParams usually share pipeline prefixes (same datasource,
same preparator, ...). FastEvalEngine memoizes each prefix so shared stages
run once across the whole params grid:

  datasource prefix  (ds name+params)                -> read_eval folds
  preparator prefix  (ds + prep)                     -> prepared data / fold
  algorithms prefix  (ds + prep + algo list)         -> batch predictions
  serving            (never cached — cheap)

Cache keys are canonical JSON of the stage params (the reference's
*PrefixParams case classes). Hit counters are exposed for tests — the
reference's FastEvalEngineTest asserts exact hit counts."""

from __future__ import annotations

import json
import threading
from collections import Counter
from concurrent.futures import Future
from typing import Any

from pio_tpu.controller.base import params_to_dict
from pio_tpu.controller.engine import Engine, EngineParams


def _key(*parts) -> str:
    def enc(p):
        if isinstance(p, tuple):
            return [p[0], params_to_dict(p[1])]
        if isinstance(p, list):
            return [enc(x) for x in p]
        return p

    return json.dumps([enc(p) for p in parts], sort_keys=True)


class FastEvalEngine(Engine):
    """Drop-in Engine whose eval() memoizes pipeline prefixes."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        # caches hold per-key Futures so a parallel params sweep
        # (MetricEvaluator workers>1) computes each shared prefix ONCE:
        # the first thread in owns the Future, later threads block on it
        self._ds_cache: dict[str, Future] = {}
        self._prep_cache: dict[str, Future] = {}
        self._algo_cache: dict[str, Future] = {}
        self._lock = threading.Lock()
        self.cache_hits = Counter()
        self.cache_misses = Counter()

    def _memo(self, cache: dict[str, Future], stage: str, key: str, compute):
        with self._lock:
            fut = cache.get(key)
            if fut is None:
                fut = cache[key] = Future()
                self.cache_misses[stage] += 1
                owner = True
            else:
                self.cache_hits[stage] += 1
                owner = False
        if owner:
            try:
                fut.set_result(compute())
            except BaseException as e:
                with self._lock:
                    cache.pop(key, None)  # a failed stage may be retried
                fut.set_exception(e)
                raise
        return fut.result()

    @classmethod
    def from_engine(cls, engine: Engine) -> "FastEvalEngine":
        return cls(
            engine.datasource_classes,
            engine.preparator_classes,
            engine.algorithm_classes,
            engine.serving_classes,
        )

    # -- prefix stages (reference getDataSourceResult etc.,
    # FastEvalEngine.scala:50-264) ------------------------------------------
    def _datasource_result(self, ctx, engine_params: EngineParams):
        def compute():
            ds = self._stage(
                self.datasource_classes, *engine_params.datasource,
                "datasource",
            )
            return ds.read_eval(ctx)

        return self._memo(
            self._ds_cache, "datasource", _key(engine_params.datasource),
            compute,
        )

    def _preparator_result(self, ctx, engine_params: EngineParams):
        def compute():
            prep = self._stage(
                self.preparator_classes, *engine_params.preparator,
                "preparator",
            )
            folds = self._datasource_result(ctx, engine_params)
            return [(prep.prepare(ctx, td), ei, qa) for td, ei, qa in folds]

        return self._memo(
            self._prep_cache, "preparator",
            _key(engine_params.datasource, engine_params.preparator),
            compute,
        )

    def _algorithms_result(self, ctx, engine_params: EngineParams):
        """-> per fold: list over algos of batch predictions (aligned with
        the fold's supplemented queries)."""
        k = _key(
            engine_params.datasource,
            engine_params.preparator,
            list(engine_params.algorithms or [("", None)]),
            engine_params.serving,  # supplement affects queries
        )

        def compute():
            algo_list = engine_params.algorithms or [("", None)]
            algos = [
                self._stage(self.algorithm_classes, n, p, "algorithm")
                for n, p in algo_list
            ]
            serving = self._stage(
                self.serving_classes, *engine_params.serving, "serving"
            )
            folds = self._preparator_result(ctx, engine_params)
            out = []
            for pd, ei, qa in folds:
                models = [a.train(ctx, pd) for a in algos]
                queries = [serving.supplement(q) for q, _ in qa]
                per_algo = [
                    a.batch_predict(m, queries)
                    for a, m in zip(algos, models)
                ]
                out.append((per_algo, ei, qa))
            return out

        return self._memo(self._algo_cache, "algorithms", k, compute)

    # -- eval override (reference FastEvalEngine.scala:310-343) -------------
    def eval(self, ctx, engine_params: EngineParams):
        serving = self._stage(
            self.serving_classes, *engine_params.serving, "serving"
        )
        results = []
        for per_algo, ei, qa in self._algorithms_result(ctx, engine_params):
            qpa = [
                (q, serving.serve(q, [preds[i] for preds in per_algo]), a)
                for i, (q, a) in enumerate(qa)
            ]
            results.append((ei, qpa))
        return results

    def clear_cache(self):
        # under the memo lock: a worker mid-_memo must not observe a
        # half-cleared cache (found by `pio lint`, attr-no-lock)
        with self._lock:
            self._ds_cache.clear()
            self._prep_cache.clear()
            self._algo_cache.clear()
            self.cache_hits.clear()
            self.cache_misses.clear()
