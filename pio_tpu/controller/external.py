"""External (any-language) engine bridge — the cross-language binding story.

The reference ships a Java controller API (core/src/main/java/.../
controller/java/*, e.g. LJavaAlgorithm) so engines can be written outside
Scala. A Python framework's equivalent isn't a JVM shim but a PROCESS
protocol: the engine is any executable speaking line-delimited JSON-RPC on
stdio, and this module bridges it into the DASE pipeline. Train spawns the
engine process, streams it the training events, and stores the opaque JSON
model it returns in the regular model store; deploy re-spawns it, loads the
model once, and proxies queries (a lock serializes the pipe — the child is
free to be internally parallel).

Wire protocol (one JSON object per line on stdin/stdout; stderr is logged):

  -> {"id": 1, "method": "describe", "params": {}}
  <- {"id": 1, "result": {"name": "...", "protocol": 1}}
  -> {"id": 2, "method": "train",
      "params": {"events": [<event wire dicts>], "config": {...}}}
  <- {"id": 2, "result": {"model": <any json>}}
  -> {"id": 3, "method": "load_model", "params": {"model": ..., "config": ...}}
  <- {"id": 3, "result": {}}
  -> {"id": 4, "method": "predict", "params": {"query": {...}}}
  <- {"id": 4, "result": {"prediction": {...}}}
  -> {"id": 5, "method": "predict_batch", "params": {"queries": [...]}}
  <- {"id": 5, "result": {"predictions": [...]}}      (optional method)

Errors: {"id": N, "error": {"message": "..."}}. An engine that doesn't
implement predict_batch returns an error for it and the bridge falls back
to per-query predicts. `examples/external-engine/` holds a stdlib-only
reference implementation of the engine side.
"""

from __future__ import annotations

import json
import logging
import queue
import subprocess
import threading
from dataclasses import dataclass, field
from typing import Any, Sequence

from pio_tpu.controller.base import (
    DataSource,
    FirstServing,
    IdentityPreparator,
    LAlgorithm,
    Params,
)
from pio_tpu.controller.engine import Engine, EngineFactory

log = logging.getLogger("pio_tpu.external")


class ExternalEngineError(RuntimeError):
    pass


class ExternalProcess:
    """One engine child process; request/response over stdio lines."""

    def __init__(self, command: Sequence[str], cwd: str | None = None,
                 timeout: float = 600.0):
        if not command:
            raise ExternalEngineError("external engine command is empty")
        self.command = list(command)
        self.timeout = timeout
        self.dead = False          # set when the bridge kills/abandons it
        self._lock = threading.Lock()
        self._next_id = 0
        try:
            self._proc = subprocess.Popen(
                self.command, cwd=cwd,
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True, bufsize=1,
            )
        except OSError as e:
            raise ExternalEngineError(
                f"cannot spawn external engine {self.command}: {e}"
            ) from e
        # drain stderr on a thread so the child can't block on a full pipe;
        # read stdout on a thread too, so call() can enforce its timeout
        # (a blocking readline could never be interrupted)
        self._out_q: queue.Queue[str] = queue.Queue()
        self._stdout_thread = threading.Thread(
            target=self._read_stdout, daemon=True
        )
        self._stdout_thread.start()
        self._stderr_thread = threading.Thread(
            target=self._drain_stderr, daemon=True
        )
        self._stderr_thread.start()

    def _read_stdout(self):
        try:
            for line in self._proc.stdout:
                self._out_q.put(line)
        except ValueError:
            pass  # pipe closed
        self._out_q.put("")  # EOF sentinel

    def _drain_stderr(self):
        try:
            for line in self._proc.stderr:
                log.info("[external %s] %s", self.command[0], line.rstrip())
        except ValueError:
            pass  # pipe closed

    def call(self, method: str, params: dict | None = None,
             timeout: float | None = None) -> Any:
        """timeout: None = the process default; <= 0 = wait indefinitely
        (training runs are legitimately long)."""
        timeout = self.timeout if timeout is None else timeout
        with self._lock:
            if self.dead or (
                self._proc.poll() is not None and self._out_q.empty()
            ):
                raise ExternalEngineError(
                    f"external engine {self.command} exited with "
                    f"rc={self._proc.poll()}"
                )
            self._next_id += 1
            req_id = self._next_id
            msg = json.dumps(
                {"id": req_id, "method": method, "params": params or {}}
            )
            try:
                self._proc.stdin.write(msg + "\n")
                self._proc.stdin.flush()
            except (BrokenPipeError, OSError) as e:
                self.dead = True
                raise ExternalEngineError(
                    f"external engine {self.command} pipe broke during "
                    f"{method}: {e}"
                ) from e
            try:
                line = self._out_q.get() if timeout <= 0 \
                    else self._out_q.get(timeout=timeout)
            except queue.Empty:
                # a hung engine would wedge the pipe; SIGKILL may not be
                # reaped by the time the caller retries, so mark dead
                # explicitly rather than trusting poll()
                self.dead = True
                self._proc.kill()
                raise ExternalEngineError(
                    f"external engine {self.command} did not answer "
                    f"{method} within {timeout}s; killed"
                ) from None
        if not line:
            raise ExternalEngineError(
                f"external engine {self.command} closed stdout during "
                f"{method} (rc={self._proc.poll()})"
            )
        try:
            resp = json.loads(line)
        except json.JSONDecodeError as e:
            raise ExternalEngineError(
                f"external engine sent invalid JSON for {method}: "
                f"{line[:200]!r}"
            ) from e
        if resp.get("id") != req_id:
            raise ExternalEngineError(
                f"external engine answered id {resp.get('id')} to request "
                f"{req_id} ({method}); the protocol is strictly serial"
            )
        if "error" in resp:
            raise ExternalEngineError(
                f"{method}: {resp['error'].get('message', resp['error'])}"
            )
        return resp.get("result")

    def close(self):
        proc = self._proc
        if proc.poll() is None:
            try:
                proc.stdin.close()
            except OSError:
                pass
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()


# ---------------------------------------------------------------------------
# DASE wrappers
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ExternalDataSourceParams(Params):
    app_name: str = ""
    event_names: tuple = ()


class ExternalDataSource(DataSource):
    """Reads the app's events and hands them to the external engine as wire
    dicts (the Event Server's JSON shape, so any language's existing client
    model applies)."""

    params_class = ExternalDataSourceParams

    def __init__(self, params: ExternalDataSourceParams):
        self.params = params

    def read_training(self, ctx) -> list[dict]:
        events = ctx.event_store.find(
            app_name=self.params.app_name,
            event_names=list(self.params.event_names) or None,
        )
        return [e.to_api_dict() for e in events]


@dataclass(frozen=True)
class ExternalAlgorithmParams(Params):
    command: tuple = ()        # argv of the engine executable
    config: dict = field(default_factory=dict)  # passed through verbatim
    workdir: str = ""          # cwd for the child ("" = inherit)
    timeout: float = 600.0     # per-RPC limit for serving/describe calls
    train_timeout: float = 0.0  # train limit; <= 0 = unbounded (trains
                                # are legitimately long; 0 matches the
                                # reference's unbounded train)

    # the engine loader absolutizes these against the engine directory
    path_fields = ("workdir",)


class ExternalAlgorithm(LAlgorithm):
    """Bridges train/predict to the engine process. The stored model is the
    opaque JSON the engine returned from `train` plus enough to respawn it
    at deploy."""

    params_class = ExternalAlgorithmParams

    def __init__(self, params: ExternalAlgorithmParams):
        self.params = params
        self._proc: ExternalProcess | None = None
        self._loaded_key: int | None = None
        self._proc_lock = threading.Lock()
        self._batch_unsupported = False

    def _spawn(self) -> ExternalProcess:
        # the CLI absolutizes a relative workdir against the engine dir at
        # load time (cli._absolutize_param_paths); one still relative here
        # (programmatic construction) resolves against the process cwd
        return ExternalProcess(
            self.params.command, cwd=self.params.workdir or None,
            timeout=self.params.timeout,
        )

    def train(self, ctx, events: list[dict]) -> dict:
        proc = self._spawn()
        try:
            info = proc.call("describe") or {}
            model = proc.call("train", {
                "events": events, "config": dict(self.params.config),
            }, timeout=self.params.train_timeout)
            if not isinstance(model, dict) or "model" not in model:
                raise ExternalEngineError(
                    "train must return {\"model\": <json>}"
                )
            return {
                "engine": info.get("name", self.params.command[0]),
                "model": model["model"],
            }
        finally:
            proc.close()

    def _serving_proc(self, model: dict) -> ExternalProcess:
        """Keep one child alive across predicts; (re)load on model change
        (reload hot-swap) or child death."""
        with self._proc_lock:
            key = id(model)
            if self._proc is not None and (
                self._loaded_key != key
                or self._proc.dead
                or self._proc._proc.poll() is not None
            ):
                self._proc.close()
                self._proc = None
            if self._proc is None:
                self._proc = self._spawn()
                self._proc.call("load_model", {
                    "model": model["model"],
                    "config": dict(self.params.config),
                })
                self._loaded_key = key
            return self._proc

    def predict(self, model: dict, query: dict) -> Any:
        proc = self._serving_proc(model)
        out = proc.call("predict", {"query": query})
        if not isinstance(out, dict) or "prediction" not in out:
            raise ExternalEngineError(
                "predict must return {\"prediction\": <json>}; got "
                f"{str(out)[:200]!r}"
            )
        return out["prediction"]

    _UNSUPPORTED_MARKERS = ("unknown method", "not implemented",
                            "unsupported", "no such method")

    def batch_predict(self, model: dict, queries) -> list:
        proc = self._serving_proc(model)
        if not self._batch_unsupported:
            try:
                out = proc.call(
                    "predict_batch", {"queries": list(queries)}
                ) or {}
                preds = out.get("predictions")
                if isinstance(preds, list) and len(preds) == len(queries):
                    return preds
                raise ExternalEngineError(
                    "predict_batch must return {\"predictions\": [...]} "
                    "matching the query count"
                )
            except ExternalEngineError as e:
                msg = str(e).lower()
                if any(m in msg for m in self._UNSUPPORTED_MARKERS):
                    # optional method: remember the refusal so the hot
                    # path doesn't pay a probe round-trip per batch
                    self._batch_unsupported = True
                    log.warning(
                        "external engine has no predict_batch (%s); "
                        "falling back to per-query predicts", e,
                    )
                else:
                    # a real failure (timeout, crash, protocol bug) must
                    # surface, not silently disable batching forever
                    raise
        return [self.predict(model, q) for q in queries]

    def close(self):
        """Stop the serving child (hooked by QueryServer.close())."""
        with self._proc_lock:
            if self._proc is not None:
                self._proc.close()
                self._proc = None
                self._loaded_key = None


class ExternalEngine(EngineFactory):
    """engine.json shape:

        {"engineFactory": "pio_tpu.controller.external.ExternalEngine",
         "datasource": {"params": {"app_name": "X"}},
         "algorithms": [{"name": "external",
                         "params": {"command": ["python3", "my_engine.py"],
                                    "config": {...}}}]}
    """

    @classmethod
    def apply(cls) -> Engine:
        return Engine(
            ExternalDataSource,
            IdentityPreparator,
            {"external": ExternalAlgorithm},
            FirstServing,
        )
