"""pio_tpu — a TPU-native machine-learning server framework.

A from-scratch re-design of Apache PredictionIO's capabilities (reference:
/root/reference, Scala/Spark) for TPU hardware: REST event collection with
pluggable storage, engines as DataSource -> Preparator -> Algorithm(s) ->
Serving (DASE) pipelines, a single-controller JAX training workflow over a
`jax.sharding.Mesh` (pjit + XLA collectives instead of Spark shuffles),
metric-driven evaluation/tuning, and a deploy server keeping models resident
in HBM.

Package layout (mirrors SURVEY.md section 7):
  data/        event model, storage abstraction, backends   (reference: data/)
  server/      event server, webhooks, admin, dashboard     (reference: data/api, tools/)
  controller/  DASE + Evaluation public API                 (reference: core/controller)
  workflow/    train/eval/deploy runtime                    (reference: core/workflow)
  ops/         JAX/Pallas numeric kernels (ALS, NB, ...)    (replaces Spark MLlib)
  parallel/    mesh, sharding, collectives helpers          (replaces Spark cluster)
  models/      engine templates, the model zoo              (reference: examples/)
  e2/          engine-building helper lib                   (reference: e2/)
  tools/       CLI + ops commands                           (reference: tools/)
  utils/       config, json, time helpers
"""

__version__ = "0.1.0"
