"""Cross-process trace assembly: collect span records from every
surface, merge them into one tree, and render it with per-hop self-time.

``pio trace <id>`` drives ``collect_trace`` → ``build_tree`` →
``render_tree``; ``pio top`` drives ``collect_span_tables`` →
``render_span_table``. Surfaces are addressed by base URL; given the
fleet router's URL, its ``/fleet.json`` is used to discover every shard
replica automatically, so the operator needs one address for the whole
fleet.
"""

from __future__ import annotations

from pio_tpu.obs.recorder import SpanRecord
from pio_tpu.utils.httpclient import HttpClientError, JsonHttpClient

# one client per polled surface, memoized for the process lifetime:
# `pio top --watch` and `pio trace` poll the same URL set every tick,
# and a throwaway client per iteration would needlessly rebuild TLS
# contexts (connections themselves already persist in the shared pool)
_clients: dict[tuple[str, float], JsonHttpClient] = {}


def _client(url: str, timeout: float) -> JsonHttpClient:
    key = (url.rstrip("/"), timeout)
    client = _clients.get(key)
    if client is None:
        client = _clients[key] = JsonHttpClient(key[0], timeout=timeout)
    return client


def discover_fleet_urls(router_url: str, timeout: float = 5.0) -> list[str]:
    """router URL -> [router URL, every shard replica URL] (best-effort:
    an unreachable router just yields itself, and `pio trace` reports
    the miss per surface)."""
    urls = [router_url.rstrip("/")]
    try:
        fleet = _client(router_url, timeout).request(
            "GET", "/fleet.json")
    except HttpClientError:
        return urls
    for group in (fleet.get("shards") or {}).values():
        for rep in group.get("replicas", ()):
            url = (rep.get("url") or "").rstrip("/")
            if url and url not in urls:
                urls.append(url)
    return urls


def collect_trace(urls: list[str], trace_id: str, server_key: str = "",
                  timeout: float = 5.0
                  ) -> tuple[list[SpanRecord], dict[str, str]]:
    """Fetch `/debug/traces.json?traceId=` from every surface ->
    (merged span records, {url: why} for surfaces that had nothing)."""
    spans: list[SpanRecord] = []
    seen: set[str] = set()
    misses: dict[str, str] = {}
    params = {"traceId": trace_id}
    if server_key:
        params["accessKey"] = server_key
    for url in urls:
        try:
            out = _client(url, timeout).request(
                "GET", "/debug/traces.json", params=params)
        except HttpClientError as e:
            misses[url] = e.message if e.status == 404 else str(e)
            continue
        for d in (out or {}).get("spans", ()):
            rec = SpanRecord.from_dict(d)
            if rec.span_id in seen:
                continue    # replicas sharing a process, repeat polls
            seen.add(rec.span_id)
            spans.append(rec)
    return spans, misses


def build_tree(spans: list[SpanRecord]) -> list[dict]:
    """Span records -> root nodes, each ``{"span", "children",
    "self_s"}``. Parentage follows ``parent_id``; spans whose parent was
    not collected (an unreachable surface, a never-sampled hop) become
    roots so nothing silently disappears. ``self_s`` is the per-hop
    self-time: the span's duration minus its direct children's — where
    the time actually went, not just where it passed through."""
    nodes = {s.span_id: {"span": s, "children": [], "self_s": s.duration_s}
             for s in spans}
    roots = []
    for s in sorted(spans, key=lambda r: r.start_s):
        node = nodes[s.span_id]
        parent = nodes.get(s.parent_id) if s.parent_id else None
        if parent is None:
            roots.append(node)
        else:
            parent["children"].append(node)
            parent["self_s"] = max(0.0, parent["self_s"] - s.duration_s)
    return roots


def _render_node(node: dict, prefix: str, is_last: bool,
                 lines: list[str]) -> None:
    s: SpanRecord = node["span"]
    branch = "" if prefix == "" and is_last is None else (
        "└─ " if is_last else "├─ ")
    flags = ""
    if s.status == "error":
        flags = " ERROR" + (f" ({s.error})" if s.error else "")
    labels = " ".join(
        f"{k}={v}" for k, v in sorted(s.labels.items())
        if k not in ("method", "path", "status"))
    lines.append(
        f"{prefix}{branch}{s.name} [{s.surface}] "
        f"{s.duration_s * 1e3:.2f}ms (self {node['self_s'] * 1e3:.2f}ms)"
        + (f" {labels}" if labels else "") + flags)
    child_prefix = prefix + ("" if is_last is None else
                             ("   " if is_last else "│  "))
    kids = sorted(node["children"], key=lambda n: n["span"].start_s)
    for i, child in enumerate(kids):
        _render_node(child, child_prefix, i == len(kids) - 1, lines)


def render_tree(trace_id: str, spans: list[SpanRecord],
                misses: dict[str, str] | None = None) -> str:
    if not spans:
        return (f"trace {trace_id}: no spans found"
                + _render_misses(misses))
    roots = build_tree(spans)
    surfaces = sorted({s.surface for s in spans})
    duration = max(s.duration_s for s in spans)
    status = ("error" if any(s.status == "error" for s in spans)
              else "ok")
    lines = [f"trace {trace_id}  status={status}  "
             f"{duration * 1e3:.2f}ms  {len(spans)} spans over "
             f"{len(surfaces)} surface(s): {', '.join(surfaces)}"]
    for root in roots:
        _render_node(root, "", None, lines)
    return "\n".join(lines) + _render_misses(misses)


def _render_misses(misses: dict[str, str] | None) -> str:
    if not misses:
        return ""
    return "\n" + "\n".join(
        f"  (no spans from {url}: {why})" for url, why in misses.items())


def collect_span_tables(urls: list[str], server_key: str = "",
                        timeout: float = 5.0
                        ) -> tuple[list[dict], dict[str, str]]:
    rows: list[dict] = []
    errors: dict[str, str] = {}
    params = {"accessKey": server_key} if server_key else None
    for url in urls:
        try:
            out = _client(url, timeout).request(
                "GET", "/debug/spans.json", params=params)
        except HttpClientError as e:
            errors[url] = str(e)
            continue
        rows.extend((out or {}).get("spans", ()))
    return rows, errors


def render_span_table(rows: list[dict],
                      errors: dict[str, str] | None = None) -> str:
    header = (f"{'SURFACE':<12} {'SPAN':<28} {'ARM':<9} "
              f"{'RATE/S':>8} {'P50 MS':>9} {'P99 MS':>9} {'ERR%':>6}")
    lines = [header]
    for r in sorted(rows, key=lambda r: (-r.get("ratePerSec", 0.0),
                                         r.get("surface", ""),
                                         r.get("span", ""))):
        lines.append(
            f"{r.get('surface', '?'):<12} {r.get('span', '?')[:28]:<28} "
            f"{r.get('arm', 'active'):<9} {r.get('ratePerSec', 0):>8.2f} "
            f"{r.get('p50Ms', 0):>9.2f} {r.get('p99Ms', 0):>9.2f} "
            f"{r.get('errorPct', 0):>6.2f}")
    if len(lines) == 1:
        lines.append("(no spans in the recent window)")
    for url, why in (errors or {}).items():
        lines.append(f"  (no span table from {url}: {why})")
    return "\n".join(lines)
