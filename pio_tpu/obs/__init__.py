"""Distributed request tracing + the uniform Prometheus plane.

Layering (docs/observability.md):

  * ``obs/context.py``  — trace ids in a contextvar + the W3C-style
    ``traceparent`` wire format (stdlib-only, imported by the
    transports);
  * ``obs/recorder.py`` — per-surface ``TraceRecorder``: span records,
    tail-based retention (errors + slowest-N + pinned + sampled), the
    live span table, slow-trace exemplars;
  * ``obs/http.py``     — ``/debug/traces.json`` + ``/debug/spans.json``
    route installer (server-key guarded);
  * ``obs/assemble.py`` — cross-process merge + rendering behind
    ``pio trace <id>`` and ``pio top``.

``make_recorder(surface)`` is the one constructor surfaces call: it
returns None when tracing is disabled (PIO_TPU_TRACE=off), and a None
recorder collapses the whole layer back to histogram-only tracing.
"""

from pio_tpu.obs.context import (
    TRACE_ECHO_REQUEST_HEADER,
    TRACE_ECHO_RESPONSE_HEADER,
    TRACEPARENT_HEADER,
    TraceContext,
    current,
    current_recorder,
    format_traceparent,
    new_trace,
    parse_traceparent,
    set_tracing,
    tracing_enabled,
    use,
)
from pio_tpu.obs.recorder import SpanRecord, TraceRecorder, chaos_point_of


def make_recorder(surface: str, **kwargs) -> TraceRecorder | None:
    """The surface-side constructor: None when PIO_TPU_TRACE disables
    tracing (surfaces then skip /debug routes and edge recording)."""
    if not tracing_enabled():
        return None
    return TraceRecorder(surface, **kwargs)


__all__ = [
    "TRACEPARENT_HEADER",
    "TRACE_ECHO_REQUEST_HEADER",
    "TRACE_ECHO_RESPONSE_HEADER",
    "SpanRecord",
    "TraceContext",
    "TraceRecorder",
    "chaos_point_of",
    "current",
    "current_recorder",
    "format_traceparent",
    "make_recorder",
    "new_trace",
    "parse_traceparent",
    "set_tracing",
    "tracing_enabled",
    "use",
]
