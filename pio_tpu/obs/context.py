"""Distributed trace context: W3C-traceparent-style ids in a contextvar.

One request = one trace. Every hop carries ``trace_id`` (the request),
``span_id`` (the current operation), and ``parent_id`` (the operation
that caused it) in a contextvar — the same ambient-propagation shape the
resilience ``Deadline`` rides — so the serving stack joins a trace with
ZERO per-call-site changes:

  * ``server/http.py``'s dispatch edge EXTRACTS the inbound
    ``traceparent`` header (or starts a fresh trace) and activates the
    context for the handler's dynamic extent;
  * ``utils/httpclient.py`` INJECTS a child context into the outbound
    ``traceparent`` header on every request, so router→shard fan-outs,
    fold-in applies, serving→storage DAO RPCs, and rollout control fans
    all join the caller's trace;
  * ``utils/tracing.py``'s ``Tracer.span`` opens a child span per stage
    and emits a span record to the ambient ``TraceRecorder``.

Wire format (the W3C trace-context header, so off-the-shelf proxies and
clients interoperate)::

    traceparent: 00-<32 hex trace id>-<16 hex span id>-<2 hex flags>

Flags bit 0 is the W3C "sampled" bit (always set — sampling here is
tail-based, decided at retention time, not at the head); bit 1 is the
pio extension "pinned" bit: a client that sent ``X-Pio-Trace: 1`` asked
for THIS request's trace, so every surface retains it unconditionally
and the response carries ``X-Pio-Trace-Id`` for the fetch-back.

This module is stdlib-only and imports nothing from pio_tpu — it sits
below both the transports and the tracing layer.
"""

from __future__ import annotations

import itertools
import os
import random
import re
import threading
from contextlib import contextmanager
from contextvars import ContextVar
from dataclasses import dataclass

TRACEPARENT_HEADER = "traceparent"
# request header: any non-empty value asks for the response to echo the
# trace id (and pins the trace in every surface's recorder)
TRACE_ECHO_REQUEST_HEADER = "x-pio-trace"
TRACE_ECHO_RESPONSE_HEADER = "X-Pio-Trace-Id"

ENV_VAR = "PIO_TPU_TRACE"   # "off"/"0"/"false" disables recorder creation

_FLAG_SAMPLED = 0x01
_FLAG_PINNED = 0x02

_TRACEPARENT_RE = re.compile(
    r"^[0-9a-f]{2}-(?P<trace>[0-9a-f]{32})-(?P<span>[0-9a-f]{16})"
    r"-(?P<flags>[0-9a-f]{2})$"
)


@dataclass(frozen=True, slots=True)
class TraceContext:
    """One node of the distributed span tree (immutable; children are
    derived, never mutated in place; slotted — several per request)."""

    trace_id: str               # 32 hex chars, shared by the whole request
    span_id: str                # 16 hex chars, this operation
    parent_id: str | None = None
    pinned: bool = False        # client asked to retain this trace

    def child(self) -> "TraceContext":
        """A fresh span under this one (same trace, same pin)."""
        return TraceContext(trace_id=self.trace_id, span_id=_span_id(),
                            parent_id=self.span_id, pinned=self.pinned)


# ids are IDENTIFIERS, not secrets, and a query opens 5+ of them — id
# cost is most of the recorder's hot-path budget (the bench smoke
# <=5%-p50 gate). secrets.token_hex costs an os.urandom syscall per id
# (~10us); instead span ids are a urandom-drawn per-process base plus an
# atomic counter (unique within the process by construction; the random
# base makes a cross-process collision inside one trace ~2^-64), and
# trace ids (one per request, off the per-span path) come from a
# urandom-seeded PRNG under a lock.
_id_rng = random.Random(int.from_bytes(os.urandom(16), "big"))
_id_lock = threading.Lock()
_span_base = _id_rng.getrandbits(64)
_span_counter = itertools.count().__next__   # C-level next(): atomic/GIL


def _span_id() -> str:
    return f"{(_span_base + _span_counter()) & 0xFFFFFFFFFFFFFFFF:016x}"


def new_trace(pinned: bool = False) -> TraceContext:
    """A fresh root context (no parent) — what a request edge opens when
    the client sent no traceparent."""
    with _id_lock:
        trace_id = f"{_id_rng.getrandbits(128):032x}"
    return TraceContext(trace_id=trace_id, span_id=_span_id(),
                        parent_id=None, pinned=pinned)


def format_traceparent(ctx: TraceContext) -> str:
    flags = _FLAG_SAMPLED | (_FLAG_PINNED if ctx.pinned else 0)
    return f"00-{ctx.trace_id}-{ctx.span_id}-{flags:02x}"


def parse_traceparent(value: str | None) -> TraceContext | None:
    """Inbound header -> the SERVER's context: a fresh span id whose
    parent is the sender's span. Malformed or all-zero ids return None
    (the edge then starts a fresh trace — garbage on the wire must never
    break a request)."""
    if not value:
        return None
    m = _TRACEPARENT_RE.match(value.strip().lower())
    if m is None:
        return None
    trace, span = m.group("trace"), m.group("span")
    if trace == "0" * 32 or span == "0" * 16:
        return None
    pinned = bool(int(m.group("flags"), 16) & _FLAG_PINNED)
    return TraceContext(trace_id=trace, span_id=_span_id(),
                        parent_id=span, pinned=pinned)


# -- ambient propagation -----------------------------------------------------

_trace_var: ContextVar[TraceContext | None] = ContextVar(
    "pio_tpu_trace", default=None)
# the surface-local TraceRecorder bound for the request's dynamic extent
# (typed as object to keep this module import-free; recorder.py owns the
# real type)
_recorder_var: ContextVar[object | None] = ContextVar(
    "pio_tpu_trace_recorder", default=None)


def current() -> TraceContext | None:
    return _trace_var.get()


def current_recorder():
    return _recorder_var.get()


def push(ctx: TraceContext):
    """Activate `ctx`; returns the token for pop(). Prefer use() — this
    pair exists for the hot span path, which cannot afford a nested
    context-manager frame."""
    return _trace_var.set(ctx)


def pop(token) -> None:
    _trace_var.reset(token)


@contextmanager
def use(ctx: TraceContext | None, recorder=None):
    """Activate a trace context (and optionally bind the surface's
    recorder) for the block — the request edge's wrapper."""
    t_ctx = _trace_var.set(ctx)
    t_rec = _recorder_var.set(recorder) if recorder is not None else None
    try:
        yield ctx
    finally:
        if t_rec is not None:
            _recorder_var.reset(t_rec)
        _trace_var.reset(t_ctx)


# -- kill switch -------------------------------------------------------------

_enabled_override: bool | None = None


def tracing_enabled() -> bool:
    """False when PIO_TPU_TRACE=off/0/false (or set_tracing(False)):
    surfaces then create no recorder and the whole layer collapses to
    the pre-existing histogram-only tracing."""
    if _enabled_override is not None:
        return _enabled_override
    return os.environ.get(ENV_VAR, "").strip().lower() not in (
        "off", "0", "false", "no")


def set_tracing(on: bool | None) -> None:
    """Override the env switch (None restores env behavior) — the bench
    tracing-overhead cell and tests flip this around server builds."""
    global _enabled_override
    # pio: lint-ok[global-no-lock] single-writer test/bench toggle,
    # flipped around surface CONSTRUCTION (make_recorder reads it once
    # per server build), never on a concurrent request path; a torn
    # read is a bool either way
    _enabled_override = on
