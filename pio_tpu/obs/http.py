"""Observability HTTP surface shared by every server.

``install_trace_routes(app, recorder, check_key)`` wires, onto any
``HttpApp``:

  * ``GET /debug/traces.json``          — retained-trace summaries;
  * ``GET /debug/traces.json?traceId=`` — one trace's span records
    (what ``pio trace`` collects from each surface and merges);
  * ``GET /debug/spans.json``           — the live span table over the
    recent window (what ``pio top`` renders).

Both are server-key guarded through the surface's own ``check_key``
(the same guard as /reload — traces carry request paths and timing).
Installing also sets ``app.recorder``, which is what switches the
transport's dispatch edge (server/http.py ``dispatch_safe``) into
traced mode — one wiring point per surface.
"""

from __future__ import annotations

from pio_tpu.obs.recorder import TraceRecorder


def install_trace_routes(app, recorder: TraceRecorder | None,
                         check_key=None) -> None:
    """No-op when tracing is disabled (recorder None) — the surface then
    serves neither /debug route and the dispatch edge stays untraced."""
    if recorder is None:
        return
    app.recorder = recorder

    def _guarded(req) -> tuple[int, dict] | None:
        if check_key is not None and not check_key(req):
            return 401, {"message": "Invalid accessKey."}
        return None

    @app.route("GET", r"/debug/traces\.json")
    def debug_traces(req):
        denied = _guarded(req)
        if denied:
            return denied
        trace_id = req.params.get("traceId")
        if trace_id:
            trace = recorder.trace_of(trace_id)
            if trace is None:
                return 404, {"message":
                             f"trace {trace_id} not retained on this "
                             "surface (expired, never sampled, or never "
                             "passed through)"}
            return 200, trace
        try:
            limit = int(req.params.get("limit", 50))
        except ValueError:
            return 400, {"message": "limit must be an integer"}
        return 200, {"surface": recorder.surface,
                     "traces": recorder.traces(limit=limit),
                     "recorder": recorder.stats()}

    @app.route("GET", r"/debug/spans\.json")
    def debug_spans(req):
        denied = _guarded(req)
        if denied:
            return denied
        return 200, {"surface": recorder.surface,
                     "spans": recorder.span_table()}
