"""Per-request span records with tail-based retention.

A ``TraceRecorder`` lives on each serving surface (query server, fleet
router, shard server, event server, storage server, fold-in folder) and
collects ``SpanRecord``s as spans FINISH — emitted by the HTTP dispatch
edge (``server/http.py``), the outbound client (``utils/httpclient.py``),
and every ``Tracer.span(...)`` stage. Records assemble per trace id; when
the surface-local edge span completes, ``finish_trace`` decides retention
TAIL-BASED — with the whole trace in hand, not a head-of-request coin
flip:

  * ERROR traces (any failed span) are always kept (bounded FIFO);
  * the SLOWEST-N traces are kept (min-heap on duration);
  * PINNED traces (client sent ``X-Pio-Trace: 1``) are always kept;
  * everything else survives with probability ``sample_rate``.

Everything is bounded: active assemblies, each retention class, the
recent-span ring the live span table aggregates over, and the exemplar
map — a recorder can never grow with traffic. ``GET /debug/traces.json``
(obs/http.py) exposes retained traces per surface; ``pio trace <id>``
(obs/assemble.py) merges the surfaces into one tree.
"""

from __future__ import annotations

import heapq
import random
import threading
import time
from collections import OrderedDict, deque
from contextlib import contextmanager
from dataclasses import dataclass, field

from pio_tpu.obs import context as tracectx


def chaos_point_of(exc: BaseException | None) -> str | None:
    """The chaos injection point attached to `exc` or anything in its
    cause chain (resilience/chaos.py stamps ``.point``) — failed spans
    get it as a ``chaos=<point>`` label so a drill's fault is visible in
    the tree as exactly the injected hop."""
    seen: set[int] = set()
    e = exc
    while e is not None and id(e) not in seen:
        seen.add(id(e))
        point = getattr(e, "point", None)
        if isinstance(point, str) and point:
            return point
        e = e.__cause__ or e.__context__
    return None


def error_fields(exc: BaseException,
                 labels: dict) -> tuple[str, dict]:
    """THE formatting of a failed span — error message + the
    ``chaos=<point>`` label when the failure was injected — shared by
    every emit site (Tracer.span, the HTTP client span, background
    root traces) so the fields cannot drift between them."""
    point = chaos_point_of(exc)
    if point:
        labels = {**labels, "chaos": point}
    return f"{type(exc).__name__}: {exc}", labels


@dataclass(slots=True)
class SpanRecord:
    """One finished span. ``start_s`` is wall-clock epoch seconds (for
    cross-process ordering in the merged tree); ``duration_s`` comes
    from the monotonic clock (immune to NTP steps). Slotted: recorders
    hold thousands of these and the hot path builds several per
    request."""

    trace_id: str
    span_id: str
    parent_id: str | None
    name: str
    surface: str
    start_s: float
    duration_s: float
    status: str = "ok"            # "ok" | "error"
    error: str | None = None
    labels: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "name": self.name,
            "surface": self.surface,
            "startS": round(self.start_s, 6),
            "durationS": round(self.duration_s, 6),
            "status": self.status,
            "error": self.error,
            "labels": self.labels,
        }

    @staticmethod
    def from_dict(d: dict) -> "SpanRecord":
        return SpanRecord(
            trace_id=d["traceId"], span_id=d["spanId"],
            parent_id=d.get("parentId"), name=d["name"],
            surface=d.get("surface", "?"),
            start_s=float(d.get("startS", 0.0)),
            duration_s=float(d.get("durationS", 0.0)),
            status=d.get("status", "ok"), error=d.get("error"),
            labels=dict(d.get("labels") or {}),
        )


class TraceRecorder:
    """See module docstring. Thread-safe; every operation is O(spans in
    one trace) or O(1) amortized under one lock — cheap enough for the
    serve hot path (the bench smoke gate holds it to <= 5% p50)."""

    def __init__(self, surface: str, *, max_errors: int = 64,
                 max_slow: int = 32, max_sampled: int = 64,
                 max_pinned: int = 64, sample_rate: float = 0.01,
                 recent_capacity: int = 2048, max_active: int = 512,
                 max_spans_per_trace: int = 512,
                 rng: random.Random | None = None):
        self.surface = surface
        self.max_errors = max_errors
        self.max_slow = max_slow
        self.max_sampled = max_sampled
        self.max_pinned = max_pinned
        self.sample_rate = sample_rate
        self.max_active = max_active
        # hard per-TRACE span cap: a reused trace id (a client replaying
        # the same traceparent, a retry loop hammering one pinned trace)
        # must not grow a retained entry without bound — every other
        # limit here caps entry COUNT, this one caps entry SIZE
        self.max_spans_per_trace = max_spans_per_trace
        self._rng = rng or random.Random()
        self._lock = threading.Lock()
        # trace id -> [SpanRecord] still assembling (edge not finished)
        self._active: OrderedDict[str, list[SpanRecord]] = OrderedDict()
        # retained traces: trace id -> entry dict; membership tracked by
        # the per-class structures below (a trace may be in several)
        self._traces: dict[str, dict] = {}
        self._errors: deque[str] = deque()
        self._pinned: deque[str] = deque()
        self._slow: list[tuple[float, int, str]] = []   # min-heap
        self._sampled: deque[str] = deque()
        self._seq = 0
        # ALL recently finished spans, retention-independent — the live
        # span table (`pio top`) aggregates over this bounded window,
        # and exemplars() derives the slowest-recent-per-span from it
        # on the READ side (nothing exemplar-shaped on the hot path)
        self._recent: deque[SpanRecord] = deque(maxlen=recent_capacity)
        self.dropped_traces = 0
        self.dropped_spans = 0

    # -- ingest --------------------------------------------------------------
    def record(self, span: SpanRecord) -> None:
        with self._lock:
            self._recent.append(span)
            spans = self._active.get(span.trace_id)
            if spans is None:
                if len(self._active) >= self.max_active:
                    # an assembly whose edge never finished (crashed
                    # connection, missing finish) must not leak
                    self._active.popitem(last=False)
                    self.dropped_traces += 1
                spans = self._active[span.trace_id] = []
            if len(spans) < self.max_spans_per_trace:
                spans.append(span)
            else:
                self.dropped_spans += 1

    def finish_trace(self, trace_id: str, pinned: bool = False) -> None:
        """The surface-local edge span completed: decide retention for
        everything assembled under `trace_id` (see module docstring).
        A later edge span of the SAME trace (the router fanning to one
        shard twice) merges into the already-retained entry."""
        with self._lock:
            spans = self._active.pop(trace_id, None)
            if not spans:
                return
            duration = max(s.duration_s for s in spans)
            is_error = any(s.status == "error" for s in spans)
            entry = self._traces.get(trace_id)
            if entry is not None:
                # merge, but never past the per-trace span cap: a client
                # replaying one trace id (reused traceparent, retry
                # loop on a pinned trace) must not grow this entry
                # linearly with traffic
                room = self.max_spans_per_trace - len(entry["spans"])
                entry["spans"].extend(spans[:max(0, room)])
                self.dropped_spans += max(0, len(spans) - max(0, room))
                entry["durationS"] = max(entry["durationS"], duration)
                if is_error and entry["status"] != "error":
                    entry["status"] = "error"
                    self._keep(self._errors, self.max_errors, trace_id)
                return
            entry = {"traceId": trace_id, "spans": spans,
                     "durationS": duration,
                     "status": "error" if is_error else "ok",
                     # pio: lint-ok[bench-clock] retention recency is
                     # wall-clock (compared against span start_s, also
                     # wall); no interval is measured with it
                     "endS": time.time()}
            keep = False
            if pinned:
                self._traces[trace_id] = entry
                self._keep(self._pinned, self.max_pinned, trace_id)
                keep = True
            if is_error:
                self._traces[trace_id] = entry
                self._keep(self._errors, self.max_errors, trace_id)
                keep = True
            self._seq += 1
            if len(self._slow) < self.max_slow:
                heapq.heappush(self._slow, (duration, self._seq, trace_id))
                self._traces[trace_id] = entry
                keep = True
            elif duration > self._slow[0][0]:
                _, _, evicted = heapq.heapreplace(
                    self._slow, (duration, self._seq, trace_id))
                self._traces[trace_id] = entry
                keep = True
                self._drop_if_unreferenced(evicted)
            if not keep and self._rng.random() < self.sample_rate:
                self._traces[trace_id] = entry
                self._keep(self._sampled, self.max_sampled, trace_id)
                keep = True
            if not keep:
                self.dropped_traces += 1

    def _keep(self, dq: deque, cap: int, trace_id: str) -> None:
        """Append to a FIFO retention class, evicting its oldest member
        (dropped entirely unless another class still references it)."""
        dq.append(trace_id)
        while len(dq) > cap:
            self._drop_if_unreferenced(dq.popleft())

    def _drop_if_unreferenced(self, trace_id: str) -> None:
        # pio: lint-ok[attr-no-lock] only called from finish_trace/_keep,
        # both already under self._lock (the same lock that serializes
        # every retention structure)
        if (trace_id in self._errors or trace_id in self._pinned
                or trace_id in self._sampled
                or any(t == trace_id for _, _, t in self._slow)):
            return
        # pio: lint-ok[attr-no-lock] still under self._lock — see above
        if self._traces.pop(trace_id, None) is not None:
            self.dropped_traces += 1  # pio: lint-ok[attr-no-lock] see above

    # -- convenience: a non-HTTP root trace (the fold-in folder's cycle) -----
    @contextmanager
    def trace(self, name: str, **labels):
        """Open a NEW root trace around a unit of background work, bind
        this recorder, and retain per the usual tail policy on exit.
        Outbound HTTP inside the block joins the trace automatically."""
        ctx = tracectx.new_trace()
        t0 = time.monotonic()
        # pio: lint-ok[bench-clock] span START is wall-clock on purpose —
        # it orders spans ACROSS processes in the merged tree (monotonic
        # clocks don't compare across hosts); the duration uses monotonic
        t0_wall = time.time()
        status, errmsg = "ok", None
        labels = {str(k): str(v) for k, v in labels.items()}
        with tracectx.use(ctx, self):
            try:
                yield ctx
            except BaseException as e:
                status = "error"
                errmsg, labels = error_fields(e, labels)
                raise
            finally:
                self.record(SpanRecord(
                    trace_id=ctx.trace_id, span_id=ctx.span_id,
                    parent_id=None, name=name, surface=self.surface,
                    start_s=t0_wall,
                    duration_s=time.monotonic() - t0,
                    status=status, error=errmsg, labels=labels))
                self.finish_trace(ctx.trace_id)

    # -- read side -----------------------------------------------------------
    def trace_of(self, trace_id: str) -> dict | None:
        """The retained (or still-assembling) trace as a JSON-ready dict."""
        with self._lock:
            entry = self._traces.get(trace_id)
            spans = list(entry["spans"]) if entry is not None else []
            spans.extend(self._active.get(trace_id, ()))
            if not spans:
                return None
            return {
                "traceId": trace_id,
                "surface": self.surface,
                "status": (entry["status"] if entry is not None
                           else "active"),
                "durationS": round(
                    entry["durationS"] if entry is not None
                    else max(s.duration_s for s in spans), 6),
                "spans": [s.to_dict() for s in spans],
            }

    def traces(self, limit: int = 50) -> list[dict]:
        """Retained-trace summaries, most recent first."""
        with self._lock:
            entries = sorted(self._traces.values(),
                             key=lambda e: e["endS"], reverse=True)[:limit]
            return [{
                "traceId": e["traceId"],
                "status": e["status"],
                "durationS": round(e["durationS"], 6),
                "spanCount": len(e["spans"]),
                "endS": round(e["endS"], 3),
            } for e in entries]

    def span_table(self) -> list[dict]:
        """Live per-(span, arm) stats over the recent-span window —
        what `pio top` renders: rate, p50, p99, error%."""
        with self._lock:
            recent = list(self._recent)
        if not recent:
            return []
        # pio: lint-ok[bench-clock] rate window = now minus span
        # start_s, which is wall-clock by design (cross-process
        # ordering) — both ends on the same clock
        now = time.time()
        window_s = max(1e-3, now - min(s.start_s for s in recent))
        groups: dict[tuple[str, str], list[SpanRecord]] = {}
        for s in recent:
            key = (s.name, s.labels.get("arm", "active"))
            groups.setdefault(key, []).append(s)
        out = []
        for (name, arm), spans in sorted(groups.items()):
            durs = sorted(s.duration_s for s in spans)
            n = len(durs)
            errors = sum(1 for s in spans if s.status == "error")
            out.append({
                "span": name,
                "arm": arm,
                "surface": self.surface,
                "count": n,
                "ratePerSec": round(n / window_s, 3),
                "p50Ms": round(durs[n // 2] * 1e3, 3),
                "p99Ms": round(durs[min(n - 1, int(n * 0.99))] * 1e3, 3),
                "errorPct": round(100.0 * errors / n, 2),
            })
        return out

    def exemplars(self) -> dict[str, dict]:
        """Slowest RECENT trace id per span name — the /metrics.json
        bridge from a p99 row to `pio trace <id>`. Computed on the read
        side from the recent-span window and restricted to traces still
        fetchable (retained or assembling), so an exemplar can never be
        an all-time-max relic whose trace 404s — it decays with the
        window like the span table does."""
        with self._lock:
            best: dict[str, SpanRecord] = {}
            for s in self._recent:
                if (s.trace_id not in self._traces
                        and s.trace_id not in self._active):
                    continue
                cur = best.get(s.name)
                if cur is None or s.duration_s > cur.duration_s:
                    best[s.name] = s
            return {
                name: {"traceId": s.trace_id,
                       "seconds": round(s.duration_s, 6)}
                for name, s in sorted(best.items())
            }

    def stats(self) -> dict:
        with self._lock:
            return {
                "surface": self.surface,
                "retainedTraces": len(self._traces),
                "activeTraces": len(self._active),
                "droppedTraces": self.dropped_traces,
                "droppedSpans": self.dropped_spans,
                "errorTraces": len(self._errors),
                "pinnedTraces": len(self._pinned),
                "slowTraces": len(self._slow),
                "sampledTraces": len(self._sampled),
            }
