"""Deterministic canary traffic split.

One pure function decides which arm serves a user: bucket
``crc32c(user_id) % 100`` (utils/durable.py's CRC32C — NEVER the
stdlib ``hash()``, which is salted per process; the single-host server,
every router replica, and every test oracle must agree across processes
and restarts). A user's bucket is a permanent property of their id, so

  * the split is STICKY: the same user hits the same arm for the whole
    rollout (no A/B flapping mid-session), and
  * ramping ``pct`` upward only ADDS users to the canary — everyone
    already in stays in, so per-user state (fold-ins, feedback) never
    oscillates between factor spaces.

This is the same determinism contract as the fleet's shard plan
(serving_fleet/plan.py ``shard_of``), applied to the traffic dimension.
"""

from __future__ import annotations

from pio_tpu.utils.durable import crc32c


def canary_bucket(user_id) -> int:
    """The user's permanent 0-99 bucket (stable across processes)."""
    return crc32c(str(user_id).encode("utf-8")) % 100


def in_canary(user_id, pct: float) -> bool:
    """True when `user_id` belongs to a `pct`-percent canary. pct <= 0
    selects nobody; pct >= 100 selects everybody (the promote ramp's
    final stage)."""
    if pct <= 0:
        return False
    if pct >= 100:
        return True
    return canary_bucket(user_id) < pct
