"""Rollout records: the durable verdict on every canaried instance.

A rollout's lifecycle state is recorded in the MODELDATA repository
alongside the candidate EngineInstance's own blob (the same pattern the
fleet uses for shard plans, serving_fleet/plan.py):

  ``<instance>:rollout`` — JSON (CRC32C-framed via utils/durable) with
  the stage ladder, the current stage, the verdict
  (in-flight | PROMOTED | ROLLED_BACK), the reason, and the guard
  evidence that justified the last transition.

The record is what makes rollback STICK: ``serve``'s instance
resolution, the fleet's ``partitioned_instances``, and the fold-in
worker's model refresh all consult ``is_auto_advance_eligible`` before
auto-advancing onto a newer COMPLETED instance — a ROLLED_BACK
instance (or one whose canary is still in flight in another process)
is skipped, so no reload/restart can quietly re-serve a model the
guards already rejected. Operators can still pin a rolled-back
instance explicitly (``--engine-instance-id``); the record blocks only
AUTOMATIC advancement.
"""

from __future__ import annotations

import json
import logging
from dataclasses import asdict, dataclass, field

from pio_tpu.utils.durable import ModelIntegrityError, frame, unframe
from pio_tpu.utils.time import format_time, utcnow

log = logging.getLogger("pio_tpu.rollout")

VERDICT_IN_FLIGHT = "IN_FLIGHT"
VERDICT_PROMOTED = "PROMOTED"
VERDICT_ROLLED_BACK = "ROLLED_BACK"


def rollout_model_id(instance_id: str) -> str:
    return f"{instance_id}:rollout"


@dataclass
class RolloutRecord:
    """One canaried instance's durable rollout state (see module doc)."""

    instance_id: str                 # the candidate being rolled out
    baseline_instance_id: str        # last-good active at begin time
    stages: tuple[int, ...]          # the pct ladder, e.g. (1, 5, 25, 100)
    stage_pct: int                   # current/final canary percentage
    verdict: str                     # IN_FLIGHT | PROMOTED | ROLLED_BACK
    reason: str = ""                 # operator/guard justification
    evidence: dict = field(default_factory=dict)  # guard snapshot
    updated: str = ""                # ISO time of the last transition

    def to_json(self) -> str:
        return json.dumps(asdict(self), sort_keys=True)

    @staticmethod
    def from_json(text: str) -> "RolloutRecord":
        d = json.loads(text)
        return RolloutRecord(
            instance_id=d["instance_id"],
            baseline_instance_id=d["baseline_instance_id"],
            stages=tuple(int(s) for s in d["stages"]),
            stage_pct=int(d["stage_pct"]),
            verdict=d["verdict"],
            reason=d.get("reason", ""),
            evidence=d.get("evidence", {}),
            updated=d.get("updated", ""),
        )


def save_transition(storage, model_id: str, record):
    """The shared transition writer: stamp ``updated``, frame, upsert —
    the one durability discipline every controller-style state machine
    (rollout records here, the fleet's reshard records in
    serving_fleet/reshard.py) persists its transitions through. The
    record needs only a mutable ``updated`` attribute and ``to_json``."""
    from pio_tpu.data.dao import Model

    record.updated = format_time(utcnow())
    storage.get_model_data_models().insert(Model(
        model_id, frame(record.to_json().encode("utf-8"))))
    return record


def save_record(storage, record: RolloutRecord) -> RolloutRecord:
    """Persist (upsert) the record, CRC32C-framed; stamps `updated`.
    This is the ONLY writer of rollout state — controller transitions
    call it, nothing else does (the `rollout-state` lint rule keeps it
    that way)."""
    return save_transition(storage, rollout_model_id(record.instance_id),
                           record)


def load_record(storage, instance_id: str) -> RolloutRecord | None:
    """The instance's rollout record, or None when it was never
    canaried. Raises ModelIntegrityError on a corrupt record blob."""
    rec = storage.get_model_data_models().get(rollout_model_id(instance_id))
    if rec is None:
        return None
    return RolloutRecord.from_json(
        unframe(rec.models, source=rollout_model_id(instance_id))
        .decode("utf-8"))


def is_auto_advance_eligible(storage, instance_id: str) -> bool:
    """May serve/fleet/fold-in AUTO-advance onto this instance?

    Eligible: never canaried (no record) or PROMOTED. Not eligible:
    ROLLED_BACK (the guards rejected it — permanently), IN_FLIGHT (its
    canary is still being judged; a restart mid-canary must stay on the
    baseline, not jump to 100% of the thing under test), or a corrupt
    record (fail safe: if we cannot read the verdict, assume the worst).
    """
    try:
        record = load_record(storage, instance_id)
    except ModelIntegrityError as e:
        log.error("rollout record for instance %s is corrupt (%s); "
                  "treating it as NOT eligible", instance_id, e)
        return False
    return record is None or record.verdict == VERDICT_PROMOTED


def rollback_abandoned(storage, engine_id: str, engine_version: str,
                       engine_variant: str,
                       reason: str) -> RolloutRecord | None:
    """Conclude an ORPHANED canary: the newest IN_FLIGHT record among
    the engine's COMPLETED instances is flipped to ROLLED_BACK (and
    returned), or None when nothing is in flight. A serving process
    that crashes mid-canary leaves an IN_FLIGHT record no controller
    owns anymore — it correctly blocks auto-advance (a restart must not
    jump to 100% of the thing under test), but without this the
    operator could never conclude it: ``pio rollback`` against a fresh
    process answered "no rollout in flight" forever."""
    import dataclasses

    instances = storage.get_metadata_engine_instances()
    for inst in instances.get_completed(engine_id, engine_version,
                                        engine_variant):
        try:
            record = load_record(storage, inst.id)
        except ModelIntegrityError:
            continue        # corrupt record: already not eligible
        if record is not None and record.verdict == VERDICT_IN_FLIGHT:
            return save_record(storage, dataclasses.replace(
                record, verdict=VERDICT_ROLLED_BACK, reason=reason))
    return None


def eligible_completed(storage, engine_id: str, engine_version: str,
                       engine_variant: str) -> list:
    """COMPLETED instances auto-advance may consider, newest first —
    ``get_completed`` minus rolled-back / in-flight canaries."""
    instances = storage.get_metadata_engine_instances()
    return [
        i for i in instances.get_completed(engine_id, engine_version,
                                           engine_variant)
        if is_auto_advance_eligible(storage, i.id)
    ]


def latest_eligible_completed(storage, engine_id: str, engine_version: str,
                              engine_variant: str):
    out = eligible_completed(storage, engine_id, engine_version,
                             engine_variant)
    return out[0] if out else None
