"""Live rollout guards: the evidence a canary stage must keep green.

Four guards, all computed from traffic the canary actually served (no
offline eval pass — the point of staged exposure is that production
traffic IS the eval set):

  * ``error_rate``  — candidate-arm exceptions per request;
  * ``latency``     — candidate mean latency as a multiple of the
                      active arm's (both arms measured on the same
                      process over the same window, so host noise
                      cancels);
  * ``empty_rate``  — empty or flagged-degraded responses on the
                      candidate arm (a model that converged badly often
                      fails soft: 200s full of nothing);
  * ``divergence``  — score-distribution drift vs the active arm,
                      measured by shadow-scoring a sample of
                      candidate-arm queries on BOTH models and
                      comparing the top-k item sets (1 - Jaccard). A
                      retrain is EXPECTED to move rankings somewhat;
                      the guard catches wholesale disagreement (skewed
                      fold, bad hyperparams, silent data regression).

Every guard stays ``pending`` (green) until its minimum sample count is
reached — a 1% stage on low traffic must not be judged on three
requests. Evaluation is pure (stats in, verdict out) so the controller
can persist the exact evidence that justified a transition.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class GuardConfig:
    """Breach thresholds. Defaults are deliberately loose — a canary
    should die for being WRONG, not for p50 jitter on a busy host."""

    max_error_rate: float = 0.05      # candidate errors / requests
    max_latency_ratio: float = 3.0    # candidate mean / active mean
    max_empty_rate: float = 0.25      # empty or degraded / requests
    max_divergence: float = 0.5       # mean (1 - topk Jaccard) vs active
    min_samples: int = 20             # per-arm requests before judging
    min_shadow_samples: int = 10      # shadow pairs before judging

    def to_dict(self) -> dict:
        return {
            "maxErrorRate": self.max_error_rate,
            "maxLatencyRatio": self.max_latency_ratio,
            "maxEmptyRate": self.max_empty_rate,
            "maxDivergence": self.max_divergence,
            "minSamples": self.min_samples,
            "minShadowSamples": self.min_shadow_samples,
        }


class ArmStats:
    """Per-arm request counters for one rollout stage. NOT internally
    locked: the owning RolloutController mutates and reads it under its
    own lock (one lock for the whole decision state, so a guard
    evaluation always sees a consistent snapshot)."""

    def __init__(self) -> None:
        self.requests = 0
        self.errors = 0
        self.empty = 0
        self.latency_total_s = 0.0

    def record(self, latency_s: float, error: bool, empty: bool) -> None:
        self.requests += 1
        if error:
            self.errors += 1
        if empty:
            self.empty += 1
        self.latency_total_s += max(0.0, latency_s)

    @property
    def mean_latency_s(self) -> float:
        return self.latency_total_s / self.requests if self.requests else 0.0

    def snapshot(self) -> dict:
        return {
            "requests": self.requests,
            "errors": self.errors,
            "empty": self.empty,
            "meanLatencySeconds": round(self.mean_latency_s, 6),
        }


class ShadowStats:
    """Divergence accumulator (same locking contract as ArmStats)."""

    def __init__(self) -> None:
        self.samples = 0
        self.divergence_total = 0.0

    def record(self, divergence: float) -> None:
        self.samples += 1
        self.divergence_total += min(1.0, max(0.0, divergence))

    @property
    def mean(self) -> float:
        return self.divergence_total / self.samples if self.samples else 0.0

    def snapshot(self) -> dict:
        return {"samples": self.samples,
                "meanDivergence": round(self.mean, 4)}


def prediction_divergence(a, b) -> float:
    """1 - Jaccard similarity of the two predictions' recommended item
    sets (1.0 = total disagreement). Non-dict / score-less predictions
    compare by equality — engines outside the itemScores shape still
    get a coarse agreement signal."""
    a_items = _item_set(a)
    b_items = _item_set(b)
    if a_items is None or b_items is None:
        return 0.0 if a == b else 1.0
    if not a_items and not b_items:
        return 0.0
    union = a_items | b_items
    return 1.0 - len(a_items & b_items) / len(union)


def _item_set(p) -> set | None:
    if not isinstance(p, dict):
        return None
    scores = p.get("itemScores")
    if not isinstance(scores, list):
        return None
    out = set()
    for s in scores:
        if isinstance(s, dict) and "item" in s:
            out.add(s["item"])
    return out


def is_empty_response(prediction) -> bool:
    """Empty/degraded-response classifier for the ``empty_rate`` guard:
    a dict prediction with no itemScores, or one flagged degraded by
    the fleet router's fallback path."""
    if not isinstance(prediction, dict):
        return False
    if prediction.get("degraded"):
        return True
    if "itemScores" in prediction:
        return not prediction["itemScores"]
    return False


def evaluate_guards(active: ArmStats, candidate: ArmStats,
                    shadow: ShadowStats,
                    config: GuardConfig) -> tuple[bool, dict]:
    """-> (all green, per-guard evidence). Pure: the caller holds its
    lock and passes consistent stats. Each guard's evidence carries
    ok/value/threshold (+ pending while under-sampled) so a breach
    verdict persisted to the rollout record is self-explanatory."""
    evidence: dict = {}

    judged = candidate.requests >= config.min_samples
    err = (candidate.errors / candidate.requests
           if candidate.requests else 0.0)
    evidence["error_rate"] = {
        "ok": (not judged) or err <= config.max_error_rate,
        "value": round(err, 4), "threshold": config.max_error_rate,
        "pending": not judged,
    }

    lat_judged = (judged and active.requests >= config.min_samples
                  and active.mean_latency_s > 0)
    ratio = (candidate.mean_latency_s / active.mean_latency_s
             if lat_judged else 0.0)
    evidence["latency"] = {
        "ok": (not lat_judged) or ratio <= config.max_latency_ratio,
        "value": round(ratio, 3), "threshold": config.max_latency_ratio,
        "pending": not lat_judged,
    }

    empty = (candidate.empty / candidate.requests
             if candidate.requests else 0.0)
    evidence["empty_rate"] = {
        "ok": (not judged) or empty <= config.max_empty_rate,
        "value": round(empty, 4), "threshold": config.max_empty_rate,
        "pending": not judged,
    }

    div_judged = shadow.samples >= config.min_shadow_samples
    evidence["divergence"] = {
        "ok": (not div_judged) or shadow.mean <= config.max_divergence,
        "value": round(shadow.mean, 4),
        "threshold": config.max_divergence,
        "pending": not div_judged,
    }

    return all(g["ok"] for g in evidence.values()), evidence
