"""RolloutController: guarded promotion of a candidate EngineInstance.

The controller owns ONE canary at a time on a serving host (the
single-host ``QueryServer`` or the fleet ``FleetRouter`` — anything
implementing the small host protocol below). It

  1. loads the candidate ALONGSIDE the active model (second model slot
     behind the host's existing swap lock — never a wholesale swap),
  2. splits traffic deterministically (``split.in_canary``: sticky
     ``crc32c(user) % 100``), ramping through configured stages only
     while the live guards (guards.py) stay green,
  3. shadow-scores a sample of candidate-arm queries on the ACTIVE
     model to measure score divergence between the arms,
  4. on ANY guard breach — or an operator ``pio rollback`` — atomically
     reverts 100% of traffic to the active (last-good) instance, and
  5. records every transition durably (state.py: the
     ``<iid>:rollout`` record in MODELDATA), so PROMOTED survives a
     restart and a ROLLED_BACK instance is never auto-advanced onto
     again.

Host protocol (duck-typed; implemented by QueryServer and FleetRouter):

  ``rollout_active_instance_id() -> str``
  ``load_candidate(instance_id)``   — load the second arm; raise on any
                                      failure (nothing swapped)
  ``promote_candidate()``           — candidate becomes the active arm
  ``drop_candidate()``              — discard the candidate arm
  ``shadow_predict(q, arm) -> prediction`` — score `q` on one arm
                                      without recording stats
  attribute ``rollout``             — the attached controller (or None)

Chaos points: ``rollout.guard`` fires inside every guard evaluation (an
injected ConnectionError IS a breach — the drill's lever) and
``rollout.promote`` inside the promote transition.

Concurrency: every stage/verdict write goes through ``_transition``
under ``self._lock`` and persists via ``state.save_record`` (the
``rollout-state`` lint rule enforces both); host mutations
(drop/promote) run OUTSIDE the lock so the controller can never hold
its lock across the host's.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

from pio_tpu.resilience import chaos
from pio_tpu.rollout import state as rstate
from pio_tpu.rollout.guards import (
    ArmStats, GuardConfig, ShadowStats, evaluate_guards, is_empty_response,
    prediction_divergence,
)
from pio_tpu.rollout.split import in_canary

log = logging.getLogger("pio_tpu.rollout")

ARM_ACTIVE = "active"
ARM_CANDIDATE = "candidate"

DEFAULT_STAGES = (1, 5, 25, 100)


class CandidateLoadError(RuntimeError):
    """The candidate could not be loaded on (part of) the serving
    layer; the rollout was auto-rolled-back before ANY traffic hit it."""


class RolloutGuardBreach(RuntimeError):
    """Promote refused: at least one guard is red."""

    def __init__(self, evidence: dict):
        super().__init__(f"guards not green: "
                         f"{[g for g, e in evidence.items() if not e.get('ok')]}")
        self.evidence = evidence


@dataclass
class RolloutConfig:
    """Canary shape. ``stages`` is the ramp ladder; a fixed-pct deploy
    is a one-stage ladder. ``auto`` advances through the ladder
    unattended while guards stay green (promote itself remains an
    explicit command)."""

    stages: tuple[int, ...] = DEFAULT_STAGES
    auto: bool = False
    min_stage_samples: int = 50     # candidate requests before advancing
    min_stage_seconds: float = 30.0
    shadow_every: int = 10          # shadow-score every Nth candidate query
    check_every: int = 5            # guard evaluation cadence (requests)
    tick_interval_s: float = 1.0    # auto-ramp timer; 0 = traffic-driven only
    guards: GuardConfig = field(default_factory=GuardConfig)


class RolloutController:
    """One guarded rollout (see module docstring)."""

    def __init__(self, storage, host, candidate_instance_id: str,
                 baseline_instance_id: str,
                 config: RolloutConfig | None = None):
        self.storage = storage
        self.host = host
        self.candidate_instance_id = candidate_instance_id
        self.baseline_instance_id = baseline_instance_id
        self.config = config or RolloutConfig()
        if not self.config.stages:
            raise ValueError("rollout needs at least one stage pct")
        self._lock = threading.RLock()
        # serializes the two CONCLUDING paths (promote / rollback) end
        # to end, INCLUDING their host mutations: a guard breach firing
        # mid-promote-fan must wait and then see the PROMOTED verdict
        # (no-op), never interleave its drop fan with the promote fan —
        # on a fleet that interleaving leaves shard groups serving the
        # rolled-back instance as active (skew) or overwrites a
        # persisted ROLLED_BACK with PROMOTED
        self._conclude_lock = threading.Lock()
        self.stage_index = 0
        self.verdict: str | None = None   # None = in flight
        self.reason = ""
        self.stage_started = time.monotonic()
        self.start_time = time.monotonic()
        self.active_stats = ArmStats()
        self.candidate_stats = ArmStats()
        self.shadow_stats = ShadowStats()
        self.last_evidence: dict = {}
        self._ticker: threading.Thread | None = None
        self._stop = threading.Event()
        # shadow scoring runs OFF the serving request thread (a shadow
        # is a full second prediction — inline it would double every
        # shadow_every-th canary request's latency); single slot,
        # skip-if-busy, so the sampler can never queue up behind a slow
        # arm either
        self._shadow_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="rollout-shadow")
        self._shadow_inflight = False

    # -- lifecycle -----------------------------------------------------------
    @classmethod
    def begin(cls, storage, host, candidate_instance_id: str,
              config: RolloutConfig | None = None) -> "RolloutController":
        """Create, persist the IN_FLIGHT record, and load the candidate
        arm. A load failure anywhere rolls the record to ROLLED_BACK
        (zero traffic ever reached the arm) and raises
        CandidateLoadError."""
        ctl = cls(storage, host, candidate_instance_id,
                  host.rollout_active_instance_id(), config)
        ctl._transition()  # durable IN_FLIGHT at stage 0
        try:
            host.load_candidate(candidate_instance_id)
        except Exception as e:
            ctl.rollback(reason=f"candidate load failed: "
                                f"{type(e).__name__}: {e}")
            raise CandidateLoadError(
                f"candidate {candidate_instance_id} could not be loaded "
                f"({e}); rollout rolled back before serving any traffic"
            ) from e
        host.rollout = ctl
        ctl._start_ticker()
        log.info("rollout begun: candidate %s vs baseline %s, stages %s",
                 candidate_instance_id, ctl.baseline_instance_id,
                 ctl.config.stages)
        return ctl

    def _start_ticker(self) -> None:
        if not (self.config.auto and self.config.tick_interval_s > 0):
            return
        self._ticker = threading.Thread(
            target=self._tick_loop, name="rollout-ticker", daemon=True)
        self._ticker.start()

    def _tick_loop(self) -> None:
        while not self._stop.wait(timeout=self.config.tick_interval_s):
            with self._lock:
                if self.verdict is not None:
                    return
            self._maybe_react()

    def close(self) -> None:
        self._stop.set()
        self._shadow_pool.shutdown(wait=False)

    # -- the single state-writer ---------------------------------------------
    def _transition(self, stage_index: int | None = None,
                    verdict: str | None = None, reason: str = "",
                    evidence: dict | None = None) -> None:
        """THE ONLY writer of stage/verdict state. Callers hold or take
        ``self._lock`` here; the new state is persisted durably (CRC32C-
        framed MODELDATA record) before the method returns, so every
        observable transition is also a recovered-after-restart one."""
        with self._lock:
            if stage_index is not None:
                self.stage_index = stage_index
                self.stage_started = time.monotonic()
            if verdict is not None:
                self.verdict = verdict
            if reason:
                self.reason = reason
            if evidence is not None:
                self.last_evidence = evidence
            record = rstate.RolloutRecord(
                instance_id=self.candidate_instance_id,
                baseline_instance_id=self.baseline_instance_id,
                stages=tuple(self.config.stages),
                stage_pct=self.stage_pct(),
                verdict=self.verdict or rstate.VERDICT_IN_FLIGHT,
                reason=self.reason,
                evidence=self.last_evidence,
            )
        rstate.save_record(self.storage, record)

    # -- traffic split -------------------------------------------------------
    def stage_pct(self) -> int:
        with self._lock:
            if self.verdict == rstate.VERDICT_ROLLED_BACK:
                return 0
            if self.verdict == rstate.VERDICT_PROMOTED:
                return 100
            return int(self.config.stages[self.stage_index])

    def arm_for(self, query) -> str:
        """Which arm serves this query. Sticky and deterministic:
        ``crc32c(user) % 100 < stage_pct``. Queries without a user field
        (and all traffic after a verdict) ride the active arm."""
        with self._lock:
            if self.verdict is not None:
                return ARM_ACTIVE
            pct = int(self.config.stages[self.stage_index])
        user = query.get("user") if isinstance(query, dict) else None
        if user is None:
            return ARM_ACTIVE
        return ARM_CANDIDATE if in_canary(user, pct) else ARM_ACTIVE

    # -- observation ---------------------------------------------------------
    def observe(self, arm: str, query, prediction, latency_s: float,
                error: bool = False) -> None:
        """Record one served request and react: shadow-score a sample
        of candidate traffic, and evaluate guards every
        ``check_every`` candidate requests. Called from the host's
        query path OUTSIDE its locks."""
        shadow_due = False
        with self._lock:
            if self.verdict is not None:
                return
            stats = (self.candidate_stats if arm == ARM_CANDIDATE
                     else self.active_stats)
            stats.record(latency_s, error,
                         (not error) and is_empty_response(prediction))
            if arm == ARM_CANDIDATE:
                # guards evaluate on ERRORED candidate requests too —
                # the error_rate guard exists precisely for a candidate
                # that crashes the predict path, and in fixed-pct mode
                # (no ticker) observe() is the only trigger
                n = self.candidate_stats.requests
                shadow_due = (not error
                              and self.config.shadow_every > 0
                              and n % self.config.shadow_every == 0
                              and not self._shadow_inflight)
                if shadow_due:
                    self._shadow_inflight = True
                check_due = n % max(1, self.config.check_every) == 0
            else:
                check_due = False
        if shadow_due:
            try:
                self._shadow_pool.submit(self._shadow_sample, query,
                                         prediction)
            except RuntimeError:        # pool shut down (close() raced)
                with self._lock:
                    self._shadow_inflight = False
        if check_due:
            self._maybe_react()

    def _shadow_sample(self, query, prediction) -> None:
        """Score one candidate-arm query on the active arm and record
        the divergence — on the shadow thread, never the request's."""
        try:
            other = self.host.shadow_predict(query, ARM_ACTIVE)
            div = prediction_divergence(prediction, other)
            with self._lock:
                self.shadow_stats.record(div)
        except Exception as e:  # noqa: BLE001 - shadow is best-effort
            log.warning("shadow scoring failed: %s", e)
        finally:
            with self._lock:
                self._shadow_inflight = False

    def _maybe_react(self) -> None:
        """Evaluate guards (under the ``rollout.guard`` chaos point):
        a breach rolls back immediately; green guards may auto-advance
        the stage ladder."""
        with self._lock:
            if self.verdict is not None:
                return
            breach_reason = ""
            try:
                chaos.maybe_inject("rollout.guard")
                ok, evidence = evaluate_guards(
                    self.active_stats, self.candidate_stats,
                    self.shadow_stats, self.config.guards)
            except ConnectionError as e:
                # drill lever: injected failure at the guard point IS a
                # breach — the rollback path must behave identically
                ok, evidence = False, {
                    "chaos": {"ok": False, "error": str(e)}}
                breach_reason = f"chaos at rollout.guard: {e}"
            self.last_evidence = evidence
            if ok:
                advance = (self.config.auto
                           and self.stage_index < len(self.config.stages) - 1
                           and self.candidate_stats.requests
                           >= self.config.min_stage_samples
                           and (time.monotonic() - self.stage_started)
                           >= self.config.min_stage_seconds)
            else:
                advance = False
                if not breach_reason:
                    red = [g for g, e in evidence.items()
                           if not e.get("ok")]
                    breach_reason = f"guard breach: {', '.join(red)}"
        if not ok:
            self.rollback(reason=breach_reason, evidence=evidence)
            return
        if advance:
            with self._lock:
                if self.verdict is not None:
                    return
                nxt = self.stage_index + 1
                # fresh evidence per stage: a 1% stage's stats must not
                # pre-judge (or pre-absolve) the 25% stage
                self.active_stats = ArmStats()
                self.candidate_stats = ArmStats()
                self.shadow_stats = ShadowStats()
                self._transition(stage_index=nxt)
            log.info("rollout advanced to stage %d%% (candidate %s)",
                     self.stage_pct(), self.candidate_instance_id)

    # -- verdicts ------------------------------------------------------------
    def rollback(self, reason: str = "operator rollback",
                 evidence: dict | None = None) -> dict:
        """Atomically revert 100% of traffic to the active instance and
        record ROLLED_BACK. Idempotent; the verdict flips under the
        lock FIRST (``arm_for`` answers active from that instant), then
        the candidate arm is dropped outside the lock. Serialized with
        promote() by ``_conclude_lock`` — a breach firing mid-promote
        waits, then no-ops against the PROMOTED verdict instead of
        racing its drop fan against the promote fan."""
        with self._conclude_lock:
            with self._lock:
                if self.verdict is not None:
                    return self.status()
                self._transition(verdict=rstate.VERDICT_ROLLED_BACK,
                                 reason=reason,
                                 evidence=evidence or self.last_evidence)
            self._stop.set()
            self._shadow_pool.shutdown(wait=False)
            try:
                self.host.drop_candidate()
            except Exception as e:  # noqa: BLE001 - traffic already
                log.warning("dropping candidate arm failed (traffic "
                            "already on the active arm): %s", e)
        log.warning("rollout ROLLED_BACK (candidate %s): %s",
                    self.candidate_instance_id, reason)
        return self.status()

    def promote(self) -> dict:
        """Candidate becomes the active instance at 100%. Refused while
        any guard is red (RolloutGuardBreach); wrapped in the
        ``rollout.promote`` chaos point — an injected failure leaves
        the rollout in flight, nothing swapped. Holds ``_conclude_lock``
        across the host swap so a concurrent guard-breach rollback can
        never interleave with (or overwrite the verdict of) the
        promote."""
        with self._conclude_lock:
            with self._lock:
                if self.verdict == rstate.VERDICT_PROMOTED:
                    return self.status()
                if self.verdict is not None:
                    raise ValueError(
                        f"rollout already concluded: {self.verdict}")
                chaos.maybe_inject("rollout.promote")
                ok, evidence = evaluate_guards(
                    self.active_stats, self.candidate_stats,
                    self.shadow_stats, self.config.guards)
                self.last_evidence = evidence
                if not ok:
                    raise RolloutGuardBreach(evidence)
            # swap OUTSIDE the controller lock (host takes its own
            # locks); a failure here leaves the rollout in flight and
            # the record IN_FLIGHT — restart then serves the baseline,
            # never half a promote
            self.host.promote_candidate()
            with self._lock:
                self._transition(stage_index=len(self.config.stages) - 1,
                                 verdict=rstate.VERDICT_PROMOTED,
                                 reason="promoted", evidence=evidence)
            self._stop.set()
            # concluded controllers are replaced, not close()d — free
            # the shadow worker now or each canary leaks a thread
            self._shadow_pool.shutdown(wait=False)
        log.info("rollout PROMOTED: %s now active",
                 self.candidate_instance_id)
        return self.status()

    # -- observability -------------------------------------------------------
    def status(self) -> dict:
        with self._lock:
            return {
                "active": self.verdict is None,
                "candidateInstanceId": self.candidate_instance_id,
                "baselineInstanceId": self.baseline_instance_id,
                "stages": list(self.config.stages),
                "stageIndex": self.stage_index,
                "stagePct": self.stage_pct(),
                "verdict": self.verdict,
                "reason": self.reason,
                "auto": self.config.auto,
                "timeInStageSeconds": round(
                    time.monotonic() - self.stage_started, 3),
                "arms": {
                    ARM_ACTIVE: self.active_stats.snapshot(),
                    ARM_CANDIDATE: self.candidate_stats.snapshot(),
                },
                "shadow": self.shadow_stats.snapshot(),
                "guards": self.last_evidence,
                "guardConfig": self.config.guards.to_dict(),
            }


# -- HTTP surface (shared by the single-host server and the router) ----------

def install_rollout_routes(app, host, storage, check_server_key) -> None:
    """Wire the rollout verbs onto a serving HttpApp:

      POST /rollout/deploy   {"pct": n | "auto": true, "instanceId"?, ...}
      POST /rollout/promote
      POST /rollout/rollback {"reason"?}
      GET  /rollout/status

    Mutating routes are server-key guarded like /reload — they move
    production traffic."""

    def _controller():
        return getattr(host, "rollout", None)

    # serializes the in-flight check against begin(): two concurrent
    # deploys must not BOTH pass the check and create two controllers
    # (last-writer-wins on host.rollout, with the loser's ticker still
    # able to drop the winner's live candidate arm)
    deploy_lock = threading.Lock()

    @app.route("POST", r"/rollout/deploy")
    def rollout_deploy(req):
        if not check_server_key(req):
            return 401, {"message": "Invalid accessKey."}
        if storage is None:
            return 503, {"message": "no storage configured; rollout "
                                    "records cannot be persisted"}
        try:
            body = req.json() or {}
        except Exception as e:  # noqa: BLE001 - malformed body
            return 400, {"message": f"Invalid body: {e}"}
        if not isinstance(body, dict):
            return 400, {"message": "body must be a JSON object"}
        try:
            config = _config_from_body(body)
        except (TypeError, ValueError) as e:
            return 400, {"message": str(e)}
        with deploy_lock:
            ctl = _controller()
            if ctl is not None and ctl.verdict is None:
                return 409, {"message": "a rollout is already in flight",
                             "rollout": ctl.status()}
            active_id = host.rollout_active_instance_id()
            candidate = body.get("instanceId")
            if candidate is None:
                c = host.config
                latest = rstate.latest_eligible_completed(
                    storage, c.engine_id, c.engine_version,
                    c.engine_variant)
                candidate = latest.id if latest is not None else None
            if candidate is None or candidate == active_id:
                return 409, {"message": "no candidate instance newer than "
                                        f"the active one ({active_id}); "
                                        "train first or pass instanceId"}
            try:
                ctl = RolloutController.begin(storage, host, candidate,
                                              config)
            except CandidateLoadError as e:
                return 503, {"message": str(e),
                             "verdict": rstate.VERDICT_ROLLED_BACK,
                             "candidateInstanceId": candidate}
        return 200, {"message": "canary serving", "rollout": ctl.status()}

    @app.route("POST", r"/rollout/promote")
    def rollout_promote(req):
        if not check_server_key(req):
            return 401, {"message": "Invalid accessKey."}
        ctl = _controller()
        if ctl is None:
            return 409, {"message": "no rollout in flight"}
        try:
            status = ctl.promote()
        except RolloutGuardBreach as e:
            return 409, {"message": f"promote refused: {e}",
                         "guards": e.evidence}
        except ValueError as e:
            return 409, {"message": str(e), "rollout": ctl.status()}
        except ConnectionError as e:
            # rollout.promote chaos / transport failure mid-promote:
            # nothing swapped, rollout still in flight
            return 503, {"message": f"promote failed: {e}",
                         "rollout": ctl.status()}
        return 200, {"message": "Promoted", "rollout": status}

    @app.route("POST", r"/rollout/rollback")
    def rollout_rollback(req):
        if not check_server_key(req):
            return 401, {"message": "Invalid accessKey."}
        try:
            body = req.json() or {}
        except Exception:  # noqa: BLE001 - body is optional
            body = {}
        reason = (body.get("reason") if isinstance(body, dict) else None) \
            or "operator rollback"
        ctl = _controller()
        if ctl is None:
            # no live controller, but a crashed canary may have left an
            # orphaned IN_FLIGHT record (blocking that instance's
            # auto-advance forever) — `pio rollback` is the documented
            # one-command way out, so conclude it here
            if storage is not None:
                c = host.config
                orphan = rstate.rollback_abandoned(
                    storage, c.engine_id, c.engine_version,
                    c.engine_variant,
                    reason=f"{reason} (abandoned canary: no rollout in "
                           "flight in this process)")
                if orphan is not None:
                    return 200, {
                        "message": "Rolled back an abandoned canary "
                                   "record (no rollout was in flight in "
                                   "this process)",
                        "instanceId": orphan.instance_id,
                        "verdict": orphan.verdict,
                    }
            return 409, {"message": "no rollout in flight"}
        return 200, {"message": "Rolled back",
                     "rollout": ctl.rollback(reason=reason)}

    # pio: lint-ok[route-unguarded] read-only status surface,
    # deliberately open like / and /metrics — `pio doctor` and the
    # deploy watchdogs poll it without a server key
    @app.route("GET", r"/rollout/status")
    def rollout_status(req):
        ctl = _controller()
        if ctl is None:
            return 200, {"active": False}
        return 200, ctl.status()


def _config_from_body(body: dict) -> RolloutConfig:
    """Parse the /rollout/deploy knobs into a RolloutConfig. ``pct``
    yields a one-stage ladder (operator promotes manually); ``auto``
    rides the default (or given) ladder unattended."""
    auto = bool(body.get("auto", False))
    stages = body.get("stages")
    if stages is not None:
        stages = tuple(int(s) for s in stages)
    elif auto:
        stages = DEFAULT_STAGES
    else:
        pct = body.get("pct")
        if pct is None:
            raise ValueError("body needs \"pct\": n or \"auto\": true")
        pct = int(pct)
        if not 0 < pct <= 100:
            raise ValueError(f"pct must be in (0, 100], got {pct}")
        stages = (pct,)
    if any(not 0 < int(s) <= 100 for s in stages):
        raise ValueError(f"stage pcts must be in (0, 100]: {stages}")
    guards = GuardConfig()
    overrides = body.get("guards") or {}
    if not isinstance(overrides, dict):
        raise ValueError("\"guards\" must be an object")
    mapping = {
        "maxErrorRate": "max_error_rate",
        "maxLatencyRatio": "max_latency_ratio",
        "maxEmptyRate": "max_empty_rate",
        "maxDivergence": "max_divergence",
        "minSamples": "min_samples",
        "minShadowSamples": "min_shadow_samples",
    }
    for key, attr in mapping.items():
        if key in overrides:
            setattr(guards, attr, type(getattr(guards, attr))(
                overrides[key]))
    # only keys PRESENT in the body override; absent ones defer to the
    # dataclass defaults (restating them here would silently fork the
    # HTTP path from a tuned RolloutConfig default)
    kwargs = {}
    for key, attr, cast in (
        ("minStageSamples", "min_stage_samples", int),
        ("minStageSeconds", "min_stage_seconds", float),
        ("shadowEvery", "shadow_every", int),
        ("checkEvery", "check_every", int),
        ("tickIntervalS", "tick_interval_s", float),
    ):
        if key in body:
            kwargs[attr] = cast(body[key])
    return RolloutConfig(stages=stages, auto=auto, guards=guards, **kwargs)
