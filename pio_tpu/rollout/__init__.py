"""Guarded model rollout: canary traffic splitting, live divergence
guards, and one-command instant rollback (docs/serving.md "Guarded
rollout").

The reference MasterActor swaps a newly trained model in wholesale;
this package replaces that all-or-nothing semantics with staged
exposure: a candidate EngineInstance is loaded ALONGSIDE the active
one, traffic splits deterministically (``crc32c(user) % 100``, sticky
per user), ramp stages advance only while live guards stay green, and
any breach — or ``pio rollback`` — reverts 100% of traffic atomically
and records a durable ROLLED_BACK verdict that reload paths respect
forever after.
"""

from pio_tpu.rollout.controller import (
    ARM_ACTIVE,
    ARM_CANDIDATE,
    DEFAULT_STAGES,
    CandidateLoadError,
    RolloutConfig,
    RolloutController,
    RolloutGuardBreach,
    install_rollout_routes,
)
from pio_tpu.rollout.guards import (
    ArmStats,
    GuardConfig,
    ShadowStats,
    evaluate_guards,
    is_empty_response,
    prediction_divergence,
)
from pio_tpu.rollout.split import canary_bucket, in_canary
from pio_tpu.rollout.state import (
    VERDICT_IN_FLIGHT,
    VERDICT_PROMOTED,
    VERDICT_ROLLED_BACK,
    RolloutRecord,
    eligible_completed,
    is_auto_advance_eligible,
    latest_eligible_completed,
    load_record,
    rollout_model_id,
    save_record,
)

__all__ = [
    "ARM_ACTIVE", "ARM_CANDIDATE", "DEFAULT_STAGES", "ArmStats",
    "CandidateLoadError", "GuardConfig", "RolloutConfig",
    "RolloutController", "RolloutGuardBreach", "RolloutRecord",
    "ShadowStats", "VERDICT_IN_FLIGHT", "VERDICT_PROMOTED",
    "VERDICT_ROLLED_BACK", "canary_bucket", "eligible_completed",
    "evaluate_guards", "in_canary", "install_rollout_routes",
    "is_auto_advance_eligible", "is_empty_response",
    "latest_eligible_completed", "load_record", "prediction_divergence",
    "rollout_model_id", "save_record",
]
