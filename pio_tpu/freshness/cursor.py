"""Durable fold-in cursor: where the folder resumes after a restart.

The cursor is a boundary on EVENT TIME (microseconds since epoch, the
columnar path's native clock) plus per-user signatures AT the boundary
microsecond:

  * every tail poll re-reads from the boundary INCLUSIVE — an event
    that lands at exactly the boundary microsecond between polls is
    seen, never skipped;
  * the signatures (user → matching-event count in the boundary window)
    make that re-read cheap to deduplicate: a boundary user refolds
    only when its count changed, so steady state does no repeat work;
  * re-folding is idempotent anyway (a fold is a pure function of the
    user's FULL history and the item factors), so the crash contract is
    at-least-once per event with identical results — the cursor only
    advances AFTER a successful apply.

Persistence rides utils/durable.py (``durable_write``: tmp + fsync +
atomic rename + CRC32C frame): a folder killed mid-save leaves either
the previous complete cursor or the new complete cursor, and bit-rot is
detected at load instead of silently rewinding to event 0. The ``pio
lint`` ``foldin-cursor`` rule enforces that no cursor/offset
persistence in this package bypasses that module.
"""

from __future__ import annotations

import json
import logging
import os
from dataclasses import dataclass, field

from pio_tpu.utils.durable import (
    ModelIntegrityError, durable_read, durable_write,
)

log = logging.getLogger("pio_tpu.freshness")

CURSOR_VERSION = 1


@dataclass
class FoldCursor:
    """Resume state. ``time_us < 0`` means "from the beginning"."""

    time_us: int = -1
    # user id -> matching-event count in the window ending at time_us
    # (only users whose NEWEST event sits exactly at the boundary are
    # kept, so the map stays bounded by one microsecond of traffic)
    boundary: dict[str, int] = field(default_factory=dict)
    folded_total: int = 0          # lifetime applied fold-ins (observability)

    def to_json(self) -> str:
        return json.dumps({
            "version": CURSOR_VERSION,
            "timeUs": self.time_us,
            "boundary": self.boundary,
            "foldedTotal": self.folded_total,
        }, sort_keys=True)

    @staticmethod
    def from_json(text: str) -> "FoldCursor":
        d = json.loads(text)
        return FoldCursor(
            time_us=int(d.get("timeUs", -1)),
            boundary={str(k): int(v)
                      for k, v in (d.get("boundary") or {}).items()},
            folded_total=int(d.get("foldedTotal", 0)),
        )


class CursorStore:
    """Load/save a FoldCursor at a filesystem path, durably."""

    def __init__(self, path: str):
        self.path = path

    def load(self) -> FoldCursor:
        """The persisted cursor, or a fresh one when absent. A corrupt
        cursor file (failed CRC) is treated as absent — the folder then
        replays from the beginning, which is slow but correct (re-folds
        are idempotent); losing fold-ins would not be."""
        if not os.path.exists(self.path):
            return FoldCursor()
        try:
            return FoldCursor.from_json(
                durable_read(self.path).decode("utf-8"))
        except (ModelIntegrityError, ValueError, KeyError) as e:
            log.error("fold-in cursor %s unreadable (%s); replaying from "
                      "the beginning", self.path, e)
            return FoldCursor()

    def save(self, cursor: FoldCursor) -> None:
        os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                    exist_ok=True)
        durable_write(self.path, cursor.to_json().encode("utf-8"))
