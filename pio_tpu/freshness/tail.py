"""Event-stream tail over the columnar batch path.

A tail poll answers one question cheaply: WHICH users gained
interactions since the cursor? It rides ``find_columnar`` (PR 4's
struct-of-arrays read — no per-event Python objects) locally, or the
event server's ``GET /tail/events.json`` columnar route remotely, and
feeds the window computation in :func:`tail_window`.

The tail orders by EVENT TIME (the only time axis the storage query API
exposes). Server-stamped events — the normal ingest path, where
``eventTime`` defaults to receive time — tail losslessly; a client that
back-dates an event BEHIND the cursor is invisible to fold-in and is
picked up by the next full ``pio train`` (documented staleness
contract, docs/freshness.md). Events at exactly the boundary
microsecond are re-read every poll and deduplicated by the cursor's
per-user signatures, so the boundary can never drop a same-microsecond
straggler.

Folding then re-reads the touched users' FULL histories (per-entity
row reads — each is small) so the solve is a pure function of
(all of u's events, item factors): idempotent under replay, and
bit-comparable to a cold solve of the same events.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

import numpy as np

from pio_tpu.data.event import Event
from pio_tpu.freshness.cursor import FoldCursor

# microseconds <-> datetime helpers shared with the columnar layer
from pio_tpu.data.columnar import _micros, _restore_time  # noqa: F401


@dataclass
class TailWindow:
    """One tail poll's verdict (see module docstring)."""

    to_fold: dict = field(default_factory=dict)   # user id -> oldest new µs
    time_us: int = -1                             # new cursor boundary
    boundary: dict = field(default_factory=dict)  # new boundary signatures
    n_rows: int = 0                               # rows scanned this poll


def tail_window(user_ids: Sequence, time_us: np.ndarray,
                cursor: FoldCursor) -> TailWindow:
    """Window verdict from decoded (user id, event µs) rows at or after
    the cursor. Pure and source-agnostic: the local columnar read and
    the HTTP tail payload both land here."""
    n = len(time_us)
    if n == 0:
        return TailWindow(time_us=cursor.time_us,
                          boundary=dict(cursor.boundary))
    t = np.asarray(time_us, dtype=np.int64)
    ids = np.asarray(user_ids, dtype=object)
    new_time = int(t.max())
    # per-user: any strictly-newer row, or a changed count at the old
    # boundary microsecond, triggers a refold
    uniq_users: dict = {}
    for j in range(n):
        u = ids[j]
        rec = uniq_users.get(u)
        if rec is None:
            uniq_users[u] = rec = {"newer": False, "at_boundary": 0,
                                   "oldest": int(t[j])}
        else:
            rec["oldest"] = min(rec["oldest"], int(t[j]))
        if t[j] > cursor.time_us:
            rec["newer"] = True
        elif t[j] == cursor.time_us:
            rec["at_boundary"] += 1
    to_fold: dict = {}
    for u, rec in uniq_users.items():
        if rec["newer"] or rec["at_boundary"] != cursor.boundary.get(u, 0):
            to_fold[u] = rec["oldest"]
    at_new = t == new_time
    boundary: dict = {}
    for u in ids[at_new]:
        boundary[u] = boundary.get(u, 0) + 1
    return TailWindow(to_fold=to_fold, time_us=new_time, boundary=boundary,
                      n_rows=n)


class LocalEventSource:
    """Tail + per-user history straight off the storage DAO (the
    in-process folder shape: ``pio foldin`` next to the event store)."""

    def __init__(self, storage, app_name: str,
                 channel_name: str | None = None,
                 entity_type: str = "user",
                 target_entity_type: str = "item",
                 event_names: Sequence[str] = ("rate", "buy")):
        from pio_tpu.data.storage import StorageError

        self.storage = storage
        app = storage.get_metadata_apps().get_by_name(app_name)
        if app is None:
            raise StorageError(f"App {app_name!r} does not exist")
        self.app_id = app.id
        self.channel_id = None
        if channel_name is not None:
            for ch in storage.get_metadata_channels().get_by_appid(app.id):
                if ch.name == channel_name:
                    self.channel_id = ch.id
                    break
            else:
                raise StorageError(
                    f"Channel {channel_name!r} does not exist in app "
                    f"{app_name!r}")
        self.entity_type = entity_type
        self.target_entity_type = target_entity_type
        self.event_names = list(event_names)

    def window(self, cursor: FoldCursor) -> TailWindow:
        cols = self.storage.get_events().find_columnar(
            app_id=self.app_id,
            channel_id=self.channel_id,
            start_time=(_restore_time(cursor.time_us, 0)
                        if cursor.time_us >= 0 else None),
            entity_type=self.entity_type,
            event_names=self.event_names,
            target_entity_type=self.target_entity_type,
        )
        keep = np.asarray(cols.target_code) >= 0   # interactions only
        ids = np.asarray(cols.entity_ids, dtype=object)[
            np.asarray(cols.entity_code)[keep]]
        return tail_window(ids, np.asarray(cols.time_us)[keep], cursor)

    def history(self, user_id) -> list[Event]:
        return list(self.storage.get_events().find(
            app_id=self.app_id,
            channel_id=self.channel_id,
            entity_type=self.entity_type,
            entity_id=user_id,
            event_names=self.event_names,
            target_entity_type=self.target_entity_type,
            limit=-1,
        ))


class HttpEventSource:
    """Tail + history over the event server's REST API (the
    cross-process folder shape): ``GET /tail/events.json`` for the
    columnar window, ``GET /events.json?entityId=…`` for histories.

    ``wait_s`` (default 10) turns the tail poll into a LONG-POLL push
    subscription: an idle window blocks server-side until an ingest
    lands, so event→fold latency is one store round trip instead of one
    poll interval. A pre-long-poll event server ignores the parameter
    and answers immediately — the folder's poll-interval loop then IS
    the fallback, unchanged. ``wait_s=0`` restores plain polling."""

    def __init__(self, url: str, access_key: str,
                 channel_name: str | None = None,
                 entity_type: str = "user",
                 target_entity_type: str = "item",
                 event_names: Sequence[str] = ("rate", "buy"),
                 timeout: float = 10.0, tail_limit: int = 20000,
                 wait_s: float = 10.0):
        from pio_tpu.utils.httpclient import JsonHttpClient

        self.wait_s = max(0.0, wait_s)
        # the transport timeout must outlive the server-side wait, or
        # every idle long-poll would surface as a client timeout
        self.client = JsonHttpClient(
            url, timeout=max(timeout, self.wait_s + 5.0))
        self.access_key = access_key
        self.channel_name = channel_name
        self.entity_type = entity_type
        self.target_entity_type = target_entity_type
        self.event_names = list(event_names)
        self.tail_limit = tail_limit

    def _params(self, **extra) -> dict:
        p = {"accessKey": self.access_key}
        if self.channel_name is not None:
            p["channel"] = self.channel_name
        p.update(extra)
        return p

    def window(self, cursor: FoldCursor) -> TailWindow:
        # negotiate the binary columnar tail (one CRC32C-framed batch,
        # decoded by pointer-cast — no per-event JSON on either end); a
        # pre-binary event server ignores the Accept header and answers
        # the JSON shape, which lands in the same tail_window fold
        from pio_tpu.data.columnar import (
            COLUMNAR_CONTENT_TYPE, decode_columnar_events,
        )

        params = self._params(
            sinceUs=str(cursor.time_us),
            limit=str(self.tail_limit),
            entityType=self.entity_type,
            targetEntityType=self.target_entity_type,
            events=",".join(self.event_names),
        )
        if self.wait_s > 0:
            params["waitS"] = str(self.wait_s)
        out = self.client.request(
            "GET", "/tail/events.json", params=params,
            accept=COLUMNAR_CONTENT_TYPE)
        if isinstance(out, bytes):
            cols = decode_columnar_events(out)
            ids = np.asarray(cols.entity_ids, dtype=object)[
                np.asarray(cols.entity_code)]
            return tail_window(ids, np.asarray(cols.time_us, np.int64),
                               cursor)
        return tail_window(out.get("entityIds", []),
                           np.asarray(out.get("timesUs", []), np.int64),
                           cursor)

    def history(self, user_id) -> list[Event]:
        from pio_tpu.utils.httpclient import HttpClientError

        events: list[Event] = []
        for name in self.event_names:
            try:
                rows = self.client.request(
                    "GET", "/events.json",
                    params=self._params(
                        entityType=self.entity_type,
                        entityId=user_id,
                        targetEntityType=self.target_entity_type,
                        event=name, limit="-1",
                    ))
            except HttpClientError as e:
                if e.status == 404:    # the route 404s an empty result
                    continue
                raise
            events.extend(Event.from_api_dict(d) for d in rows)
        events.sort(key=lambda e: e.event_time)
        return events
