"""Appliers: land refreshed user rows in the serving layer.

Three shapes, one contract — ``apply(rows, staleness_s) -> dict`` where
``rows`` maps user id → (k,) float sequence, raising
``FoldInApplyError`` when NOTHING durable was applied (the folder then
keeps the users pending and the cursor does not advance):

  * ``LocalServingApplier``  — in-process QueryServer (tests, bench,
    and ``pio deploy`` + folder in one process);
  * ``ServingHttpApplier``   — ``POST /model/upsert_users`` on a
    single-host deploy server (server-key guarded);
  * ``RouterFleetApplier``   — ``POST /fleet/upsert_users`` on the
    fleet router, which crc32c-routes each row to EVERY replica of its
    owning shard group (the same plan queries route by, so a fold-in
    lands exactly where /shard/user_row will look for it). During a
    live reshard the router ALSO dual-writes rows of moving partitions
    to their NEW owner group (docs/serving.md "Elastic resharding"), so
    freshness never regresses across the cutover; dual-write delivery is
    best-effort and reported under ``reshardDualFailures`` without ever
    flipping ``ok`` — the primary (old-plan) owner remains the applier's
    durability contract until the plan swap.

Apply is idempotent (a row upsert with the same bytes is a no-op in
effect), so the folder may replay after a crash or partial failure
without corrupting serving state.

All three appliers also take ``items`` (item id → row): EXISTING items'
factor rows are upserted together with the two-stage retrieval sidecar
(quantized table + cluster assignment, ops/retrieval.py) in the same
atomic swap, so refreshed items are retrievable through the candidate
tier the moment apply returns. Unknown item ids are rejected, never
appended — a new item needs the dense index space only a retrain
assigns.
"""

from __future__ import annotations

from typing import Mapping, Sequence


class FoldInApplyError(ConnectionError):
    """No serving target accepted the fold-in batch. ConnectionError
    subclass so resilience classification (``is_transient``) retries it
    — a down serving layer is an outage to ride out, not a bug."""


class LocalServingApplier:
    """Apply straight into an in-process QueryServer."""

    def __init__(self, query_server):
        self.query_server = query_server

    def apply(self, rows: Mapping[object, Sequence[float]],
              staleness_s: float | None = None,
              items: Mapping[object, Sequence[float]] | None = None,
              ) -> dict:
        return self.query_server.foldin_upsert(rows, staleness_s,
                                               items=items)


class ServingHttpApplier:
    """Apply to a single-host deploy server over its REST surface."""

    def __init__(self, url: str, server_key: str = "",
                 timeout: float = 10.0):
        from pio_tpu.utils.httpclient import JsonHttpClient

        self.client = JsonHttpClient(url, timeout=timeout)
        self.server_key = server_key

    def apply(self, rows: Mapping[object, Sequence[float]],
              staleness_s: float | None = None,
              items: Mapping[object, Sequence[float]] | None = None,
              ) -> dict:
        from pio_tpu.utils.httpclient import HttpClientError

        body = {"users": {u: [float(x) for x in r]
                          for u, r in rows.items()}}
        if items:
            body["items"] = {i: [float(x) for x in r]
                             for i, r in items.items()}
        if staleness_s is not None:
            body["stalenessSeconds"] = staleness_s
        params = ({"accessKey": self.server_key}
                  if self.server_key else None)
        try:
            return self.client.request("POST", "/model/upsert_users",
                                       body, params=params)
        except HttpClientError as e:
            raise FoldInApplyError(
                f"serving upsert failed: {e.message}") from e


class RouterFleetApplier:
    """Apply through the fleet router (one address; the router fans each
    row to every replica of its crc32c owner shard group)."""

    def __init__(self, url: str, server_key: str = "",
                 timeout: float = 10.0):
        from pio_tpu.utils.httpclient import JsonHttpClient

        self.client = JsonHttpClient(url, timeout=timeout)
        self.server_key = server_key

    def apply(self, rows: Mapping[object, Sequence[float]],
              staleness_s: float | None = None,
              items: Mapping[object, Sequence[float]] | None = None,
              ) -> dict:
        from pio_tpu.utils.httpclient import HttpClientError

        body = {"users": {u: [float(x) for x in r]
                          for u, r in rows.items()}}
        if items:
            body["items"] = {i: [float(x) for x in r]
                             for i, r in items.items()}
        if staleness_s is not None:
            body["stalenessSeconds"] = staleness_s
        params = ({"accessKey": self.server_key}
                  if self.server_key else None)
        try:
            out = self.client.request("POST", "/fleet/upsert_users",
                                      body, params=params)
        except HttpClientError as e:
            raise FoldInApplyError(
                f"fleet upsert failed: {e.message}") from e
        if not out.get("ok", False):
            # a whole owner group rejected/unreachable: those users'
            # rows are NOT servable — keep them pending and retry
            raise FoldInApplyError(
                f"fleet upsert incomplete: {out.get('failedGroups')}")
        return out
