"""The fold-in worker: tail → solve → apply, with a durable cursor.

One cycle (``run_once``):

  1. tail the event stream from the cursor (columnar window) and merge
     newly-touched users into the pending set, each stamped with its
     oldest unserved event time — the ``staleness_seconds`` numerator;
  2. read the pending users' FULL histories and solve refreshed rows
     (``FoldInSolver`` → the trainer's normal-equations kernel), under
     the ``foldin.solve`` chaos point;
  3. apply the rows to serving under the ``foldin.apply`` chaos point,
     inside a circuit breaker (a down serving layer trips it and the
     folder backs off instead of hammering);
  4. only when every window user is served does the durable cursor
     advance — a crash ANYWHERE in the cycle replays the window
     (idempotently) instead of losing it.

The whole cycle runs under an optional ``Deadline`` budget so a wedged
storage backend cannot hang the folder forever; every failure mode
degrades to batch-stale serving (the pending set and staleness gauge
grow, ``/readyz`` flips once past the staleness budget) and NEVER
touches serving availability.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Sequence

from pio_tpu.data.eventstore import make_value_fn
from pio_tpu.freshness.apply import FoldInApplyError
from pio_tpu.freshness.cursor import CursorStore, FoldCursor
from pio_tpu.freshness.solver import FoldInSolver
from pio_tpu.freshness.tail import LocalEventSource, _micros
from pio_tpu.ops import als
from pio_tpu.resilience import (
    CircuitBreaker, CircuitOpenError, Deadline,
)
from pio_tpu.resilience import chaos
from pio_tpu.server.http import (
    AsyncHttpServer, HttpApp, HttpServer, Request,
)
from pio_tpu.utils.time import format_time, utcnow

log = logging.getLogger("pio_tpu.freshness")


@dataclass
class FoldInConfig:
    """Folder wiring. The training-read fields (event_names/value_*)
    and the ALS params MUST mirror the deployed engine's — ``pio
    foldin`` derives both from the same engine.json the trainer and
    deploy read, so they cannot drift by hand."""

    app_name: str = ""
    channel_name: str | None = None
    engine_id: str = ""
    engine_version: str = "1"
    engine_variant: str = "default"
    # training-read semantics (mirror models.recommendation.DataSourceParams)
    entity_type: str = "user"
    target_entity_type: str = "item"
    event_names: Sequence[str] = ("rate", "buy")
    value_key: str | None = "rating"
    default_value: float = 4.0
    value_event: str | None = "rate"
    # solver params (mirror the deployed ALSAlgorithmParams; only
    # rank/reg/alpha/implicit matter — ops/als.fold_in_params pins the
    # rest to the bit-conservative fold-in variant)
    als_params: als.ALSParams = field(default_factory=als.ALSParams)
    # worker knobs
    state_path: str = "foldin_cursor.bin"   # durable cursor location
    # a FRESH cursor (no state file) starts at "now" by default: only
    # events ingested from here on fold in, and the trained rows keep
    # serving untouched until their users act again. replay=True starts
    # from the beginning of the event log instead — every historical
    # user gets re-folded against the current item factors (a full
    # fold-in rebuild; the oracle tests use it)
    replay: bool = False
    poll_interval_s: float = 0.5
    cycle_budget_s: float = 30.0            # Deadline around one cycle; 0=off
    max_batch_users: int = 1024             # users per solve/apply batch
    staleness_budget_s: float = 60.0        # readyz + doctor warn threshold
    # health server (create_foldin_server)
    ip: str = "127.0.0.1"
    port: int = 8100
    backend: str = "threaded"
    server_key: str = ""    # guards /debug trace routes ("" = open)


class FoldInWorker:
    """See module docstring. Thread-safe: the loop thread mutates state
    under ``_lock``; the health app and tests read snapshots."""

    def __init__(self, storage, config: FoldInConfig, applier,
                 source=None):
        self.storage = storage
        self.config = config
        self.applier = applier
        self.source = source or LocalEventSource(
            storage, config.app_name, config.channel_name,
            entity_type=config.entity_type,
            target_entity_type=config.target_entity_type,
            event_names=config.event_names,
        )
        self.solver = FoldInSolver(config.als_params,
                                   max_batch_users=config.max_batch_users)
        self.value_fn = make_value_fn(
            config.value_key, config.default_value, config.value_event)
        self.cursor_store = CursorStore(config.state_path)
        self.cursor = self.cursor_store.load()
        if self.cursor.time_us < 0 and not config.replay:
            # fresh start, no replay: pin the boundary at "now" and
            # persist it immediately so a restart before the first
            # successful cycle resumes from the same point
            self.cursor = FoldCursor(time_us=_micros(utcnow()))
            self.cursor_store.save(self.cursor)
        self.start_time = utcnow()
        # distributed tracing (pio_tpu/obs/): each fold cycle is one
        # root trace (there is no inbound HTTP to join), so a slow or
        # failed cycle is inspectable span-by-span — tail read, solve,
        # apply — and the apply's outbound HTTP (router/serving upsert)
        # carries the trace into the serving fleet
        from pio_tpu.obs import make_recorder
        from pio_tpu.utils.tracing import Tracer

        self.recorder = make_recorder("folder")
        self.tracer = Tracer(recorder=self.recorder)
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # apply-side breaker: serving down -> open -> folder backs off
        # (half-open re-probes; fold-ins meanwhile accumulate pending)
        self.apply_breaker = CircuitBreaker(
            "foldin.apply", min_calls=3, failure_rate=0.5, open_s=2.0)
        # state (under _lock)
        self._pending: dict = {}        # user id -> oldest unserved event µs
        self.folded_total = self.cursor.folded_total
        self.applied_batches = 0
        self.skipped_unknown_items = 0
        self.failures = 0
        self.last_error: str | None = None
        self.last_apply_time = None
        self.last_fold_staleness_s: float | None = None
        self.instance_skew = 0
        self._model = None
        self._instance_id: str | None = None

    # -- model (item factors + item index) ----------------------------------
    def _refresh_model(self) -> None:
        """(Re)load the latest COMPLETED instance's factor model; cheap
        id check per cycle, blob read only on change. Fold-in solves
        against THESE item factors, which are the ones serving scores
        with — the oracle contract."""
        from pio_tpu.rollout.state import latest_eligible_completed
        from pio_tpu.serving_fleet.fleet import resolve_fleet_model

        c = self.config
        # rollout-eligibility (pio_tpu/rollout/): fold-in must solve
        # against the instance traffic actually rides — never a
        # rolled-back or still-in-canary one serving wouldn't auto-load
        latest = latest_eligible_completed(
            self.storage, c.engine_id, c.engine_version, c.engine_variant)
        if latest is None:
            raise ValueError(
                f"no COMPLETED instance of engine {c.engine_id} "
                f"{c.engine_version} {c.engine_variant}; train first")
        if self._model is not None and latest.id == self._instance_id:
            return
        instance, model = resolve_fleet_model(
            self.storage, c.engine_id, c.engine_version, c.engine_variant,
            instance_id=latest.id)
        with self._lock:
            self._model = model
            self._instance_id = instance.id
        log.info("fold-in solving against instance %s", instance.id)

    # -- one cycle -----------------------------------------------------------
    def run_once(self) -> dict:
        """One tail→solve→apply cycle; returns cycle stats. Raises on
        failure (the loop catches; tests call this directly). With
        tracing on the cycle is one root trace — failed cycles are
        always retained (tail-based error retention), so the runbook's
        first stop for a wedged folder is its /debug/traces.json."""
        if self.recorder is not None:
            with self.recorder.trace("foldin.cycle"):
                return self._run_budgeted()
        return self._run_budgeted()

    def _run_budgeted(self) -> dict:
        if self.config.cycle_budget_s > 0:
            with Deadline.budget(self.config.cycle_budget_s):
                return self._cycle()
        return self._cycle()

    def _cycle(self) -> dict:
        self._refresh_model()
        with self.tracer.span("tail"):
            window = self.source.window(self.cursor)
        with self._lock:
            for u, oldest in window.to_fold.items():
                prev = self._pending.get(u)
                self._pending[u] = oldest if prev is None \
                    else min(prev, oldest)
        stats = {"windowRows": window.n_rows,
                 "touched": len(window.to_fold),
                 "folded": 0, "skipped": 0}
        # drain the WHOLE pending set in max_batch_users-sized apply
        # batches before touching the cursor: folding only one batch per
        # cycle would wedge the cursor forever whenever a window holds
        # more distinct users than one batch (--replay on a big log,
        # or a traffic burst) — the next poll re-reads the same window
        # from the stuck cursor and re-pends the users just served, so
        # `done` below could never become true. Each iteration pops
        # every user it took (applied or skipped), so the loop
        # terminates; the cycle Deadline still bounds total time (a
        # deadline mid-drain leaves the cursor put — replay, not loss).
        while True:
            with self._lock:
                batch_users = list(
                    self._pending)[:self.config.max_batch_users]
            if not batch_users:
                break
            Deadline.check("foldin batch")
            with self.tracer.span("solve", users=len(batch_users)):
                histories = {u: self.source.history(u)
                             for u in batch_users}
                rows = self.solver.solve(
                    self._model.factors.item_factors, self._model.items,
                    histories, self.value_fn)
            unplaceable = [u for u in batch_users if u not in rows]
            if rows:
                with self._lock:
                    oldest_us = min(self._pending[u] for u in rows
                                    if u in self._pending)
                staleness = max(
                    0.0, (_micros(utcnow()) - oldest_us) / 1e6)
                with self.tracer.span("apply", users=len(rows)), \
                        self.apply_breaker.guard():
                    chaos.maybe_inject("foldin.apply")
                    result = self.applier.apply(rows, staleness)
                with self._lock:
                    for u in rows:
                        self._pending.pop(u, None)
                    for u in unplaceable:
                        self._pending.pop(u, None)
                    self.folded_total += len(rows)
                    self.applied_batches += 1
                    self.skipped_unknown_items += len(unplaceable)
                    self.last_apply_time = utcnow()
                    self.last_fold_staleness_s = staleness
                served = result.get("engineInstanceId")
                if served and served != self._instance_id:
                    with self._lock:
                        self.instance_skew += 1
                    log.warning(
                        "fold-in solved against instance %s but serving "
                        "runs %s; rows applied — `/reload` serving to "
                        "converge", self._instance_id, served)
                stats["folded"] += len(rows)
                stats["skipped"] += len(unplaceable)
            else:
                with self._lock:
                    for u in unplaceable:
                        self._pending.pop(u, None)
                    self.skipped_unknown_items += len(unplaceable)
                stats["skipped"] += len(unplaceable)
        # the durable cursor advances ONLY once nothing in this window
        # is still pending: a crash-restart then re-reads from the old
        # boundary and replays the unserved users instead of losing them
        with self._lock:
            done = not self._pending
        if done and (window.time_us != self.cursor.time_us
                     or window.boundary != self.cursor.boundary
                     or self.folded_total != self.cursor.folded_total):
            self.cursor = FoldCursor(
                time_us=window.time_us,
                boundary=window.boundary,
                folded_total=self.folded_total,
            )
            self.cursor_store.save(self.cursor)
        with self._lock:
            self.last_error = None
        return stats

    # -- loop ----------------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        # pio: lint-ok[context-loss] deliberate detach: the fold-in loop
        # is a process-lifetime worker started at deploy time, not on a
        # request path — there is no Deadline/trace to carry
        self._thread = threading.Thread(
            target=self._loop, name="foldin", daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(timeout=self.config.poll_interval_s):
            try:
                self.run_once()
            except CircuitOpenError as e:
                # serving down and breaker open: expected backoff, not
                # an error to page on; pending/staleness say the rest
                with self._lock:
                    self.last_error = f"apply breaker open: {e}"
            except Exception as e:  # noqa: BLE001 - degrade, never die:
                # a wedged folder means batch-stale serving, not outage
                with self._lock:
                    self.failures += 1
                    self.last_error = f"{type(e).__name__}: {e}"
                log.warning("fold-in cycle failed: %s", e, exc_info=True)

    # -- observability -------------------------------------------------------
    def staleness_seconds(self) -> float:
        """Age of the OLDEST event seen by the tail but not yet
        servable (0.0 when fully caught up) — the event-ingest →
        servable gauge the freshness contract is written against."""
        with self._lock:
            if not self._pending:
                return 0.0
            oldest = min(self._pending.values())
        return max(0.0, (_micros(utcnow()) - oldest) / 1e6)

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._pending)

    def snapshot(self) -> dict:
        staleness = self.staleness_seconds()
        with self._lock:
            return {
                "stalenessSeconds": round(staleness, 3),
                "stalenessBudgetSeconds": self.config.staleness_budget_s,
                "queueDepth": len(self._pending),
                "foldedTotal": self.folded_total,
                "appliedBatches": self.applied_batches,
                "skippedUnknownItems": self.skipped_unknown_items,
                "failures": self.failures,
                "lastError": self.last_error,
                "lastApplyTime": (format_time(self.last_apply_time)
                                  if self.last_apply_time else None),
                "lastFoldStalenessSeconds": self.last_fold_staleness_s,
                "instanceSkew": self.instance_skew,
                "cursorTimeUs": self.cursor.time_us,
                "modelInstanceId": self._instance_id,
                "applyBreaker": self.apply_breaker.snapshot().state,
                "startTime": format_time(self.start_time),
            }


def build_foldin_app(worker: FoldInWorker) -> HttpApp:
    """The folder's own health surface. ``/healthz`` carries the
    freshness gauges inline (the contract: staleness_seconds and queue
    depth are liveness-cheap, no storage round-trip); ``/readyz`` flips
    once staleness exceeds its budget or the apply breaker is open —
    "stop trusting freshness", which routes nothing away from serving
    (serving has its own readyz) but pages the operator via doctor."""
    app = HttpApp("foldin")

    @app.route("GET", r"/")
    def root(req: Request):
        return 200, {"status": "alive", "role": "foldin",
                     **worker.snapshot()}

    @app.route("GET", r"/healthz")
    def healthz(req: Request):
        return 200, {
            "status": "alive",
            "staleness_seconds": round(worker.staleness_seconds(), 3),
            "foldin_queue_depth": worker.queue_depth(),
        }

    @app.route("GET", r"/readyz")
    def readyz(req: Request):
        snap = worker.snapshot()
        checks = {
            "freshness": {
                "ok": (snap["stalenessSeconds"]
                       <= worker.config.staleness_budget_s),
                "stalenessSeconds": snap["stalenessSeconds"],
                "budgetSeconds": worker.config.staleness_budget_s,
                "queueDepth": snap["queueDepth"],
            },
            "applyBreaker": {
                "ok": snap["applyBreaker"] != "open",
                "state": snap["applyBreaker"],
            },
        }
        ready = all(c["ok"] for c in checks.values())
        return (200 if ready else 503), {"ready": ready, "checks": checks}

    @app.route("GET", r"/metrics\.json")
    def metrics(req: Request):
        out = worker.snapshot()
        out["spans"] = worker.tracer.snapshot()
        if worker.recorder is not None:
            out["exemplars"] = worker.recorder.exemplars()
        return 200, out

    @app.route("GET", r"/metrics")
    def metrics_prometheus(req: Request):
        """Prometheus twin of /metrics.json through the shared renderer:
        the freshness SLO gauges (staleness_seconds, queue depth) become
        scrapeable — not just doctor-visible — plus the cycle-stage span
        summaries, all under `surface="folder"`."""
        from pio_tpu.server.http import RawResponse
        from pio_tpu.utils.httpclient import pool_counters
        from pio_tpu.utils.tracing import (
            PROMETHEUS_CONTENT_TYPE, prometheus_text,
        )

        snap = worker.snapshot()
        counters = {
            "staleness_seconds": snap["stalenessSeconds"],
            "staleness_budget_seconds": snap["stalenessBudgetSeconds"],
            "foldin_queue_depth": float(snap["queueDepth"]),
            "foldin_folded_total": float(snap["foldedTotal"]),
            "foldin_applied_batches_total": float(snap["appliedBatches"]),
            "foldin_failures_total": float(snap["failures"]),
            "uptime_seconds":
                (utcnow() - worker.start_time).total_seconds(),
        }
        # the folder's tail long-poll + apply fans ride the keep-alive
        # pool (docs/performance.md "Internal RPC plane")
        counters.update(pool_counters())
        return 200, RawResponse(
            prometheus_text(worker.tracer.snapshot(), counters,
                            labels={"surface": "folder"}),
            PROMETHEUS_CONTENT_TYPE)

    # distributed tracing (pio_tpu/obs/): per-cycle traces fetchable
    # from the folder's own surface (FoldInConfig.server_key guards)
    from pio_tpu.obs.http import install_trace_routes
    from pio_tpu.server.http import server_key_ok

    app.tracer = worker.tracer
    install_trace_routes(
        app, worker.recorder,
        lambda req: server_key_ok(req, worker.config.server_key))

    return app


def create_foldin_server(worker: FoldInWorker):
    """-> http transport for the folder's health surface (start() it;
    with port=0 the bound port is known after start)."""
    c = worker.config
    server_cls = AsyncHttpServer if c.backend == "async" else HttpServer
    return server_cls(build_foldin_app(worker), host=c.ip, port=c.port)
