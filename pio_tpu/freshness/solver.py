"""Batched fold-in solve: pending users → refreshed factor rows.

One solve call takes the pending users' FULL event histories, folds
them to (item, value) pairs with EXACTLY the training read's semantics
(``make_value_fn`` + the "last"-dedup rule of ``to_interactions``), and
solves one ridge system per user against the fixed item factors through
``ops/als.als_fold_in`` — the same ``_normal_equations`` kernel the
trainer runs, not a fork of it.

Batches are pow2-bucketed on BOTH axes (pending users, total events) by
``als_fold_in`` itself, so a steady fold-in stream compiles O(log²)
programs and then serves from the persistent compile cache (PR 4). The
solve is batch-composition invariant bit-for-bit (see
``_solve_rows_invariant``): user u's refreshed row does not depend on
who shares the batch — the property the oracle parity tests pin.
"""

from __future__ import annotations

import logging
from typing import Callable, Iterable, Mapping, Sequence

import numpy as np

from pio_tpu.data.event import Event
from pio_tpu.ops import als
from pio_tpu.resilience import chaos

log = logging.getLogger("pio_tpu.freshness")


def user_pairs(events: Iterable[Event],
               value_fn: Callable[[Event], float | None]) -> list[tuple]:
    """One user's events → deduplicated (item_id, value) pairs, with the
    training fold's exact semantics (``to_interactions`` dedup="last"):
    latest value per item by event time wins, pair order is first
    occurrence in time order. Shared by the folder AND the oracle tests
    so value extraction cannot drift from the solve contract."""
    vals: dict = {}
    for e in sorted(events, key=lambda ev: ev.event_time):
        if e.target_entity_id is None:
            continue
        v = value_fn(e)
        if v is None:
            continue
        vals[e.target_entity_id] = float(v)
    return list(vals.items())


class FoldInSolver:
    """See module docstring. ``max_batch_users`` bounds one device
    dispatch (and keeps the dense-id bucket well under the ops layer's
    ``auto_cg_rows`` exact-solve threshold)."""

    def __init__(self, params: als.ALSParams, max_batch_users: int = 1024):
        self.params = params
        self.max_batch_users = max(1, int(max_batch_users))

    def solve(
        self,
        item_factors,
        items_index,
        histories: Mapping[object, Sequence[Event]],
        value_fn: Callable[[Event], float | None],
    ) -> dict:
        """-> {user_id: (k,) float32 row} for every user with ≥ 1 known
        item. Users whose events reference only items absent from the
        model's item index are skipped (there is nothing to score them
        against until the next train) — callers leave them pending-free:
        re-tailing them without new events would busy-loop."""
        per_user: list[tuple] = []   # (user_id, item_idx arr, values arr)
        for uid, events in histories.items():
            pairs = user_pairs(events, value_fn)
            known = [(items_index.bimap.get(it, -1), v) for it, v in pairs]
            known = [(i, v) for i, v in known if i >= 0]
            if not known:
                continue
            idx = np.fromiter((i for i, _ in known), np.int32,
                              count=len(known))
            val = np.fromiter((v for _, v in known), np.float32,
                              count=len(known))
            per_user.append((uid, idx, val))
        out: dict = {}
        for lo in range(0, len(per_user), self.max_batch_users):
            chunk = per_user[lo:lo + self.max_batch_users]
            # chaos drill point: a spec targeting foldin.solve fails the
            # batch HERE — after histories were read, before any row is
            # produced — the "killed mid-batch" shape the freshness-chaos
            # CI job replays
            chaos.maybe_inject("foldin.solve")
            u = np.concatenate([
                np.full(len(idx), j, np.int32)
                for j, (_, idx, _) in enumerate(chunk)
            ])
            i = np.concatenate([idx for _, idx, _ in chunk])
            v = np.concatenate([val for _, _, val in chunk])
            rows = np.asarray(als.als_fold_in(
                item_factors, u, i, v, len(chunk), self.params))
            for j, (uid, _, _) in enumerate(chunk):
                out[uid] = rows[j]
        return out
