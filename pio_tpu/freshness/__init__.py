"""Streaming ALS fold-in: event → recommendation in seconds.

The train→deploy loop is batch-only — a new user's events do nothing
until the next ``pio train``. This subsystem closes that gap with the
MLlib-ALS division of labor (Meng et al., 1505.06807; the reference's
DASE serving split): heavy factorization stays offline, and a cheap
per-user ridge solve against the FIXED item factors runs online:

  tail   (tail.py)    — follow the event stream over the columnar batch
                        path (``find_columnar`` locally, the event
                        server's ``GET /tail/events.json`` remotely) and
                        detect users with new interactions;
  cursor (cursor.py)  — a durable resume point (utils/durable.py
                        framing + atomic write) so a restarted folder
                        continues where it stopped, with no replay loss;
  solve  (solver.py)  — batched fold-in of pending users' FULL event
                        histories through the exact per-row
                        normal-equations kernel training uses
                        (ops/als.py ``als_fold_in`` → ``_normal_equations``),
                        pow2-bucketed for the persistent compile cache;
  apply  (apply.py)   — hot-swap the refreshed user rows into serving:
                        the single-host QueryServer (in-process or
                        ``POST /model/upsert_users``) or every replica
                        of the owning shard group of the fleet
                        (``POST /fleet/upsert_users`` on the router,
                        crc32c-routed by the recorded shard plan);
  folder (folder.py)  — the worker loop wiring it together, with
                        ``foldin.solve`` / ``foldin.apply`` chaos
                        points, an apply circuit breaker, a per-cycle
                        deadline, and ``staleness_seconds`` + queue
                        depth exported on its ``/healthz``/``/readyz``.

Failure contract: a wedged folder degrades serving to batch-stale —
queries keep answering from the last trained model — and NEVER takes
serving down; the fold-in cursor only advances after a successful
apply, so a crash anywhere in the cycle replays (idempotently — a fold
is a pure function of the user's full history and the item factors)
rather than loses. docs/freshness.md has the architecture, the
staleness contract, and the runbook.
"""

from pio_tpu.freshness.apply import (
    FoldInApplyError,
    LocalServingApplier,
    RouterFleetApplier,
    ServingHttpApplier,
)
from pio_tpu.freshness.cursor import CursorStore, FoldCursor
from pio_tpu.freshness.folder import (
    FoldInConfig,
    FoldInWorker,
    build_foldin_app,
    create_foldin_server,
)
from pio_tpu.freshness.solver import FoldInSolver, user_pairs
from pio_tpu.freshness.tail import TailWindow, tail_window

__all__ = [
    "CursorStore",
    "FoldCursor",
    "FoldInApplyError",
    "FoldInConfig",
    "FoldInSolver",
    "FoldInWorker",
    "LocalServingApplier",
    "RouterFleetApplier",
    "ServingHttpApplier",
    "TailWindow",
    "build_foldin_app",
    "create_foldin_server",
    "tail_window",
    "user_pairs",
]
