"""Durable sweep records — fold checkpoints and the best-params verdict.

Two CRC32C-framed JSON records ride the MODELDATA repository keyed by
the EvaluationInstance id, the same pattern the fleet uses for shard
plans and the rollout controller for verdicts:

  ``<eval-iid>:sweep``        — per-unit (fold / candidate) results,
      written after every completed unit. A killed sweep resumes from
      this record: completed units are never recomputed, which is what
      makes resume's result identical to the uninterrupted run.
  ``<eval-iid>:best_params``  — the winning EngineParams (variant-shaped
      JSON ready for ``engine_params_from_variant``), the score, and
      the metric. ``pio train --from-eval`` / ``pio deploy --from-eval``
      consume it; ``pio doctor`` compares it against what production
      serves.

All writes go through utils/durable's framing (the ``eval-determinism``
rule family's sibling ``foldin-cursor``/``hint-log`` contracts apply the
same way: no raw file writes in this package).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from pio_tpu.controller.base import params_to_dict
from pio_tpu.controller.engine import EngineParams
from pio_tpu.data.dao import Model
from pio_tpu.utils.durable import ModelIntegrityError, frame, unframe


def sweep_model_id(eval_id: str) -> str:
    return f"{eval_id}:sweep"


def best_params_model_id(eval_id: str) -> str:
    return f"{eval_id}:best_params"


def engine_params_to_variant(ep: EngineParams) -> dict:
    """EngineParams -> the engine.json variant stage shape, so the
    record round-trips through ``Engine.engine_params_from_variant`` and
    comes back TYPED (params_class dataclasses, not raw dicts)."""
    return {
        "datasource": {"name": ep.datasource[0],
                       "params": params_to_dict(ep.datasource[1]) or {}},
        "preparator": {"name": ep.preparator[0],
                       "params": params_to_dict(ep.preparator[1]) or {}},
        "algorithms": [
            {"name": n, "params": params_to_dict(p) or {}}
            for n, p in (ep.algorithms or [])
        ],
        "serving": {"name": ep.serving[0],
                    "params": params_to_dict(ep.serving[1]) or {}},
    }


@dataclass
class SweepState:
    """The sweep's durable progress: ordered unit keys + per-unit result
    payloads. A unit is one crash-safe slice of work — a fold on the
    batched ALS path, a candidate on the sequential fallback."""

    eval_id: str
    spec: dict = field(default_factory=dict)
    units: list[str] = field(default_factory=list)
    completed: dict[str, dict] = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps({
            "eval_id": self.eval_id,
            "spec": self.spec,
            "units": self.units,
            "completed": self.completed,
        }, sort_keys=True)

    @staticmethod
    def from_json(text: str) -> "SweepState":
        d = json.loads(text)
        return SweepState(
            eval_id=d["eval_id"], spec=d.get("spec", {}),
            units=list(d.get("units", [])),
            completed=dict(d.get("completed", {})),
        )


def save_sweep_state(storage, state: SweepState) -> None:
    storage.get_model_data_models().insert(Model(
        sweep_model_id(state.eval_id),
        frame(state.to_json().encode("utf-8")),
    ))


def load_sweep_state(storage, eval_id: str) -> SweepState | None:
    rec = storage.get_model_data_models().get(sweep_model_id(eval_id))
    if rec is None:
        return None
    return SweepState.from_json(
        unframe(rec.models, source=sweep_model_id(eval_id))
        .decode("utf-8"))


def save_best_params(storage, eval_id: str, best_ep: EngineParams,
                     score: float, metric: str,
                     engine_id: str = "", engine_version: str = "",
                     engine_variant: str = "",
                     all_scores: list | None = None) -> dict:
    """Persist the sweep's verdict; returns the payload written."""
    payload = {
        "evaluationInstanceId": eval_id,
        "metric": metric,
        "score": None if score != score else score,   # NaN -> null
        "engineId": engine_id,
        "engineVersion": engine_version,
        "engineVariant": engine_variant,
        "variant": engine_params_to_variant(best_ep),
        "allScores": all_scores or [],
    }
    storage.get_model_data_models().insert(Model(
        best_params_model_id(eval_id),
        frame(json.dumps(payload, sort_keys=True).encode("utf-8")),
    ))
    return payload


def load_best_params(storage, eval_id: str) -> dict | None:
    """The ``:best_params`` payload, or None when the eval never
    finished a sweep. Raises ModelIntegrityError on a corrupt frame —
    --from-eval must fail loudly, never train on garbage params."""
    rec = storage.get_model_data_models().get(best_params_model_id(eval_id))
    if rec is None:
        return None
    return json.loads(
        unframe(rec.models, source=best_params_model_id(eval_id))
        .decode("utf-8"))


def latest_best_params(storage):
    """-> (EvaluationInstance, payload) for the newest EVALCOMPLETED
    instance carrying a readable best-params record, or None. Corrupt
    records are SKIPPED, newest-first — the ONE scan `pio doctor`'s
    eval row and --from-eval latest both ride."""
    dao = storage.get_metadata_evaluation_instances()
    for inst in dao.get_completed():
        try:
            payload = load_best_params(storage, inst.id)
        except ModelIntegrityError:
            continue   # corrupt record: keep looking, newest-first
        if payload is not None:
            return inst, payload
    return None


def resolve_from_eval(storage, eval_id: str) -> tuple[str, dict]:
    """-> (eval instance id, best-params payload) for --from-eval.
    ``eval_id`` may be a concrete EvaluationInstance id or "latest"
    (the most recent EVALCOMPLETED instance carrying a record)."""
    if eval_id != "latest":
        payload = load_best_params(storage, eval_id)
        if payload is None:
            inst = storage.get_metadata_evaluation_instances().get(eval_id)
            detail = ("no such evaluation instance" if inst is None
                      else f"instance status is {inst.status} and no "
                           "best-params record was persisted")
            raise ValueError(
                f"--from-eval {eval_id}: no best-params record "
                f"({detail}; run `pio eval --sweep` first)")
        return eval_id, payload
    found = latest_best_params(storage)
    if found is None:
        raise ValueError(
            "--from-eval latest: no completed evaluation carries a "
            "best-params record (run `pio eval --sweep` first)")
    return found[0].id, found[1]
