"""Deterministic evaluation splits over the columnar event path.

Two split families feed the sweep (ISSUE 13 / ROADMAP item 5), both
seeded and bit-reproducible — rerunning a sweep over unchanged data
produces byte-identical fold assignments, which is what makes the
crash-resume drill's "resume == uninterrupted" contract checkable at
all:

 * ``seeded_kfold`` — k-fold over deduped COO interaction rows. Fold
   tags come from ``np.random.default_rng(seed).permutation(n) % k``:
   exactly balanced, seeded, and independent of the storage backend's
   row order beyond the deterministic stable time sort
   ``columnar_interactions`` already applies. (The legacy
   ``e2.crossvalidation.split_interactions`` index-mod-k split is the
   seed==None degenerate case and stays for the reference-parity
   tests.)
 * ``time_rolling_folds`` — event-time rolling ("forward chaining")
   splits straight off the columnar read (``find_columnar`` ->
   ``columnar_interactions``): fold f trains on every event before
   boundary b_f and tests on the window [b_f, b_{f+1}), boundaries at
   event-count quantiles. This is the split that respects the serving
   reality (models predict the future, not a random subsample).

Every fold's train split keeps the FULL user/item id tables, so factor
shapes are identical across folds and candidates — one compiled train
program serves the whole sweep (the compile-cache lever), and item
indices are comparable across folds at scoring time.

Determinism contract (enforced by the ``eval-determinism`` lint rule):
nothing in this module may read the wall clock, draw from an unseeded
RNG, or iterate a set where order reaches the fold assignment.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from pio_tpu.data.bimap import EntityIdIndex
from pio_tpu.data.columnar import ColumnarEvents, columnar_interactions
from pio_tpu.data.eventstore import Interactions


@dataclass
class EvalFold:
    """One fold: a train split plus per-user heldout relevance.

    ``train`` shares the FULL id tables (see module doc); the test side
    is already index-encoded — ``actual_idx[j]`` / ``seen_idx[j]`` are
    the heldout / train-seen item indices of ``test_user_idx[j]``.
    Users whose heldout set is empty after the exclude-seen dedup are
    dropped (the Option-metric None semantics: unscorable, excluded)."""

    info: dict
    train: Interactions
    test_user_idx: np.ndarray            # (B,) int32
    actual_idx: list[np.ndarray] = field(default_factory=list)
    seen_idx: list[np.ndarray] = field(default_factory=list)

    @property
    def n_test_users(self) -> int:
        return len(self.test_user_idx)

    def qa_pairs(self, num: int = 10) -> list[tuple[dict, list]]:
        """The (query, actual) shape the generic Engine.eval path and
        the QPA metric contract consume — the recommendation template's
        {"user", "num", "blackList"} query against heldout item ids."""
        users = self.train.users
        items = self.train.items
        out = []
        for j, u in enumerate(self.test_user_idx):
            q: dict = {"user": users.id_of(int(u)), "num": num}
            seen = self.seen_idx[j]
            if len(seen):
                q["blackList"] = items.decode(seen)
            out.append((q, items.decode(self.actual_idx[j])))
        return out


def _user_groups(user_idx: np.ndarray, item_idx: np.ndarray,
                 tag: np.ndarray):
    """Sort rows by user and yield (user, items_in_group, tags_in_group)
    slices — one vectorized lexsort instead of a per-user Python scan."""
    order = np.lexsort((item_idx, user_idx))
    u_s = user_idx[order]
    i_s = item_idx[order]
    t_s = tag[order]
    bounds = np.flatnonzero(
        np.concatenate([[True], u_s[1:] != u_s[:-1], [True]]))
    for s, e in zip(bounds[:-1], bounds[1:]):
        yield int(u_s[s]), i_s[s:e], t_s[s:e]


def _fold_from_masks(data: Interactions, train_mask: np.ndarray,
                     test_mask: np.ndarray, info: dict,
                     exclude_seen: bool) -> EvalFold:
    train = Interactions(
        user_idx=data.user_idx[train_mask],
        item_idx=data.item_idx[train_mask],
        values=data.values[train_mask],
        users=data.users,
        items=data.items,
    )
    test_users: list[int] = []
    actuals: list[np.ndarray] = []
    seens: list[np.ndarray] = []
    # tag: 1 = test row, 0 = train row, -1 = neither (other folds' train
    # rows in the rolling split still count as "seen" only when they
    # precede the boundary — callers encode that in the masks)
    tag = np.full(len(data), -1, np.int8)
    tag[train_mask] = 0
    tag[test_mask] = 1
    involved = train_mask | test_mask
    for u, items, tags in _user_groups(
            data.user_idx[involved], data.item_idx[involved],
            tag[involved]):
        test_items = np.unique(items[tags == 1]).astype(np.int32)
        if not len(test_items):
            continue
        seen = np.unique(items[tags == 0]).astype(np.int32)
        if exclude_seen and len(seen):
            test_items = test_items[~np.isin(test_items, seen)]
            if not len(test_items):
                continue
        test_users.append(u)
        actuals.append(test_items)
        seens.append(seen if exclude_seen else np.zeros(0, np.int32))
    return EvalFold(
        info=info,
        train=train,
        test_user_idx=np.array(test_users, np.int32),
        actual_idx=actuals,
        seen_idx=seens,
    )


def seeded_kfold(
    data: Interactions,
    k: int,
    seed: int = 42,
    exclude_seen: bool = True,
) -> list[EvalFold]:
    """Seeded, balanced k-fold over deduped interaction rows (see
    module doc). ``seed`` fully determines the assignment for a given
    row count — same data, same seed, same folds, bit-for-bit."""
    if k <= 1:
        raise ValueError(f"k-fold needs k >= 2, got {k}")
    n = len(data)
    tags = np.random.default_rng(seed).permutation(n) % k
    folds = []
    for f in range(k):
        test_mask = tags == f
        folds.append(_fold_from_masks(
            data, ~test_mask, test_mask,
            info={"kind": "kfold", "fold": f, "k": k, "seed": seed},
            exclude_seen=exclude_seen,
        ))
    return folds


def _interactions_with_times(
    cols: ColumnarEvents,
    value_key: str | None,
    default_value: float,
    dedup: str,
    value_event: str | None,
) -> tuple[Interactions, np.ndarray]:
    """Full-data Interactions plus each deduped row's effective event
    time (dedup="last": the pair's LAST occurrence — the time at which
    that interaction reached its final value; "sum"/"none": likewise the
    last/own occurrence). The time column is what the rolling split cuts
    on; the COO construction itself is columnar_interactions verbatim,
    so values/dedup semantics cannot drift from the training read."""
    full_cols = columnar_interactions(
        cols, value_key=value_key, default_value=default_value,
        dedup=dedup, value_event=value_event,
    )
    users = EntityIdIndex(full_cols.users)
    items = EntityIdIndex(full_cols.items)
    inter = Interactions(
        user_idx=full_cols.user_idx.astype(np.int32),
        item_idx=full_cols.item_idx.astype(np.int32),
        values=full_cols.values,
        users=users,
        items=items,
    )
    # effective time per deduped row: max event time over the (user,
    # item) pair's occurrences, computed with the same stable time sort
    # + target filter columnar_interactions applies
    n = len(cols)
    order = (np.argsort(cols.time_us, kind="stable") if n
             else np.zeros(0, np.int64))
    keep = order[cols.target_code[order] >= 0]
    ent_ids = np.array(cols.entity_ids, dtype=object)
    tgt_ids = np.array(cols.target_ids, dtype=object)
    # map raw event rows -> dense COO indices through the id tables
    u_raw = users.encode(ent_ids[cols.entity_code[keep]])
    i_raw = items.encode(tgt_ids[cols.target_code[keep]])
    pair_raw = u_raw.astype(np.int64) * max(len(items), 1) + i_raw
    pair_coo = (inter.user_idx.astype(np.int64) * max(len(items), 1)
                + inter.item_idx)
    times_raw = cols.time_us[keep]
    uniq, inverse = np.unique(pair_raw, return_inverse=True)
    last_t = np.full(len(uniq), np.iinfo(np.int64).min, np.int64)
    np.maximum.at(last_t, inverse, times_raw)
    times = last_t[np.searchsorted(uniq, pair_coo)]
    return inter, times


def time_rolling_folds(
    cols: ColumnarEvents,
    n_folds: int,
    value_key: str | None = "rating",
    default_value: float = 1.0,
    dedup: str = "last",
    value_event: str | None = None,
    exclude_seen: bool = True,
) -> list[EvalFold]:
    """Event-time rolling splits: boundaries at interaction-count
    quantiles; fold f trains on interactions strictly before b_f and
    tests on [b_f, b_{f+1}). Fully deterministic — no RNG at all; the
    boundaries are a pure function of the event times."""
    if n_folds < 1:
        raise ValueError(f"rolling split needs n_folds >= 1, got {n_folds}")
    data, times = _interactions_with_times(
        cols, value_key, default_value, dedup, value_event)
    n = len(data)
    if n < (n_folds + 1) * 2:
        raise ValueError(
            f"rolling split needs at least {(n_folds + 1) * 2} "
            f"interactions for {n_folds} fold(s), got {n}")
    t_sorted = np.sort(times, kind="stable")
    # boundary f sits at count-quantile (f+1)/(n_folds+1): the first
    # fold still trains on a meaningful prefix, the last tests on the
    # most recent window
    bounds = [
        int(t_sorted[min(n - 1, (f + 1) * n // (n_folds + 1))])
        for f in range(n_folds)
    ]
    bounds.append(int(t_sorted[-1]) + 1)
    folds = []
    for f in range(n_folds):
        lo, hi = bounds[f], bounds[f + 1]
        train_mask = times < lo
        test_mask = (times >= lo) & (times < hi)
        folds.append(_fold_from_masks(
            data, train_mask, test_mask,
            info={"kind": "time", "fold": f, "k": n_folds,
                  "boundaryUs": lo, "untilUs": hi},
            exclude_seen=exclude_seen,
        ))
    return folds


def folds_for(
    data_or_cols,
    split: str,
    k: int,
    seed: int = 42,
    exclude_seen: bool = True,
    value_key: str | None = "rating",
    default_value: float = 1.0,
    dedup: str = "last",
    value_event: str | None = None,
) -> list[EvalFold]:
    """Dispatch: ``split="kfold"`` takes an Interactions (or columnar
    events, folded here); ``split="time"`` needs ColumnarEvents (times
    live only on the raw event rows)."""
    if split == "kfold":
        if isinstance(data_or_cols, ColumnarEvents):
            data_or_cols, _ = _interactions_with_times(
                data_or_cols, value_key, default_value, dedup,
                value_event)
        return seeded_kfold(data_or_cols, k, seed=seed,
                            exclude_seen=exclude_seen)
    if split == "time":
        if not isinstance(data_or_cols, ColumnarEvents):
            raise ValueError(
                "time_rolling_folds needs the columnar event rows "
                "(find_columnar output) — Interactions carry no times")
        return time_rolling_folds(
            data_or_cols, k, value_key=value_key,
            default_value=default_value, dedup=dedup,
            value_event=value_event, exclude_seen=exclude_seen)
    raise ValueError(f"unknown split kind {split!r} "
                     "(expected 'kfold' or 'time')")
