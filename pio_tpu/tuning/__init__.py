"""pio_tpu.tuning — device-parallel evaluation & hyperparameter sweeps.

The third DASE pillar (ROADMAP item 5): deterministic splits
(``splits``), vectorized ranking metrics with scalar oracles
(``metrics``), the batched sweep runner (``sweep``), durable
fold/best-params records (``records``), and the sweep's observability
surface (``server``). Entry points: ``pio eval --sweep`` (tools/cli.py)
-> ``workflow.evaluate.run_sweep_evaluation``.
"""

from pio_tpu.tuning.metrics import (  # noqa: F401
    AUC,
    MAPAtK,
    NDCGAtK,
    PrecisionAtK,
    RankingMetric,
    RecallAtK,
    parse_metric,
)
from pio_tpu.tuning.records import (  # noqa: F401
    load_best_params,
    resolve_from_eval,
    save_best_params,
)
from pio_tpu.tuning.splits import (  # noqa: F401
    EvalFold,
    folds_for,
    seeded_kfold,
    time_rolling_folds,
)
from pio_tpu.tuning.sweep import (  # noqa: F401
    SweepConfig,
    SweepRunner,
    group_candidates,
)
