"""Vectorized ranking metrics — batched JAX ops over top-k rankings.

The sweep's metric layer (ISSUE 13): MAP@k, NDCG@k, precision@k and AUC
as fixed-shape batched device ops, so scoring C stacked candidates x B
test users is a handful of einsum/top-k/cumsum dispatches instead of
C x B Python loops. Every metric also plugs into the existing
``controller.evaluation.Metric`` contract (``calculate`` over the
(query, prediction, actual) triples the generic Engine.eval path
produces), and each vectorized kernel has a pure-Python scalar oracle
(``*_scalar``) that the parity suite fuzzes against — the vectorized
form is never the only definition of a score.

Definitions (binary relevance):

 * precision@k  = |top-k ∩ actual| / min(k, |actual|)  — the repo's
   existing PrecisionAtK convention (tp over the best achievable, so a
   perfect ranking scores 1.0 even when |actual| < k);
 * MAP@k        = (1 / min(k, |actual|)) * sum_{i<=k, rel_i} P@i
   (average precision at each hit, truncated at k);
 * NDCG@k       = DCG@k / IDCG@k with gain 1 / log2(1 + rank);
 * AUC          = P(score(pos) > score(neg)) + 0.5 P(=) over the
   user's (positive, candidate-negative) pairs — needs the FULL score
   row, so it only runs on paths that have one (the batched sweep; the
   QPA adapter raises a clear error instead of silently approximating).

Per-user scores are averaged with Option semantics: a user with no
actuals is excluded, a user with actuals but no predictions scores 0
(under-predicting is penalized, never excluded).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from pio_tpu.controller.evaluation import EvalDataSet, Metric

# masked-score sentinel: seen-in-train / padded items are pushed below
# any real score before the top-k (callers of the sweep scorer)
MASKED_SCORE = -1e30


# ---------------------------------------------------------------------------
# batched kernels (jit; fixed shapes from the caller's padding)
# ---------------------------------------------------------------------------

def hits_matrix(topk_idx, actual_idx):
    """(..., K) ranked item indices x (..., A) -1-padded actuals ->
    (..., K) float32 hit indicators."""
    hit = (topk_idx[..., :, None] == actual_idx[..., None, :])
    hit &= (actual_idx[..., None, :] >= 0)
    return jnp.any(hit, axis=-1).astype(jnp.float32)


def _n_actual(actual_idx):
    return jnp.sum((actual_idx >= 0), axis=-1).astype(jnp.float32)


@partial(jax.jit, static_argnames=("k",))
def precision_at_k_batch(topk_idx, actual_idx, k: int):
    """-> (...,) per-user precision@k; users with no actuals get NaN
    (excluded by the nanmean aggregation)."""
    hits = hits_matrix(topk_idx[..., :k], actual_idx)
    n_act = _n_actual(actual_idx)
    denom = jnp.minimum(jnp.float32(k), n_act)
    score = jnp.sum(hits, axis=-1) / jnp.maximum(denom, 1.0)
    return jnp.where(n_act > 0, score, jnp.nan)


@partial(jax.jit, static_argnames=("k",))
def recall_at_k_batch(topk_idx, actual_idx, k: int):
    """-> (...,) per-user recall@k = |top-k ∩ actual| / |actual|."""
    hits = hits_matrix(topk_idx[..., :k], actual_idx)
    n_act = _n_actual(actual_idx)
    score = jnp.sum(hits, axis=-1) / jnp.maximum(n_act, 1.0)
    return jnp.where(n_act > 0, score, jnp.nan)


@partial(jax.jit, static_argnames=("k",))
def map_at_k_batch(topk_idx, actual_idx, k: int):
    hits = hits_matrix(topk_idx[..., :k], actual_idx)
    prec_at_i = jnp.cumsum(hits, axis=-1) / jnp.arange(
        1, k + 1, dtype=jnp.float32)
    n_act = _n_actual(actual_idx)
    ap = jnp.sum(prec_at_i * hits, axis=-1) / jnp.maximum(
        jnp.minimum(jnp.float32(k), n_act), 1.0)
    return jnp.where(n_act > 0, ap, jnp.nan)


@partial(jax.jit, static_argnames=("k",))
def ndcg_at_k_batch(topk_idx, actual_idx, k: int):
    hits = hits_matrix(topk_idx[..., :k], actual_idx)
    discounts = 1.0 / jnp.log2(jnp.arange(2, k + 2, dtype=jnp.float32))
    dcg = jnp.sum(hits * discounts, axis=-1)
    n_act = _n_actual(actual_idx)
    ideal_n = jnp.minimum(n_act, jnp.float32(k)).astype(jnp.int32)
    idcg = jnp.cumsum(discounts)[
        jnp.maximum(ideal_n - 1, 0)]
    score = dcg / jnp.where(idcg > 0, idcg, 1.0)
    return jnp.where(n_act > 0, score, jnp.nan)


def _auc_row(scores, pos_mask, valid_mask):
    """One user's AUC from a full score row: for each positive, count
    negatives strictly below (a win) and tied (half a win) via two
    searchsorteds into the sorted negative scores — O(I log I), exact
    tie handling, no O(I^2) pairwise matrix."""
    neg_mask = valid_mask & ~pos_mask
    neg_sorted = jnp.sort(jnp.where(neg_mask, scores, jnp.inf))
    below = jnp.searchsorted(neg_sorted, scores, side="left")
    upto = jnp.searchsorted(neg_sorted, scores, side="right")
    is_pos = pos_mask & valid_mask
    wins = jnp.sum(jnp.where(
        is_pos, below + 0.5 * (upto - below), 0.0))
    n_pos = jnp.sum(is_pos)
    n_neg = jnp.sum(neg_mask)
    return jnp.where(
        (n_pos > 0) & (n_neg > 0),
        wins / jnp.maximum(n_pos * n_neg, 1).astype(jnp.float32),
        jnp.nan)


@jax.jit
def auc_batch(scores, pos_mask, valid_mask):
    """(..., I) full score rows -> (...,) per-user AUC.

    ``pos_mask`` marks the heldout positives, ``valid_mask`` the items
    eligible as negatives OR positives (False = excluded: seen-in-train
    items and padding). Ties between a positive and a negative count
    0.5, matching the pairwise scalar oracle exactly."""
    lead = scores.shape[:-1]
    flat = (-1, scores.shape[-1])
    out = jax.vmap(_auc_row)(
        scores.reshape(flat),
        pos_mask.reshape(flat),
        valid_mask.reshape(flat))
    return out.reshape(lead)


# ---------------------------------------------------------------------------
# pure-Python scalar oracles (the parity suite's ground truth)
# ---------------------------------------------------------------------------

def precision_at_k_scalar(ranked: Sequence, actual: Sequence,
                          k: int) -> float | None:
    actual_set = set(actual)
    if not actual_set:
        return None
    tp = sum(1 for it in list(ranked)[:k] if it in actual_set)
    return tp / min(k, len(actual_set))


def recall_at_k_scalar(ranked: Sequence, actual: Sequence,
                       k: int) -> float | None:
    actual_set = set(actual)
    if not actual_set:
        return None
    tp = sum(1 for it in list(ranked)[:k] if it in actual_set)
    return tp / len(actual_set)


def map_at_k_scalar(ranked: Sequence, actual: Sequence,
                    k: int) -> float | None:
    actual_set = set(actual)
    if not actual_set:
        return None
    hits = 0
    total = 0.0
    for i, it in enumerate(list(ranked)[:k], start=1):
        if it in actual_set:
            hits += 1
            total += hits / i
    return total / min(k, len(actual_set))


def ndcg_at_k_scalar(ranked: Sequence, actual: Sequence,
                     k: int) -> float | None:
    actual_set = set(actual)
    if not actual_set:
        return None
    dcg = sum(
        1.0 / math.log2(i + 1)
        for i, it in enumerate(list(ranked)[:k], start=1)
        if it in actual_set)
    idcg = sum(1.0 / math.log2(i + 1)
               for i in range(1, min(k, len(actual_set)) + 1))
    return dcg / idcg


def auc_scalar(scores: Sequence[float], positives: Sequence[int],
               valid: Sequence[int] | None = None) -> float | None:
    """O(P*N) pairwise oracle over one user's full score row."""
    pos_set = set(positives)
    idxs = (range(len(scores)) if valid is None else valid)
    pos = [scores[i] for i in idxs if i in pos_set]
    neg = [scores[i] for i in idxs if i not in pos_set]
    if not pos or not neg:
        return None
    wins = sum(
        1.0 if p > n else (0.5 if p == n else 0.0)
        for p in pos for n in neg)
    return wins / (len(pos) * len(neg))


# ---------------------------------------------------------------------------
# Metric-contract adapters (the generic Engine.eval / QPA path)
# ---------------------------------------------------------------------------

def pad_actuals(actuals: Sequence[np.ndarray], pad_to: int | None = None
                ) -> np.ndarray:
    """Ragged per-user index arrays -> (B, A) int32, -1-padded."""
    width = max((len(a) for a in actuals), default=0)
    if pad_to is not None:
        width = max(width, pad_to)
    out = np.full((len(actuals), max(width, 1)), -1, np.int32)
    for j, a in enumerate(actuals):
        out[j, :len(a)] = a
    return out


def nanmean_sum_count(per_user: np.ndarray) -> tuple[float, int]:
    """-> (sum, count) over non-NaN per-user scores; the sweep persists
    these per fold so the overall mean weights users, not folds."""
    valid = ~np.isnan(per_user)
    return float(np.sum(per_user[valid])), int(np.count_nonzero(valid))


class RankingMetric(Metric[float]):
    """Vectorized ranking metric: Metric contract over QPA triples AND a
    batched ``score_ranked(topk_idx, actual_idx)`` array path — the two
    entry points share the ONE jitted kernel, so the sweep's batched
    scores and the generic path's scores cannot drift."""

    higher_is_better = True
    needs_full_scores = False

    def __init__(self, k: int = 10):
        self.k = int(k)

    @property
    def header(self) -> str:
        return f"{self._NAME}@{self.k}"

    @property
    def key(self) -> str:
        return f"{self._NAME.lower()}@{self.k}"

    # -- batched array path -------------------------------------------------
    def score_ranked(self, topk_idx, actual_idx) -> np.ndarray:
        """(..., K>=k) ranked indices x (..., A) padded actuals ->
        per-user scores with NaN for unscorable users."""
        if topk_idx.shape[-1] < self.k:
            # rankings shorter than k: pad with an impossible index so
            # the missing tail scores as misses, never as hits
            pad = self.k - topk_idx.shape[-1]
            topk_idx = jnp.concatenate([
                jnp.asarray(topk_idx),
                jnp.full(topk_idx.shape[:-1] + (pad,), -2,
                         jnp.asarray(topk_idx).dtype)], axis=-1)
        return np.asarray(self._KERNEL(
            jnp.asarray(topk_idx), jnp.asarray(actual_idx), self.k))

    # -- QPA / Metric-contract path ----------------------------------------
    def calculate(self, ctx, eval_data_set: EvalDataSet) -> float:
        ranked_ids: list[list] = []
        actual_ids: list[list] = []
        for _, qpa in eval_data_set:
            for _q, p, a in qpa:
                ranked_ids.append(_ranked_items(p))
                actual_ids.append(list(a or []))
        if not ranked_ids:
            return float("nan")
        # local id vocabulary: metric only needs equality, not identity
        vocab: dict[Any, int] = {}
        def enc(ids):
            out = np.empty(len(ids), np.int32)
            for j, it in enumerate(ids):
                code = vocab.get(it)
                if code is None:
                    code = vocab[it] = len(vocab)
                out[j] = code
            return out
        topk = pad_actuals(
            [enc(r[:self.k]) for r in ranked_ids], pad_to=self.k)
        # -1 padding in the RANKING must never match -1 actual padding
        topk[topk < 0] = -2
        actual = pad_actuals([enc(a) for a in actual_ids])
        per_user = self.score_ranked(topk, actual)
        s, c = nanmean_sum_count(per_user)
        return s / c if c else float("nan")


def _ranked_items(prediction) -> list:
    if isinstance(prediction, dict):
        return [s["item"] for s in prediction.get("itemScores", [])]
    return list(prediction or [])


class MAPAtK(RankingMetric):
    _NAME = "MAP"
    _KERNEL = staticmethod(map_at_k_batch)


class NDCGAtK(RankingMetric):
    _NAME = "NDCG"
    _KERNEL = staticmethod(ndcg_at_k_batch)


class PrecisionAtK(RankingMetric):
    _NAME = "Precision"
    _KERNEL = staticmethod(precision_at_k_batch)


class RecallAtK(RankingMetric):
    _NAME = "Recall"
    _KERNEL = staticmethod(recall_at_k_batch)


class AUC(Metric[float]):
    """Area under the ROC curve over full score rows (batched path
    only: a top-k ItemScores list cannot rank the items it omitted, so
    the QPA adapter refuses rather than silently approximating)."""

    higher_is_better = True
    needs_full_scores = True
    k = 0

    @property
    def header(self) -> str:
        return "AUC"

    key = "auc"

    def score_full(self, scores, pos_mask, valid_mask) -> np.ndarray:
        return np.asarray(auc_batch(
            jnp.asarray(scores), jnp.asarray(pos_mask),
            jnp.asarray(valid_mask)))

    def calculate(self, ctx, eval_data_set: EvalDataSet) -> float:
        raise ValueError(
            "AUC needs full per-item score rows; it is computed on the "
            "batched sweep path (pio eval --sweep), not from top-k "
            "prediction lists — use map@k/ndcg@k/precision@k here")


_METRIC_NAMES = {
    "map": MAPAtK, "ndcg": NDCGAtK, "precision": PrecisionAtK,
    "p": PrecisionAtK, "recall": RecallAtK, "r": RecallAtK,
}


def parse_metric(spec: str) -> Metric:
    """'map@10' / 'ndcg@5' / 'precision@10' / 'auc' -> metric object."""
    s = spec.strip().lower()
    if s == "auc":
        return AUC()
    name, _, k = s.partition("@")
    cls = _METRIC_NAMES.get(name)
    if cls is None or not k:
        raise ValueError(
            f"unknown metric {spec!r} (expected map@K, ndcg@K, "
            "precision@K, recall@K, or auc)")
    try:
        return cls(int(k))
    except ValueError:
        raise ValueError(f"bad k in metric {spec!r}") from None
