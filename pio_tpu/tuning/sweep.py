"""Batched hyperparameter sweep — train and score N candidates as one
device program instead of N sequential trains.

The tentpole of ISSUE 13 (ROADMAP item 5): candidates that share array
shapes — same rank / iteration count / implicitness, differing only in
the continuous hyperparams (lambda, alpha) — are STACKED into one
vmapped ALS train+score program (``ops.als.als_train_stacked``), so a
sweep's cost is one layout build + one compile + batched MXU work, not
N of each. The lever is Chiu et al. (1612.01437): distributed
factorization is dominated by data movement, so batch the work that
shares data. Shape-incompatible candidates fall into per-shape groups
(each still batched); candidates the batched path cannot express at all
(two-tower, sequence, any non-ALS engine) fall back to grouped
sequential runs through the engine's own eval path — NEVER an error.

Crash safety rides the PR-3 machinery's pattern: the sweep's unit of
work (a fold on the batched path, a candidate on the sequential path)
checkpoints its results into the durable ``<eval-iid>:sweep`` record
after completion; a killed sweep resumed with the same EvaluationInstance
id skips completed units and — because splits, inits and metrics are all
seeded/deterministic — produces a result identical to the uninterrupted
run. ``eval.fold`` / ``eval.candidate`` chaos points make that drill
scriptable, and the same names are the span labels on the obs plane.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from pio_tpu.controller.engine import Engine, EngineParams
from pio_tpu.controller.evaluation import (
    Metric,
    MetricEvaluatorResult,
    MetricScores,
)
from pio_tpu.ops import als
from pio_tpu.ops.bucketing import pow2_bucket
from pio_tpu.resilience import chaos
from pio_tpu.tuning.metrics import (
    AUC,
    MASKED_SCORE,
    RankingMetric,
    nanmean_sum_count,
    pad_actuals,
)
from pio_tpu.tuning.records import (
    SweepState,
    load_sweep_state,
    save_sweep_state,
)
from pio_tpu.tuning.splits import EvalFold, folds_for

log = logging.getLogger("pio_tpu.tuning")


@dataclass
class SweepConfig:
    metric: Metric
    other_metrics: list[Metric] = field(default_factory=list)
    split: str = "kfold"            # kfold | time
    folds: int = 3
    seed: int = 42
    exclude_seen: bool = True
    # eval-user batch per scoring dispatch: bounds the (C, B, I) score
    # block; pow2-bucketed so varying tails reuse compiled programs
    batch_users: int = 512

    def all_metrics(self) -> list[Metric]:
        return [self.metric, *self.other_metrics]


# ---------------------------------------------------------------------------
# candidate shape grouping
# ---------------------------------------------------------------------------

_ALS_CONTINUOUS = ("lambda_", "alpha")
# algo-param fields the stacked trainer actually maps into ALSParams
# (see _train_group); a grid varying anything OUTSIDE this set — e.g.
# validation_fraction — cannot be expressed batched and must fall back
# to the sequential path, or the sweep would silently not vary it
_ALS_BATCHED_FIELDS = frozenset({
    "rank", "num_iterations", "lambda_", "alpha", "implicit_prefs",
    "seed", "chunk", "cg_iters", "cg_warm_iters", "cg_warm_sweeps",
})


def _als_algo_params(ep: EngineParams):
    """The (name, params) of an ALS-shaped first algorithm, or None —
    the batched path's eligibility test. 'ALS-shaped' = carries the
    rank/lambda_/alpha/implicit_prefs factor-model surface."""
    algos = ep.algorithms or []
    if len(algos) != 1:
        return None
    name, p = algos[0]
    for f in ("rank", "lambda_", "alpha", "implicit_prefs",
              "num_iterations"):
        if not hasattr(p, f):
            return None
    return name, p


def _shape_key(p) -> tuple:
    """Everything about the algo params EXCEPT the vmapped continuous
    hyperparams: candidates sharing this key train as one stacked
    program."""
    d = {f.name: getattr(p, f.name) for f in dataclasses.fields(p)}
    for cont in _ALS_CONTINUOUS:
        d.pop(cont, None)
    return tuple(sorted((k, repr(v)) for k, v in d.items()))


def group_candidates(
    candidates: Sequence[EngineParams],
) -> tuple[dict[tuple, list[int]], bool]:
    """-> ({shape key: candidate indices}, batchable). batchable is
    False when ANY candidate is not ALS-shaped or datasource/serving
    params differ across candidates (the batched path reads the data
    once — a grid that varies the read is a different experiment)."""
    if not candidates:
        raise ValueError("sweep needs at least one candidate")
    base = candidates[0]
    groups: dict[tuple, list[int]] = {}
    field_values: dict[str, set] = {}
    for i, ep in enumerate(candidates):
        algo = _als_algo_params(ep)
        if algo is None:
            return {}, False
        if (ep.datasource != base.datasource
                or ep.preparator != base.preparator
                or ep.serving != base.serving):
            return {}, False
        p = algo[1]
        if dataclasses.is_dataclass(p):
            for f in dataclasses.fields(p):
                field_values.setdefault(f.name, set()).add(
                    repr(getattr(p, f.name)))
            # best-sweep validation selection is a different training
            # program than the stacked trainer runs: candidates asking
            # for it must train through the real ALSAlgorithm.train
            if getattr(p, "validation_fraction", 0.0):
                return {}, False
        groups.setdefault(_shape_key(p), []).append(i)
    # a grid axis the stacked trainer cannot express (it maps only
    # _ALS_BATCHED_FIELDS into ALSParams) would otherwise be a silent
    # no-op: identical scores, arbitrary "winner"
    for name, vals in field_values.items():
        if len(vals) > 1 and name not in _ALS_BATCHED_FIELDS:
            log.info("sweep falls back to sequential: grid varies %r, "
                     "which the stacked trainer does not map", name)
            return {}, False
    return groups, True


# ---------------------------------------------------------------------------
# batched scoring
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("k",))
def _stacked_topk(user_f, item_f, uidx, seen_pad, k: int):
    """(C,U,r) x (C,I,r) factors -> per-candidate top-k over the eval
    users, with seen-in-train items masked below any real score.
    Returns (scores (C,B,I), top_idx (C,B,k)) — scores feed AUC, the
    ranking feeds the top-k metrics."""
    uf = jnp.take(user_f, uidx, axis=1)                  # (C, B, r)
    scores = jnp.einsum(
        "cbr,cir->cbi", uf, item_f,
        preferred_element_type=jnp.float32)
    n_items = item_f.shape[1]
    b = uidx.shape[0]
    # scatter the -1-padded seen ids into a (B, I) mask via an overflow
    # column that the pad rows land in
    seen_cols = jnp.where(seen_pad >= 0, seen_pad, n_items)
    seen_mask = jnp.zeros((b, n_items + 1), bool).at[
        jnp.arange(b)[:, None], seen_cols].set(True)[:, :n_items]
    masked = jnp.where(seen_mask[None], MASKED_SCORE, scores)
    _, top_idx = jax.lax.top_k(masked, k)
    return masked, top_idx


def _score_stacked(
    stacked: als.StackedALSModel,
    fold: EvalFold,
    metrics: Sequence[Metric],
    batch_users: int,
) -> list[list[tuple[float, int]]]:
    """-> per candidate, per metric: (sum, count) over the fold's test
    users. Users stream in pow2-bucketed batches so the (C, B, I) score
    block stays bounded and the compiled program count stays O(log)."""
    n_cand = len(stacked)
    n_items = int(stacked.item_factors.shape[1])
    k_rank = max((m.k for m in metrics if isinstance(m, RankingMetric)),
                 default=0)
    k_top = pow2_bucket(max(k_rank, 1), cap=max(n_items, 1))
    want_full = any(isinstance(m, AUC) for m in metrics)
    sums = [[0.0] * len(metrics) for _ in range(n_cand)]
    counts = [[0] * len(metrics) for _ in range(n_cand)]
    b_total = fold.n_test_users
    pos = 0
    while pos < b_total:
        hi = min(pos + batch_users, b_total)
        b = hi - pos
        bb = pow2_bucket(b)
        uidx = np.zeros(bb, np.int32)
        uidx[:b] = fold.test_user_idx[pos:hi]
        actual = pad_actuals(fold.actual_idx[pos:hi])
        seen = pad_actuals(fold.seen_idx[pos:hi])
        # pad the user tail AND bucket the ragged widths: each width
        # bucket compiles once
        aw = pow2_bucket(actual.shape[1])
        sw = pow2_bucket(seen.shape[1])
        actual_p = np.full((bb, aw), -1, np.int32)
        actual_p[:b, :actual.shape[1]] = actual
        seen_p = np.full((bb, sw), -1, np.int32)
        seen_p[:b, :seen.shape[1]] = seen
        scores, top_idx = _stacked_topk(
            stacked.user_factors, stacked.item_factors,
            jnp.asarray(uidx), jnp.asarray(seen_p), k_top)
        top_np = np.asarray(top_idx)[:, :b]
        pos_mask = valid_mask = None
        if want_full:
            pos_mask = np.zeros((bb, n_items), bool)
            valid_mask = np.ones((bb, n_items), bool)
            for j in range(b):
                pos_mask[j, fold.actual_idx[pos + j]] = True
                s = fold.seen_idx[pos + j]
                if len(s):
                    valid_mask[j, s] = False
                valid_mask[j, fold.actual_idx[pos + j]] = True
        for mi, metric in enumerate(metrics):
            if isinstance(metric, AUC):
                shape = (n_cand,) + pos_mask.shape
                per_user = metric.score_full(
                    scores,
                    np.broadcast_to(pos_mask, shape),
                    np.broadcast_to(valid_mask, shape))[:, :b]
            else:
                per_user = metric.score_ranked(
                    top_np, np.asarray(actual_p)[None, :b])
            for c in range(n_cand):
                s, n = nanmean_sum_count(per_user[c])
                sums[c][mi] += s
                counts[c][mi] += n
        pos = hi
    return [
        [(sums[c][m], counts[c][m]) for m in range(len(metrics))]
        for c in range(n_cand)
    ]


# ---------------------------------------------------------------------------
# the runner
# ---------------------------------------------------------------------------

class SweepRunner:
    """Drives one sweep against a persisted EvaluationInstance id.

    ``run(ctx)`` returns a MetricEvaluatorResult (the exact shape the
    classic MetricEvaluator produces, so the dashboard/instance-record
    rendering is shared)."""

    def __init__(
        self,
        engine: Engine,
        candidates: Sequence[EngineParams],
        storage,
        config: SweepConfig,
        eval_id: str,
        tracer=None,
    ):
        from pio_tpu.utils.tracing import Tracer

        self.engine = engine
        self.candidates = list(candidates)
        self.storage = storage
        self.config = config
        self.eval_id = eval_id
        self.tracer = tracer or Tracer()
        self.groups, self.batchable = group_candidates(self.candidates)
        self.mode = "batched" if self.batchable else "sequential"
        self.last_sweep_seconds: float | None = None
        # optional progress hook: on_unit(done, total) after every
        # persisted unit (the eval server's /healthz progress)
        self.on_unit = None

    # -- durable unit bookkeeping -------------------------------------------
    def _load_or_init_state(self, units: list[str]) -> SweepState:
        state = load_sweep_state(self.storage, self.eval_id)
        spec = {
            "mode": self.mode,
            "split": self.config.split,
            "folds": self.config.folds,
            "seed": self.config.seed,
            # the FULL metric list and candidate grid, not just counts:
            # resuming with a same-cardinality but different grid (or an
            # added metric column) would otherwise pass the check and
            # aggregate fold results computed from different params —
            # the corrupted average would pick the deployed winner
            "metrics": [m.header for m in self.config.all_metrics()],
            "candidates": [ep.to_json() for ep in self.candidates],
        }
        if state is not None:
            if state.units != units or state.spec != spec:
                raise ValueError(
                    f"evaluation {self.eval_id} has a persisted sweep "
                    "state with a different plan (grid/split/seed "
                    "changed?) — start a fresh eval instead of resuming")
            done = [u for u in units if u in state.completed]
            if done:
                log.info("sweep %s resume: %d/%d unit(s) already "
                         "completed (%s)", self.eval_id, len(done),
                         len(units), ", ".join(done))
        else:
            state = SweepState(eval_id=self.eval_id, spec=spec,
                               units=units)
            save_sweep_state(self.storage, state)
        if self.on_unit is not None:
            # progress surfaces show done/TOTAL from the first poll, not
            # only after the first unit completes
            self.on_unit(len(state.completed), len(state.units))
        return state

    def _complete_unit(self, state: SweepState, unit: str,
                       payload: dict) -> None:
        state.completed[unit] = payload
        save_sweep_state(self.storage, state)
        if self.on_unit is not None:
            self.on_unit(len(state.completed), len(state.units))

    # -- entry ---------------------------------------------------------------
    def run(self, ctx) -> MetricEvaluatorResult:
        t0 = time.perf_counter()
        recorder = getattr(self.tracer, "recorder", None)
        if recorder is not None:
            # the whole sweep is ONE root trace (the folder's cycle
            # idiom): eval.fold / eval.candidate spans land in the
            # recorder, so `pio top --url <metrics-port>` shows them
            # live and a failed sweep's tree is always retained
            with recorder.trace("eval.sweep"):
                result = self._run_traced(ctx)
        else:
            result = self._run_traced(ctx)
        dt = time.perf_counter() - t0
        self.last_sweep_seconds = dt
        self.tracer.record("eval_sweep_seconds", dt)
        return result

    def _run_traced(self, ctx) -> MetricEvaluatorResult:
        with self.tracer.span("eval.sweep", mode=self.mode):
            if self.batchable:
                return self._run_batched(ctx)
            return self._run_sequential(ctx)

    # -- batched ALS path ----------------------------------------------------
    def _read_folds(self, ctx) -> list[EvalFold]:
        _, ds_params = self.candidates[0].datasource
        c = self.config
        # EXACTLY the recommendation datasource's training-read value
        # semantics (value_key="rating" unconditionally; value_event
        # restricts the property read to that one event name) — the
        # time split must score candidates on the same values the
        # winner later trains on
        common = dict(
            value_key="rating",
            default_value=getattr(ds_params, "implicit_value", 1.0),
            value_event=getattr(ds_params, "rating_event", None),
            dedup="last",
        )
        if c.split == "time":
            store = ctx.event_store
            app_id, channel_id = store._resolve(
                ds_params.app_name,
                getattr(ds_params, "channel_name", None))
            cols = self.storage.get_events().find_columnar(
                app_id=app_id, channel_id=channel_id,
                entity_type="user", target_entity_type="item",
                event_names=list(getattr(ds_params, "event_names",
                                         ("rate", "buy"))),
            )
            return folds_for(cols, "time", c.folds,
                             exclude_seen=c.exclude_seen, **common)
        ds, _prep, _algos, _serv = self.engine._doers(self.candidates[0])
        data = ds.read_training(ctx)
        return folds_for(data, "kfold", c.folds, seed=c.seed,
                         exclude_seen=c.exclude_seen)

    def _train_group(self, ctx, fold: EvalFold,
                     cand_idx: list[int]) -> als.StackedALSModel:
        algos = [_als_algo_params(self.candidates[i]) for i in cand_idx]
        _, p0 = algos[0]
        base = als.ALSParams(
            rank=p0.rank,
            iterations=p0.num_iterations,
            reg=p0.lambda_,
            alpha=p0.alpha,
            implicit=p0.implicit_prefs,
            seed=p0.seed if getattr(p0, "seed", None) is not None else 3,
            chunk=getattr(p0, "chunk", 65536),
            cg_iters=getattr(p0, "cg_iters", -1),
            cg_warm_iters=getattr(p0, "cg_warm_iters", 6),
            cg_warm_sweeps=getattr(p0, "cg_warm_sweeps", 2),
        )
        regs = np.array([p.lambda_ for _, p in algos], np.float32)
        alphas = np.array([p.alpha for _, p in algos], np.float32)
        t = fold.train
        return als.als_train_stacked(
            t.user_idx, t.item_idx, t.values, t.n_users, t.n_items,
            base, regs, alphas, mesh=getattr(ctx, "mesh", None))

    def _run_batched(self, ctx) -> MetricEvaluatorResult:
        c = self.config
        metrics = c.all_metrics()
        units = [f"fold{f}" for f in range(c.folds)]
        state = self._load_or_init_state(units)
        folds: list[EvalFold] | None = None
        group_list = sorted(self.groups.items())   # deterministic order
        for f, unit in enumerate(units):
            if unit in state.completed:
                continue
            chaos.maybe_inject(f"eval.fold.{f}")
            if folds is None:
                folds = self._read_folds(ctx)      # read once, lazily:
                # a fully-resumed sweep re-reads nothing
            fold = folds[f]
            per_cand: list[dict | None] = [None] * len(self.candidates)
            with self.tracer.span("eval.fold", fold=f,
                                  testUsers=fold.n_test_users):
                for gi, (_key, cand_idx) in enumerate(group_list):
                    chaos.maybe_inject(f"eval.candidate.{gi}")
                    with self.tracer.span(
                            "eval.candidate", group=gi,
                            candidates=len(cand_idx), fold=f):
                        stacked = self._train_group(ctx, fold, cand_idx)
                        scored = _score_stacked(
                            stacked, fold, metrics, c.batch_users)
                    for local, ci in enumerate(cand_idx):
                        per_cand[ci] = {
                            m.header: list(scored[local][mi])
                            for mi, m in enumerate(metrics)
                        }
            self._complete_unit(state, unit, {"candidates": per_cand})
        return self._result_from_fold_state(state, metrics)

    def _result_from_fold_state(
            self, state: SweepState,
            metrics: list[Metric]) -> MetricEvaluatorResult:
        n = len(self.candidates)
        agg = [[(0.0, 0)] * len(metrics) for _ in range(n)]
        for unit in state.units:
            payload = state.completed[unit]["candidates"]
            for ci in range(n):
                for mi, m in enumerate(metrics):
                    s0, c0 = agg[ci][mi]
                    s1, c1 = payload[ci][m.header]
                    agg[ci][mi] = (s0 + s1, c0 + c1)
        scores = []
        for ci, ep in enumerate(self.candidates):
            means = [
                (s / c if c else float("nan")) for s, c in agg[ci]
            ]
            scores.append((ep, MetricScores(
                score=means[0], other_scores=means[1:])))
        return _pick_best(scores, self.config.metric, metrics)

    # -- grouped sequential fallback ----------------------------------------
    def _run_sequential(self, ctx) -> MetricEvaluatorResult:
        c = self.config
        if c.split == "time":
            raise ValueError(
                "--split time is not supported on the sequential "
                "fallback: the engine's own read_eval defines its "
                "folds (the sequence engine's rolling read_eval is "
                "already time-respecting; others use index-mod-k) — "
                "use --split kfold here")
        metrics = c.all_metrics()
        full_scorable = [m for m in metrics
                         if not getattr(m, "needs_full_scores", False)]
        if len(full_scorable) != len(metrics):
            dropped = [m.header for m in metrics
                       if getattr(m, "needs_full_scores", False)]
            if self.config.metric.header in dropped:
                raise ValueError(
                    f"primary metric {self.config.metric.header} needs "
                    "full score rows, which the sequential fallback "
                    "(non-ALS engines) cannot provide — pick a top-k "
                    "metric (map@K / ndcg@K / precision@K)")
            log.warning("sequential fallback drops full-score "
                        "metric(s): %s", ", ".join(dropped))
            metrics = full_scorable
        units = [f"cand{i}" for i in range(len(self.candidates))]
        state = self._load_or_init_state(units)
        fast = _fast_engine(self.engine)
        # rankings must be at least as deep as the deepest metric k:
        # read_eval queries default num=10, which would force ranks
        # k+1..K to misses and silently cap e.g. recall@20 at recall@10
        k_need = max((m.k for m in metrics if isinstance(m, RankingMetric)),
                     default=0)
        for i, unit in enumerate(units):
            if unit in state.completed:
                continue
            chaos.maybe_inject(f"eval.candidate.{i}")
            ep = _with_eval_folds(self.candidates[i], c.folds, k_need)
            with self.tracer.span("eval.candidate", idx=i):
                eval_set = fast.eval(ctx, ep)
                payload = {
                    m.header: m.calculate(ctx, eval_set)
                    for m in metrics
                }
            self._complete_unit(state, unit, {"scores": payload})
        scores = []
        for i, ep in enumerate(self.candidates):
            payload = state.completed[units[i]]["scores"]
            scores.append((ep, MetricScores(
                score=payload[metrics[0].header],
                other_scores=[payload[m.header] for m in metrics[1:]],
            )))
        return _pick_best(scores, metrics[0], metrics)


def _fast_engine(engine: Engine) -> Engine:
    """Wrap the engine's class maps in a FastEvalEngine so candidates
    sharing a datasource/preparator prefix run those stages once."""
    from pio_tpu.controller.fasteval import FastEvalEngine

    return FastEvalEngine(
        engine.datasource_classes, engine.preparator_classes,
        engine.algorithm_classes, engine.serving_classes)


def _with_eval_folds(ep: EngineParams, folds: int,
                     k_need: int = 0) -> EngineParams:
    """The sequential path scores through the engine's own read_eval;
    a datasource that gates fold production on an eval_k param gets the
    sweep's fold count when it was left unset, and an eval_num
    shallower than the deepest metric k is raised to it (a 10-item
    ranking cannot score recall@20)."""
    name, p = ep.datasource
    if p is None:
        return ep
    updates: dict = {}
    if hasattr(p, "eval_k") and getattr(p, "eval_k", 0) in (0, None):
        updates["eval_k"] = folds
    if k_need and hasattr(p, "eval_num") \
            and getattr(p, "eval_num", 0) < k_need:
        updates["eval_num"] = k_need
    if not updates:
        return ep
    try:
        return dataclasses.replace(
            ep, datasource=(name, dataclasses.replace(p, **updates)))
    except TypeError:
        return ep


def _pick_best(scores, primary: Metric,
               metrics: list[Metric]) -> MetricEvaluatorResult:
    """Result assembly around the SHARED best-candidate selection
    (controller.evaluation.pick_best_index — the classic evaluator's
    NaN-never-wins policy, one implementation)."""
    from pio_tpu.controller.evaluation import pick_best_index

    best_idx = pick_best_index(scores, primary)
    return MetricEvaluatorResult(
        best_score=scores[best_idx][1],
        best_engine_params=scores[best_idx][0],
        best_idx=best_idx,
        metric_header=primary.header,
        other_metric_headers=[m.header for m in metrics[1:]],
        engine_params_scores=list(scores),
    )
