"""The sweep's observability surface — `pio eval --sweep --metrics-port`.

A sweep is a batch job, but a LONG one (it trains the whole grid), so it
gets the same plane every other surface has: ``/healthz`` with progress,
``/metrics.json``, Prometheus ``/metrics`` (the ``eval_sweep_seconds``
histogram + best-score gauge under ``surface="eval"``), and the
``/debug`` trace routes — `pio top --url http://host:port` shows the
``eval.fold`` / ``eval.candidate`` span table live, and `pio trace`
resolves a sweep's span tree like any request's.
"""

from __future__ import annotations

import threading

from pio_tpu.server.http import (
    HttpApp,
    HttpServer,
    RawResponse,
    Request,
    server_key_ok,
)
from pio_tpu.utils.tracing import (
    PROMETHEUS_CONTENT_TYPE,
    prometheus_histogram,
    prometheus_text,
)

# fixed wall-clock buckets (seconds): sweeps span smoke-test seconds to
# overnight grids
_BUCKETS_S = (1.0, 5.0, 15.0, 60.0, 300.0, 1800.0, 7200.0)


class EvalStatus:
    """Thread-safe sweep progress the HTTP surface reads."""

    def __init__(self, tracer, recorder=None):
        self.tracer = tracer
        self.recorder = recorder
        self._lock = threading.Lock()
        self._state = {
            "phase": "starting", "evalId": None, "mode": None,
            "unitsTotal": 0, "unitsDone": 0,
            "bestScore": None, "metric": None,
        }
        self._sweep_counts = [0] * (len(_BUCKETS_S) + 1)
        self._sweep_sum = 0.0
        self._sweep_n = 0

    def update(self, **kv) -> None:
        with self._lock:
            self._state.update(kv)

    def observe_sweep_seconds(self, dt: float) -> None:
        with self._lock:
            self._sweep_sum += dt
            self._sweep_n += 1
            for i, ub in enumerate(_BUCKETS_S):
                if dt <= ub:
                    self._sweep_counts[i] += 1
                    return
            self._sweep_counts[-1] += 1

    def snapshot(self) -> dict:
        with self._lock:
            return dict(
                self._state,
                sweepSeconds={
                    "bucketsS": list(_BUCKETS_S),
                    "counts": list(self._sweep_counts[:-1]),
                    "count": self._sweep_n,
                    "sumSeconds": self._sweep_sum,
                },
            )


def build_eval_app(status: EvalStatus, server_key: str = "") -> HttpApp:
    app = HttpApp("eval")

    @app.route("GET", r"/")
    def root(req: Request):
        return 200, {"status": "alive", "role": "eval",
                     **status.snapshot()}

    @app.route("GET", r"/healthz")
    def healthz(req: Request):
        snap = status.snapshot()
        return 200, {"status": "alive", "phase": snap["phase"],
                     "unitsDone": snap["unitsDone"],
                     "unitsTotal": snap["unitsTotal"]}

    @app.route("GET", r"/metrics\.json")
    def metrics_json(req: Request):
        out = status.snapshot()
        out["spans"] = status.tracer.snapshot()
        if status.recorder is not None:
            out["exemplars"] = status.recorder.exemplars()
        return 200, out

    @app.route("GET", r"/metrics")
    def metrics_prometheus(req: Request):
        from pio_tpu.utils.httpclient import pool_counters

        snap = status.snapshot()
        counters = {
            "eval_units_done": float(snap["unitsDone"]),
            "eval_units_total": float(snap["unitsTotal"]),
        }
        counters.update(pool_counters())
        if snap["bestScore"] is not None:
            counters["eval_best_score"] = float(snap["bestScore"])
        text = prometheus_text(
            status.tracer.snapshot(), counters,
            labels={"surface": "eval"})
        h = snap["sweepSeconds"]
        lines = prometheus_histogram(
            "eval_sweep_seconds", h["bucketsS"], h["counts"],
            h["count"], h["sumSeconds"], labels={"surface": "eval"})
        return 200, RawResponse(
            text + "\n".join(lines) + "\n", PROMETHEUS_CONTENT_TYPE)

    from pio_tpu.obs.http import install_trace_routes

    app.tracer = status.tracer
    install_trace_routes(
        app, status.recorder,
        lambda req: server_key_ok(req, server_key))
    return app


def create_eval_server(status: EvalStatus, ip: str = "127.0.0.1",
                       port: int = 0, server_key: str = "") -> HttpServer:
    """-> started-on-demand HTTP transport (port=0: bound port known
    after start())."""
    return HttpServer(build_eval_app(status, server_key),
                      host=ip, port=port)
