"""SimRank similarity as dense MXU iteration.

Replaces the reference friend-recommendation template's Delta-SimRank over
GraphX (examples/experimental/scala-parallel-friend-recommendation/src/main/
scala/DeltaSimRankRDD.scala): there each iteration materializes RDD deltas
over in-neighbor cartesian products and reduces by key — a shuffle per
iteration. The TPU formulation is the closed matrix recurrence

    S_{t+1} = decay * W^T S_t W,   diag(S) := 1

with W the in-neighbor-normalized adjacency (W[i,j] = A[i,j]/indeg(j)):
two dense (n,n) matmuls per iteration on the MXU, no shuffles, no deltas.
The reference's delta trick exists because Spark pays per-pair traffic;
here the full n^2 state is a resident HBM buffer (n <= ~16k nodes on a
16GB chip — beyond that, sample the graph first: the reference ships node
and forest-fire sampling datasources for exactly this reason, mirrored in
models/friendrecommendation.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("n_pad", "iterations"))
def _simrank_jit(src, dst, n_pad: int, iterations: int, decay):
    """Dense SimRank: build W from COO edges, iterate the recurrence."""
    A = jnp.zeros((n_pad, n_pad), jnp.float32)
    A = A.at[src, dst].add(1.0, mode="drop")
    A = jnp.minimum(A, 1.0)            # parallel edges count once
    indeg = A.sum(axis=0)              # in-degree of each dst column
    W = A * jnp.where(indeg > 0, 1.0 / jnp.maximum(indeg, 1.0), 0.0)[None, :]
    Wb = W.astype(jnp.bfloat16)
    eye = jnp.eye(n_pad, dtype=bool)

    def body(_, S):
        # decay * W^T S W, then pin the diagonal back to 1
        T = jnp.einsum(
            "ij,ik->jk", Wb, S.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )  # W^T S
        S = decay * jnp.einsum(
            "ij,jk->ik", T.astype(jnp.bfloat16), Wb,
            preferred_element_type=jnp.float32,
        )  # (W^T S) W
        return jnp.where(eye, 1.0, S)

    S0 = jnp.eye(n_pad, dtype=jnp.float32)
    return jax.lax.fori_loop(0, iterations, body, S0)


def simrank_scores(
    src: np.ndarray,
    dst: np.ndarray,
    n_nodes: int,
    decay: float = 0.8,
    iterations: int = 5,
) -> np.ndarray:
    """-> (n_nodes, n_nodes) SimRank matrix (host numpy).

    decay/iterations mirror the reference SimRankParams
    (SimRankAlgorithm.scala:10-12; DeltaSimRankRDD.decay default 0.8)."""
    if n_nodes <= 0:
        return np.zeros((0, 0), np.float32)
    n_pad = max(128, -(-n_nodes // 128) * 128)
    s = np.ascontiguousarray(src, dtype=np.int32)
    d = np.ascontiguousarray(dst, dtype=np.int32)
    S = _simrank_jit(
        jnp.asarray(s), jnp.asarray(d), n_pad, int(iterations),
        jnp.float32(decay),
    )
    return np.asarray(S)[:n_nodes, :n_nodes]


def simrank_topk(S: np.ndarray, k: int):
    """Top-k most similar nodes per node, self excluded.
    Returns (scores, idx): (n, k)."""
    n = S.shape[0]
    if n == 0:
        return np.zeros((0, 0), np.float32), np.zeros((0, 0), np.int64)
    k = max(1, min(int(k), n - 1))
    M = S.copy()
    np.fill_diagonal(M, -np.inf)
    idx = np.argpartition(-M, k - 1, axis=1)[:, :k]
    part = np.take_along_axis(M, idx, axis=1)
    order = np.argsort(-part, axis=1, kind="stable")
    idx = np.take_along_axis(idx, order, axis=1)
    scores = np.take_along_axis(part, order, axis=1)
    return scores.astype(np.float32), idx
