"""Cosine-similarity top-k over factor/embedding matrices.

Replaces the reference similarproduct template's driver-side cosine over
MLlib ALS productFeatures (examples/scala-parallel-similarproduct/multi/
src/main/scala/LikeAlgorithm.scala:21-86, ALSAlgorithm.scala cosine loop).
There the per-item cosine is an RDD map over all items per query; here it is
one normalized (B,k)x(k,I) matmul + lax.top_k on the MXU, with an optional
sharded path for catalogs too large for one chip.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from pio_tpu.ops.bucketing import pow2_bucket


@jax.jit
def normalize_rows(m: jax.Array, eps: float = 1e-9) -> jax.Array:
    return m / (jnp.linalg.norm(m, axis=1, keepdims=True) + eps)


@partial(jax.jit, static_argnames=("k",))
def _cosine_topk_jit(matrix_n, queries, k: int):
    q = normalize_rows(queries)
    scores = q @ matrix_n.T  # (B, I)
    return jax.lax.top_k(scores, k)


def cosine_topk(matrix: jax.Array, queries: jax.Array, k: int):
    """matrix: (I, d) item vectors; queries: (B, d). Returns (scores, idx)
    of the k most cosine-similar rows per query. BOTH k and the batch dim
    are bucketed to powers of two pre-jit (compile-cache bound — the
    serving micro-batcher produces arbitrary B), trimmed on host; zero
    padding rows are NaN-safe (normalize_rows' eps) and sliced away."""
    n = matrix.shape[0]
    k = max(1, min(int(k), n))
    bucket = pow2_bucket(k, cap=n)
    b = queries.shape[0]
    bb = pow2_bucket(b)
    if bb != b:
        queries = jnp.concatenate(
            [queries, jnp.zeros((bb - b, queries.shape[1]),
                                queries.dtype)])
    matrix_n = normalize_rows(matrix)
    scores, idx = _cosine_topk_jit(matrix_n, queries, bucket)
    return scores[:b, :k], idx[:b, :k]


def mean_vector(matrix: jax.Array, indices: np.ndarray) -> jax.Array:
    """Average of the given rows — the similarproduct query combiner
    (reference ALSAlgorithm.scala: sum of query-item feature vectors)."""
    return jnp.mean(matrix[jnp.asarray(indices)], axis=0, keepdims=True)


@partial(jax.jit, static_argnames=("n_items", "n_items_pad", "user_batch",
                                   "k"))
def _column_cosine_topk_jit(u_local, i_b, v_b, n_items: int,
                            n_items_pad: int, user_batch: int, k: int,
                            threshold):
    """Exact all-pairs column cosine + top-k on device.

    G = M^T M for the column-normalized user x item matrix M, accumulated
    as one (I,B)x(B,I) matmul per user batch: a lax.scan scatters each
    batch's pre-bucketed COO slice (host-grouped, so total scatter work is
    O(nnz)) into a dense strip, casts to bf16, and feeds the MXU with f32
    accumulation. Then diagonal masked, sub-threshold entries zeroed (the
    DIMSUM `threshold` contract: entries below it are not guaranteed),
    top-k per row.

    u_local/i_b/v_b: (n_batches, L) with sentinel-padded entries that drop
    out of range on every scatter.

    Normalization comes from the accumulated Gram's own diagonal (the true
    column norms AFTER duplicate (user, item) entries have summed in the
    scatter) — pre-normalizing raw COO values would over-count columns
    with duplicate entries."""

    def body(G, xs):
        ul, ib, vb = xs
        D = jnp.zeros((user_batch, n_items_pad), jnp.float32)
        D = D.at[ul, ib].add(vb, mode="drop")
        Db = D.astype(jnp.bfloat16)
        G = G + jnp.einsum(
            "bi,bj->ij", Db, Db, preferred_element_type=jnp.float32
        )
        return G, None

    G0 = jnp.zeros((n_items_pad, n_items_pad), jnp.float32)
    G, _ = jax.lax.scan(body, G0, (u_local, i_b, v_b))
    d = jnp.diagonal(G)
    inv = jnp.where(d > 0, jax.lax.rsqrt(jnp.maximum(d, 1e-30)), 0.0)
    G = G * inv[:, None] * inv[None, :]
    G = jnp.where(G >= threshold, G, 0.0)
    # self-similarity and padding columns must never rank: padded ids
    # would decode out of range in callers that trust the idx contract
    mask = jnp.eye(n_items_pad, dtype=bool) | (
        jnp.arange(n_items_pad)[None, :] >= n_items
    )
    G = jnp.where(mask, -1e9, G)
    return jax.lax.top_k(G, k)


def column_cosine_topk(
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    values: np.ndarray,
    n_users: int,
    n_items: int,
    k: int,
    threshold: float = 0.0,
    user_batch: int = 4096,
    chunk: int = 65536,
):
    """All-pairs item-to-item cosine over the raw interaction matrix — the
    TPU answer to MLlib `RowMatrix.columnSimilarities(threshold)` as used
    by the reference DIMSUM similarproduct template
    (examples/experimental/scala-parallel-similarproduct-dimsum/src/main/
    scala/DIMSUMAlgorithm.scala:125-132).

    DIMSUM's oversampling/threshold scheme exists to bound Spark shuffle
    traffic; on TPU the co-occurrence Gram matrix is a dense bf16 matmul
    stream (2*n_users*n_items^2 FLOPs on the MXU — ~200 TFLOP at the
    ML-20M shape, measured 7.4s warm on one v5e), so the EXACT similarities are
    computed and `threshold` is honored only as the reference's contract
    knob (entries below it zero). Memory bound: the f32 Gram matrix is
    (n_items^2), so catalogs up to ~50k items fit a 16GB chip; larger
    catalogs should use the ALS-factor cosine path (`cosine_topk`), which
    is rank-compressed.

    Returns (scores, idx): (n_items, k) host arrays, k-nearest per item.
    """
    n_items_pad = max(256, -(-n_items // 256) * 256)
    k = max(1, min(int(k), n_items - 1))
    k_bucket = pow2_bucket(k, cap=n_items_pad)

    u = np.ascontiguousarray(user_idx, dtype=np.int64)
    i = np.ascontiguousarray(item_idx, dtype=np.int32)
    v = np.ascontiguousarray(values, dtype=np.float32)

    # group the COO by user batch on host so each scan step scatters only
    # its own slice — total scatter work stays O(nnz), not
    # O(nnz * n_batches). Skewed batches waste padding; widening the batch
    # evens them out (bounded so the dense strip stays ~<=2GB).
    while True:
        n_batches = max(1, -(-n_users // user_batch))
        counts = np.bincount(u // user_batch, minlength=n_batches)
        L = -(-int(counts.max()) // max(1, chunk)) * max(1, chunk)
        # stop once: padding waste is bounded, OR widening cannot help any
        # more (single batch / batch >= n_users), OR the dense strip would
        # exceed ~2GB. L is floored at `chunk`, so the waste bound alone
        # would otherwise escalate tiny inputs to the memory cap.
        if (n_batches * L <= 4 * max(len(u), 1)
                or n_batches == 1
                or user_batch >= n_users
                or user_batch * n_items_pad >= 1 << 29):
            break
        user_batch *= 2

    order = np.argsort(u // user_batch, kind="stable")
    u, i, v = u[order], i[order], v[order]
    starts = np.zeros(n_batches + 1, np.int64)
    np.cumsum(np.bincount(u // user_batch, minlength=n_batches),
              out=starts[1:])
    u_b = np.full((n_batches, L), user_batch, np.int32)   # sentinel: OOB row
    i_b = np.full((n_batches, L), n_items_pad, np.int32)  # sentinel: OOB col
    v_b = np.zeros((n_batches, L), np.float32)
    for b in range(n_batches):
        s, e = starts[b], starts[b + 1]
        u_b[b, : e - s] = u[s:e] - b * user_batch
        i_b[b, : e - s] = i[s:e]
        v_b[b, : e - s] = v[s:e]

    scores, idx = _column_cosine_topk_jit(
        jnp.asarray(u_b), jnp.asarray(i_b), jnp.asarray(v_b),
        n_items, n_items_pad, user_batch, k_bucket, jnp.float32(threshold),
    )
    return np.asarray(scores)[:n_items, :k], np.asarray(idx)[:n_items, :k]
