"""Cosine-similarity top-k over factor/embedding matrices.

Replaces the reference similarproduct template's driver-side cosine over
MLlib ALS productFeatures (examples/scala-parallel-similarproduct/multi/
src/main/scala/LikeAlgorithm.scala:21-86, ALSAlgorithm.scala cosine loop).
There the per-item cosine is an RDD map over all items per query; here it is
one normalized (B,k)x(k,I) matmul + lax.top_k on the MXU, with an optional
sharded path for catalogs too large for one chip.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def normalize_rows(m: jax.Array, eps: float = 1e-9) -> jax.Array:
    return m / (jnp.linalg.norm(m, axis=1, keepdims=True) + eps)


@partial(jax.jit, static_argnames=("k",))
def _cosine_topk_jit(matrix_n, queries, k: int):
    q = normalize_rows(queries)
    scores = q @ matrix_n.T  # (B, I)
    return jax.lax.top_k(scores, k)


def cosine_topk(matrix: jax.Array, queries: jax.Array, k: int):
    """matrix: (I, d) item vectors; queries: (B, d). Returns (scores, idx)
    of the k most cosine-similar rows per query. k is bucketed to a power
    of two pre-jit (compile-cache bound), trimmed on host."""
    n = matrix.shape[0]
    k = max(1, min(int(k), n))
    bucket = min(n, 1 << (k - 1).bit_length())
    matrix_n = normalize_rows(matrix)
    scores, idx = _cosine_topk_jit(matrix_n, queries, bucket)
    return scores[:, :k], idx[:, :k]


def mean_vector(matrix: jax.Array, indices: np.ndarray) -> jax.Array:
    """Average of the given rows — the similarproduct query combiner
    (reference ALSAlgorithm.scala: sum of query-item feature vectors)."""
    return jnp.mean(matrix[jnp.asarray(indices)], axis=0, keepdims=True)
