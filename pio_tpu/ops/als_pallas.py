"""Pallas TPU kernel for the ALS normal-equation accumulation.

The third accumulation strategy (ops/als.py accum="pallas"), designed for
the case where neither XLA path reaches the memory bound
(eval/ALS_ROOFLINE.md):

 * "carry":   scatter into a (n,k,k) lax.scan carry — re-streams the
              accumulator once per chunk if the backend materializes it;
 * "stacked": per-slot blocks as scan outputs + grouped sorted
              scatter-add — bounded temp, but still materializes S·k²
              floats and trusts XLA's scatter lowering;
 * "pallas":  THIS kernel. Slots are processed in GROUPS (bounding the
              XLA factor-gather temp at group_slots·W·k bytes); within a
              group the kernel fuses the per-slot (k,W)x(W,k) MXU
              products with a SEGMENT FLUSH: slots are row-sorted
              (_device_slot_layout) and TPU Pallas grids execute
              sequentially on a core, so a (k,k) VMEM scratch
              accumulates the open row's partial blocks (scratch
              persists across grid steps) and DMAs each segment that
              ENDS inside the group to A in HBM. The group's final open
              segment is emitted as a "trail" output — a row may span
              groups, and each group contributes at most one trail — and
              every trail folds in afterwards with ONE tiny
              n_groups-row scatter-add (rows are sorted, flush is the
              only writer of its row, so flush + trail-adds sum exactly;
              no cross-group seeding or host synchronization needed).
              A/b zero-initialize via input/output aliasing, so empty
              rows read as zeros with no extra pass over A.

Per-sweep traffic: the factor gather (written once by XLA per group,
re-read once by the kernel), the zero-fill + one write of A, and row ids
streamed through SMEM one (1,1,chunk)-block per grid step. No scatter
over k² blocks, no (n,k,k) carry, no unbounded temp.

Status: HARDWARE-VALIDATED on v5e (round 3): compiles through Mosaic
after three portability fixes (LANE-wide accumulators/outputs — per-row
(K,K) DMA slices of a lane-padded HBM memref are rejected; (1,1,chunk)
SMEM row blocks — 1-d s32 operands tile T(1024) vs Mosaic's T(128);
second-minor block dims must divide 8) and matches the XLA paths to
~1e-7 relative on real hardware. Measured users-half ne at the ML-20M
shape: pallas 0.249 s vs stacked 0.211 / carry 0.199 — the serial
per-slot MXU dots (at forced HIGHEST precision: Mosaic lacks HIGH) and
per-segment DMA flushes underrun XLA's batched einsum, so auto still
never selects it; correctness stays pinned in interpret mode
(tests/test_als_pallas.py) and eval/als_accum_bench.py carries the
hardware A/B cell.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _ne_kernel(rows_ref,            # (1, 1, chunk) int32 SMEM block (this step)
               y_ref,               # (1, chunk, W, K) VMEM block
               wo_ref,              # (1, chunk, W)    outer weights
               wr_ref,              # (1, chunk, W)    rhs weights
               a_init_ref,          # aliased -> a_out (zero-filled)
               b_init_ref,          # aliased -> b_out
               a_out,               # (n_pad, K, LANE) HBM (aliased)
               b_out,               # (n_pad, LANE) HBM (aliased)
               trail_a,             # (K, LANE) VMEM block: group's open tail
               trail_b,             # (1, LANE)
               trail_row,           # (1, 1) int32 SMEM
               acc_a,               # (K, LANE) f32 VMEM scratch
               acc_b,               # (1, LANE) f32 VMEM scratch
               cur_row,             # (1,) int32 SMEM scratch
               dma_sem,
               *, chunk: int):
    """One grid step = `chunk` consecutive slots; the sequential TPU grid
    + persistent scratch carry the open row segment across steps. Segments
    that END inside the group DMA to A/b; the group's last open segment
    goes to the trail outputs (folded across groups by the caller).

    Accumulators/outputs are LANE(=128)-wide with columns [K:] zero:
    Mosaic requires HBM memref slices to be lane-tile aligned (a (K,K)
    row slice of a lane-padded (n,K,K) buffer is rejected with "Slice
    shape along dimension 2 must be aligned to tiling (128)"), and the
    physical HBM bytes are identical to XLA's padded layout anyway."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    step = pl.program_id(0)
    n_steps = pl.num_programs(0)
    K = y_ref.shape[3]
    LANE = acc_a.shape[1]

    @pl.when(step == 0)
    def _init():
        cur_row[0] = rows_ref[0, 0, 0]
        acc_a[...] = jnp.zeros_like(acc_a)
        acc_b[...] = jnp.zeros_like(acc_b)

    def flush(row):
        a_copy = pltpu.make_async_copy(acc_a, a_out.at[row], dma_sem)
        a_copy.start()
        a_copy.wait()
        b_copy = pltpu.make_async_copy(
            acc_b, b_out.at[pl.ds(row, 1)], dma_sem)
        b_copy.start()
        b_copy.wait()

    def slot_body(i, _):
        row = rows_ref[0, 0, i]

        @pl.when(row != cur_row[0])
        def _new_segment():
            flush(cur_row[0])
            acc_a[...] = jnp.zeros_like(acc_a)
            acc_b[...] = jnp.zeros_like(acc_b)
            cur_row[0] = row

        y = y_ref[0, i].astype(jnp.float32)          # (W, K)
        wo = wo_ref[0, i].astype(jnp.float32)        # (W,)
        wr = wr_ref[0, i].astype(jnp.float32)
        yw = y * wo[:, None]
        if LANE > K:  # zero-pad the rhs operand so the dot fills the lanes
            yw = jnp.concatenate(
                [yw, jnp.zeros((yw.shape[0], LANE - K), jnp.float32)], axis=1
            )
        # HIGHEST: the default 1-pass bf16 MXU contraction loses ~3e-3
        # relative on A, which the CG solve cannot recover (same rationale
        # as _chunk_blocks' Precision.HIGH; Mosaic supports only
        # DEFAULT|HIGHEST for dot_general, so XLA's 3-pass HIGH middle
        # ground is unavailable in-kernel)
        acc_a[...] += jax.lax.dot_general(
            y, yw, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        b_row = jnp.sum(y * wr[:, None], axis=0)     # (K,)
        if LANE > K:
            b_row = jnp.concatenate(
                [b_row, jnp.zeros((LANE - K,), jnp.float32)]
            )
        acc_b[...] += b_row[None, :]
        return ()

    jax.lax.fori_loop(0, chunk, slot_body, (), unroll=False)

    @pl.when(step == n_steps - 1)
    def _emit_trail():  # the group's last open segment is NEVER flushed
        trail_a[...] = acc_a[...]
        trail_b[...] = acc_b[...]
        trail_row[0, 0] = cur_row[0]


def _run_group(rows_g, y_g, wo_g, wr_g, a_buf, b_buf, *, chunk: int,
               k: int, W: int, lane: int, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_steps = rows_g.shape[0] // chunk
    smem = pltpu.MemorySpace.SMEM
    hbm = pltpu.MemorySpace.HBM
    return pl.pallas_call(
        functools.partial(_ne_kernel, chunk=chunk),
        grid=(n_steps,),
        in_specs=[
            # (1, 1, chunk) SMEM block: 1-d s32 operands tile T(1024)
            # on the XLA side vs Mosaic's T(128) and fail layout checks,
            # and a (1, chunk) block trips the "second-minor divisible by
            # 8" rule — a middle singleton dim satisfies both
            pl.BlockSpec((1, 1, chunk), lambda i: (i, 0, 0),
                         memory_space=smem),
            pl.BlockSpec((1, chunk, W, k), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, chunk, W), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, chunk, W), lambda i: (i, 0, 0)),
            pl.BlockSpec(memory_space=hbm),         # a_init (aliased)
            pl.BlockSpec(memory_space=hbm),         # b_init (aliased)
        ],
        out_specs=[
            pl.BlockSpec(memory_space=hbm),         # a_out
            pl.BlockSpec(memory_space=hbm),         # b_out
            # trail blocks revisit the same VMEM tile every step: Mosaic
            # writes them back once at grid end
            pl.BlockSpec((k, lane), lambda i: (0, 0)),
            pl.BlockSpec((1, lane), lambda i: (0, 0)),
            pl.BlockSpec(memory_space=smem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(a_buf.shape, jnp.float32),
            jax.ShapeDtypeStruct(b_buf.shape, jnp.float32),
            jax.ShapeDtypeStruct((k, lane), jnp.float32),
            jax.ShapeDtypeStruct((1, lane), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((k, lane), jnp.float32),
            pltpu.VMEM((1, lane), jnp.float32),
            pltpu.SMEM((1,), jnp.int32),
            pltpu.SemaphoreType.DMA,
        ],
        # A/b accumulate in place across groups (indices count ALL inputs)
        input_output_aliases={4: 0, 5: 1},
        interpret=interpret,
    )(rows_g.reshape(n_steps, 1, chunk), y_g, wo_g, wr_g, a_buf, b_buf)


def normal_equations_pallas(layout, other_factors, n_self: int,
                            implicit: bool, alpha: float,
                            chunk_slots: int = 128,
                            group_slots: int = 65536,
                            bf16_gather: bool = True,
                            interpret: bool | None = None):
    """Pallas segment-flush accumulation: -> A (n_self,k,k), b (n_self,k).

    Same contract as ops/als._normal_equations minus the shared YtY /
    reg terms (added by the caller for implicit mode, as there).

    chunk_slots sizes the VMEM working set (y block = chunk·W·k·2 bytes,
    128·128·64·2 = 2 MB double-buffered); group_slots bounds the XLA
    factor-gather temp (group·W·k·2 = 1.07 GB at the defaults). Fully
    traceable — no host synchronization — so it jits inside the training
    scan like the XLA paths."""
    if interpret is None:
        interpret = jax.devices()[0].platform == "cpu"
    rows, idx, val, lens = layout
    k = other_factors.shape[1]
    S, W = idx.shape
    chunk = min(chunk_slots, S)
    # pad the slot axis to a whole number of kernel chunks with sentinel
    # slots (row n_self keeps the ids sorted; zero lens -> zero weights)
    pad = -S % chunk
    if pad:
        rows = jnp.concatenate(
            [rows, jnp.full((pad,), n_self, rows.dtype)])
        idx = jnp.concatenate([idx, jnp.zeros((pad, W), idx.dtype)])
        val = jnp.concatenate([val, jnp.zeros((pad, W), val.dtype)])
        lens = jnp.concatenate([lens, jnp.zeros((pad,), lens.dtype)])
        S += pad

    src = (
        other_factors.astype(jnp.bfloat16) if bf16_gather else other_factors
    )
    mask = (
        jnp.arange(W, dtype=jnp.int32)[None, :] < lens[:, None]
    ).astype(jnp.float32)
    vf = val.astype(jnp.float32)
    if implicit:
        w_outer = alpha * vf * mask
        w_rhs = (1.0 + alpha * vf) * mask
    else:
        w_outer = mask
        w_rhs = vf * mask

    # one padding row absorbs the sentinel segment's writes; LANE(128)-
    # wide buffers with zero columns [k:] — Mosaic's HBM slice alignment
    # demands lane-tile-aligned row DMAs (see _ne_kernel), and the
    # physical bytes equal XLA's lane-padded layout anyway
    lane = max(128, -(-k // 128) * 128)  # round UP to a lane multiple
    n_pad = n_self + 1
    a_buf = jnp.zeros((n_pad, k, lane), jnp.float32)
    b_buf = jnp.zeros((n_pad, lane), jnp.float32)

    g_slots = max(chunk, (group_slots // chunk) * chunk)
    t_rows, t_as, t_bs = [], [], []
    for lo in range(0, S, g_slots):
        hi = min(S, lo + g_slots)
        y_g = src[idx[lo:hi]]                   # bounded gather temp
        n_steps = (hi - lo) // chunk
        a_buf, b_buf, tr_a, tr_b, tr_row = _run_group(
            rows[lo:hi],
            y_g.reshape(n_steps, chunk, W, k),
            w_outer[lo:hi].reshape(n_steps, chunk, W),
            w_rhs[lo:hi].reshape(n_steps, chunk, W),
            a_buf, b_buf, chunk=chunk, k=k, W=W, lane=lane,
            interpret=interpret,
        )
        t_rows.append(tr_row.reshape(1))
        t_as.append(tr_a)
        t_bs.append(tr_b)
    # fold every group's trailing open segment: the flush is the ONLY
    # in-kernel writer of a row (its segment ends in exactly one group),
    # so flush + trail adds reconstruct rows spanning group boundaries
    A = a_buf.at[jnp.concatenate(t_rows)].add(
        jnp.stack(t_as), mode="drop")
    b = b_buf.at[jnp.concatenate(t_rows)].add(
        jnp.concatenate(t_bs), mode="drop")
    return A[:n_self, :, :k], b[:n_self, :k]
