"""Pallas TPU kernel for the ALS normal-equation accumulation.

The third accumulation strategy (ops/als.py accum="pallas"), designed for
the case where neither XLA path reaches the memory bound
(eval/ALS_ROOFLINE.md):

 * "carry":   scatter into a (n,k,k) lax.scan carry — re-streams the
              accumulator once per chunk if the backend materializes it;
 * "stacked": per-slot blocks as scan outputs + grouped sorted
              scatter-add — bounded temp, but still materializes S·k²
              floats and trusts XLA's scatter lowering;
 * "pallas":  THIS kernel. Slots are processed in GROUPS (bounding the
              XLA factor-gather temp at group_slots·W·k bytes); within a
              group the kernel fuses the per-slot (k,W)x(W,k) MXU
              products with a SEGMENT FLUSH: slots are row-sorted
              (_device_slot_layout) and TPU Pallas grids execute
              sequentially on a core, so a (k,k) VMEM scratch
              accumulates the open row's partial blocks (scratch
              persists across grid steps) and DMAs each segment that
              ENDS inside the group to A in HBM. The group's final open
              segment is emitted as a "trail" output — a row may span
              groups, and each group contributes at most one trail — and
              every trail folds in afterwards with ONE tiny
              n_groups-row scatter-add (rows are sorted, flush is the
              only writer of its row, so flush + trail-adds sum exactly;
              no cross-group seeding or host synchronization needed).
              A/b zero-initialize via input/output aliasing, so empty
              rows read as zeros with no extra pass over A.

Per-sweep traffic: the factor gather (written once by XLA per group,
re-read once by the kernel), the zero-fill + one write of A, and row ids
streamed through SMEM one (1,1,chunk)-block per grid step. No scatter
over k² blocks, no (n,k,k) carry, no unbounded temp.

Status: HARDWARE-VALIDATED on v5e (round 3): compiles through Mosaic
after three portability fixes (LANE-wide accumulators/outputs — per-row
(K,K) DMA slices of a lane-padded HBM memref are rejected; (1,1,chunk)
SMEM row blocks — 1-d s32 operands tile T(1024) vs Mosaic's T(128);
second-minor block dims must divide 8) and matches the XLA paths to
~1e-7 relative on real hardware. Measured users-half ne at the ML-20M
shape: pallas 0.249 s vs stacked 0.211 / carry 0.199 — the serial
per-slot MXU dots (at forced HIGHEST precision: Mosaic lacks HIGH) and
per-segment DMA flushes underrun XLA's batched einsum, so auto still
never selects it; correctness stays pinned in interpret mode
(tests/test_als_pallas.py) and eval/als_accum_bench.py carries the
hardware A/B cell.

Round 6 adds the STREAMING accumulation path (eval/ALS_ROOFLINE.md
round-6 plan; CPU-validated in interpret mode, on-chip A/B staged in
eval/run_tpu_evidence.sh for the next tunnel window):

 * gather_rows_stream — double-buffered HBM->VMEM streaming gather
   (any table size; mini-group g+1's per-row copies in flight while g
   stores), the custom gather the roofline note calls for;
 * _segment_kernel_stream (accum="stream") — overlapped segment flush:
   each A-row DMA starts at its flush point and is awaited at the NEXT
   flush that reuses the staging slot, hiding the 65 ms/sweep of
   exposed flush latency;
 * lane-packed A: the streaming flush can write A rows (n, k²) —
   k² is a 128-multiple, so no lane padding (a 2x byte cut at k=64) —
   and packed_block_matvec consumes the packed rows natively in CG, so
   the packed form survives end-to-end with no XLA relayout.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _pad_lanes(x, lane: int):
    """Zero-pad the last dim to LANE (see _segment_kernel docstring)."""
    k = x.shape[-1]
    if lane == k:
        return x
    return jnp.concatenate(
        [x, jnp.zeros((*x.shape[:-1], lane - k), x.dtype)], axis=-1)


def _memory_space(pltpu):
    """pltpu.MemorySpace on modern jax; on jax<0.5 the members live on
    TPUMemorySpace and HBM is spelled ANY (compiler-placed, lands in
    HBM for buffers this size)."""
    ms = getattr(pltpu, "MemorySpace", None)
    if ms is not None:
        return ms

    class _Compat:
        SMEM = pltpu.TPUMemorySpace.SMEM
        VMEM = pltpu.TPUMemorySpace.VMEM
        ANY = pltpu.TPUMemorySpace.ANY
        HBM = pltpu.TPUMemorySpace.ANY

    return _Compat


def _segment_kernel(*refs, chunk: int, slot_fn):
    """Shared segment-flush kernel body. refs =
    (rows_ref (1,1,chunk) SMEM, *data_refs, a_init, b_init,   <- inputs
     a_out (n_pad,K,LANE) HBM, b_out (n_pad,LANE) HBM,        <- aliased
     trail_a (K,LANE), trail_b (1,LANE), trail_row (1,1) SMEM,
     acc_a, acc_b, cur_row, dma_sem)                          <- scratch

    One grid step = `chunk` consecutive slots; the sequential TPU grid +
    persistent scratch carry the open row segment across steps. Segments
    that END inside the group DMA to A/b (each A row written exactly
    once); the group's last open segment goes to the trail outputs,
    folded across groups by the caller. `slot_fn(data_refs, i, K, LANE)`
    -> (blk (K,LANE), b_row (LANE,)) produces slot i's contribution —
    the only difference between the fused-ne and scatter-only variants.

    Accumulators/outputs are LANE(=128-multiple)-wide with columns [K:]
    zero: Mosaic requires HBM memref slices to be lane-tile aligned (a
    (K,K) row slice of a lane-padded (n,K,K) buffer is rejected with
    "Slice shape along dimension 2 must be aligned to tiling (128)"),
    and the physical HBM bytes equal XLA's padded layout anyway."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    (rows_ref, *data_refs, _a_init, _b_init, a_out, b_out,
     trail_a, trail_b, trail_row, acc_a, acc_b, cur_row, dma_sem) = refs
    step = pl.program_id(0)
    n_steps = pl.num_programs(0)
    K = acc_a.shape[0]
    LANE = acc_a.shape[1]

    @pl.when(step == 0)
    def _init():
        cur_row[0] = rows_ref[0, 0, 0]
        acc_a[...] = jnp.zeros_like(acc_a)
        acc_b[...] = jnp.zeros_like(acc_b)

    def flush(row):
        a_copy = pltpu.make_async_copy(acc_a, a_out.at[row], dma_sem)
        a_copy.start()
        a_copy.wait()
        b_copy = pltpu.make_async_copy(
            acc_b, b_out.at[pl.ds(row, 1)], dma_sem)
        b_copy.start()
        b_copy.wait()

    def slot_body(i, _):
        row = rows_ref[0, 0, i]

        @pl.when(row != cur_row[0])
        def _new_segment():
            flush(cur_row[0])
            acc_a[...] = jnp.zeros_like(acc_a)
            acc_b[...] = jnp.zeros_like(acc_b)
            cur_row[0] = row

        blk, b_row = slot_fn(data_refs, i, K, LANE)
        acc_a[...] += blk
        acc_b[...] += b_row[None, :]
        return ()

    jax.lax.fori_loop(0, chunk, slot_body, (), unroll=False)

    @pl.when(step == n_steps - 1)
    def _emit_trail():  # the group's last open segment is NEVER flushed
        trail_a[...] = acc_a[...]
        trail_b[...] = acc_b[...]
        trail_row[0, 0] = cur_row[0]


def _ne_slot_fn(data_refs, i, K, LANE):
    """Fused variant: per-slot (K,W)x(W,LANE) MXU product from gathered
    factors + weights. HIGHEST precision: the default 1-pass bf16 MXU
    contraction loses ~3e-3 relative on A, which the CG solve cannot
    recover (same rationale as _chunk_blocks' Precision.HIGH; Mosaic
    supports only DEFAULT|HIGHEST for dot_general, so XLA's 3-pass HIGH
    middle ground is unavailable in-kernel)."""
    y_ref, wo_ref, wr_ref = data_refs
    y = y_ref[0, i].astype(jnp.float32)          # (W, K)
    wo = wo_ref[0, i].astype(jnp.float32)        # (W,)
    wr = wr_ref[0, i].astype(jnp.float32)
    yw = _pad_lanes(y * wo[:, None], LANE)       # dot fills the lanes
    blk = jax.lax.dot_general(
        y, yw, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    b_row = _pad_lanes(jnp.sum(y * wr[:, None], axis=0), LANE)
    return blk, b_row


def _flush_slot_fn(data_refs, i, K, LANE):
    """Scatter-only variant (accum="hybrid"): blocks precomputed by
    XLA's batched MXU einsum; the kernel only streams and flushes."""
    ablk_ref, bblk_ref = data_refs
    return (_pad_lanes(ablk_ref[0, i], LANE),
            _pad_lanes(bblk_ref[0, i], LANE))


def _segment_kernel_stream(*refs, chunk: int, slot_fn, packed: bool):
    """Overlapped-flush variant of _segment_kernel (accum="stream").

    Same segment algebra — sequential grid, persistent scratch carrying
    the open row, trail emitted for the group's last open segment — but
    the flush no longer serializes behind its own DMA: each segment end
    copies the accumulator into one of TWO staging slots, STARTS the
    HBM row writes, and returns to the MXU dots immediately; the wait
    happens at the NEXT flush that wants the same slot (or at the trail
    emit). In the round-5 profile the in-kernel start+wait flushes were
    65 ms/sweep of exposed DMA latency — two staged slots hide a flush
    behind at least one full following segment of compute.

    With packed=True the flush additionally writes A rows LANE-PACKED:
    a_out is (n_pad, k²) — k² is a 128-multiple for every supported k,
    so the physical HBM row carries no lane padding (at k=64 that
    halves A's streamed bytes: the 2x tax eval/ALS_ROOFLINE.md charges
    every k=64 buffer) and the packed batched matvec
    (packed_block_matvec) consumes it natively — no XLA relayout at
    the scatter/solve boundary. The pack itself is a per-FLUSH (per
    A-row, not per-slot) (K,LANE)->(1,K*K) VMEM reshape.

    refs = (rows_ref, *data_refs, a_init, b_init,   <- inputs
            a_out, b_out, trail_a, trail_b, trail_row,  <- outputs
            acc_a, acc_b, stage_a, stage_b, cur_row, st,
            sem_a0, sem_a1, sem_b0, sem_b1)         <- scratch

    st (3,) SMEM: [next staging slot, pending row of slot 0, pending
    row of slot 1] (-1 = no DMA in flight). Staging slots are indexed
    with PYTHON ints via parity branches so every ref slice except the
    destination row is static (the round-3 Mosaic portability rules);
    the destination a_out.at[row] with a traced row is the pattern the
    plain kernel hardware-validated. Waits reconstruct the same copy
    descriptor they started — descriptor equality is what pairs a wait
    with its start."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    (rows_ref, *data_refs, _a_init, _b_init, a_out, b_out,
     trail_a, trail_b, trail_row,
     acc_a, acc_b, stage_a, stage_b, cur_row, st,
     sem_a0, sem_a1, sem_b0, sem_b1) = refs
    step = pl.program_id(0)
    n_steps = pl.num_programs(0)
    K = acc_a.shape[0]
    LANE = acc_a.shape[1]
    sems = ((sem_a0, sem_b0), (sem_a1, sem_b1))

    @pl.when(step == 0)
    def _init():
        cur_row[0] = rows_ref[0, 0, 0]
        st[0] = 0
        st[1] = -1
        st[2] = -1
        acc_a[...] = jnp.zeros_like(acc_a)
        acc_b[...] = jnp.zeros_like(acc_b)

    def dmas(slot: int, row):
        sem_a, sem_b = sems[slot]
        if packed:
            a_src = stage_a.at[pl.ds(slot, 1)]          # (1, K*K)
            a_dst = a_out.at[pl.ds(row, 1)]
        else:
            a_src = stage_a.at[pl.ds(slot * K, K)]      # (K, LANE)
            a_dst = a_out.at[row]
        return (
            pltpu.make_async_copy(a_src, a_dst, sem_a),
            pltpu.make_async_copy(
                stage_b.at[pl.ds(slot, 1)], b_out.at[pl.ds(row, 1)],
                sem_b),
        )

    def drain(slot: int):
        """Wait out the slot's in-flight row write, if any."""
        @pl.when(st[1 + slot] >= 0)
        def _():
            a_copy, b_copy = dmas(slot, st[1 + slot])
            a_copy.wait()
            b_copy.wait()

    def flush_into(slot: int, row):
        drain(slot)  # the slot's previous DMA must land before reuse
        if packed:
            stage_a[pl.ds(slot, 1), :] = (
                acc_a[...][:, :K].reshape(1, K * K))
        else:
            stage_a[pl.ds(slot * K, K), :] = acc_a[...]
        stage_b[pl.ds(slot, 1), :] = acc_b[...]
        a_copy, b_copy = dmas(slot, row)
        a_copy.start()
        b_copy.start()
        st[1 + slot] = row

    def flush(row):
        @pl.when(st[0] == 0)
        def _slot0():
            flush_into(0, row)

        @pl.when(st[0] != 0)
        def _slot1():
            flush_into(1, row)

        st[0] = 1 - st[0]

    def slot_body(i, _):
        row = rows_ref[0, 0, i]

        @pl.when(row != cur_row[0])
        def _new_segment():
            flush(cur_row[0])
            acc_a[...] = jnp.zeros_like(acc_a)
            acc_b[...] = jnp.zeros_like(acc_b)
            cur_row[0] = row

        blk, b_row = slot_fn(data_refs, i, K, LANE)
        acc_a[...] += blk
        acc_b[...] += b_row[None, :]
        return ()

    jax.lax.fori_loop(0, chunk, slot_body, (), unroll=False)

    @pl.when(step == n_steps - 1)
    def _emit_trail():
        drain(0)  # every in-flight row write lands before the kernel ends
        drain(1)
        trail_a[...] = acc_a[...]   # trail stays UNPACKED; the caller's
        trail_b[...] = acc_b[...]   # fold packs it (n_groups tiny rows)
        trail_row[0, 0] = cur_row[0]


def _run_segment_group(rows_g, data, data_specs, a_buf, b_buf, *,
                       chunk: int, k: int, lane: int, slot_fn,
                       interpret: bool, overlap: bool = False,
                       packed: bool = False):
    """One pallas_call over a group: rows + variant-specific data blocks
    in, aliased A/b buffers accumulated in place, trail emitted.
    overlap/packed select the streaming-flush kernel variant
    (_segment_kernel_stream); packed implies the streaming kernel — the
    plain kernel's acc-shaped DMA cannot write (1, k²) rows."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_steps = rows_g.shape[0] // chunk
    smem = _memory_space(pltpu).SMEM
    hbm = _memory_space(pltpu).HBM
    n_in = 1 + len(data) + 2
    if overlap or packed:
        kernel = functools.partial(
            _segment_kernel_stream, chunk=chunk, slot_fn=slot_fn,
            packed=packed)
        scratch = [
            pltpu.VMEM((k, lane), jnp.float32),          # acc_a
            pltpu.VMEM((1, lane), jnp.float32),          # acc_b
            pltpu.VMEM((2, k * k) if packed else (2 * k, lane),
                       jnp.float32),                     # stage_a (2 slots)
            pltpu.VMEM((2, lane), jnp.float32),          # stage_b
            pltpu.SMEM((1,), jnp.int32),                 # cur_row
            pltpu.SMEM((3,), jnp.int32),                 # slot + pendings
            pltpu.SemaphoreType.DMA,                     # sem_a0
            pltpu.SemaphoreType.DMA,                     # sem_a1
            pltpu.SemaphoreType.DMA,                     # sem_b0
            pltpu.SemaphoreType.DMA,                     # sem_b1
        ]
    else:
        kernel = functools.partial(
            _segment_kernel, chunk=chunk, slot_fn=slot_fn)
        scratch = [
            pltpu.VMEM((k, lane), jnp.float32),
            pltpu.VMEM((1, lane), jnp.float32),
            pltpu.SMEM((1,), jnp.int32),
            pltpu.SemaphoreType.DMA,
        ]
    return pl.pallas_call(
        kernel,
        grid=(n_steps,),
        in_specs=[
            # (1, 1, chunk) SMEM block: 1-d s32 operands tile T(1024)
            # on the XLA side vs Mosaic's T(128) and fail layout checks,
            # and a (1, chunk) block trips the "second-minor divisible by
            # 8" rule — a middle singleton dim satisfies both
            pl.BlockSpec((1, 1, chunk), lambda i: (i, 0, 0),
                         memory_space=smem),
            *data_specs,
            pl.BlockSpec(memory_space=hbm),         # a_init (aliased)
            pl.BlockSpec(memory_space=hbm),         # b_init (aliased)
        ],
        out_specs=[
            pl.BlockSpec(memory_space=hbm),         # a_out
            pl.BlockSpec(memory_space=hbm),         # b_out
            # trail blocks revisit the same VMEM tile every step: Mosaic
            # writes them back once at grid end
            pl.BlockSpec((k, lane), lambda i: (0, 0)),
            pl.BlockSpec((1, lane), lambda i: (0, 0)),
            pl.BlockSpec(memory_space=smem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(a_buf.shape, jnp.float32),
            jax.ShapeDtypeStruct(b_buf.shape, jnp.float32),
            jax.ShapeDtypeStruct((k, lane), jnp.float32),
            jax.ShapeDtypeStruct((1, lane), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ],
        scratch_shapes=scratch,
        # A/b accumulate in place across groups (indices count ALL inputs)
        input_output_aliases={n_in - 2: 0, n_in - 1: 1},
        interpret=interpret,
    )(rows_g.reshape(n_steps, 1, chunk), *data, a_buf, b_buf)


def _pad_slots(layout, pad: int, n_self: int):
    """Append `pad` sentinel slots (row id n_self — keeps the sorted-rows
    invariant; zero lens/idx/val contribute nothing) to a slot layout."""
    rows, idx, val, lens = layout
    if not pad:
        return layout
    W = idx.shape[1]
    return (
        jnp.concatenate([rows, jnp.full((pad,), n_self, rows.dtype)]),
        jnp.concatenate([idx, jnp.zeros((pad, W), idx.dtype)]),
        jnp.concatenate([val, jnp.zeros((pad, W), val.dtype)]),
        jnp.concatenate([lens, jnp.zeros((pad,), lens.dtype)]),
    )


def _lane_for(k: int) -> int:
    return max(128, -(-k // 128) * 128)  # round UP to a lane multiple


def _chain_groups(n_self: int, k: int, groups, packed: bool = False):
    """Run group thunks in sequence over aliased A/b buffers and fold
    each group's trailing open segment: the in-kernel flush is the ONLY
    writer of a row (its segment ends in exactly one group), so flush +
    trail adds reconstruct rows spanning group boundaries exactly.
    `groups` yields thunks (a_buf, b_buf) -> 5-tuple from
    _run_segment_group. One padding row absorbs the sentinel segment.

    packed=True allocates A lane-packed (n_pad, k²) — the streaming
    flush kernel writes packed rows — and packs the (few, one per
    group) UNPACKED trails on the XLA side before the fold; the packed
    zero-init also streams k²/  (k·LANE) of the padded bytes (half, at
    k=64)."""
    lane = _lane_for(k)
    n_pad = n_self + 1
    if packed:
        a_buf = jnp.zeros((n_pad, k * k), jnp.float32)
    else:
        a_buf = jnp.zeros((n_pad, k, lane), jnp.float32)
    b_buf = jnp.zeros((n_pad, lane), jnp.float32)
    t_rows, t_as, t_bs = [], [], []
    for run in groups:
        a_buf, b_buf, tr_a, tr_b, tr_row = run(a_buf, b_buf, lane)
        t_rows.append(tr_row.reshape(1))
        t_as.append(tr_a)
        t_bs.append(tr_b)
    t_a = jnp.stack(t_as)                       # (n_groups, k, lane)
    if packed:
        t_a = t_a[:, :, :k].reshape(len(t_as), k * k)
    A = a_buf.at[jnp.concatenate(t_rows)].add(t_a, mode="drop")
    b = b_buf.at[jnp.concatenate(t_rows)].add(
        jnp.concatenate(t_bs), mode="drop")
    if packed:
        return A[:n_self], b[:n_self, :k]
    return A[:n_self, :, :k], b[:n_self, :k]


def normal_equations_pallas(layout, other_factors, n_self: int,
                            implicit: bool, alpha: float,
                            chunk_slots: int = 128,
                            group_slots: int = 65536,
                            bf16_gather: bool = True,
                            interpret: bool | None = None):
    """Fused Pallas segment-flush accumulation: -> A (n_self,k,k),
    b (n_self,k). Same contract as ops/als._normal_equations minus the
    shared YtY / reg terms (added by the caller for implicit mode).

    chunk_slots sizes the VMEM working set (y block = chunk·W·k·2 bytes,
    128·128·64·2 = 2 MB double-buffered); group_slots bounds the XLA
    factor-gather temp (group·W·k·2 = 1.07 GB at the defaults). Fully
    traceable — no host synchronization — so it jits inside the training
    scan like the XLA paths."""
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.devices()[0].platform == "cpu"
    rows, idx, val, lens = layout
    k = other_factors.shape[1]
    S, W = idx.shape
    chunk = min(chunk_slots, S)
    # pad the slot axis to a whole number of kernel chunks
    pad = -S % chunk
    rows, idx, val, lens = _pad_slots((rows, idx, val, lens), pad, n_self)
    S += pad

    src = (
        other_factors.astype(jnp.bfloat16) if bf16_gather else other_factors
    )
    mask = (
        jnp.arange(W, dtype=jnp.int32)[None, :] < lens[:, None]
    ).astype(jnp.float32)
    vf = val.astype(jnp.float32)
    if implicit:
        w_outer = alpha * vf * mask
        w_rhs = (1.0 + alpha * vf) * mask
    else:
        w_outer = mask
        w_rhs = vf * mask

    g_slots = max(chunk, (group_slots // chunk) * chunk)

    def group_thunk(lo, hi):
        def run(a_buf, b_buf, lane):
            y_g = src[idx[lo:hi]]               # bounded gather temp
            n_steps = (hi - lo) // chunk
            data = (y_g.reshape(n_steps, chunk, W, k),
                    w_outer[lo:hi].reshape(n_steps, chunk, W),
                    w_rhs[lo:hi].reshape(n_steps, chunk, W))
            specs = (
                pl.BlockSpec((1, chunk, W, k), lambda i: (i, 0, 0, 0)),
                pl.BlockSpec((1, chunk, W), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, chunk, W), lambda i: (i, 0, 0)),
            )
            return _run_segment_group(
                rows[lo:hi], data, specs, a_buf, b_buf, chunk=chunk,
                k=k, lane=lane, slot_fn=_ne_slot_fn, interpret=interpret,
            )
        return run

    groups = [group_thunk(lo, min(S, lo + g_slots))
              for lo in range(0, S, g_slots)]
    return _chain_groups(n_self, k, groups)


# ---------------------------------------------------------------------------
# accum="hybrid": XLA batched-MXU blocks + the shared segment-flush kernel
# with the scatter-only slot_fn — no in-kernel dots, pure streaming adds
# ---------------------------------------------------------------------------

def normal_equations_hybrid(layout, other_factors, n_self: int,
                            implicit: bool, alpha: float,
                            chunk_slots: int = 32768,
                            kernel_chunk: int = 128,
                            group_slots: int = 65536,
                            bf16_gather: bool = True,
                            interpret: bool | None = None,
                            gather: str = "xla",
                            overlap: bool = False,
                            packed: bool = False):
    """accum="hybrid": XLA builds the per-slot blocks (batched MXU
    einsum, _chunk_blocks — the hardware A/B showed it beats in-kernel
    serial dots), the shared segment-flush kernel replaces only the
    scatter-add into A (the ~13%-of-peak emitter, 118 ms/sweep in the
    round-3 profile) so each A row is written exactly once. Same
    contract/trail algebra and group chaining as
    normal_equations_pallas.

    overlap=True (accum="stream") swaps in the overlapped-flush kernel
    (_segment_kernel_stream): segment flushes start their HBM DMA and
    wait at the NEXT flush point instead of in-kernel, hiding the
    65 ms/sweep of exposed flush latency the round-5 profile charged
    the hybrid kernel. packed=True additionally stores A lane-packed
    (n_self, k²) — returned 2-d; consumers feed it to
    packed_block_matvec / unpack once for the exact solve."""
    import math as _math

    from jax.experimental import pallas as pl

    from pio_tpu.ops.als import _chunk_blocks  # lazy: als imports us lazily

    if interpret is None:
        interpret = jax.devices()[0].platform == "cpu"
    rows, idx, val, lens = layout
    k = other_factors.shape[1]
    S, W = idx.shape
    # VMEM-budget the kernel chunk: the blocks block is chunk*k*k*4 bytes
    # DOUBLE-buffered by the pallas pipeline, and the whole stack must fit
    # the 16 MB scoped limit (measured: chunk=128 at k=128 overflows by
    # 130 KB); 4 MB per buffer keeps headroom for b/trail/acc up to the
    # k=256 cap (ops/als.py falls back to stacked above it). The chunk is
    # then rounded DOWN to a power of two that divides chunk_slots: a
    # non-divisor chunk makes quantum = lcm(chunk, chunk_slots) explode
    # (k=96 -> chunk 113, lcm(113, 8192) = 925k slots of blocks temp).
    vmem_chunk = max(8, (4 * 2**20) // (k * k * 4))
    cap = max(1, min(kernel_chunk, vmem_chunk, S))
    chunk = 1 << (cap.bit_length() - 1)
    while chunk > 1 and chunk_slots % chunk:
        chunk //= 2
    # every group must hold WHOLE XLA-scan chunks (chunk_slots) and WHOLE
    # kernel chunks, or the scan collapses to one giant chunk and the
    # gather temp that chunk_slots exists to bound becomes unbounded —
    # pad S to the combined quantum so even the last group divides
    quantum = chunk * chunk_slots // _math.gcd(chunk, chunk_slots)
    pad = -S % quantum
    rows, idx, val, lens = _pad_slots((rows, idx, val, lens), pad, n_self)
    S += pad
    src = (
        other_factors.astype(jnp.bfloat16) if bf16_gather else other_factors
    )
    from pio_tpu.ops.als import blocks_group_budget_slots

    g_eff = min(group_slots, blocks_group_budget_slots(k))
    g_slots = max(quantum, (g_eff // quantum) * quantum)

    def group_thunk(lo, hi):
        def run(a_buf, b_buf, lane):
            # blocks via the XLA scan exactly as accum="stacked"
            # builds them; quantum padding guarantees divisibility
            c_sz = chunk_slots
            n_ch = (hi - lo) // c_sz
            xs = (idx[lo:hi].reshape(n_ch, c_sz, W),
                  val[lo:hi].reshape(n_ch, c_sz, W),
                  lens[lo:hi].reshape(n_ch, c_sz))

            def body(_, xs_c):
                i_c, v_c, l_c = xs_c
                return None, _chunk_blocks(src, i_c, v_c, l_c,
                                           implicit, alpha, gather=gather)

            _, (a_blks, b_blks) = jax.lax.scan(body, None, xs)
            n_steps = (hi - lo) // chunk
            data = (a_blks.reshape(n_steps, chunk, k, k),
                    b_blks.reshape(n_steps, chunk, k))
            specs = (
                pl.BlockSpec((1, chunk, k, k), lambda i: (i, 0, 0, 0)),
                pl.BlockSpec((1, chunk, k), lambda i: (i, 0, 0)),
            )
            return _run_segment_group(
                rows[lo:hi], data, specs, a_buf, b_buf, chunk=chunk,
                k=k, lane=lane, slot_fn=_flush_slot_fn,
                interpret=interpret, overlap=overlap, packed=packed,
            )
        return run

    groups = [group_thunk(lo, min(S, lo + g_slots))
              for lo in range(0, S, g_slots)]
    return _chain_groups(n_self, k, groups, packed=packed)


# ---------------------------------------------------------------------------
# VMEM-resident factor gather (the round-4 lever on the slot-gather wall)
# ---------------------------------------------------------------------------

# table-size budget for keeping the whole factor matrix VMEM-resident:
# 16 MB scoped VMEM minus the output block's double buffer and headroom
GATHER_VMEM_TABLE_BUDGET = 10 * 2**20


def gather_table_bytes(n_rows: int, k: int, bf16: bool) -> int:
    """Physical VMEM bytes for an (n_rows, k) factor table at TPU lane
    padding (minor dim padded UP to a multiple of 128, matching the
    padding gather_rows_pallas applies — max(128, k) would under-count
    e.g. k=192, which physically pads to 256)."""
    lane = _lane_for(k)
    return n_rows * lane * (2 if bf16 else 4)


def _gather_kernel_copy(idx_ref, table_ref, out_ref, *, rows_per_step,
                        group):
    """Row-copy variant: `group` dynamic (1,k) loads stacked into one
    tile-aligned store. The table ref is VMEM-resident (constant index
    map), so every load is a VMEM dynamic slice — no HBM traffic beyond
    the one-time table load and the output writes."""
    from jax.experimental import pallas as pl

    def body(g, _):
        base = g * group
        rows = [
            table_ref[pl.ds(idx_ref[0, 0, base + u], 1), :]
            for u in range(group)
        ]
        out_ref[pl.ds(base, group), :] = jnp.concatenate(rows, axis=0)
        return 0

    jax.lax.fori_loop(0, rows_per_step // group, body, 0)


def _gather_kernel_take(idx_ref, table_ref, out_ref, *, rows_per_step,
                        group):
    """jnp.take variant: materialize the VMEM table once per step and
    let Mosaic lower the vector gather (tpu dynamic-gather path where
    supported). Interpret-mode-validated; the on-hardware A/B against
    the copy variant is staged in eval/als_accum_bench.py (gather
    cells) and had not landed as of round 4 — keep in sync with
    ALSParams.gather's "auto" resolution in ops/als.py."""
    del group
    tbl = table_ref[:, :]
    rows = idx_ref[0, 0, :rows_per_step]
    out_ref[:, :] = jnp.take(tbl, rows, axis=0)


_GATHER_KERNELS = {"copy": _gather_kernel_copy, "take": _gather_kernel_take}


@functools.partial(
    jax.jit, static_argnames=("rows_per_step", "variant", "group",
                              "interpret"))
def gather_rows_pallas(table, idx, rows_per_step: int = 1024,
                       variant: str = "copy", group: int = 8,
                       interpret: bool | None = None):
    """Gather rows of a SMALL factor table with the table pinned in VMEM.

    table (N, k) f32/bf16, idx (M,) int32 -> (M, k) table[idx].

    Why this exists: XLA's gather emitter runs ~10x off HBM peak when
    the table is small enough to fit VMEM (eval/ALS_ROOFLINE.md /
    als_kernel_lab.py: a 20x cliff keyed on the 16 MB boundary, decided
    at codegen and unreachable from JAX — every padding trick fused
    away). At the ML-20M shape the users-half gathers the ITEM factor
    table (26,744 x 64 bf16 = 6.8 MB padded), squarely in the slow
    regime; this kernel makes the VMEM residency explicit instead of
    hoping for the emitter's fast path. Tables over
    GATHER_VMEM_TABLE_BUDGET stay on the XLA path (they already take
    the fast emitter).

    M must divide by rows_per_step (callers pad; slot layouts already
    quantize), and the idx values must be in-range (the ALS layouts
    guarantee < n plus a zero-filled sentinel row).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    import math

    if interpret is None:
        interpret = jax.devices()[0].platform == "cpu"
    n, k = table.shape
    (m,) = idx.shape
    assert m % rows_per_step == 0, (m, rows_per_step)
    # the copy variant loops rows_per_step//group times — group must
    # divide rows_per_step or trailing rows are silently dropped (and a
    # group larger than the step would write nothing at all)
    group = math.gcd(group, rows_per_step)
    lane = _lane_for(k)   # 128 < k < 256 must pad to 256, not k itself
    tbl = _pad_lanes(table, lane)
    steps = m // rows_per_step
    out = pl.pallas_call(
        functools.partial(
            _GATHER_KERNELS[variant], rows_per_step=rows_per_step,
            group=group),
        grid=(steps,),
        in_specs=(
            # (1,1,R) SMEM: 1-d s32 operands tile T(1024) vs Mosaic's
            # T(128) (round-3 portability rule)
            pl.BlockSpec((1, 1, rows_per_step), lambda i: (i, 0, 0),
                         memory_space=_memory_space(pltpu).SMEM),
            # whole table, constant index map -> fetched once, resident
            pl.BlockSpec((n, lane), lambda i: (0, 0)),
        ),
        out_specs=pl.BlockSpec((rows_per_step, lane), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m, lane), table.dtype),
        interpret=interpret,
    )(idx.reshape(steps, 1, rows_per_step), tbl)
    return out[:, :k]


# ---------------------------------------------------------------------------
# round-6 streaming gather: double-buffered HBM->VMEM row DMA, any table size
# ---------------------------------------------------------------------------

def _gather_kernel_stream(idx_ref, table_ref, out_ref, buf, sem0, sem1,
                          *, rows_per_step, group):
    """Double-buffered streaming gather: the table stays in HBM (no
    VMEM-residency precondition — this is the variant that covers the
    ML-20M USERS table the pallas-copy/take kernels cannot) and rows
    are fetched with per-row async copies into a 2-slot VMEM staging
    buffer: while mini-group g's rows land in slot g%2 and store to the
    output block, mini-group g+1's copies are ALREADY in flight into
    the other slot — the prefetch the XLA gather emitter never issues
    (the ~10x-off-peak wall in eval/ALS_ROOFLINE.md). The output block
    is written sequentially, so the pipeline's write-back streams at
    peak, and the caller reshapes it straight into the (C, W, k) layout
    the blocks einsum consumes — no intermediate XLA copy (the 38 ms
    y-copy in the round-5 profile).

    Staging slots are selected by PARITY branches so every buffer/
    semaphore index except the table row is static (round-3 Mosaic
    rules); waits reconstruct their start's descriptor. All copies on
    one slot share one DMA semaphore — same-size (1, lane) rows, so
    sequential waits pair with completions regardless of order."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_groups = rows_per_step // group
    sems = (sem0, sem1)

    def row_dma(slot: int, base, u):
        r = idx_ref[0, 0, base + u]
        return pltpu.make_async_copy(
            table_ref.at[pl.ds(r, 1), :],
            buf.at[pl.ds(slot * group + u, 1), :],
            sems[slot],
        )

    def start(slot: int, g):
        def body(u, _):
            row_dma(slot, g * group, u).start()
            return 0

        jax.lax.fori_loop(0, group, body, 0, unroll=False)

    def finish(slot: int, g):
        def body(u, _):
            row_dma(slot, g * group, u).wait()
            return 0

        jax.lax.fori_loop(0, group, body, 0, unroll=False)
        out_ref[pl.ds(g * group, group), :] = (
            buf[slot * group:(slot + 1) * group, :])

    def by_parity(g, fn):
        @pl.when(g % 2 == 0)
        def _even():
            fn(0, g)

        @pl.when(g % 2 != 0)
        def _odd():
            fn(1, g)

    start(0, 0)

    def body(g, _):
        @pl.when(g + 1 < n_groups)
        def _prefetch():
            by_parity(g + 1, start)

        by_parity(g, finish)
        return 0

    jax.lax.fori_loop(0, n_groups, body, 0, unroll=False)


@functools.partial(
    jax.jit, static_argnames=("rows_per_step", "group", "interpret"))
def gather_rows_stream(table, idx, rows_per_step: int = 512,
                       group: int = 32, interpret: bool | None = None):
    """Streaming gather of table rows with HBM->VMEM double buffering.

    table (N, k) f32/bf16 — ANY size, stays in HBM; idx (M,) int32 ->
    (M, k) table[idx]. M is padded internally to a rows_per_step
    multiple (sentinel index 0), so any M works; `group` (clamped to a
    divisor of rows_per_step) sets the prefetch depth — the copies of
    mini-group g+1 are in flight while g's rows store.

    This is ALSParams.gather="stream": unlike the VMEM-resident
    pallas-copy/take variants it has no table-size precondition, so it
    is the candidate for BOTH halves of the sweep (the users-half table
    is 4x over GATHER_VMEM_TABLE_BUDGET at the ML-20M shape). The
    on-hardware A/B lives in eval/als_accum_bench.py (stream cells);
    auto keeps the XLA gather until that A/B lands a win."""
    import math

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = jax.devices()[0].platform == "cpu"
    n, k = table.shape
    (m,) = idx.shape
    # output blocks are (rows_per_step, lane): second-minor must stay a
    # multiple of 8 (round-3 Mosaic rule)
    rows_per_step = max(8, rows_per_step - rows_per_step % 8)
    group = math.gcd(group, rows_per_step)
    lane = _lane_for(k)
    tbl = _pad_lanes(table, lane)
    pad = -m % rows_per_step
    idx_p = (
        jnp.concatenate([idx, jnp.zeros((pad,), idx.dtype)]) if pad else idx
    )
    steps = (m + pad) // rows_per_step
    out = pl.pallas_call(
        functools.partial(_gather_kernel_stream,
                          rows_per_step=rows_per_step, group=group),
        grid=(steps,),
        in_specs=(
            pl.BlockSpec((1, 1, rows_per_step), lambda i: (i, 0, 0),
                         memory_space=_memory_space(pltpu).SMEM),
            # the whole table as an HBM memref: rows are DMA'd on demand
            pl.BlockSpec(memory_space=_memory_space(pltpu).HBM),
        ),
        out_specs=pl.BlockSpec((rows_per_step, lane), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m + pad, lane), table.dtype),
        scratch_shapes=[
            pltpu.VMEM((2 * group, lane), table.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(idx_p.reshape(steps, 1, rows_per_step), tbl)
    return out[:m, :k]


# ---------------------------------------------------------------------------
# round-6 lane-packed batched matvec: the CG half of the packed-A path
# ---------------------------------------------------------------------------

def _matvec_block_rows(k: int, cap: int = 256) -> int:
    """VMEM-budgeted row block for packed_block_matvec: the (B, k²) f32
    A block is double-buffered by the pallas pipeline, and the (k², k)
    reduction operand (resident, constant index map) costs k³·4 bytes
    (1 MB at k=64, 8 MB at k=128) of the 16 MB scoped budget — 2 MB per
    A buffer keeps the stack under it through k=128. Power of two, >= 8
    (second-minor rule)."""
    b = max(8, (2 * 2**20) // (k * k * 4))
    b = 1 << (b.bit_length() - 1)
    return min(cap, b)


def _packed_matvec_kernel(a_ref, x_ref, r_ref, o_ref, *, k):
    """o[b, i] = sum_j a[b, i*k+j] * x[b, j], no unpack to (B, k, k):
    x is lane-TILED k times (xt[b, i*k+j] = x[b, j] — a static lane
    concat, no relayout), multiplied elementwise against the packed
    rows, and the contiguous k-lane groups are summed with one MXU dot
    against a constant 0/1 selection matrix R (r_ref, R[m, i] =
    [m//k == i]). The selection dot spends k× the matvec's FLOPs, but
    the op is HBM-bound by A's packed bytes, which is the term the
    packing halves at k=64 — the on-chip A/B against the XLA reshape
    matvec is the als_kernel_lab.py packed cells."""
    x = x_ref[...]
    xt = jnp.concatenate([x] * k, axis=1)          # (B, k²)
    p = a_ref[...] * xt
    o_ref[...] = jax.lax.dot_general(
        p, r_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )


@functools.partial(
    jax.jit, static_argnames=("block_rows", "interpret"))
def packed_block_matvec(a_packed, x, block_rows: int = 256,
                        interpret: bool | None = None):
    """Batched block-diagonal matvec on LANE-PACKED A.

    a_packed (n, k²) f32 — row b is A_b flattened row-major; x (n, k)
    f32 -> (n, k) with out[b] = A_b @ x[b]. n must divide by block_rows
    (callers pad once OUTSIDE their CG loop — _solve_packed in
    ops/als.py — so no per-iteration pad traffic).

    Why this exists: the packed batched matvec is 6.1x faster than the
    lane-padded einsum in isolation (eval/als_kernel_lab.py), but
    composed through XLA the (n,k²)->(n,k,k) reshape before the dot is
    a real relayout paid per solve (eval/ALS_ROOFLINE.md). This kernel
    consumes the packed rows natively, so the packed form survives from
    the flush kernel through every CG iteration with no relayout —
    tests/test_als_pallas.py pins that property on the optimized HLO."""
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.devices()[0].platform == "cpu"
    n, k2 = a_packed.shape
    k = x.shape[1]
    assert k * k == k2, (k, k2)
    block = min(block_rows, _matvec_block_rows(k))
    assert n % block == 0, (n, block)
    m_i = jnp.arange(k2, dtype=jnp.int32) // k
    r = (m_i[:, None] == jnp.arange(k, dtype=jnp.int32)[None, :]).astype(
        jnp.float32)
    return pl.pallas_call(
        functools.partial(_packed_matvec_kernel, k=k),
        grid=(n // block,),
        in_specs=(
            pl.BlockSpec((block, k2), lambda i: (i, 0)),
            pl.BlockSpec((block, k), lambda i: (i, 0)),
            # constant index map -> fetched once, resident across steps
            pl.BlockSpec((k2, k), lambda i: (0, 0)),
        ),
        out_specs=pl.BlockSpec((block, k), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, k), jnp.float32),
        interpret=interpret,
    )(a_packed, x, r)
