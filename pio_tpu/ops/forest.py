"""Random forest classifier (host-side numpy).

Parity target: MLlib RandomForest as used by the classification template's
add-algorithm variant (examples/scala-parallel-classification/add-algorithm/
src/main/scala/RandomForestAlgorithm.scala:28-43). Tree induction is
branchy, data-dependent control flow — exactly what XLA is bad at — and the
reference runs it on tiny property tables, so this deliberately stays a
host-side numpy implementation (the L-algorithm shape); batched *inference*
could move on-device if catalogs grow.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np


@dataclass
class _Node:
    feature: int = -1
    threshold: float = 0.0
    left: Optional["_Node"] = None
    right: Optional["_Node"] = None
    prediction: int = 0

    @property
    def is_leaf(self) -> bool:
        return self.left is None


def _gini(counts: np.ndarray) -> float:
    total = counts.sum()
    if total == 0:
        return 0.0
    p = counts / total
    return float(1.0 - (p * p).sum())


def _best_split(x, y, n_classes, feature_subset, min_leaf):
    best = (None, None, np.inf)
    n = len(y)
    parent_counts = np.bincount(y, minlength=n_classes)
    for f in feature_subset:
        vals = x[:, f]
        for t in np.unique(vals)[:-1]:
            mask = vals <= t
            nl = mask.sum()
            if nl < min_leaf or n - nl < min_leaf:
                continue
            lc = np.bincount(y[mask], minlength=n_classes)
            rc = parent_counts - lc
            score = (nl * _gini(lc) + (n - nl) * _gini(rc)) / n
            if score < best[2]:
                best = (f, float(t), score)
    return best


def _grow(x, y, n_classes, max_depth, min_leaf, n_sub, rng) -> _Node:
    node = _Node(prediction=int(np.bincount(y, minlength=n_classes).argmax()))
    if max_depth <= 0 or len(np.unique(y)) == 1 or len(y) < 2 * min_leaf:
        return node
    n_feat = x.shape[1]
    subset = rng.choice(n_feat, size=min(n_sub, n_feat), replace=False)
    f, t, score = _best_split(x, y, n_classes, subset, min_leaf)
    if f is None and len(subset) < n_feat:
        # the sampled subset had no usable split (e.g. already-exhausted
        # features); fall back to the full set before giving up
        f, t, score = _best_split(x, y, n_classes, range(n_feat), min_leaf)
    if f is None:
        return node
    mask = x[:, f] <= t
    node.feature, node.threshold = f, t
    node.left = _grow(x[mask], y[mask], n_classes, max_depth - 1, min_leaf, n_sub, rng)
    node.right = _grow(x[~mask], y[~mask], n_classes, max_depth - 1, min_leaf, n_sub, rng)
    return node


def _predict_one(node: _Node, row: np.ndarray) -> int:
    while not node.is_leaf:
        node = node.left if row[node.feature] <= node.threshold else node.right
    return node.prediction


@dataclass
class RandomForestModel:
    trees: list[_Node] = field(default_factory=list)
    n_classes: int = 2

    def predict(self, x: np.ndarray) -> np.ndarray:
        """(B, D) -> (B,) majority-vote labels."""
        x = np.atleast_2d(x)
        votes = np.zeros((len(x), self.n_classes), np.int64)
        for tree in self.trees:
            for i, row in enumerate(x):
                votes[i, _predict_one(tree, row)] += 1
        return votes.argmax(axis=1)


def random_forest_train(
    x: np.ndarray,
    y: np.ndarray,
    n_classes: int,
    num_trees: int = 10,
    max_depth: int = 5,
    min_leaf: int = 1,
    feature_subset: str = "auto",
    seed: int = 0,
) -> RandomForestModel:
    """Reference RandomForest.trainClassifier parameter shape
    (numTrees/maxDepth/featureSubsetStrategy)."""
    x = np.asarray(x, np.float32)
    y = np.asarray(y, np.int64)
    rng = np.random.default_rng(seed)
    n_feat = x.shape[1]
    n_sub = (
        max(1, int(np.sqrt(n_feat)))
        if feature_subset == "auto"
        else n_feat
    )
    trees = []
    for _ in range(num_trees):
        idx = rng.integers(0, len(y), size=len(y))  # bootstrap
        trees.append(
            _grow(x[idx], y[idx], n_classes, max_depth, min_leaf, n_sub, rng)
        )
    return RandomForestModel(trees=trees, n_classes=n_classes)
