"""Random forest classifier — histogram split search + array-flattened trees.

Parity target: MLlib RandomForest as used by the classification template's
add-algorithm variant (examples/scala-parallel-classification/add-algorithm/
src/main/scala/RandomForestAlgorithm.scala:28-43). MLlib grows trees by
histogram split search over quantile bins (Strategy maxBins, default 32);
this does the same, vectorized in numpy: features are quantile-binned once,
each node accumulates per-feature class histograms in a single np.add.at
pass, and all candidate thresholds are scored at once from cumulative
counts — O(n_node * features) per node instead of the naive
O(n_node * uniques * features) threshold scan. Tree GROWTH stays host-side
(branchy, data-dependent control flow — what XLA is bad at). Trained trees
are flattened to (tree, node) index arrays, so INFERENCE is a fixed
max_depth-step gather loop batched over rows x trees: vectorized numpy for
ad-hoc queries, or a jitted on-device path (`predict_device`) for large
catalogs.

`max_bins=0` selects the exact unique-threshold search (the pre-histogram
behavior) — kept for small property tables and as the accuracy yardstick
the histogram path is tested against.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Optional

import numpy as np


@dataclass
class _Node:
    feature: int = -1
    threshold: float = 0.0
    left: Optional["_Node"] = None
    right: Optional["_Node"] = None
    prediction: int = 0

    @property
    def is_leaf(self) -> bool:
        return self.left is None


# ---------------------------------------------------------------------------
# binning
# ---------------------------------------------------------------------------

_BIN_SAMPLE = 100_000


def _quantile_thresholds(x: np.ndarray, max_bins: int, rng) -> np.ndarray:
    """(D, max_bins-1) per-feature candidate thresholds at quantile points
    (MLlib findSplits uses sampled quantiles the same way). Repeated
    quantiles of low-cardinality features just yield duplicate thresholds —
    harmless: their histogram bins are empty."""
    sample = x
    if len(x) > _BIN_SAMPLE:
        sample = x[rng.choice(len(x), _BIN_SAMPLE, replace=False)]
    qs = np.linspace(0, 1, max_bins + 1)[1:-1]
    return np.quantile(sample, qs, axis=0).T.astype(np.float32)  # (D, B-1)


def _bin_features(x: np.ndarray, thresholds: np.ndarray) -> np.ndarray:
    """bin b <=> thresholds[b-1] < x <= thresholds[b]; so a split at bin j
    means x <= thresholds[j]."""
    binned = np.empty(x.shape, np.int16)
    for f in range(x.shape[1]):
        binned[:, f] = np.searchsorted(thresholds[f], x[:, f], side="left")
    return binned


# ---------------------------------------------------------------------------
# split search
# ---------------------------------------------------------------------------

def _best_split_hist(binned, y, feature_subset, n_classes, n_bins, min_leaf):
    """One histogram pass over the node's rows scores every (feature, bin)
    threshold simultaneously. Returns (feature, bin, score) or (None,)*3."""
    sub = binned[:, feature_subset]                     # (n, F)
    n, n_feat = sub.shape
    hist = np.zeros((n_feat, n_bins, n_classes), np.int64)
    f_idx = np.broadcast_to(np.arange(n_feat), sub.shape)
    np.add.at(hist, (f_idx, sub, y[:, None]), 1)

    left = hist.cumsum(axis=1).astype(np.float64)       # counts with bin <= j
    total = left[:, -1:, :]
    right = total - left
    nl = left.sum(-1)                                   # (F, B)
    nr = right.sum(-1)
    # weighted gini: nl*gini_l = nl - sum_c lc^2 / nl
    gl = nl - (left * left).sum(-1) / np.maximum(nl, 1)
    gr = nr - (right * right).sum(-1) / np.maximum(nr, 1)
    score = (gl + gr) / n
    score[(nl < min_leaf) | (nr < min_leaf)] = np.inf
    score[:, -1] = np.inf  # last bin has no threshold (right side empty)
    flat = score.argmin()
    fi, b = divmod(flat, n_bins)
    if not np.isfinite(score[fi, b]):
        return None, None, np.inf
    return int(feature_subset[fi]), int(b), float(score[fi, b])


def _gini(counts: np.ndarray) -> float:
    total = counts.sum()
    if total == 0:
        return 0.0
    p = counts / total
    return float(1.0 - (p * p).sum())


def _best_split_exact(x, y, n_classes, feature_subset, min_leaf):
    """Exact search over every unique value (max_bins=0 path)."""
    best = (None, None, np.inf)
    n = len(y)
    parent_counts = np.bincount(y, minlength=n_classes)
    for f in feature_subset:
        vals = x[:, f]
        for t in np.unique(vals)[:-1]:
            mask = vals <= t
            nl = mask.sum()
            if nl < min_leaf or n - nl < min_leaf:
                continue
            lc = np.bincount(y[mask], minlength=n_classes)
            rc = parent_counts - lc
            score = (nl * _gini(lc) + (n - nl) * _gini(rc)) / n
            if score < best[2]:
                best = (f, float(t), score)
    return best


# ---------------------------------------------------------------------------
# growth
# ---------------------------------------------------------------------------

def _grow(x, binned, y, thresholds, n_classes, max_depth, min_leaf, n_sub,
          n_bins, rng) -> _Node:
    node = _Node(prediction=int(np.bincount(y, minlength=n_classes).argmax()))
    if max_depth <= 0 or len(np.unique(y)) == 1 or len(y) < 2 * min_leaf:
        return node
    n_feat = x.shape[1]
    subset = rng.choice(n_feat, size=min(n_sub, n_feat), replace=False)

    def search(feats):
        if n_bins:
            f, b, score = _best_split_hist(
                binned, y, np.asarray(feats), n_classes, n_bins, min_leaf
            )
            t = None if f is None else float(thresholds[f][b])
            return f, t, b, score
        f, t, score = _best_split_exact(x, y, n_classes, feats, min_leaf)
        return f, t, None, score

    f, t, b, score = search(subset)
    if f is None and len(subset) < n_feat:
        # the sampled subset had no usable split (e.g. already-exhausted
        # features); fall back to the full set before giving up
        f, t, b, score = search(np.arange(n_feat))
    if f is None:
        return node
    # split on the binned representation when binning is on, so growth and
    # the stored raw threshold stay consistent (bin <= b <=> x <= t)
    mask = (binned[:, f] <= b) if n_bins else (x[:, f] <= t)
    node.feature, node.threshold = f, t
    node.left = _grow(x[mask], binned[mask], y[mask], thresholds, n_classes,
                      max_depth - 1, min_leaf, n_sub, n_bins, rng)
    node.right = _grow(x[~mask], binned[~mask], y[~mask], thresholds,
                       n_classes, max_depth - 1, min_leaf, n_sub, n_bins, rng)
    return node


# ---------------------------------------------------------------------------
# array flattening + batched inference
# ---------------------------------------------------------------------------

def _flatten(root: _Node) -> tuple[np.ndarray, ...]:
    """Preorder arrays: feature (-1 = leaf), threshold, left, right (leaves
    self-loop so the gather loop can run a fixed depth), prediction."""
    feats, thrs, lefts, rights, preds = [], [], [], [], []

    def visit(node: _Node) -> int:
        i = len(feats)
        feats.append(node.feature)
        thrs.append(node.threshold)
        lefts.append(i)
        rights.append(i)
        preds.append(node.prediction)
        if not node.is_leaf:
            lefts[i] = visit(node.left)
            rights[i] = visit(node.right)
        return i

    visit(root)
    return (
        np.asarray(feats, np.int32),
        np.asarray(thrs, np.float32),
        np.asarray(lefts, np.int32),
        np.asarray(rights, np.int32),
        np.asarray(preds, np.int32),
    )


@dataclass
class RandomForestModel:
    """Stacked (num_trees, max_nodes) arrays; leaves self-loop, unused
    padding nodes are leaves predicting class 0 but are never reached."""

    feature: np.ndarray = field(default_factory=lambda: np.zeros((0, 0), np.int32))
    threshold: np.ndarray = field(default_factory=lambda: np.zeros((0, 0), np.float32))
    left: np.ndarray = field(default_factory=lambda: np.zeros((0, 0), np.int32))
    right: np.ndarray = field(default_factory=lambda: np.zeros((0, 0), np.int32))
    prediction: np.ndarray = field(default_factory=lambda: np.zeros((0, 0), np.int32))
    n_classes: int = 2
    max_depth: int = 5

    @staticmethod
    def from_trees(trees: list[_Node], n_classes: int,
                   max_depth: int) -> "RandomForestModel":
        flat = [_flatten(t) for t in trees]
        n_nodes = max(len(f[0]) for f in flat)

        def stack(i, dtype, fill=0):
            out = np.full((len(flat), n_nodes), fill, dtype)
            for t, arrs in enumerate(flat):
                out[t, : len(arrs[i])] = arrs[i]
            return out

        return RandomForestModel(
            feature=stack(0, np.int32, -1),
            threshold=stack(1, np.float32),
            left=stack(2, np.int32),
            right=stack(3, np.int32),
            prediction=stack(4, np.int32),
            n_classes=n_classes,
            max_depth=max_depth,
        )

    def _votes(self, x: np.ndarray) -> np.ndarray:
        """(B, D) -> (B, T) per-tree class votes, vectorized over both."""
        B = len(x)
        T = self.feature.shape[0]
        tree = np.arange(T)
        cur = np.zeros((B, T), np.int32)
        rows = np.arange(B)[:, None]
        for _ in range(self.max_depth):
            f = self.feature[tree, cur]                       # (B, T)
            go_left = x[rows, np.maximum(f, 0)] <= self.threshold[tree, cur]
            nxt = np.where(go_left, self.left[tree, cur], self.right[tree, cur])
            cur = np.where(f >= 0, nxt, cur)                  # leaves stay
        return self.prediction[tree, cur]

    def predict(self, x: np.ndarray) -> np.ndarray:
        """(B, D) -> (B,) majority-vote labels."""
        x = np.atleast_2d(np.asarray(x, np.float32))
        votes = self._votes(x)
        counts = np.zeros((len(x), self.n_classes), np.int64)
        np.add.at(counts, (np.arange(len(x))[:, None], votes), 1)
        return counts.argmax(axis=1)

    def predict_device(self, x) -> "jax.Array":  # noqa: F821
        """Jitted on-device inference for large catalogs: the same fixed
        max_depth gather loop as `_votes`, compiled once per batch shape."""
        import jax.numpy as jnp

        return _predict_jit(
            jnp.asarray(self.feature), jnp.asarray(self.threshold),
            jnp.asarray(self.left), jnp.asarray(self.right),
            jnp.asarray(self.prediction), jnp.asarray(x, jnp.float32),
            self.n_classes, self.max_depth,
        )


def _predict_jit(feature, threshold, left, right, prediction, x,
                 n_classes: int, max_depth: int):
    import jax
    from jax import lax
    import jax.numpy as jnp

    @partial(jax.jit, static_argnums=(6, 7))
    def run(feature, threshold, left, right, prediction, x, n_classes,
            max_depth):
        B, T = x.shape[0], feature.shape[0]
        tree = jnp.arange(T)

        def step(_, cur):
            f = feature[tree, cur]
            go_left = x[jnp.arange(B)[:, None], jnp.maximum(f, 0)] <= \
                threshold[tree, cur]
            nxt = jnp.where(go_left, left[tree, cur], right[tree, cur])
            return jnp.where(f >= 0, nxt, cur)

        cur = lax.fori_loop(
            0, max_depth, step, jnp.zeros((B, T), jnp.int32)
        )
        votes = prediction[tree, cur]                        # (B, T)
        counts = jax.vmap(
            lambda v: jnp.bincount(v, length=n_classes)
        )(votes)
        return counts.argmax(axis=1)

    return run(feature, threshold, left, right, prediction, x, n_classes,
               max_depth)


# ---------------------------------------------------------------------------
# training entry point
# ---------------------------------------------------------------------------

def random_forest_train(
    x: np.ndarray,
    y: np.ndarray,
    n_classes: int,
    num_trees: int = 10,
    max_depth: int = 5,
    min_leaf: int = 1,
    feature_subset: str = "auto",
    max_bins: int = 32,
    seed: int = 0,
) -> RandomForestModel:
    """Reference RandomForest.trainClassifier parameter shape
    (numTrees/maxDepth/featureSubsetStrategy/maxBins). max_bins=0 selects
    the exact unique-threshold search."""
    x = np.ascontiguousarray(x, np.float32)
    y = np.asarray(y, np.int64)
    min_leaf = max(1, min_leaf)  # empty children are never valid splits
    rng = np.random.default_rng(seed)
    n_feat = x.shape[1]
    n_sub = (
        max(1, int(np.sqrt(n_feat)))
        if feature_subset == "auto"
        else n_feat
    )
    if max_bins:
        thresholds = _quantile_thresholds(x, max_bins, rng)
        binned = _bin_features(x, thresholds)
        n_bins = thresholds.shape[1] + 1
    else:
        thresholds = np.zeros((n_feat, 0), np.float32)
        binned = np.zeros(x.shape, np.int16)
        n_bins = 0
    trees = []
    for _ in range(num_trees):
        idx = rng.integers(0, len(y), size=len(y))  # bootstrap
        trees.append(
            _grow(x[idx], binned[idx], y[idx], thresholds, n_classes,
                  max_depth, min_leaf, n_sub, n_bins, rng)
        )
    return RandomForestModel.from_trees(trees, n_classes, max_depth)
