"""Technical indicators over (time, tickers) log-price matrices.

Replaces the reference stock template's per-ticker saddle Series pipelines
(examples/experimental/scala-stock/src/main/scala/Indicators.scala: RSI via
rolling means of signed returns, shift-difference returns) with matrix ops
over ALL tickers at once: rolling means are cumsum differences, EMA is a
`lax.scan` — every indicator is (T, N) in, (T, N) out, so the whole
universe rides one kernel instead of a Scala loop per symbol.

All functions take log prices; leading positions that lack a full window
are emitted as 0 (the reference fills NA with 0,
Indicators.scala getRet `.fillNA(_ => 0.0)`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def log_returns(log_price: jax.Array, d: int = 1) -> jax.Array:
    """d-day log return: x_t - x_{t-d}; first d rows are 0 (reference
    RegressionStrategy.getRet / ShiftsIndicator)."""
    shifted = jnp.roll(log_price, d, axis=0)
    out = log_price - shifted
    return out.at[:d].set(0.0)


def rolling_mean(x: jax.Array, window: int) -> jax.Array:
    """Trailing mean over `window` rows via cumsum difference; rows with an
    incomplete window are 0."""
    c = jnp.cumsum(x, axis=0)
    c = jnp.concatenate([jnp.zeros_like(c[:1]), c], axis=0)
    # value at row t (t >= window-1) = mean of rows t-window+1 .. t
    out = (c[window:] - c[:-window]) / window
    pad = jnp.zeros(
        (min(window - 1, x.shape[0]),) + x.shape[1:], x.dtype
    )
    return jnp.concatenate([pad, out], axis=0)[: x.shape[0]]


def rsi(log_price: jax.Array, period: int = 14) -> jax.Array:
    """Relative Strength Index on daily log returns (reference
    RSIIndicator: RS = rolling-mean(gains) / rolling-mean(losses),
    RSI = 100 - 100/(1+RS)); incomplete windows emit 0, flat windows 50."""
    ret = log_returns(log_price, 1)
    gains = jnp.maximum(ret, 0.0)
    losses = jnp.maximum(-ret, 0.0)
    avg_g = rolling_mean(gains, period)
    avg_l = rolling_mean(losses, period)
    rs = avg_g / jnp.maximum(avg_l, 1e-12)
    out = 100.0 - 100.0 / (1.0 + rs)
    # flat window (no gains, no losses): RSI conventionally 50
    flat = (avg_g <= 1e-12) & (avg_l <= 1e-12)
    out = jnp.where(flat, 50.0, out)
    # rows [:period] contain the artificial zero return at row 0 inside the
    # window; row `period` is the first RSI over `period` real returns
    return out.at[:period].set(0.0)


def ema(x: jax.Array, period: int) -> jax.Array:
    """Exponential moving average (alpha = 2/(period+1)) down the time
    axis via lax.scan."""
    alpha = 2.0 / (period + 1.0)

    def step(carry, row):
        carry = alpha * row + (1 - alpha) * carry
        return carry, carry

    _, out = jax.lax.scan(step, x[0], x)
    return out


def indicator_matrix(log_price: jax.Array, spec: tuple) -> jax.Array:
    """(T, N) log prices -> (T, N, F) feature tensor for the strategy
    regression. spec entries: ("return", d) | ("rsi", period) |
    ("ema_ratio", period) — the reference's indicator set
    (ShiftsIndicator / RSIIndicator) plus an EMA-distance feature."""
    feats = []
    for kind, arg in spec:
        if kind == "return":
            feats.append(log_returns(log_price, int(arg)))
        elif kind == "rsi":
            feats.append(rsi(log_price, int(arg)) / 100.0)  # scale to ~[0,1]
        elif kind == "ema_ratio":
            feats.append(log_price - ema(log_price, int(arg)))
        else:
            raise ValueError(f"unknown indicator {kind!r}")
    return jnp.stack(feats, axis=-1)
