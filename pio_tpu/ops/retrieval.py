"""Two-stage retrieval: quantized candidate generation + exact re-rank.

Every top-k today is an exact einsum over the full item matrix —
O(items*k) f32 traffic per query forever (ops/als.py recommend_topk).
Chiu et al. (1612.01437) show data movement, not FLOPs, dominates the
scoring scan at scale, so this module shrinks the BYTES a query touches:

  tier 1 (candidates): score the k-means CENTROIDS (C << n rows),
      expand the top ``nprobe`` clusters, and scan only those clusters'
      rows in a quantized dtype (bf16 halves the scan bytes, per-row-
      scaled int8 quarters them);
  tier 2 (re-rank):    re-score the surviving ``rerank_k`` rows with the
      ORACLE einsum over the untouched f32 factors, so the scores a
      caller sees are always exact f32 — quantization can only affect
      WHICH rows survive to tier 2, never their final scores.

Exactness contract: ``mode: "exact"`` callers never enter this module's
scan (the serving paths branch to the literal oracle computation), and a
clustered scan with ``nprobe >= n_clusters`` (exhaustive) falls through
to the same oracle path — bit-identical results in both cases, pinned by
tests/test_retrieval.py. Non-exhaustive clustered retrieval promises
recall (the retrieval-parity CI gate: recall@10 >= 0.95 at the default
nprobe on seeded factors), not bit-parity.

Quantized tables are persisted/transferred through ONE codec
(``table_to_bytes``/``table_from_bytes``): a CRC32C frame
(utils/durable.py envelope, magic ``PIOQ\\x01``) around the rpcwire-
style ``u8 kind | u32 header_len | header_json | sections`` layout, so
truncation and bit-rot die at decode as ``RetrievalCodecError`` — never
a silently wrong candidate. Encoding is a PURE function of the f32 rows
(round-to-nearest-even bf16; per-row absmax/127 int8), which is what
makes the fold-in re-encode contract and the reshard carry-vs-rebuild
equivalence hold: re-encoding a row anywhere yields the same bytes.

The clustered scan kernel follows the ops/als_pallas.py discipline:
``quantized_scores_pallas`` is the Pallas TPU scan (dequantize
in-register, MXU dot), interpret-mode CPU parity tests pin it against
the XLA fallback, and ``impl="auto"`` stays pinned to the XLA path
until an on-hardware A/B shows the kernel winning. All shape knobs
(cluster count, padded cluster width, rerank width, batch, k) are
pow2-bucketed through ops/bucketing.py so the serving mix compiles
O(log) programs into the persistent compile cache.
"""

from __future__ import annotations

import json
import math
import struct
from dataclasses import dataclass, replace
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from pio_tpu.ops.bucketing import pow2_bucket
from pio_tpu.utils import durable

RETRIEVAL_MAGIC = b"PIOQ\x01"

_KIND_QTABLE = 1
_PREFIX = struct.Struct(">BI")    # kind, header length (rpcwire layout)
_F32 = np.dtype("<f4")
_I8 = np.dtype("<i1")
_U16 = np.dtype("<u2")
_I32 = np.dtype("<i4")

_MODES = ("exact", "clustered")
_DTYPES = ("bf16", "int8")
_IMPLS = ("auto", "xla", "pallas")

# drift bounds the fuzz gate holds the codec to (tests/test_retrieval.py):
# round-to-nearest-even to 8 mantissa bits errs <= 2^-8 relative per
# element; symmetric int8 errs <= half a quantization step = absmax/254
BF16_REL_BOUND = 2.0 ** -8
INT8_STEP_DEN = 254.0


class RetrievalCodecError(ValueError):
    """A quantized-table blob that fails the frame CRC, promises counts
    its sections cannot hold, or carries trailing bytes. Permanent for
    that blob — callers rebuild the table from the f32 rows (which are
    the source of truth) instead of retrying."""


@dataclass(frozen=True)
class RetrievalParams:
    """The engine.json ``retrieval`` block (docs/serving.md "Two-stage
    retrieval"). ``mode: "exact"`` is the default and keeps every
    serving path on today's oracle einsum untouched."""

    mode: str = "exact"
    dtype: str = "int8"    # candidate-tier scan dtype
    # clusters expanded per query. The default is sized against the
    # auto cluster count at CI-gate scale (recall@10 >= 0.95 on seeded
    # ALS factors at nprobe 32 of C=64 — near-isotropic small-rank
    # factors need ~half the clusters; structured real catalogs reach
    # the same recall at far smaller fractions, see docs/serving.md
    # tuning runbook): raise nprobe for recall, lower it for speed.
    nprobe: int = 32
    rerank_k: int = 1024   # survivors re-scored by the exact oracle
    n_clusters: int = 0    # 0 = auto: pow2 near sqrt(n_items)
    seed: int = 0          # k-means init seed (determinism contract)
    kmeans_iters: int = 8
    impl: str = "auto"     # candidate-scan kernel: auto|xla|pallas

    def __post_init__(self):
        # validate here, not at scan time: a typo'd mode would otherwise
        # silently serve exact (never entering the clustered branch)
        if self.mode not in _MODES:
            raise ValueError(
                f"retrieval.mode={self.mode!r}; expected one of {_MODES}")
        if self.dtype not in _DTYPES:
            raise ValueError(
                f"retrieval.dtype={self.dtype!r}; expected one of {_DTYPES}")
        if self.impl not in _IMPLS:
            raise ValueError(
                f"retrieval.impl={self.impl!r}; expected one of {_IMPLS}")
        if self.nprobe < 1:
            raise ValueError(f"retrieval.nprobe={self.nprobe} must be >= 1")
        if self.rerank_k < 1:
            raise ValueError(
                f"retrieval.rerank_k={self.rerank_k} must be >= 1")
        if self.n_clusters < 0:
            raise ValueError(
                f"retrieval.n_clusters={self.n_clusters} must be >= 0")
        if self.kmeans_iters < 1:
            raise ValueError(
                f"retrieval.kmeans_iters={self.kmeans_iters} must be >= 1")

    @classmethod
    def from_config(cls, d: "dict | None") -> "RetrievalParams":
        """Parse the engine.json block with the same unknown-key
        rejection discipline as controller params_from_dict — a typo'd
        knob must fail deploy, not silently serve defaults."""
        if d is None:
            return cls()
        if not isinstance(d, dict):
            raise ValueError(
                f"retrieval config must be an object, got {type(d).__name__}")
        allowed = {f for f in cls.__dataclass_fields__}
        unknown = sorted(set(d) - allowed)
        if unknown:
            raise ValueError(
                f"unknown retrieval config key(s) {unknown}; "
                f"allowed: {sorted(allowed)}")
        return cls(**d)

    def resolved_n_clusters(self, n_items: int) -> int:
        """The cluster count that actually runs: the explicit knob, or
        the auto rule (pow2 nearest sqrt(n) — the classic IVF balance
        point: centroid scan cost C and per-cluster scan cost n/C meet
        at sqrt(n)); always <= n_items, pow2 where possible so the
        compiled scan program is shared across same-bucket catalogs."""
        n = max(1, int(n_items))
        want = self.n_clusters if self.n_clusters > 0 else max(
            1, int(math.sqrt(n)))
        return min(pow2_bucket(want), n)

    def is_exhaustive(self, n_items: int) -> bool:
        """True when the clustered scan would expand EVERY cluster —
        callers must then take the oracle path (bit-parity falls out of
        running the identical computation, not of this module matching
        it ULP-for-ULP)."""
        return self.nprobe >= self.resolved_n_clusters(n_items)


# ---------------------------------------------------------------------------
# quantized item-factor tables (the one encode/decode)
# ---------------------------------------------------------------------------

@dataclass
class QuantizedTable:
    """Quantized item rows in ORIGINAL item order. ``data`` is
    (n,k) uint16 bf16 bit patterns or (n,k) int8; ``scales`` is the
    (n,) f32 per-row dequantization scale (all-ones for bf16, kept
    explicit so both dtypes share one scan expression)."""

    dtype: str
    data: np.ndarray
    scales: np.ndarray

    @property
    def shape(self) -> tuple:
        return tuple(self.data.shape)

    def nbytes(self) -> int:
        return int(self.data.nbytes + self.scales.nbytes)

    def decode(self) -> np.ndarray:
        """f32 rows as the scan sees them (the dequantized view the
        drift bound is stated against)."""
        if self.dtype == "bf16":
            return (self.data.astype(np.uint32) << 16).view(np.float32)
        return self.data.astype(np.float32) * self.scales[:, None]


def encode_rows(rows, dtype: str) -> tuple[np.ndarray, np.ndarray]:
    """Quantize f32 rows -> (data, scales). A PURE function of the row
    bytes: the fold-in re-encode and the reshard carry/rebuild paths
    both rely on re-encoding being reproducible anywhere."""
    rows = np.ascontiguousarray(rows, dtype=np.float32)
    if rows.ndim != 2:
        raise ValueError(f"encode_rows expects (n, k), got {rows.shape}")
    n = rows.shape[0]
    if dtype == "bf16":
        u = rows.view(np.uint32)
        # round-to-nearest-even to the high 16 bits (matches the
        # hardware f32->bf16 cast, so a device-side re-encode agrees)
        bias = np.uint32(0x7FFF) + ((u >> 16) & np.uint32(1))
        data = ((u + bias) >> 16).astype(np.uint16)
        return data, np.ones(n, np.float32)
    if dtype == "int8":
        amax = np.max(np.abs(rows), axis=1) if rows.size else np.zeros(n)
        scales = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
        q = np.clip(np.rint(rows / scales[:, None]), -127, 127)
        return q.astype(np.int8), scales
    raise ValueError(f"unknown quantization dtype {dtype!r}")


def quantize_table(rows, dtype: str) -> QuantizedTable:
    data, scales = encode_rows(rows, dtype)
    return QuantizedTable(dtype=dtype, data=data, scales=scales)


def score_drift_bound(table: QuantizedTable, user_row) -> np.ndarray:
    """Per-item upper bound on |quantized score - exact score| for one
    user row — the analytic guarantee the fuzz gate checks empirically.
    bf16: elementwise relative error <= 2^-8; int8: elementwise absolute
    error <= absmax/254 (half a step)."""
    u = np.abs(np.asarray(user_row, np.float32))
    if table.dtype == "bf16":
        elem = BF16_REL_BOUND * np.abs(table.decode())
        return elem @ u
    step_half = (table.scales * 127.0) / INT8_STEP_DEN
    return step_half * np.sum(u)


# -- the one codec (CRC32C-framed like the wire codecs) ----------------------

def table_to_bytes(table: QuantizedTable) -> bytes:
    """One ``PIOQ`` frame: durable envelope | u8 kind | u32 header_len |
    header json | data bytes | scales bytes."""
    data = np.ascontiguousarray(
        table.data, dtype=_U16 if table.dtype == "bf16" else _I8)
    scales = np.ascontiguousarray(table.scales, dtype=_F32)
    n, k = (data.shape if data.ndim == 2 else (0, 0))
    if scales.shape != (n,):
        raise RetrievalCodecError(
            f"quantized table sections disagree: {n} rows but "
            f"{scales.shape} scales")
    header = json.dumps(
        {"dtype": table.dtype, "n": int(n), "k": int(k)},
        separators=(",", ":")).encode()
    payload = (_PREFIX.pack(_KIND_QTABLE, len(header)) + header
               + data.tobytes() + scales.tobytes())
    return durable.frame(payload, magic=RETRIEVAL_MAGIC)


def table_from_bytes(blob: bytes) -> QuantizedTable:
    """Verify + decode a ``table_to_bytes`` frame. Truncation at ANY
    byte and bit-flips anywhere die here (frame CRC, then exact section
    lengths) as RetrievalCodecError; counts are bounded BEFORE any
    allocation (the columnar wire's oversized-frame lesson)."""
    if not durable.is_framed(blob, RETRIEVAL_MAGIC):
        raise RetrievalCodecError("not a PIOQ quantized-table frame")
    try:
        payload = durable.unframe(blob, source="quantized table",
                                  magic=RETRIEVAL_MAGIC)
    except durable.ModelIntegrityError as e:
        raise RetrievalCodecError(str(e)) from e
    if len(payload) < _PREFIX.size:
        raise RetrievalCodecError("quantized-table frame too short for "
                                  "its prefix")
    kind, hdr_len = _PREFIX.unpack_from(payload)
    if kind != _KIND_QTABLE:
        raise RetrievalCodecError(
            f"quantized-table frame kind {kind} where {_KIND_QTABLE} "
            "was expected")
    if hdr_len > len(payload) - _PREFIX.size:
        raise RetrievalCodecError(
            "quantized-table frame header overruns the payload")
    end = _PREFIX.size + hdr_len
    try:
        header = json.loads(payload[_PREFIX.size:end].decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise RetrievalCodecError(
            f"malformed quantized-table header: {e}") from e
    if not isinstance(header, dict):
        raise RetrievalCodecError(
            "quantized-table header must be a JSON object")
    dtype = header.get("dtype")
    if dtype not in _DTYPES:
        raise RetrievalCodecError(
            f"quantized-table dtype {dtype!r} not one of {_DTYPES}")
    try:
        n = int(header["n"])
        k = int(header["k"])
    except (KeyError, TypeError, ValueError) as e:
        raise RetrievalCodecError(
            "quantized-table header missing n/k counts") from e
    if not (0 <= n <= 1 << 28) or not (0 <= k <= 1 << 16):
        raise RetrievalCodecError(
            f"quantized-table counts out of range: n={n} k={k}")
    body = payload[end:]
    elem = _U16 if dtype == "bf16" else _I8
    data_bytes = elem.itemsize * n * k
    scale_bytes = _F32.itemsize * n
    if len(body) != data_bytes + scale_bytes:
        raise RetrievalCodecError(
            f"quantized-table sections truncated or trailing: "
            f"{len(body)} body bytes where {data_bytes + scale_bytes} "
            "were declared")
    data = np.frombuffer(body, dtype=elem, count=n * k).reshape(n, k)
    scales = np.frombuffer(body, dtype=_F32, count=n, offset=data_bytes)
    return QuantizedTable(dtype=dtype, data=data.copy(),
                          scales=scales.copy())


# ---------------------------------------------------------------------------
# deterministic seeded k-means (the clustering beside the f32 partition)
# ---------------------------------------------------------------------------

def kmeans_cluster(rows, n_clusters: int, seed: int = 0,
                   iters: int = 8) -> tuple[np.ndarray, np.ndarray]:
    """-> (assign (n,) int32, centroids (C,k) f32). Plain Lloyd's with a
    seeded distinct-row init, all numpy: rebuilding the clustering from
    the same f32 rows yields the same assignment everywhere the reshard
    or fold-in paths might rebuild it. Empty clusters keep their
    previous centroid (deterministic; they simply attract nothing)."""
    rows = np.ascontiguousarray(rows, dtype=np.float32)
    n, k = rows.shape
    c = max(1, min(int(n_clusters), n))
    rng = np.random.RandomState(seed)
    cent = rows[rng.choice(n, size=c, replace=False)].astype(np.float32)
    assign = np.zeros(n, np.int32)
    row_sq = np.einsum("nk,nk->n", rows, rows)
    for _ in range(max(1, iters)):
        # squared distance via the matmul identity; row term constant in
        # the argmin but kept for a well-scaled comparison
        d = (row_sq[:, None] - 2.0 * (rows @ cent.T)
             + np.einsum("ck,ck->c", cent, cent)[None, :])
        assign = np.argmin(d, axis=1).astype(np.int32)
        for ci in range(c):
            members = rows[assign == ci]
            if len(members):
                cent[ci] = members.mean(axis=0)
    return assign, cent


# ---------------------------------------------------------------------------
# the retrieval index (host truth + device layout)
# ---------------------------------------------------------------------------

@dataclass
class RetrievalIndex:
    """Host-side sidecar beside a shard's/model's f32 item rows: the
    quantized table and the clustering, both in ORIGINAL item order.
    This is what fold-in updates in place (re-encode row, reassign
    cluster against the frozen centroids) and what the budget
    accounting charges; the padded device layout derives from it."""

    params: RetrievalParams
    table: QuantizedTable
    centroids: np.ndarray    # (C, k) f32
    assign: np.ndarray       # (n,) int32 cluster per item

    @property
    def n_clusters(self) -> int:
        return int(self.centroids.shape[0])

    def nbytes(self) -> int:
        return int(self.table.nbytes() + self.centroids.nbytes
                   + self.assign.nbytes)

    def updated(self, positions, new_rows) -> "RetrievalIndex":
        """Copy-on-write fold-in update: re-encode the given rows and
        reassign their clusters against the FROZEN centroids (the
        retrain/repartition path rebuilds the clustering wholesale; a
        fold-in must not move every other item's cluster). Returns a
        new index; the old one keeps serving until the atomic swap."""
        positions = np.asarray(positions, np.int64)
        new_rows = np.ascontiguousarray(new_rows, np.float32)
        data, scales = encode_rows(new_rows, self.params.dtype)
        tb = QuantizedTable(self.params.dtype, self.table.data.copy(),
                            self.table.scales.copy())
        tb.data[positions] = data
        tb.scales[positions] = scales
        assign = self.assign.copy()
        d = (-2.0 * (new_rows @ self.centroids.T)
             + np.einsum("ck,ck->c", self.centroids,
                         self.centroids)[None, :])
        assign[positions] = np.argmin(d, axis=1).astype(np.int32)
        return RetrievalIndex(self.params, tb, self.centroids, assign)


def build_index(item_factors, params: RetrievalParams) -> RetrievalIndex:
    """Quantized table + clustering from the f32 item rows — the whole
    sidecar is a deterministic function of (rows, params), so any
    holder of the f32 partition can rebuild an identical index."""
    rows = np.ascontiguousarray(np.asarray(item_factors), np.float32)
    c = params.resolved_n_clusters(rows.shape[0])
    assign, cent = kmeans_cluster(rows, c, seed=params.seed,
                                  iters=params.kmeans_iters)
    return RetrievalIndex(params, quantize_table(rows, params.dtype),
                          cent, assign)


def sidecar_nbytes_estimate(n_items: int, k: int,
                            params: RetrievalParams) -> int:
    """Upper-bound estimate of a clustered retrieval sidecar's bytes
    BEFORE building it — what the shard memory-budget check charges in
    addition to the f32 partition (the budget must reject a load that
    would only blow up after the expensive k-means). Counts the host
    table + clustering plus the padded (C, Lmax) device layout at a 2x
    padding allowance (the device layout pads clusters to a shared
    pow2 width; a pathologically imbalanced clustering can exceed the
    allowance, which is why the shard re-checks the REALIZED bytes
    after the build, before any swap)."""
    if params.mode != "clustered" or n_items <= 0:
        return 0
    isize = 2 if params.dtype == "bf16" else 1
    c = params.resolved_n_clusters(n_items)
    host = n_items * k * isize + n_items * 8 + c * k * 4
    device = 2 * n_items * (k * isize + 4 + 4)   # table + scales + gidx
    return int(host + device + c * k * 4)


@dataclass
class DeviceRetrievalIndex:
    """The on-device scan layout: clusters padded to a shared pow2
    width Lmax so every shape in the scan program is static.
    ``gidx`` carries -1 in pad slots; pad scores are masked to -inf
    before any top-k, so padding can never surface as a candidate."""

    params: RetrievalParams
    n_items: int
    centroids: jax.Array     # (C, k) f32
    table: jax.Array         # (C, Lmax, k) int8 | bfloat16
    scales: jax.Array        # (C, Lmax) f32
    gidx: jax.Array          # (C, Lmax) int32, -1 = pad

    @property
    def n_clusters(self) -> int:
        return int(self.centroids.shape[0])

    @property
    def pad_width(self) -> int:
        return int(self.table.shape[1])

    def nbytes(self) -> int:
        return int(sum(int(np.dtype(a.dtype).itemsize) * a.size
                       for a in (self.centroids, self.table,
                                 self.scales, self.gidx)))


def build_device_index(index: RetrievalIndex) -> DeviceRetrievalIndex:
    """Pad each cluster to the pow2-bucketed max cluster size and
    device_put the scan arrays. The pad factor is bounded: a degenerate
    clustering (one giant cluster) degenerates toward Lmax ~= n — never
    MORE than one table copy per cluster-width bucket — and the shard
    budget check charged a 2x allowance up front."""
    n, k = index.table.shape
    c = index.n_clusters
    counts = np.bincount(index.assign, minlength=c)
    lmax = pow2_bucket(int(counts.max()) if n else 1)
    order = np.argsort(index.assign, kind="stable")
    np_dtype = np.uint16 if index.params.dtype == "bf16" else np.int8
    table = np.zeros((c, lmax, k), np_dtype)
    scales = np.zeros((c, lmax), np.float32)
    gidx = np.full((c, lmax), -1, np.int32)
    starts = np.zeros(c + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    for ci in range(c):
        rows = order[starts[ci]:starts[ci + 1]]
        table[ci, :len(rows)] = index.table.data[rows]
        scales[ci, :len(rows)] = index.table.scales[rows]
        gidx[ci, :len(rows)] = rows
    if index.params.dtype == "bf16":
        table_dev = jax.device_put(
            jax.lax.bitcast_convert_type(jnp.asarray(table), jnp.bfloat16))
    else:
        table_dev = jax.device_put(jnp.asarray(table))
    return DeviceRetrievalIndex(
        params=index.params, n_items=n,
        centroids=jax.device_put(jnp.asarray(index.centroids)),
        table=table_dev,
        scales=jax.device_put(jnp.asarray(scales)),
        gidx=jax.device_put(jnp.asarray(gidx)),
    )


# ---------------------------------------------------------------------------
# the clustered MIPS scan (XLA fallback + Pallas kernel)
# ---------------------------------------------------------------------------

def resolved_impl(impl: str) -> str:
    """"auto" stays pinned to the XLA scan until the on-hardware A/B
    (bench retrieval cell on a TPU window) shows the Pallas kernel
    winning — the als_pallas.py discipline: interpret-validated kernels
    do not serve by default."""
    return "xla" if impl == "auto" else impl


def quantized_scores_xla(table2d, scales, u) -> jax.Array:
    """XLA reference scan: dequantize in-register, one (M,k)x(k,) MXU
    dot, f32 accumulation. ``table2d`` is (M,k) int8/bf16, ``scales``
    (M,) f32, ``u`` (k,) f32."""
    return jnp.einsum(
        "mk,k->m", table2d.astype(jnp.float32), u,
        preferred_element_type=jnp.float32) * scales


def quantized_scores_pallas(table2d, scales, u, *,
                            interpret: bool = True) -> jax.Array:
    """Pallas TPU scan over one quantized block: the table block stays
    in its storage dtype until the in-register astype feeding the MXU
    dot (the whole point — HBM->VMEM moves 1-2 bytes/element, not 4).

    Layout notes (Mosaic tiling): the row count pads to the int8
    sublane tile (32) and k to the 128 lane; the user row is broadcast
    to a (k_pad, LANE) operand so the product is one lane-aligned MXU
    dot whose output columns are identical — column 0 is the answer.
    Status: interpret-mode CPU parity vs quantized_scores_xla is pinned
    in tests/test_retrieval.py; ``interpret=False`` compiles via Mosaic
    but has not had a hardware A/B yet, so resolved_impl never selects
    this path from "auto"."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    del pltpu  # memory spaces default correctly for whole-array blocks
    m, k = table2d.shape
    lane = 128
    m_pad = m + (-m % 32)
    k_pad = k + (-k % lane)
    tb = table2d
    if (m_pad, k_pad) != (m, k):
        tb = jnp.zeros((m_pad, k_pad), table2d.dtype).at[:m, :k].set(tb)
    u_lanes = jnp.zeros((k_pad, lane), jnp.float32).at[:k, :].set(
        jnp.broadcast_to(u[:, None], (k, lane)))

    def kernel(q_ref, u_ref, out_ref):
        q = q_ref[...].astype(jnp.float32)
        out_ref[...] = jax.lax.dot_general(
            q, u_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((m_pad, lane), jnp.float32),
        interpret=interpret,
    )(tb, u_lanes)
    return out[:m, 0] * scales


@partial(jax.jit, static_argnames=("nprobe", "rerank", "k", "impl"))
def _clustered_topk_jit(u, centroids, table, scales, gidx, item_factors,
                        nprobe: int, rerank: int, k: int, impl: str):
    """One compiled two-stage query batch. u (B,k_f); returns
    (scores (B,k) f32, gidx (B,k) i32) with -inf/-1 where fewer than k
    real candidates survived. Tier-2 scores come from the ORACLE einsum
    over the f32 rows — the quantized tier only chooses candidates."""
    b = u.shape[0]
    c, lmax, kf = table.shape
    cs = jnp.einsum("bk,ck->bc", u, centroids,
                    preferred_element_type=jnp.float32)
    _, top_c = jax.lax.top_k(cs, nprobe)               # (B, nprobe)
    sub_q = table[top_c]                               # (B, P, Lmax, kf)
    sub_s = scales[top_c]                              # (B, P, Lmax)
    sub_g = gidx[top_c]                                # (B, P, Lmax)
    if impl == "pallas":
        # interpret-mode kernel over each query's survivor block; the
        # XLA path below is what "auto" serves (see resolved_impl)
        def one(args):
            q2d, s2d, urow = args
            return quantized_scores_pallas(
                q2d.reshape(nprobe * lmax, kf), s2d.reshape(-1), urow)
        qs = jax.lax.map(one, (sub_q, sub_s, u)).reshape(b, nprobe * lmax)
    else:
        qs = jnp.einsum(
            "bplk,bk->bpl", sub_q.astype(jnp.float32), u,
            preferred_element_type=jnp.float32,
        ).reshape(b, nprobe * lmax) * sub_s.reshape(b, nprobe * lmax)
    flat_g = sub_g.reshape(b, nprobe * lmax)
    qs = jnp.where(flat_g >= 0, qs, -jnp.inf)
    _, cpos = jax.lax.top_k(qs, rerank)                # (B, rerank)
    cand_g = jnp.take_along_axis(flat_g, cpos, axis=1)
    rows = item_factors[jnp.clip(cand_g, 0, None)]     # (B, rerank, kf)
    exact = jnp.einsum("brk,bk->br", rows, u,
                       preferred_element_type=jnp.float32)
    exact = jnp.where(cand_g >= 0, exact, -jnp.inf)
    scores, pos = jax.lax.top_k(exact, k)
    out_g = jnp.take_along_axis(cand_g, pos, axis=1)
    return scores, jnp.where(jnp.isfinite(scores), out_g, -1)


def candidate_topk(didx: DeviceRetrievalIndex, item_factors, user_rows,
                   k: int):
    """Two-stage top-k for a batch of user rows against the clustered
    index. Mirrors ops/als.py recommend_topk's bucketing contract: the
    batch dim, k, and the rerank width are pow2-bucketed before jit and
    trimmed on host, so the serving mix compiles O(log) programs.

    ``item_factors`` is the arm's EXISTING f32 device matrix (the
    re-rank oracle source) — the index never duplicates it. Callers
    must drop entries with gidx -1 (fewer real candidates than k).

    Exhaustive scans (nprobe >= n_clusters) must not reach this
    function: callers branch to the literal oracle path first (see the
    module docstring's exactness contract)."""
    u = np.asarray(user_rows, np.float32)
    if u.ndim == 1:
        u = u[None, :]
    b = u.shape[0]
    n_scan = didx.n_clusters * didx.pad_width
    nprobe = min(didx.params.nprobe, didx.n_clusters)
    k = max(1, min(int(k), didx.n_items))
    k_bucket = pow2_bucket(k, cap=didx.n_items)
    rerank = pow2_bucket(
        max(didx.params.rerank_k, k_bucket),
        cap=min(nprobe * didx.pad_width, n_scan))
    k_bucket = min(k_bucket, rerank)
    b_bucket = pow2_bucket(b)
    if b_bucket != b:
        u = np.concatenate([u, np.zeros((b_bucket - b, u.shape[1]),
                                        np.float32)])
    scores, gidx = _clustered_topk_jit(
        jnp.asarray(u), didx.centroids, didx.table, didx.scales,
        didx.gidx, item_factors, nprobe=nprobe, rerank=rerank,
        k=k_bucket, impl=resolved_impl(didx.params.impl))
    return np.asarray(scores)[:b, :k], np.asarray(gidx)[:b, :k]


def recall_at_k(got_gidx, oracle_gidx) -> float:
    """Fraction of the oracle's top-k the candidate tier recovered —
    the retrieval-parity CI gate's metric (order-insensitive: tier 2
    re-scores exactly, so membership is what tier 1 owes)."""
    got = np.asarray(got_gidx)
    want = np.asarray(oracle_gidx)
    if want.ndim == 1:
        got, want = got[None, :], want[None, :]
    hits = sum(len(set(g.tolist()) & set(w.tolist()))
               for g, w in zip(got, want))
    return hits / max(1, want.size)
