"""Mixture-of-experts FFN with expert parallelism (ep) over the mesh.

Net-new beyond the reference's capability set (like the sequence family it
plugs into — SURVEY.md §5 notes the reference has no sequence models at
all), this is the framework's expert-parallel building block: the MoE FFN
drops in for the dense FFN of the sequential recommender's transformer
blocks.

TPU-first design:
 * routing and dispatch are ONE-HOT MATMULS, not gathers: tokens are
   combined into per-expert capacity slots with a (tokens, experts*cap)
   dispatch matrix — einsums the MXU tiles well, and shapes stay static
   (capacity-dropped tokens pass through on the residual path, the
   standard Switch-Transformer treatment);
 * expert parallelism shards the EXPERT axis over mesh devices with
   `shard_map`: tokens are exchanged to their experts' devices via
   `jax.lax.all_to_all` over ICI (the collective the reference's Spark
   shuffle would have played), expert FFNs run local dense matmuls, and a
   second all_to_all returns expert outputs to the tokens' devices;
 * the router's load-balance auxiliary loss (mean fraction x mean prob per
   expert) keeps experts busy so capacity drops stay rare.

Single-device (ep=1) and expert-parallel paths compute the same function;
tests pin them together and pin top-1 routing against a per-token loop.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pio_tpu.utils.jaxcompat import ensure_jax_compat

ensure_jax_compat()  # jax<0.5: install the jax.shard_map forwarding wrapper


@dataclass(frozen=True)
class MoEConfig:
    n_experts: int = 4
    d_model: int = 64
    d_ff: int = 128
    capacity_factor: float = 1.25  # slots per expert = cf * tokens/experts


def init_moe_params(key, cfg: MoEConfig) -> dict:
    kr, k1, k2 = jax.random.split(key, 3)
    s1 = 1.0 / np.sqrt(cfg.d_model)
    s2 = 1.0 / np.sqrt(cfg.d_ff)
    return {
        "router": jax.random.normal(kr, (cfg.d_model, cfg.n_experts)) * s1,
        "w_in": jax.random.normal(
            k1, (cfg.n_experts, cfg.d_model, cfg.d_ff)) * s1,
        "b_in": jnp.zeros((cfg.n_experts, cfg.d_ff)),
        "w_out": jax.random.normal(
            k2, (cfg.n_experts, cfg.d_ff, cfg.d_model)) * s2,
        "b_out": jnp.zeros((cfg.n_experts, cfg.d_model)),
    }


def _capacity(n_tokens: int, n_experts: int, cf: float) -> int:
    return max(1, int(np.ceil(cf * n_tokens / n_experts)))


def _route(x, router, n_experts: int, capacity: int):
    """Top-1 routing -> (dispatch (T, E, C), combine (T, E, C), aux_loss).

    dispatch is a 0/1 tensor placing each kept token into its expert's
    next free capacity slot; combine carries the router probability for
    the weighted return path. Tokens beyond capacity have all-zero rows
    (they fall through on the residual connection)."""
    logits = x @ router                       # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)       # (T,)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]

    one_hot = jax.nn.one_hot(expert, n_experts, dtype=jnp.float32)  # (T, E)
    # position of each token within its expert's queue (exclusive cumsum)
    pos = jnp.cumsum(one_hot, axis=0) - one_hot          # (T, E)
    pos = jnp.sum(pos * one_hot, axis=1)                 # (T,)
    keep = pos < capacity
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                            dtype=jnp.float32)  # (T, C)
    dispatch = one_hot[:, :, None] * pos_oh[:, None, :]  # (T, E, C)
    dispatch = dispatch * keep[:, None, None]
    combine = dispatch * gate[:, None, None]

    # Switch-Transformer load-balance loss: E * sum_e f_e * P_e
    frac = one_hot.mean(axis=0)               # fraction routed per expert
    mean_prob = probs.mean(axis=0)
    aux = n_experts * jnp.sum(frac * mean_prob)
    return dispatch, combine, aux


def _expert_ffn(params, xs):
    """xs: (E, C, D) slots -> (E, C, D); one batched dense FFN per expert."""
    h = jnp.einsum("ecd,edf->ecf", xs, params["w_in"])
    h = jax.nn.relu(h + params["b_in"][:, None, :])
    out = jnp.einsum("ecf,efd->ecd", h, params["w_out"])
    return out + params["b_out"][:, None, :]


def moe_ffn(params, x, cfg: MoEConfig):
    """Single-device MoE FFN. x: (T, D) -> (y (T, D), aux_loss)."""
    T = x.shape[0]
    cap = _capacity(T, cfg.n_experts, cfg.capacity_factor)
    dispatch, combine, aux = _route(x, params["router"], cfg.n_experts, cap)
    slots = jnp.einsum("tec,td->ecd", dispatch, x)       # (E, C, D)
    outs = _expert_ffn(params, slots)
    y = jnp.einsum("tec,ecd->td", combine, outs)
    return y, aux


def moe_ffn_ep(params, x, cfg: MoEConfig, mesh: Mesh, axis: str = "data"):
    """Expert-parallel MoE FFN over `axis`: tokens sharded per device,
    experts sharded per device; two all_to_all collectives move capacity
    slots to and from the experts' home devices.

    x: (T, D) GLOBAL tokens (T divisible by mesh[axis]). The router is
    replicated; w_in/b_in/w_out/b_out are sharded on the expert axis.
    Returns (y (T, D), aux_loss).

    Capacity semantics: each device budgets cf * t_local / E slots per
    expert from ITS shard (Switch-style), vs moe_ffn's one global
    cf * T / E pool — so a skewed routing distribution can drop tokens
    here that the single-device path keeps. Equivalence with moe_ffn
    (which tests pin, up to float reassociation) holds exactly when no
    expert exceeds capacity on any device."""
    n_dev = mesh.shape[axis]
    if cfg.n_experts % n_dev != 0:
        raise ValueError(
            f"n_experts ({cfg.n_experts}) must divide over {n_dev} devices"
        )
    T = x.shape[0]
    if T % n_dev != 0:
        raise ValueError(
            f"token count ({T}) must divide over {n_dev} devices"
        )
    t_local = T // n_dev
    cap = _capacity(t_local, cfg.n_experts, cfg.capacity_factor)

    spec_tok = P(axis)                # tokens: leading dim sharded
    spec_exp = P(axis)                # expert tensors: expert dim sharded
    spec_rep = P()

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(
            {"router": spec_rep, "w_in": spec_exp, "b_in": spec_exp,
             "w_out": spec_exp, "b_out": spec_exp},
            spec_tok,
        ),
        out_specs=(spec_tok, spec_rep),
        check_vma=False,
    )
    def run(p_local, x_local):
        # local routing against ALL experts (router replicated)
        dispatch, combine, aux = _route(
            x_local, p_local["router"], cfg.n_experts, cap
        )
        slots = jnp.einsum("tec,td->ecd", dispatch, x_local)  # (E, C, D)
        # slots for expert e live on every device; all_to_all rotates the
        # expert axis so device k receives ITS experts' slots from every
        # device: (E, C, D) -> (E/n, n*C, D) after reshape
        e_loc = cfg.n_experts // n_dev
        shuffled = jax.lax.all_to_all(
            slots.reshape(n_dev, e_loc, cap, -1),
            axis, split_axis=0, concat_axis=0, tiled=False,
        )  # (n_dev, e_loc, cap, D): source-device major
        shuffled = jnp.moveaxis(shuffled, 0, 1).reshape(
            e_loc, n_dev * cap, -1
        )
        outs = _expert_ffn(
            {k: p_local[k] for k in ("w_in", "b_in", "w_out", "b_out")},
            shuffled,
        )  # (e_loc, n*cap, D)
        back = jnp.moveaxis(
            outs.reshape(e_loc, n_dev, cap, -1), 1, 0
        )  # (n_dev, e_loc, cap, D)
        returned = jax.lax.all_to_all(
            back, axis, split_axis=0, concat_axis=0, tiled=False,
        ).reshape(cfg.n_experts, cap, -1)
        y = jnp.einsum("tec,ecd->td", combine, returned)
        # aux averaged across devices (it is a mean statistic)
        aux = jax.lax.pmean(aux, axis)
        return y, aux

    shard_p = {
        "router": jax.device_put(
            params["router"], NamedSharding(mesh, spec_rep)),
        "w_in": jax.device_put(params["w_in"], NamedSharding(mesh, spec_exp)),
        "b_in": jax.device_put(params["b_in"], NamedSharding(mesh, spec_exp)),
        "w_out": jax.device_put(
            params["w_out"], NamedSharding(mesh, spec_exp)),
        "b_out": jax.device_put(
            params["b_out"], NamedSharding(mesh, spec_exp)),
    }
    xs = jax.device_put(x, NamedSharding(mesh, spec_tok))
    return run(shard_p, xs)
