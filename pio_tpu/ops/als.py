"""ALS (alternating least squares) matrix factorization as a TPU kernel.

Replaces MLlib's `ALS.train` / `ALS.trainImplicit` (invoked by the reference
recommendation templates, e.g. examples/scala-parallel-recommendation/
custom-query/src/main/scala/ALSAlgorithm.scala:56-67). MLlib block-partitions
the factor matrices and shuffles ratings between executors each sweep; the
TPU formulation instead builds *batched dense normal equations* and solves
them with a single batched Cholesky on the MXU:

    for each user u:  (Y_u^T C_u Y_u + lambda I) x_u = Y_u^T C_u p_u

 * ratings live as fixed-size COO arrays (user_idx, item_idx, value) padded
   to a static shape — XLA-friendly, no dynamic shapes;
 * per-rating outer products y_i y_i^T are accumulated into per-user k x k
   systems with a `lax.scan` over chunks + scatter-add (`.at[].add`), so
   peak memory is O(n_users k^2 + chunk k^2), never O(nnz k^2);
 * both explicit ALS and implicit-feedback ALS (Hu-Koren-Volinsky: weights
   c = 1 + alpha r, preferences p = 1) share the same accumulation;
 * the multi-chip path (`als_train_sharded`) partitions users/items into
   per-device blocks with `shard_map`; each half-sweep all_gathers the
   opposing factor block over ICI — the analogue of MLlib's shuffle, but a
   single fused collective.

Padding convention: padded COO entries point at row index n_self (one extra
dummy row) so they accumulate harmlessly and are dropped.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pio_tpu.parallel.mesh import DATA_AXIS


@dataclass(frozen=True)
class ALSParams:
    rank: int = 16
    iterations: int = 10
    reg: float = 0.1          # lambda (MLlib default 0.01; templates use 0.01)
    alpha: float = 1.0        # implicit confidence scale
    implicit: bool = False
    seed: int = 3
    chunk: int = 65536        # COO entries per scan step


@jax.tree_util.register_pytree_node_class
@dataclass
class ALSModel:
    """Factor matrices. user_factors: (n_users, k); item_factors: (n_items, k)."""

    user_factors: jax.Array
    item_factors: jax.Array

    def tree_flatten(self):
        return (self.user_factors, self.item_factors), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def _pad_coo(rows, cols, vals, chunk, dummy_row):
    """Pad COO arrays to a multiple of `chunk`; pads point at dummy_row."""
    nnz = rows.shape[0]
    target = max(chunk, math.ceil(nnz / chunk) * chunk)
    pad = target - nnz
    rows = np.concatenate([rows, np.full(pad, dummy_row, rows.dtype)])
    cols = np.concatenate([cols, np.zeros(pad, cols.dtype)])
    vals = np.concatenate([vals, np.zeros(pad, vals.dtype)])
    return rows, cols, vals


def _normal_equations(self_idx, other_idx, vals, other_factors, n_self,
                      implicit: bool, alpha: float):
    """Accumulate per-row normal equations A (n_self+1,k,k), b (n_self+1,k).

    self_idx/other_idx/vals are (n_chunks, chunk) int32/int32/f32.
    """
    k = other_factors.shape[1]

    def body(carry, chunk_data):
        A, b = carry
        s_idx, o_idx, v = chunk_data
        y = other_factors[o_idx]  # (C, k) gather
        if implicit:
            # c = 1 + alpha*v; A += (c-1) y y^T ; b += c * y   (p == 1)
            w_outer = alpha * v
            w_rhs = 1.0 + alpha * v
        else:
            # every real entry weights 1; pads land on the dummy row
            w_outer = jnp.ones_like(v)
            w_rhs = v
        outer = jnp.einsum("c,ci,cj->cij", w_outer, y, y)
        rhs = w_rhs[:, None] * y
        A = A.at[s_idx].add(outer)
        b = b.at[s_idx].add(rhs)
        return (A, b), None

    A0 = jnp.zeros((n_self + 1, k, k), dtype=jnp.float32)
    b0 = jnp.zeros((n_self + 1, k), dtype=jnp.float32)
    (A, b), _ = jax.lax.scan(body, (A0, b0), (self_idx, other_idx, vals))
    return A[:n_self], b[:n_self]


def _solve_factors(self_idx, other_idx, vals, other_factors, n_self,
                   reg, implicit, alpha):
    A, b = _normal_equations(
        self_idx, other_idx, vals, other_factors, n_self, implicit, alpha
    )
    k = other_factors.shape[1]
    eye = jnp.eye(k, dtype=jnp.float32)
    if implicit:
        # shared Y^T Y term (confidence-1 part handled in accumulation)
        yty = other_factors.T @ other_factors
        A = A + yty[None, :, :]
    A = A + reg * eye[None, :, :]
    chol = jax.scipy.linalg.cho_factor(A)
    return jax.scipy.linalg.cho_solve(chol, b)


def init_factors(n: int, rank: int, key) -> jax.Array:
    # MLlib-style init: abs normal scaled by 1/sqrt(rank) keeps initial
    # predictions O(1)
    return jnp.abs(jax.random.normal(key, (n, rank), dtype=jnp.float32)) / math.sqrt(rank)


# ---------------------------------------------------------------------------
# single-device (one chip) path — jitted whole-train
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("n_users", "n_items", "params"))
def _train_jit(by_user, by_item, n_users: int, n_items: int, params: ALSParams,
               user0, item0):
    u_rows, u_cols, u_vals = by_user
    i_rows, i_cols, i_vals = by_item

    def sweep(carry, _):
        users, items = carry
        users = _solve_factors(
            u_rows, u_cols, u_vals, items, n_users,
            params.reg, params.implicit, params.alpha,
        )
        items = _solve_factors(
            i_rows, i_cols, i_vals, users, n_items,
            params.reg, params.implicit, params.alpha,
        )
        return (users, items), None

    (users, items), _ = jax.lax.scan(
        sweep, (user0, item0), None, length=params.iterations
    )
    return users, items


def als_train(
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    values: np.ndarray,
    n_users: int,
    n_items: int,
    params: ALSParams,
) -> ALSModel:
    """Train on one device (or one logical device under jit)."""
    chunk = min(params.chunk, max(1024, len(values)))
    u_rows, u_cols, u_vals = _pad_coo(
        user_idx.astype(np.int32), item_idx.astype(np.int32),
        values.astype(np.float32), chunk, n_users,
    )
    i_rows, i_cols, i_vals = _pad_coo(
        item_idx.astype(np.int32), user_idx.astype(np.int32),
        values.astype(np.float32), chunk, n_items,
    )
    shape = (-1, chunk)
    by_user = tuple(a.reshape(shape) for a in (u_rows, u_cols, u_vals))
    by_item = tuple(a.reshape(shape) for a in (i_rows, i_cols, i_vals))

    key = jax.random.PRNGKey(params.seed)
    ku, ki = jax.random.split(key)
    user0 = init_factors(n_users, params.rank, ku)
    item0 = init_factors(n_items, params.rank, ki)
    users, items = _train_jit(
        by_user, by_item, n_users, n_items, params, user0, item0
    )
    return ALSModel(users, items)


# ---------------------------------------------------------------------------
# sharded multi-chip path — users/items blocked per device, all_gather per
# half-sweep (the MLlib-shuffle replacement)
# ---------------------------------------------------------------------------

def _block(n: int, n_dev: int) -> int:
    return math.ceil(n / n_dev)


def als_train_sharded(
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    values: np.ndarray,
    n_users: int,
    n_items: int,
    params: ALSParams,
    mesh: Mesh,
) -> ALSModel:
    """Multi-device ALS over the mesh's data axis.

    Host-side layout: users (and their ratings) are partitioned into
    contiguous blocks, one per device; likewise items. Each half-sweep every
    device solves its block's normal equations against the full opposing
    factor matrix, obtained by `all_gather` over ICI (factors are small:
    n x k; the ratings never move).
    """
    n_dev = mesh.shape[DATA_AXIS]
    ub, ib = _block(n_users, n_dev), _block(n_items, n_dev)
    chunk = min(params.chunk, max(1024, math.ceil(len(values) / n_dev)))

    def partition(rows, cols, vals, block):
        """-> per-device (n_dev, n_chunks, chunk) arrays with local row ids."""
        order = np.argsort(rows, kind="stable")
        rows, cols, vals = rows[order], cols[order], vals[order]
        dev_of = rows // block
        per_dev = [[], [], []]
        max_chunks = 0
        buckets = []
        for dv in range(n_dev):
            m = dev_of == dv
            r = (rows[m] - dv * block).astype(np.int32)  # local row id
            c = cols[m].astype(np.int32)
            v = vals[m].astype(np.float32)
            r, c, v = _pad_coo(r, c, v, chunk, block)  # pads -> dummy row
            buckets.append((r, c, v))
            max_chunks = max(max_chunks, len(r) // chunk)
        for r, c, v in buckets:
            # equalize chunk counts across devices (SPMD needs equal shapes)
            pad = max_chunks * chunk - len(r)
            r = np.concatenate([r, np.full(pad, block, np.int32)])
            c = np.concatenate([c, np.zeros(pad, np.int32)])
            v = np.concatenate([v, np.zeros(pad, np.float32)])
            per_dev[0].append(r.reshape(max_chunks, chunk))
            per_dev[1].append(c.reshape(max_chunks, chunk))
            per_dev[2].append(v.reshape(max_chunks, chunk))
        return tuple(np.stack(x) for x in per_dev)  # (n_dev, n_chunks, chunk)

    by_user = partition(
        user_idx.astype(np.int64), item_idx.astype(np.int64),
        values.astype(np.float32), ub,
    )
    by_item = partition(
        item_idx.astype(np.int64), user_idx.astype(np.int64),
        values.astype(np.float32), ib,
    )

    key = jax.random.PRNGKey(params.seed)
    ku, ki = jax.random.split(key)
    user0 = np.array(init_factors(ub * n_dev, params.rank, ku))
    item0 = np.array(init_factors(ib * n_dev, params.rank, ki))
    # zero the phantom rows beyond n_users/n_items: they receive no ratings
    # (and solve to ~0 anyway), but a non-zero init would contaminate the
    # shared Y^T Y term of the implicit-ALS first sweep
    user0[n_users:] = 0.0
    item0[n_items:] = 0.0
    user0 = user0.reshape(n_dev, ub, params.rank)
    item0 = item0.reshape(n_dev, ib, params.rank)

    dev_spec = P(DATA_AXIS)  # leading axis = device blocks

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(dev_spec,) * 4,
        out_specs=dev_spec,
        check_vma=False,
    )
    def run(by_user_shard, by_item_shard, u0, i0):
        u_rows, u_cols, u_vals = (a[0] for a in by_user_shard)
        i_rows, i_cols, i_vals = (a[0] for a in by_item_shard)

        def sweep(carry, _):
            users, items = carry  # local blocks (ub, k) / (ib, k)
            all_items = jax.lax.all_gather(
                items, DATA_AXIS, tiled=True
            )  # (ib*n_dev, k)
            users = _solve_factors(
                u_rows, u_cols, u_vals, all_items, u0.shape[1],
                params.reg, params.implicit, params.alpha,
            )
            all_users = jax.lax.all_gather(users, DATA_AXIS, tiled=True)
            items = _solve_factors(
                i_rows, i_cols, i_vals, all_users, i0.shape[1],
                params.reg, params.implicit, params.alpha,
            )
            return (users, items), None

        (users, items), _ = jax.lax.scan(
            sweep, (u0[0], i0[0]), None, length=params.iterations
        )
        return users[None], items[None]

    sharding = NamedSharding(mesh, dev_spec)
    by_user = tuple(jax.device_put(a, sharding) for a in by_user)
    by_item = tuple(jax.device_put(a, sharding) for a in by_item)
    u0 = jax.device_put(user0, sharding)
    i0 = jax.device_put(item0, sharding)
    users, items = run(by_user, by_item, u0, i0)
    users = users.reshape(-1, params.rank)[:n_users]
    items = items.reshape(-1, params.rank)[:n_items]
    return ALSModel(users, items)


# ---------------------------------------------------------------------------
# prediction / scoring
# ---------------------------------------------------------------------------

@jax.jit
def predict_pairs(model: ALSModel, user_idx, item_idx) -> jax.Array:
    return jnp.einsum(
        "nk,nk->n",
        model.user_factors[user_idx],
        model.item_factors[item_idx],
    )


@partial(jax.jit, static_argnames=("k",))
def _topk_jit(model: ALSModel, user_idx, k: int):
    scores = model.user_factors[user_idx] @ model.item_factors.T  # (B, I)
    return jax.lax.top_k(scores, k)


def recommend_topk(model: ALSModel, user_idx, k: int):
    """Top-k items for a batch of users: one (B,k)x(k,I) matmul + lax.top_k
    (the MXU path serving /queries.json).

    k is bucketed to the next power of two before jit so per-query k values
    (e.g. num + len(blackList)) don't each compile a fresh XLA program; the
    exact-k trim happens on host."""
    n_items = model.item_factors.shape[0]
    k = max(1, min(int(k), n_items))
    bucket = min(n_items, 1 << (k - 1).bit_length())
    scores, idx = _topk_jit(model, user_idx, bucket)
    return scores[:, :k], idx[:, :k]


def rmse(model: ALSModel, user_idx, item_idx, values) -> float:
    pred = predict_pairs(
        model, jnp.asarray(user_idx), jnp.asarray(item_idx)
    )
    return float(jnp.sqrt(jnp.mean((pred - jnp.asarray(values)) ** 2)))
