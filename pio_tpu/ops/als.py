"""ALS (alternating least squares) matrix factorization as a TPU kernel.

Replaces MLlib's `ALS.train` / `ALS.trainImplicit` (invoked by the reference
recommendation templates, e.g. examples/scala-parallel-recommendation/
custom-query/src/main/scala/ALSAlgorithm.scala:56-67). MLlib block-partitions
the factor matrices and shuffles ratings between executors each sweep; the
TPU formulation is built around three hardware facts measured on v5e:

 * per-rating outer-product scatters are HBM-bound (O(nnz*k^2) traffic), so
   the per-row normal equations  (Y^T C Y + lambda I) x = Y^T C p  are
   accumulated as *batched matmuls* over fixed-width rating slots — MXU
   work with O(nnz*k) traffic;
 * the solve is short warm-started Jacobi-CG by default: XLA's batched
   Cholesky does not use the MXU (measured 10 GFLOP/s on (138k,64,64)
   v5e — 1.16 s of a 1.75 s half-sweep), while CG is pure batched
   matvecs. At the auto cap max(16, rank//4), per-sweep component timing
   on the ML-20M shape shows the solve at 142 ms vs Cholesky's 1157 ms,
   and quality is at parity or better: implicit objective within 1e-5
   relative of the exact solve, explicit heldout RMSE *lower* (1.310 vs
   1.352 at rank 64; 1.291 vs 1.322 at rank 100 — the inexact inner
   solve early-stops the per-row overfit that exact ALS commits to).
   cg_iters=0 selects the exact Cholesky when bit-exactness matters;
 * the host is slow relative to the chip (single-core sort of 20M ratings
   costs more than the whole train), so the slot layout itself is built
   ON DEVICE from the raw COO arrays: one stable `lax.sort` by row, then
   an all-vectorized slot/column assignment and a monotone scatter. Only
   the three contiguous COO arrays ever cross the host->HBM link.

The multi-chip path (`als_train_sharded`) partitions users/items into
per-device blocks with `shard_map`; each half-sweep all_gathers the
opposing factor block over ICI — the analogue of MLlib's shuffle, but a
single fused collective.

Ratings slots are (width,)-wide segments of one row's ratings; rows with
more ratings than `width` naturally occupy several slots, and their partial
normal-equation blocks scatter-add into the same row system.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pio_tpu.utils.jaxcompat import ensure_jax_compat

ensure_jax_compat()  # jax<0.5: install the jax.shard_map forwarding wrapper

from pio_tpu.ops.bucketing import pow2_bucket
from pio_tpu.parallel.mesh import DATA_AXIS


@dataclass(frozen=True)
class ALSParams:
    rank: int = 16
    iterations: int = 10
    reg: float = 0.1          # lambda (MLlib default 0.01; templates use 0.01)
    alpha: float = 1.0        # implicit confidence scale
    implicit: bool = False
    seed: int = 3
    chunk: int = 65536        # nnz bucketing quantum: ratings are padded to a
                              # multiple of this so retrains with slightly
                              # different data sizes reuse the compiled program
    width: int = 128          # ratings per slot (= MXU contraction width)
    chunk_slots: int = 8192   # slots per accumulation step (bounds gather temp)
    # gather the opposing factors in bf16 when building the normal
    # equations: halves that gather's HBM traffic. With the short-CG solve
    # (which removed the Cholesky wall that used to hide it) this measures
    # +15% end-to-end at the ML-20M shape on v5e (29.7M vs 25.7M
    # ratings/s warm); heldout-RMSE delta vs f32 is 1.7e-4 relative on 2M
    # ratings (bf16+CG 1.33714 vs f32+Cholesky 1.33691), so it defaults
    # on. Set False for bit-conservative factor builds.
    bf16_gather: bool = True
    cg_iters: int = -1        # -1: auto (per-side: exact Cholesky for
                              # small row batches, short warm-started CG
                              # for large); 0: exact batched Cholesky;
                              # >0: explicit CG iteration count
    # auto mode switches a side to CG above this many rows: below it the
    # batched Cholesky costs <~70ms (linear in batch; 1157ms at 138k on
    # v5e) so exactness is free; above it CG's MXU matvecs win big
    auto_cg_rows: int = 8192
    # warm-sweep CG schedule: after `cg_warm_sweeps` full-strength sweeps,
    # drop to `cg_warm_iters` CG iterations (-1 keeps the full count).
    # Rationale from the v5e per-op profile (eval/ALS_ROOFLINE.md): the CG
    # matvecs are the sweep's single largest term (134 ms of ~520 ms at
    # the ML-20M shape) and the only one already running at HBM peak, so
    # fewer iterations is the one lever that cuts REAL traffic instead of
    # emitter overhead. ALS warm-starts each solve from the previous
    # sweep's factors; once the outer iteration is near its fixed point
    # the inner Krylov correction is small and half the iterations hold
    # the heldout RMSE (measured: see eval/RMSE_PARITY.md).
    # Default 6 (vs the cold cap of 16): measured on v5e at the ML-20M
    # shape the schedule is worth ~-75 ms/sweep; per the committed grid
    # artifact (eval/CG_WARM_QUALITY.json) explicit heldout RMSE is
    # flat-to-better at 8 and 6 (0.44459 / 0.44435 vs 0.44494 full) and
    # the implicit objective is BETTER than full-strength CG at both
    # (-2.5% at 8, -3.3% at 6 — the inexact inner solve mildly
    # regularizes), while 4 flips to +2.4% WORSE; 6 is the default, -1
    # disables the schedule.
    cg_warm_iters: int = 6
    cg_warm_sweeps: int = 2
    # normal-equation accumulation strategy:
    #   "carry":   scatter-add each chunk's blocks into the (n,k,k)
    #              accumulator inside the scan (the accumulator is a loop
    #              carry — if XLA materializes the carry per iteration the
    #              full accumulator re-streams once per chunk);
    #   "stacked": chunks emit their blocks as scan OUTPUTS (no big carry),
    #              then one sorted scatter-add per slot group folds them
    #              into A — bounded temp via group_slots;
    #   "pallas":  fused Pallas segment-flush kernel (ops/als_pallas.py):
    #              no scatter, no carry, each A row written once;
    #   "hybrid":  XLA batched-MXU blocks + Pallas segment-flush scatter
    #              (ops/als_pallas.py normal_equations_hybrid) — keeps
    #              the fast einsum, replaces only the scatter emitter;
    #   "stream":  hybrid with the OVERLAPPED flush kernel
    #              (_segment_kernel_stream): each A-row DMA starts at
    #              its flush point and is awaited at the next flush
    #              that reuses the staging slot, hiding the
    #              65 ms/sweep of exposed flush latency the round-5
    #              profile charged the hybrid kernel's in-kernel waits;
    #   "auto":    per-backend (see resolved_accum)
    accum: str = "auto"
    # store A lane-packed (n, k²) end-to-end: the streaming flush
    # kernel writes packed rows (k² is a 128-multiple — no lane
    # padding, a 2x byte cut on A at rank 64) and the CG solve consumes
    # them through the Pallas packed batched matvec, so the 6.1x
    # isolated packed-matvec win (eval/als_kernel_lab.py) composes with
    # no XLA relayout at the scatter/solve boundary
    # (eval/ALS_ROOFLINE.md "Lane-packed A" verdict). Requires the
    # streaming flush: accum="hybrid" is promoted to "stream", the XLA
    # accumulation paths ignore the flag (resolved_packed() reports
    # what actually ran). Exact-Cholesky sides unpack once per solve.
    packed_a: bool = False
    # stacked mode: max slots whose (k,k) blocks are materialized at once;
    # temp bytes = group_slots * k * k * 4 (73k slots @ k=64 = 1.2 GB)
    group_slots: int = 73728
    # slot-gather implementation for the normal-equation build:
    #   "xla":         the plain src[idx] gather (XLA emitter);
    #   "pallas-copy" / "pallas-take": VMEM-resident Pallas gather
    #       (ops/als_pallas.py gather_rows_pallas) — XLA's emitter runs
    #       ~10x off HBM peak for VMEM-sized tables and the decision is
    #       out of reach from JAX (eval/ALS_ROOFLINE.md); applied only
    #       when the table fits GATHER_VMEM_TABLE_BUDGET, XLA otherwise;
    #   "stream":      double-buffered HBM->VMEM streaming gather
    #       (ops/als_pallas.py gather_rows_stream): per-row async
    #       copies with mini-group prefetch, ANY table size — the
    #       custom gather eval/ALS_ROOFLINE.md calls for on both sweep
    #       halves (the users-half table is 4x over the VMEM budget);
    #   "auto":        currently "xla" — the Pallas variants are
    #       interpret-mode-validated; flips only when the on-hardware
    #       A/B (eval/als_accum_bench.py gather cells) shows a win
    gather: str = "auto"

    _GATHER_MODES = ("auto", "xla", "pallas-copy", "pallas-take", "stream")
    _ACCUM_MODES = ("auto", "carry", "stacked", "pallas", "hybrid", "stream")

    def __post_init__(self):
        # validate here, not in the kernel: "pallas" alone would pass a
        # startswith check and then IndexError inside the jit trace, and
        # any other typo would silently fall back to the XLA path
        if self.gather not in self._GATHER_MODES:
            raise ValueError(
                f"ALSParams.gather={self.gather!r}; "
                f"expected one of {self._GATHER_MODES}")
        # same rationale for accum: the dispatch chain and the packed_a
        # promotion key on exact strings, so a typo ("strem") would
        # silently run the stacked path unpacked
        if self.accum not in self._ACCUM_MODES:
            raise ValueError(
                f"ALSParams.accum={self.accum!r}; "
                f"expected one of {self._ACCUM_MODES}")

    def resolved_cg_iters(self, n_self: int | None = None) -> int:
        """-1 (default) = auto, decided per factor side by its row count:

        * n_self <= auto_cg_rows: exact batched Cholesky (0) — at small
          batch the solve is not the bottleneck, and on noiseless/tiny
          data the exact solve measurably generalizes better;
        * large sides: short warm-started Jacobi-CG capped at
          max(16, rank//4). Measured on v5e at the ML-20M shape (rank
          64, implicit, warm): 28.2M ratings/s at cg=8, 25.7M at cg=16,
          vs 10.5M with the exact Cholesky — XLA's batched Cholesky runs
          at ~10 GFLOP/s on TPU while CG is batched matvecs on the MXU.
          Quality at the cap is at parity or better at realistic scale
          (implicit objective within 1e-5; explicit heldout RMSE lower:
          1.310 vs 1.352 at rank 64, 1.291 vs 1.322 at rank 100 — the
          inexact inner solve early-stops per-row overfit). CG
          convergence is governed by conditioning, not the Krylov
          dimension, so the cap grows only mildly with rank; the warm
          start carries convergence across sweeps.

        With n_self=None (size unknown) auto returns the CG cap."""
        if self.cg_iters >= 0:
            return self.cg_iters
        if n_self is not None and n_self <= self.auto_cg_rows:
            return 0
        return max(16, self.rank // 4)

    def resolved_accum(self) -> str:
        """The accumulation strategy that actually runs ("auto" resolves
        here, next to resolved_cg_iters, so callers — bench artifacts
        included — can report the real mode, not the knob). Rank-aware:
        _normal_equations falls back hybrid/stream->stacked above k=256
        (the segment-flush kernel's VMEM blocks exceed the 16 MB scoped
        budget), and this mirror applies the same rule so artifacts
        never report a mode that did not run. packed_a promotes hybrid
        to stream (packed rows need the streaming flush kernel).

        auto is per-backend: on TPU "hybrid" (XLA batched-MXU blocks +
        Pallas segment-flush scatter) measured 0.439 s/sweep at the
        ML-20M shape vs stacked 0.485 / carry 0.499 — the XLA
        scatter-add emitter runs at ~13% of streaming peak and the
        kernel writes each A row exactly once instead
        (eval/ALS_ROOFLINE.md, eval/als_accum_bench.py). auto stays on
        hybrid — NOT stream — until the on-chip A/B
        (eval/als_accum_bench.py stream cells) shows the overlapped
        flush winning on hardware. On CPU the Pallas kernel only exists
        in interpret mode, and carry measured fastest of the XLA paths,
        so carry stays."""
        mode = self.accum
        if mode == "auto":
            mode = "hybrid" if _accelerator_backend() else "carry"
        if self.packed_a and mode == "hybrid":
            mode = "stream"    # packed rows require the streaming flush
        if mode in ("hybrid", "stream") and self.rank > 256:
            mode = "stacked"   # keep in sync with _normal_equations
        return mode

    def resolved_packed(self) -> bool:
        """True when A actually flows lane-packed: packed_a requested
        AND the resolved accumulation is the streaming flush kernel
        (the XLA paths and the k>256 fallback produce (n,k,k))."""
        return self.packed_a and self.resolved_accum() == "stream"


@dataclass(frozen=True)
class ALSValidation:
    """Per-sweep heldout trajectory from `als_train_validated`.

    The reference's eval workflow picks the best PARAMS
    (MetricEvaluator.scala:138-161) but always keeps the LAST sweep's
    model; measured on ML-20M the heldout RMSE curve bottoms at sweep
    2-3 and then climbs (eval/RMSE_PARITY.json: 0.568 at sweep 2 ->
    0.594 at 10), so "final" silently commits the worst point on its
    own curve. The TPU-idiomatic fix is best-sweep SELECTION inside the
    compiled scan — data-dependent early exit is not expressible under
    jit's static control flow, but tracking argmin factors as a scan
    carry costs one factor copy (~42 MB at the ML-20M shape) and two
    jnp.where selects per sweep, so the full schedule runs at
    unchanged throughput and the returned model is the curve's
    minimum, not its tail."""

    curve: tuple          # heldout RMSE after each sweep, in order
    best_sweep: int       # 1-based sweep index of the minimum
    best_rmse: float
    final_rmse: float     # last sweep's RMSE (what "no selection" returns)


@jax.tree_util.register_pytree_node_class
@dataclass
class ALSModel:
    """Factor matrices. user_factors: (n_users, k); item_factors: (n_items, k)."""

    user_factors: jax.Array
    item_factors: jax.Array

    def tree_flatten(self):
        return (self.user_factors, self.item_factors), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def _accelerator_backend() -> bool:
    """True on TPU-class backends (incl. the tunneled 'axon' platform,
    which does not report platform == 'tpu'); False on cpu/gpu."""
    import jax

    try:
        dev = jax.devices()[0]
    except Exception:  # noqa: BLE001 - backend init failure: be conservative
        return False
    return dev.platform not in ("cpu", "gpu")


def blocks_group_budget_slots(k: int) -> int:
    """Max slots whose (k,k) f32 blocks may be materialized at once —
    the ALSParams.group_slots default (73728) is k=64-tuned (1.2 GB);
    the temp scales k^2, so group sizing caps by BYTES too or rank 128
    OOMs HBM at the ML-20M shape (measured 22.6G of 15.75G). Shared by
    the stacked (als.py) and hybrid (als_pallas.py) accumulation
    paths."""
    return max(1, (1_200 * 2**20) // (k * k * 4))


def _slots_for(nnz: int, n_self: int, width: int, chunk_slots: int) -> int:
    """Static upper bound on slot count, padded to a chunk multiple.

    At most min(n_self, nnz) rows are non-empty (each adds one boundary
    slot) plus nnz//width width-overflow splits — so the layout stays
    O(nnz) even when the id space is much larger than the data.
    """
    s = nnz // width + 1 + min(n_self, nnz)
    return math.ceil(s / chunk_slots) * chunk_slots


def _device_slot_layout(u, o, v, n_self: int, width: int, slots_max: int):
    """Build the slot layout on device from (possibly sentinel-padded) COO.

    u: (nnz,) int32 row ids; entries with u >= n_self are padding and are
    dropped. o: opposing-side ids; v: values. Returns
    (rows (S,), idx (S,width), val (S,width), lens (S,)).

    The scatter destination index slot_id*width+col is strictly increasing
    in the sorted order, so the writes are sequential in HBM.
    """
    nnz = u.shape[0]
    u_s, o_s, v_s = jax.lax.sort((u, o, v), num_keys=1, is_stable=True)
    t = jnp.arange(nnz, dtype=jnp.int32)
    newrow = jnp.concatenate(
        [jnp.ones((1,), bool), u_s[1:] != u_s[:-1]]
    )
    row_start = jax.lax.cummax(jnp.where(newrow, t, 0))
    pos = t - row_start                       # position within the row
    newslot = newrow | (pos % width == 0)     # heavy rows split every `width`
    slot_id = jnp.cumsum(newslot.astype(jnp.int32)) - 1
    col = pos % width
    valid = u_s < n_self

    slot_id = jnp.where(valid, slot_id, slots_max)  # OOB -> dropped
    # unused slots carry the sentinel row id n_self: the accumulation
    # scatter drops them (mode="drop"), and the slot->row index stays
    # globally NON-DECREASING (real slots ascend, sentinel tail is the
    # max) so scatters can declare indices_are_sorted
    rows = (
        jnp.full((slots_max,), n_self, jnp.int32)
        .at[slot_id].min(u_s, mode="drop")
    )
    lens = (
        jnp.zeros((slots_max,), jnp.int32)
        .at[slot_id].add(1, mode="drop")
    )
    idx = (
        jnp.zeros((slots_max, width), jnp.int32)
        .at[slot_id, col].set(o_s, mode="drop")
    )
    val = (
        jnp.zeros((slots_max, width), jnp.float32)
        .at[slot_id, col].set(v_s, mode="drop")
    )
    return rows, idx, val, lens


def _gather_pow2_rows(m: int, cap: int = 1024) -> int:
    """Largest power of two <= cap dividing m (pallas grid step size)."""
    r = 1
    while r < cap and m % (r * 2) == 0:
        r *= 2
    return r


def _chunk_blocks(src, i_c, v_c, l_c, implicit: bool, alpha: float,
                  gather: str = "xla"):
    """One slot chunk -> per-slot normal-equation blocks
    a_blk (C,k,k), b_blk (C,k) via batched MXU matmuls."""
    W = i_c.shape[1]
    mask = (
        jnp.arange(W, dtype=jnp.int32)[None, :] < l_c[:, None]
    ).astype(jnp.float32)
    if gather == "stream":
        from pio_tpu.ops.als_pallas import gather_rows_stream

        # double-buffered HBM->VMEM streaming gather: no table-size
        # precondition, and the output block is written sequentially in
        # exactly the (C*W, k) layout this reshape consumes — no XLA
        # copy between the gather and the blocks einsum (the 38 ms
        # y-copy in the round-5 profile)
        n, k = src.shape
        C = i_c.shape[0]
        flat = i_c.reshape(-1)
        y = gather_rows_stream(
            src, flat,
            rows_per_step=_gather_pow2_rows(flat.shape[0], cap=512),
        ).reshape(C, W, k).astype(jnp.float32)
    elif gather.startswith("pallas"):
        from pio_tpu.ops.als_pallas import (
            GATHER_VMEM_TABLE_BUDGET, gather_rows_pallas, gather_table_bytes,
        )

        n, k = src.shape
        fits = gather_table_bytes(
            n, k, src.dtype == jnp.bfloat16) <= GATHER_VMEM_TABLE_BUDGET
        if fits:
            C = i_c.shape[0]
            flat = i_c.reshape(-1)
            y = gather_rows_pallas(
                src, flat,
                rows_per_step=_gather_pow2_rows(flat.shape[0]),
                variant=gather.split("-", 1)[1],
            ).reshape(C, W, k).astype(jnp.float32)
        else:
            y = src[i_c].astype(jnp.float32)  # big table: fast emitter
    else:
        y = src[i_c].astype(jnp.float32)  # (C, W, k) gather
    if implicit:
        # c = 1 + alpha*v; A += (c-1) y y^T ; b += c * y   (p == 1)
        w_outer = alpha * v_c * mask
        w_rhs = (1.0 + alpha * v_c) * mask
    else:
        w_outer = mask
        w_rhs = v_c * mask
    # Precision.HIGH (3-pass bf16): the MXU's default 1-pass contraction
    # loses ~3e-3 relative on A, which the CG solve then cannot recover;
    # HIGH restores ~1e-5 at ~3x the matmul passes
    a_blk = jnp.einsum(
        "bwi,bwj->bij", y * w_outer[:, :, None], y,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGH,
    )
    b_blk = jnp.einsum(
        "bwk,bw->bk", y, w_rhs, preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGH,
    )
    return a_blk, b_blk


def _normal_equations(layout, other_factors, n_self, implicit: bool,
                      alpha: float, chunk_slots: int,
                      bf16_gather: bool = False, accum: str = "auto",
                      group_slots: int = 73728, gather: str = "auto",
                      packed: bool = False):
    """Accumulate per-row normal equations A (n_self,k,k), b (n_self,k).

    Slots sharing a row (rows wider than `width`) scatter-add into the same
    row system; the slot->row index is non-decreasing with a sentinel tail
    (see _device_slot_layout), so every scatter declares
    indices_are_sorted=True.

    accum="carry" keeps A as a lax.scan carry and scatters each chunk into
    it — O(1) temp, but a backend that materializes the carry per iteration
    re-streams the full (n,k,k) accumulator once per chunk (measured as the
    dominant cost at ML-20M scale on v5e: ~2.3 GB x ~36 chunks per sweep).
    accum="stacked" emits per-slot blocks as scan OUTPUTS and folds each
    group of `group_slots` slots into A with ONE sorted scatter-add — the
    accumulator is written, not carried, at the price of a bounded
    (group_slots,k,k) temp.

    packed=True requests lane-packed A (n_self, k²); only the streaming
    flush kernel can produce it, so accum="hybrid" is promoted to
    "stream" and the XLA paths return (n,k,k) regardless (callers
    detect the form by A.ndim — see _solve_factors)."""
    rows, idx, val, lens = layout
    k = other_factors.shape[1]
    S, W = idx.shape
    # bf16 source halves the gather's HBM traffic — the build's bottleneck;
    # the f32 upcast happens in-register before the (still f32-accumulated)
    # matmuls. RMSE impact measured at 5e-5 relative (ALSParams.bf16_gather)
    src = (
        other_factors.astype(jnp.bfloat16) if bf16_gather else other_factors
    )
    if accum == "auto":
        # keep in sync with ALSParams.resolved_accum (per-backend choice)
        accum = "hybrid" if _accelerator_backend() else "carry"
    if gather == "auto":
        gather = "xla"   # keep in sync with ALSParams.gather docstring
    # every caller pads S to a chunk_slots multiple via _slots_for
    assert S % chunk_slots == 0, (S, chunk_slots)

    if accum == "pallas":
        from pio_tpu.ops.als_pallas import normal_equations_pallas

        # the kernel sizes its own VMEM chunk; cap by the layout's chunk
        return normal_equations_pallas(
            layout, other_factors, n_self, implicit, alpha,
            chunk_slots=min(128, chunk_slots),
            bf16_gather=bf16_gather,
        )

    if packed and accum == "hybrid":
        accum = "stream"   # packed rows require the streaming flush

    if accum in ("hybrid", "stream") and k > 256:
        # the kernel's VMEM blocks block is >=8 slots x k^2 x 4 B double-
        # buffered; beyond k=256 that exceeds the 16 MB scoped VMEM no
        # matter the chunk, so high ranks take the XLA scatter path
        accum = "stacked"

    if accum in ("hybrid", "stream"):
        from pio_tpu.ops.als_pallas import normal_equations_hybrid

        # XLA batched-MXU blocks + Pallas segment-flush in place of the
        # XLA scatter-add (the 118 ms/sweep, ~13%-of-peak emitter —
        # eval/ALS_ROOFLINE.md); "stream" overlaps the flush DMAs and
        # optionally writes A lane-packed
        return normal_equations_hybrid(
            layout, other_factors, n_self, implicit, alpha,
            chunk_slots=chunk_slots, group_slots=group_slots,
            bf16_gather=bf16_gather, gather=gather,
            overlap=(accum == "stream"),
            packed=packed,  # packed implies accum=="stream" (promoted)
        )

    if accum == "carry":
        n_ch = S // chunk_slots

        def body(carry, xs):
            A, b = carry
            r_c, i_c, v_c, l_c = xs
            a_blk, b_blk = _chunk_blocks(
                src, i_c, v_c, l_c, implicit, alpha, gather=gather
            )
            A = A.at[r_c].add(
                a_blk, mode="drop", indices_are_sorted=True
            )
            b = b.at[r_c].add(
                b_blk, mode="drop", indices_are_sorted=True
            )
            return (A, b), None

        xs = (
            rows.reshape(n_ch, chunk_slots),
            idx.reshape(n_ch, chunk_slots, W),
            val.reshape(n_ch, chunk_slots, W),
            lens.reshape(n_ch, chunk_slots),
        )
        A0 = jnp.zeros((n_self, k, k), dtype=jnp.float32)
        b0 = jnp.zeros((n_self, k), dtype=jnp.float32)
        (A, b), _ = jax.lax.scan(body, (A0, b0), xs)
        return A, b

    if accum != "stacked":
        raise ValueError(f"unknown accum mode {accum!r}")
    # group = as many whole chunks as fit the temp budget (bytes-capped:
    # see blocks_group_budget_slots)
    ch_per_group = max(
        1, min(group_slots, blocks_group_budget_slots(k)) // chunk_slots)
    g_slots = ch_per_group * chunk_slots
    n_groups = math.ceil(S / g_slots)
    A = jnp.zeros((n_self, k, k), dtype=jnp.float32)
    b = jnp.zeros((n_self, k), dtype=jnp.float32)
    for g in range(n_groups):
        lo = g * g_slots
        hi = min(S, lo + g_slots)
        n_ch = (hi - lo) // chunk_slots
        c_sz = chunk_slots
        xs = (
            idx[lo:hi].reshape(n_ch, c_sz, W),
            val[lo:hi].reshape(n_ch, c_sz, W),
            lens[lo:hi].reshape(n_ch, c_sz),
        )

        def body(_, xs_c):
            i_c, v_c, l_c = xs_c
            return None, _chunk_blocks(
                src, i_c, v_c, l_c, implicit, alpha, gather=gather
            )

        _, (a_blks, b_blks) = jax.lax.scan(body, None, xs)
        r_g = rows[lo:hi]
        A = A.at[r_g].add(
            a_blks.reshape(hi - lo, k, k), mode="drop",
            indices_are_sorted=True,
        )
        b = b.at[r_g].add(
            b_blks.reshape(hi - lo, k), mode="drop",
            indices_are_sorted=True,
        )
    return A, b


def _cg_body(mv, dinv, b, x0, n_iter: int):
    """The Jacobi-CG iteration shared by the lane-padded and packed
    matvec forms: only `mv` (the batched A@x) and `dinv` differ."""
    x = x0
    r = b - mv(x)
    z = r * dinv
    p = z
    rz = jnp.sum(r * z, -1)

    def body(_, st):
        x, r, p, rz = st
        ap = mv(p)
        alpha = rz / jnp.maximum(jnp.sum(p * ap, -1), 1e-30)
        x = x + alpha[:, None] * p
        r = r - alpha[:, None] * ap
        z = r * dinv
        rz_new = jnp.sum(r * z, -1)
        beta = rz_new / jnp.maximum(rz, 1e-30)
        p = z + beta[:, None] * p
        return (x, r, p, rz_new)

    x, *_ = jax.lax.fori_loop(0, n_iter, body, (x, r, p, rz))
    return x


def _cg_solve(A, b, x0, n_iter: int):
    """Batched Jacobi-preconditioned conjugate gradient for SPD systems.

    ALS is block coordinate descent, so the inexact inner solve (relative
    residual ~1e-4 at 24 iters on k=64) does not change the fixed point it
    converges to; warm-starting from the previous sweep's factors keeps
    later sweeps cheap.
    """
    dinv = 1.0 / jnp.diagonal(A, axis1=1, axis2=2)

    def mv(x):
        return jnp.einsum(
            "bij,bj->bi", A, x, preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGH,
        )

    return _cg_body(mv, dinv, b, x0, n_iter)


def _cg_solve_packed(Ap, b, x0, n_iter: int, block_rows: int):
    """_cg_solve on LANE-PACKED A (n, k²): the matvec is the Pallas
    packed batched matvec (ops/als_pallas.py packed_block_matvec), so
    no (n,k²)->(n,k,k) relayout appears inside the CG loop — the
    structural property tests/test_als_pallas.py pins on the optimized
    HLO. The Jacobi diagonal is a k-element strided take per solve
    (outside the loop)."""
    from pio_tpu.ops.als_pallas import packed_block_matvec

    k = b.shape[1]
    diag = Ap[:, jnp.arange(k, dtype=jnp.int32) * (k + 1)]
    dinv = 1.0 / diag

    def mv(x):
        return packed_block_matvec(Ap, x, block_rows=block_rows)

    return _cg_body(mv, dinv, b, x0, n_iter)


def _shared_yty(other_factors, yty):
    """Shared Y^T Y term (confidence-1 part handled in accumulation).
    The sharded trainer passes a psum-reduced `yty` built from the
    LOCAL opposing block: recomputing it from the gathered matrix
    would be O(n_dev) redundant FLOPs on every device (measured as
    the dominant super-linear term in eval/WEAK_SCALING.json)."""
    if yty is not None:
        return yty
    return jnp.matmul(
        other_factors.T, other_factors,
        precision=jax.lax.Precision.HIGH,
    )


def _solve_packed(A, b, reg, implicit, alpha, other_factors, yty, x0,
                  cg_iters: int):
    """The solve on LANE-PACKED A (n, k²) from the streaming flush
    kernel: the reg/yty terms are elementwise adds in packed space, and
    CG runs on the Pallas packed matvec — the packed form survives from
    the flush to the last CG iteration with no relayout. The one pad to
    the matvec's row-block multiple happens HERE, once per solve,
    outside the CG loop (identity rows keep the padded diagonal
    invertible; padded b/x0 are zero, and CG's per-row arithmetic never
    mixes rows, so the pad is exact). Exact-Cholesky sides (cg_iters=0:
    small row batches, bit-exactness escapes) unpack once — also
    outside any loop."""
    from pio_tpu.ops.als_pallas import _matvec_block_rows

    n_self, k2 = A.shape
    k = b.shape[1]
    eye_flat = jnp.eye(k, dtype=jnp.float32).reshape(k2)
    if implicit:
        A = A + _shared_yty(other_factors, yty).reshape(k2)[None, :]
    A = A + reg * eye_flat[None, :]
    if cg_iters <= 0:
        A3 = A.reshape(n_self, k, k)
        chol = jax.scipy.linalg.cho_factor(A3)
        return jax.scipy.linalg.cho_solve(chol, b)
    block = _matvec_block_rows(k)
    pad = -n_self % block
    if pad:
        A = jnp.concatenate(
            [A, jnp.broadcast_to(eye_flat, (pad, k2))])
        b = jnp.concatenate([b, jnp.zeros((pad, k), b.dtype)])
        if x0 is not None:
            x0 = jnp.concatenate([x0, jnp.zeros((pad, k), jnp.float32)])
    if x0 is None:
        x0 = jnp.zeros_like(b)
    x = _cg_solve_packed(A, b, x0, cg_iters, block)
    return x[:n_self]


def _solve_factors(layout, other_factors, n_self, reg, implicit, alpha,
                   chunk_slots, x0=None, cg_iters: int = 0,
                   bf16_gather: bool = False, accum: str = "auto",
                   group_slots: int = 73728, yty=None,
                   gather: str = "auto", packed: bool = False):
    A, b = _normal_equations(
        layout, other_factors, n_self, implicit, alpha, chunk_slots,
        bf16_gather=bf16_gather, accum=accum, group_slots=group_slots,
        gather=gather, packed=packed,
    )
    if A.ndim == 2:
        # the streaming flush produced lane-packed (n, k²) rows
        return _solve_packed(A, b, reg, implicit, alpha, other_factors,
                             yty, x0, cg_iters)
    k = other_factors.shape[1]
    eye = jnp.eye(k, dtype=jnp.float32)
    if implicit:
        A = A + _shared_yty(other_factors, yty)[None, :, :]
    A = A + reg * eye[None, :, :]
    if cg_iters > 0:
        if x0 is None:
            x0 = jnp.zeros_like(b)
        return _cg_solve(A, b, x0, cg_iters)
    chol = jax.scipy.linalg.cho_factor(A)
    return jax.scipy.linalg.cho_solve(chol, b)


def init_factors(n: int, rank: int, key) -> jax.Array:
    # MLlib-style init: abs normal scaled by 1/sqrt(rank) keeps initial
    # predictions O(1)
    return jnp.abs(jax.random.normal(key, (n, rank), dtype=jnp.float32)) / math.sqrt(rank)


# ---------------------------------------------------------------------------
# single-device (one chip) path — layout build + train in one jitted program
# ---------------------------------------------------------------------------

def _cg_schedule(params: ALSParams, cg_u: int, cg_i: int):
    """-> (n_full, n_warm, w_u, w_i): how many sweeps run at full CG
    strength vs at the warm count, and the per-side warm iteration
    counts (a side on the exact-Cholesky path, cg=0, stays exact).
    Shared by the single-device and sharded trainers so both execute
    the identical schedule."""
    n_full = params.iterations
    n_warm = 0
    # >= 1: cg_iters=0 is the exact-Cholesky sentinel in _solve_factors,
    # so a 0 here would make the "cheap" warm phase the expensive exact
    # solve; 0 and negative both mean "schedule off"
    if 1 <= params.cg_warm_iters < max(cg_u, cg_i):
        n_full = min(params.iterations, max(0, params.cg_warm_sweeps))
        n_warm = params.iterations - n_full
    w_u = params.cg_warm_iters if cg_u > 0 else cg_u
    w_i = params.cg_warm_iters if cg_i > 0 else cg_i
    return n_full, n_warm, w_u, w_i


def _build_layouts(u, i, v, n_users: int, n_items: int, params: ALSParams):
    """Slot layouts for both halves + the chunk size actually used."""
    nnz = u.shape[0]
    cs = min(params.chunk_slots, _slots_for(nnz, 0, params.width, 1))
    su = _slots_for(nnz, n_users, params.width, cs)
    si = _slots_for(nnz, n_items, params.width, cs)
    by_user = _device_slot_layout(u, i, v, n_users, params.width, su)
    by_item = _device_slot_layout(i, u, v, n_items, params.width, si)
    return by_user, by_item, cs


def _sweep_factory(by_user, by_item, n_users: int, n_items: int, cs: int,
                   params: ALSParams, reg=None, alpha=None):
    """-> sweep_with(cg_u_n, cg_i_n): the scan body shared by the plain,
    validated, layout-resident, and stacked trainers.

    ``reg``/``alpha`` override the params' values and may be TRACED
    scalars — the stacked sweep vmaps candidates over them (they only
    feed arithmetic: `alpha * v` in the accumulation weights and
    `A + reg*I` in the solve), while everything shape- or
    branch-determining in ALSParams stays static."""
    if reg is None:
        reg = params.reg
    if alpha is None:
        alpha = params.alpha

    def sweep_with(cg_u_n: int, cg_i_n: int):
        def sweep(carry, _):
            users, items = carry
            users = _solve_factors(
                by_user, items, n_users,
                reg, params.implicit, alpha, cs,
                x0=users, cg_iters=cg_u_n, bf16_gather=params.bf16_gather,
                accum=params.accum, group_slots=params.group_slots,
                gather=params.gather, packed=params.packed_a,
            )
            items = _solve_factors(
                by_item, users, n_items,
                reg, params.implicit, alpha, cs,
                x0=items, cg_iters=cg_i_n, bf16_gather=params.bf16_gather,
                accum=params.accum, group_slots=params.group_slots,
                gather=params.gather, packed=params.packed_a,
            )
            return (users, items), None
        return sweep
    return sweep_with


def _run_schedule(sweep_with, params: ALSParams, cg_u: int, cg_i: int,
                  carry):
    """Run the two-phase warm-CG schedule: full-strength CG while cold,
    cg_warm_iters once the warm start carries most of the solution (see
    cg_warm_iters). Shared by every trainer variant."""
    n_full, n_warm, w_u, w_i = _cg_schedule(params, cg_u, cg_i)
    if n_full:
        carry, _ = jax.lax.scan(
            sweep_with(cg_u, cg_i), carry, None, length=n_full
        )
    if n_warm:
        carry, _ = jax.lax.scan(
            sweep_with(w_u, w_i), carry, None, length=n_warm
        )
    return carry


@partial(jax.jit, static_argnames=("n_users", "n_items", "params"))
def _train_jit(u, i, v, n_users: int, n_items: int, params: ALSParams,
               user0, item0):
    by_user, by_item, cs = _build_layouts(u, i, v, n_users, n_items, params)
    cg_u = params.resolved_cg_iters(n_users)
    cg_i = params.resolved_cg_iters(n_items)
    sweep_with = _sweep_factory(by_user, by_item, n_users, n_items, cs,
                                params)
    return _run_schedule(sweep_with, params, cg_u, cg_i, (user0, item0))


@partial(jax.jit, static_argnames=("n_users", "n_items", "params"))
def _train_val_jit(u, i, v, vu, vi, vv, n_users: int, n_items: int,
                   params: ALSParams, user0, item0):
    """Training scan with per-sweep heldout RMSE + best-sweep tracking.

    The heldout slice rides the scan as three fixed-shape device arrays;
    after each sweep the carry keeps the argmin factors via two scalar-
    predicate selects (see ALSValidation). Returns
    (best_users, best_items, curve) with curve (iterations,) f32."""
    by_user, by_item, cs = _build_layouts(u, i, v, n_users, n_items, params)
    cg_u = params.resolved_cg_iters(n_users)
    cg_i = params.resolved_cg_iters(n_items)
    sweep_with = _sweep_factory(by_user, by_item, n_users, n_items, cs,
                                params)

    def val_sweep_with(cg_u_n: int, cg_i_n: int):
        inner = sweep_with(cg_u_n, cg_i_n)

        def sweep(carry, _):
            (users, items), (bu, bi, br) = carry
            (users, items), _ = inner((users, items), None)
            pred = jnp.einsum(
                "nk,nk->n", users[vu], items[vi],
                preferred_element_type=jnp.float32,
            )
            r = jnp.sqrt(jnp.mean((pred - vv) ** 2))
            better = r < br
            bu = jnp.where(better, users, bu)
            bi = jnp.where(better, items, bi)
            br = jnp.where(better, r, br)
            return ((users, items), (bu, bi, br)), r
        return sweep

    n_full, n_warm, w_u, w_i = _cg_schedule(params, cg_u, cg_i)
    carry = ((user0, item0),
             (user0, item0, jnp.array(jnp.inf, jnp.float32)))
    curves = []
    if n_full:
        carry, c = jax.lax.scan(
            val_sweep_with(cg_u, cg_i), carry, None, length=n_full
        )
        curves.append(c)
    if n_warm:
        carry, c = jax.lax.scan(
            val_sweep_with(w_u, w_i), carry, None, length=n_warm
        )
        curves.append(c)
    (_, _), (bu, bi, _) = carry
    return bu, bi, jnp.concatenate(curves)


# ---------------------------------------------------------------------------
# device-resident layout reuse (retrain / trajectory fast path)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ALSLayouts:
    """Slot layouts resident in HBM, reusable across train calls.

    At the ML-20M shape the one-time on-device layout build + host->HBM
    transfer is ~6 s against 4.7 s of actual sweeps
    (eval/TPU_BENCH_r03.json train decomposition); every als_train call
    was paying the build again because the layout lived inside the jit.
    Building once and passing the result back in makes retrain loops,
    per-sweep trajectory evals, and warm-started continuation calls pay
    it exactly once. ~2x the COO bytes in HBM (idx+val padded to slot
    width), freed when the object is dropped."""

    by_user: tuple     # (rows, idx, val, lens) device arrays
    by_item: tuple
    cs: int
    n_users: int
    n_items: int
    width: int         # layouts are rank-blind: any rank trains on them


@partial(jax.jit, static_argnames=("n_users", "n_items", "params"))
def _layouts_jit(u, i, v, n_users: int, n_items: int, params: ALSParams):
    by_user, by_item, _cs = _build_layouts(
        u, i, v, n_users, n_items, params)
    return by_user, by_item


def als_build_layouts(
    user_idx, item_idx, values, n_users: int, n_items: int,
    params: ALSParams,
) -> ALSLayouts:
    """Build both slot layouts on device and return them for reuse via
    ``als_train(..., layouts=...)``. Inputs may be host numpy or
    device-resident jax arrays (same contract as als_train)."""
    u, i, v = _prep_coo(user_idx, item_idx, values, n_users, n_items, params)
    nnz = u.shape[0]
    cs = min(params.chunk_slots, _slots_for(nnz, 0, params.width, 1))
    by_user, by_item = _layouts_jit(u, i, v, n_users, n_items, params)
    return ALSLayouts(by_user, by_item, cs, n_users, n_items, params.width)


@partial(jax.jit, static_argnames=("n_users", "n_items", "cs", "params"))
def _train_from_layouts_jit(bu_rows, bu_idx, bu_val, bu_lens,
                            bi_rows, bi_idx, bi_val, bi_lens,
                            n_users: int, n_items: int, cs: int,
                            params: ALSParams, user0, item0):
    by_user = (bu_rows, bu_idx, bu_val, bu_lens)
    by_item = (bi_rows, bi_idx, bi_val, bi_lens)
    cg_u = params.resolved_cg_iters(n_users)
    cg_i = params.resolved_cg_iters(n_items)
    sweep_with = _sweep_factory(by_user, by_item, n_users, n_items, cs,
                                params)
    return _run_schedule(sweep_with, params, cg_u, cg_i, (user0, item0))


def als_warm_compile(
    nnz: int, n_users: int, n_items: int, params: ALSParams,
    sweep_lengths: tuple[int, ...] = (),
) -> int:
    """AOT-compile the layout-build and layouts-train programs for this
    COO shape WITHOUT executing anything: abstract ShapeDtypeStruct
    inputs through ``.lower().compile()``. With the persistent compile
    cache (utils/compilecache.py) each ``.compile()`` on a warm restart
    is a deserialize, so a train process front-loads — or entirely skips
    — its XLA work while e.g. the host->HBM transfer is in flight,
    instead of the old warm-up idiom of EXECUTING the programs on
    zero-filled arrays (whose pointless math burned device time and
    polluted measurements). Shape/static derivation mirrors
    ``_prep_coo``/``als_build_layouts`` exactly, so the later real
    dispatch compiles byte-identical HLO and hits the cache.
    Returns the number of programs compiled."""
    nnz_pad = nnz + (-nnz % max(1, params.chunk))
    u = jax.ShapeDtypeStruct((nnz_pad,), jnp.int32)
    v = jax.ShapeDtypeStruct((nnz_pad,), jnp.float32)
    _layouts_jit.lower(
        u, u, v, n_users=n_users, n_items=n_items, params=params
    ).compile()
    n = 1
    if not sweep_lengths:
        return n
    by_user, by_item = jax.eval_shape(
        lambda a, b, c: _layouts_jit(
            a, b, c, n_users=n_users, n_items=n_items, params=params),
        u, u, v,
    )
    cs = min(params.chunk_slots, _slots_for(nnz_pad, 0, params.width, 1))
    user0, item0 = jax.eval_shape(
        lambda: _init_or(None, n_users, n_items, params))
    for length in sweep_lengths:
        p = dataclasses.replace(params, iterations=length)
        _train_from_layouts_jit.lower(
            *by_user, *by_item, n_users=n_users, n_items=n_items,
            cs=cs, params=p, user0=user0, item0=item0,
        ).compile()
        n += 1
    return n


def als_train(
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    values: np.ndarray,
    n_users: int,
    n_items: int,
    params: ALSParams,
    init: ALSModel | None = None,
    layouts: "ALSLayouts | None" = None,
) -> ALSModel:
    """Train on one device (or one logical device under jit).

    `init` warm-starts from an existing model (e.g. to continue sweeps or to
    record a per-sweep metric trajectory by calling with iterations=1 in a
    loop — the compiled program is reused across such calls).

    Inputs may be host numpy OR device-resident jax arrays: device inputs
    skip the host conversion/padding copies entirely (pad concatenation
    happens on device), so retrain loops that keep the COO arrays in HBM
    pay the host->device transfer once, not per call.

    `layouts` (from als_build_layouts, same data/params) skips the
    per-call slot-layout rebuild entirely — the retrain/trajectory fast
    path; the COO args are ignored then (pass the same arrays for
    clarity)."""
    user0, item0 = _init_or(init, n_users, n_items, params)
    if layouts is not None:
        if (layouts.n_users, layouts.n_items, layouts.width) != \
                (n_users, n_items, params.width):
            raise ValueError(
                f"layouts built for shape ({layouts.n_users}, "
                f"{layouts.n_items}, width {layouts.width}), train called "
                f"with ({n_users}, {n_items}, width {params.width})")
        users, items = _train_from_layouts_jit(
            *layouts.by_user, *layouts.by_item,
            n_users, n_items, layouts.cs, params, user0, item0,
        )
        return ALSModel(users, items)
    u, i, v = _prep_coo(user_idx, item_idx, values, n_users, n_items, params)
    users, items = _train_jit(
        u, i, v, n_users, n_items, params, user0, item0
    )
    return ALSModel(users, items)


def _prep_coo(user_idx, item_idx, values, n_users, n_items,
              params: ALSParams):
    """Dtype-normalize + sentinel-pad the COO arrays (host numpy or
    device jax arrays alike — device inputs never round-trip to host)."""
    on_device = isinstance(user_idx, jax.Array)
    if on_device:
        u = user_idx.astype(jnp.int32)
        i = item_idx.astype(jnp.int32)
        v = values.astype(jnp.float32)
    else:
        u = np.ascontiguousarray(user_idx, dtype=np.int32)
        i = np.ascontiguousarray(item_idx, dtype=np.int32)
        v = np.ascontiguousarray(values, dtype=np.float32)
    # bucket nnz to a params.chunk multiple so retrains with slightly
    # different data sizes reuse the compiled program; padding entries
    # carry the sentinel id on BOTH sides (u = n_users, i = n_items) so
    # whichever side keys the layout drops them via its valid mask
    pad = -u.shape[0] % max(1, params.chunk)
    if pad:
        xp = jnp if on_device else np
        u = xp.concatenate([u, xp.full(pad, n_users, xp.int32)])
        i = xp.concatenate([i, xp.full(pad, n_items, xp.int32)])
        v = xp.concatenate([v, xp.zeros(pad, xp.float32)])
    return u, i, v


def _init_or(init: ALSModel | None, n_users: int, n_items: int,
             params: ALSParams):
    if init is not None:
        return init.user_factors, init.item_factors
    key = jax.random.PRNGKey(params.seed)
    ku, ki = jax.random.split(key)
    return (init_factors(n_users, params.rank, ku),
            init_factors(n_items, params.rank, ki))


def als_train_validated(
    user_idx, item_idx, values,
    n_users: int, n_items: int, params: ALSParams,
    val_user_idx, val_item_idx, val_values,
    init: ALSModel | None = None,
) -> tuple[ALSModel, ALSValidation]:
    """Train with a heldout slice scored after every sweep; return the
    BEST-sweep model plus the full trajectory (see ALSValidation — the
    TPU-shaped replacement for early stopping). The heldout slice must
    be disjoint from the training triples; for implicit models the
    curve is RMSE of raw scores against the heldout values — a proxy
    (ranking metrics are the real objective there), but a monotone
    regression on it still flags overfit sweeps."""
    u, i, v = _prep_coo(user_idx, item_idx, values, n_users, n_items, params)
    vu = jnp.asarray(np.asarray(val_user_idx), jnp.int32)
    vi = jnp.asarray(np.asarray(val_item_idx), jnp.int32)
    vv = jnp.asarray(np.asarray(val_values), jnp.float32)
    user0, item0 = _init_or(init, n_users, n_items, params)
    bu, bi, curve = _train_val_jit(
        u, i, v, vu, vi, vv, n_users, n_items, params, user0, item0
    )
    raw = np.asarray(curve)
    # argmin on the UNROUNDED curve: the scan's strict `r < br` keeps the
    # truly-lowest sweep, and ties after rounding must not relabel it
    best_sweep = int(np.argmin(raw)) + 1
    curve_h = tuple(round(float(x), 6) for x in raw)
    return ALSModel(bu, bi), ALSValidation(
        curve=curve_h,
        best_sweep=best_sweep,
        best_rmse=curve_h[best_sweep - 1],
        final_rmse=curve_h[-1],
    )


# ---------------------------------------------------------------------------
# stacked multi-candidate path — the hyperparameter sweep's batched train:
# one layout build + one compiled program trains EVERY candidate that
# shares the static shape config (rank, iterations, implicit, CG
# schedule), vmapped over the continuous hyperparams (reg, alpha)
# ---------------------------------------------------------------------------

def sweep_safe_params(params: ALSParams) -> ALSParams:
    """The static config the stacked trainer actually runs: the pure-XLA
    accumulation paths (carry on CPU, stacked on accelerators) with the
    plain XLA gather. The Pallas kernels (hybrid/stream/packed) are
    written for a single candidate's block shapes and do not vmap; the
    stacked program trades them for candidate-level batching — which is
    the bigger lever for a sweep (Chiu et al. 1612.01437: batch the
    work, amortize the data movement)."""
    accum = "stacked" if _accelerator_backend() else "carry"
    return dataclasses.replace(
        params, accum=accum, gather="xla", packed_a=False)


@jax.tree_util.register_pytree_node_class
@dataclass
class StackedALSModel:
    """C candidates' factors as one stacked pytree:
    user_factors (C, n_users, k), item_factors (C, n_items, k)."""

    user_factors: jax.Array
    item_factors: jax.Array

    def __len__(self) -> int:
        return int(self.user_factors.shape[0])

    def candidate(self, c: int) -> ALSModel:
        return ALSModel(self.user_factors[c], self.item_factors[c])

    def tree_flatten(self):
        return (self.user_factors, self.item_factors), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


@partial(jax.jit, static_argnames=("n_users", "n_items", "params"))
def _train_stacked_jit(u, i, v, regs, alphas, n_users: int, n_items: int,
                       params: ALSParams, user0, item0):
    by_user, by_item, cs = _build_layouts(u, i, v, n_users, n_items, params)
    cg_u = params.resolved_cg_iters(n_users)
    cg_i = params.resolved_cg_iters(n_items)

    def train_one(reg, alpha):
        sweep_with = _sweep_factory(
            by_user, by_item, n_users, n_items, cs, params,
            reg=reg, alpha=alpha,
        )
        return _run_schedule(sweep_with, params, cg_u, cg_i, (user0, item0))

    # vmap over the candidate axis: the slot layouts and the init
    # factors broadcast (closure), only (reg, alpha) and the factor
    # carries batch — so the gather/einsum work is shared-shape and XLA
    # fuses the C candidates into batched MXU ops instead of C dispatches
    return jax.vmap(train_one)(regs, alphas)


def als_train_stacked(
    user_idx, item_idx, values,
    n_users: int, n_items: int,
    params: ALSParams,
    regs, alphas,
    mesh: Mesh | None = None,
) -> StackedALSModel:
    """Train C candidates sharing ``params``' static config as ONE
    batched program, differing per candidate only in (reg, alpha).

    The candidate count is pow2-bucketed (padding repeats the last
    candidate) so sweeps of 5, 7 or 8 points hit the same compiled
    program in the persistent compile cache; the pad is trimmed before
    returning. All candidates start from the identical seeded init, so
    candidate c's result matches a sequential ``als_train`` with the
    same (reg, alpha) up to batched-op reassociation (the parity suite
    in tests/test_tuning.py pins the tolerance).

    With a multi-device ``mesh`` whose data axis divides the bucketed
    candidate count, the candidate axis is sharded across devices (the
    SNIPPETS.md [1] pjit pattern: annotate the inputs, let GSPMD
    partition the embarrassingly-parallel candidate dimension)."""
    params = sweep_safe_params(params)
    # reg/alpha are fully overridden by the traced vectors below, but
    # ALSParams is a STATIC jit arg — normalize them so two sweeps whose
    # grids merely start at different values hash to the same compiled
    # program (the pow2-bucketing would otherwise be defeated by the
    # first candidate's values leaking into the cache key)
    params = dataclasses.replace(params, reg=0.0, alpha=1.0)
    regs = np.ascontiguousarray(regs, dtype=np.float32)
    alphas = np.ascontiguousarray(alphas, dtype=np.float32)
    if regs.shape != alphas.shape or regs.ndim != 1 or not len(regs):
        raise ValueError(
            f"regs/alphas must be equal-length 1-d vectors, got "
            f"{regs.shape} / {alphas.shape}")
    n_cand = len(regs)
    bucket = pow2_bucket(n_cand)
    if bucket != n_cand:
        regs = np.concatenate(
            [regs, np.full(bucket - n_cand, regs[-1], np.float32)])
        alphas = np.concatenate(
            [alphas, np.full(bucket - n_cand, alphas[-1], np.float32)])
    u, i, v = _prep_coo(user_idx, item_idx, values, n_users, n_items, params)
    user0, item0 = _init_or(None, n_users, n_items, params)
    regs_d, alphas_d = jnp.asarray(regs), jnp.asarray(alphas)
    if mesh is not None and mesh.devices.size > 1:
        n_dev = mesh.devices.size
        if bucket % n_dev == 0:
            cand_sharding = NamedSharding(mesh, P(DATA_AXIS))
            regs_d = jax.device_put(regs_d, cand_sharding)
            alphas_d = jax.device_put(alphas_d, cand_sharding)
    users, items = _train_stacked_jit(
        u, i, v, regs_d, alphas_d, n_users, n_items, params, user0, item0)
    return StackedALSModel(users[:n_cand], items[:n_cand])


# ---------------------------------------------------------------------------
# sharded multi-chip path — users/items blocked per device, all_gather per
# half-sweep (the MLlib-shuffle replacement)
# ---------------------------------------------------------------------------

def _block(n: int, n_dev: int) -> int:
    return math.ceil(n / n_dev)


@functools.lru_cache(maxsize=64)
def _sharded_train_fn(mesh: Mesh, ub: int, ib: int, su: int, si: int,
                      cs: int, params: ALSParams):
    """Compiled sharded-train program, cached on its static config.

    Building the shard_map closure inside als_train_sharded made every
    retrain call re-trace the whole program (~13 s of fixed cost per
    call on an 8-virtual-device CPU mesh — measured while building
    eval/weak_scaling.py); Mesh and the frozen ALSParams are hashable,
    so the program is constructed once per (mesh, shapes, params) and
    jit keeps the executable across calls."""
    dev_spec = P(DATA_AXIS)  # leading axis = device blocks
    # each device solves its LOCAL block of rows, so the auto exact-vs-CG
    # decision keys on the per-device batch size
    cg_u = params.resolved_cg_iters(ub)
    cg_i = params.resolved_cg_iters(ib)

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(dev_spec,) * 8,
        out_specs=dev_spec,
        check_vma=False,
    )
    def run(u_r, u_c, u_v, i_r, i_c, i_v, u0, i0):
        by_user = _device_slot_layout(
            u_r[0], u_c[0], u_v[0], ub, params.width, su
        )
        by_item = _device_slot_layout(
            i_r[0], i_c[0], i_v[0], ib, params.width, si
        )

        def gram_psum(block):
            """Y^T Y of the full factor matrix from the LOCAL block:
            per-device (b,k)x(k,b) matmul + one (k,k) psum over ICI —
            O(1) per device instead of the O(n_dev) every device would
            pay recomputing it from the gathered matrix."""
            g = jnp.matmul(block.T, block,
                           precision=jax.lax.Precision.HIGH)
            return jax.lax.psum(g, DATA_AXIS)

        def sweep_with(cg_u_n: int, cg_i_n: int):
            def sweep(carry, _):
                users, items = carry  # local blocks (ub, k) / (ib, k)
                yty_i = gram_psum(items) if params.implicit else None
                all_items = jax.lax.all_gather(
                    items, DATA_AXIS, tiled=True
                )  # (ib*n_dev, k)
                users = _solve_factors(
                    by_user, all_items, ub,
                    params.reg, params.implicit, params.alpha, cs,
                    x0=users, cg_iters=cg_u_n,
                    bf16_gather=params.bf16_gather,
                    accum=params.accum, group_slots=params.group_slots,
                    yty=yty_i, gather=params.gather,
                    packed=params.packed_a,
                )
                yty_u = gram_psum(users) if params.implicit else None
                all_users = jax.lax.all_gather(
                    users, DATA_AXIS, tiled=True
                )
                items = _solve_factors(
                    by_item, all_users, ib,
                    params.reg, params.implicit, params.alpha, cs,
                    x0=items, cg_iters=cg_i_n,
                    bf16_gather=params.bf16_gather,
                    accum=params.accum, group_slots=params.group_slots,
                    yty=yty_u, gather=params.gather,
                    packed=params.packed_a,
                )
                return (users, items), None
            return sweep

        # same two-phase warm-CG schedule as _train_jit so the sharded
        # path is numerically aligned with the single-device one
        n_full, n_warm, w_u, w_i = _cg_schedule(params, cg_u, cg_i)
        carry = (u0[0], i0[0])
        if n_full:
            carry, _ = jax.lax.scan(
                sweep_with(cg_u, cg_i), carry, None, length=n_full
            )
        if n_warm:
            carry, _ = jax.lax.scan(
                sweep_with(w_u, w_i), carry, None, length=n_warm
            )
        users, items = carry
        return users[None], items[None]

    return jax.jit(run)


def als_train_sharded(
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    values: np.ndarray,
    n_users: int,
    n_items: int,
    params: ALSParams,
    mesh: Mesh,
) -> ALSModel:
    """Multi-device ALS over the mesh's data axis.

    Host-side work is only a per-device split of the COO arrays (users and
    their ratings partitioned into contiguous blocks, one per device;
    likewise items), sentinel-padded so every device carries the same
    shapes. Each device builds its slot layouts locally; each half-sweep
    every device solves its block's normal equations against the full
    opposing factor matrix, obtained by `all_gather` over ICI (factors are
    small: n x k; the ratings never move).
    """
    n_dev = mesh.shape[DATA_AXIS]
    ub, ib = _block(n_users, n_dev), _block(n_items, n_dev)

    def partition(rows, cols, vals, block):
        """-> (n_dev, nnz_max) stacked COO with LOCAL row ids; padding
        entries carry row id = block (the sentinel >= any local id)."""
        dev_of = rows // block
        per_dev = [np.flatnonzero(dev_of == dv) for dv in range(n_dev)]
        # bucket to a chunk multiple for compile reuse across retrains
        nnz_max = max(len(ix) for ix in per_dev)
        nnz_max += -nnz_max % max(1, params.chunk)
        r_st = np.full((n_dev, nnz_max), block, np.int32)
        c_st = np.zeros((n_dev, nnz_max), np.int32)
        v_st = np.zeros((n_dev, nnz_max), np.float32)
        for dv, ix in enumerate(per_dev):
            r_st[dv, :len(ix)] = rows[ix] - dv * block
            c_st[dv, :len(ix)] = cols[ix]
            v_st[dv, :len(ix)] = vals[ix]
        return r_st, c_st, v_st, nnz_max

    rows = np.asarray(user_idx, dtype=np.int64)
    cols = np.asarray(item_idx, dtype=np.int64)
    vals = np.asarray(values, dtype=np.float32)
    u_r, u_c, u_v, u_nnz = partition(rows, cols, vals, ub)
    i_r, i_c, i_v, i_nnz = partition(cols, rows, vals, ib)

    key = jax.random.PRNGKey(params.seed)
    ku, ki = jax.random.split(key)
    # draw the init at the UNPADDED shape — the exact same draw
    # als_train makes — then zero-pad the phantom rows. Drawing at the
    # padded shape and truncating is only prefix-stable under
    # partitionable threefry (jax >= 0.5 default); on 0.4.x it yields a
    # completely different init than the single-device path, and the two
    # trainers then converge to different factor gauges (the sharded-vs-
    # single drift failures on jax 0.4.37). Zero phantom rows are also
    # required regardless: a non-zero init would contaminate the shared
    # Y^T Y term of the implicit-ALS first sweep.
    user0 = np.zeros((ub * n_dev, params.rank), np.float32)
    item0 = np.zeros((ib * n_dev, params.rank), np.float32)
    user0[:n_users] = np.array(init_factors(n_users, params.rank, ku))
    item0[:n_items] = np.array(init_factors(n_items, params.rank, ki))
    user0 = user0.reshape(n_dev, ub, params.rank)
    item0 = item0.reshape(n_dev, ib, params.rank)

    cs = min(params.chunk_slots, _slots_for(max(u_nnz, i_nnz), 0, params.width, 1))
    su = _slots_for(u_nnz, ub, params.width, cs)
    si = _slots_for(i_nnz, ib, params.width, cs)

    # cache key: only program-relevant fields — seed and chunk are
    # host-side (init RNG / padding quantum) and chunk_slots is already
    # folded into cs, so varying them must not re-trace
    import dataclasses

    key_params = dataclasses.replace(params, seed=0, chunk=0,
                                     chunk_slots=cs)
    run = _sharded_train_fn(mesh, ub, ib, su, si, cs, key_params)
    dev_spec = P(DATA_AXIS)
    sharding = NamedSharding(mesh, dev_spec)
    put = lambda a: jax.device_put(a, sharding)  # noqa: E731
    users, items = run(
        put(u_r), put(u_c), put(u_v), put(i_r), put(i_c), put(i_v),
        put(user0), put(item0),
    )
    users = users.reshape(-1, params.rank)[:n_users]
    items = items.reshape(-1, params.rank)[:n_items]
    return ALSModel(users, items)


# ---------------------------------------------------------------------------
# streaming fold-in: refresh user rows against FIXED item factors
# ---------------------------------------------------------------------------

def _solve_rows_invariant(A, b):
    """Exact per-row solve whose bits do NOT depend on the batch size:
    `lax.map` compiles ONE unbatched (k,k) Cholesky program and runs it
    per row, so row u's solution is identical whether u is solved alone
    or among any batch mates — unlike the BATCHED cho_factor/cho_solve,
    whose CPU lowering drifts by an ULP with batch size (measured; this
    is what the fold-in oracle parity test would catch). Fold-in
    batches are small (≤ a few thousand rows), so per-row is cheap."""
    def solve_one(ab):
        a_row, b_row = ab
        return jax.scipy.linalg.cho_solve(
            jax.scipy.linalg.cho_factor(a_row), b_row)

    return jax.lax.map(solve_one, (A, b))


@partial(jax.jit, static_argnames=("n_users", "params"))
def _fold_in_jit(u, i, v, item_factors, n_users: int, params: ALSParams):
    nnz = u.shape[0]
    cs = min(params.chunk_slots, _slots_for(nnz, 0, params.width, 1))
    su = _slots_for(nnz, n_users, params.width, cs)
    by_user = _device_slot_layout(u, i, v, n_users, params.width, su)
    A, b = _normal_equations(
        by_user, item_factors, n_users, params.implicit, params.alpha, cs,
        bf16_gather=params.bf16_gather, accum=params.accum,
        group_slots=params.group_slots, gather=params.gather,
        packed=params.packed_a,
    )
    k = item_factors.shape[1]
    if params.implicit:
        A = A + _shared_yty(item_factors, None)[None, :, :]
    A = A + params.reg * jnp.eye(k, dtype=jnp.float32)[None, :, :]
    return _solve_rows_invariant(A, b)


def fold_in_params(params: ALSParams) -> ALSParams:
    """The bit-conservative variant of `params` a fold-in solve runs
    under: f32 gather and the plain XLA accumulation/gather paths, so a
    refreshed row is a pure function of (events, item factors) — the
    same answer on every backend, every batch composition, and every
    restart. Iteration-schedule fields are irrelevant (fold-in is one
    half-sweep); they are zeroed so they cannot fragment the jit cache."""
    return dataclasses.replace(
        params, bf16_gather=False, accum="carry", gather="xla",
        packed_a=False, iterations=1, cg_warm_iters=-1, seed=0, chunk=0,
    )


def als_fold_in(
    item_factors,
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    values: np.ndarray,
    n_users: int,
    params: ALSParams,
) -> jax.Array:
    """Solve one ridge system per user against FIXED item factors — the
    online half of ALS (the MLlib lineage's fold-in): exactly ONE
    user half-sweep of `_normal_equations` + the exact solve, nothing
    else. Returns (n_users, k) f32 rows.

    `user_idx` holds LOCAL dense ids in [0, n_users); `item_idx` indexes
    `item_factors` rows. Users in [0, n_users) with no events get the
    zero row (b = 0 under the exact solve), which callers treat as
    "don't apply".

    Batch-composition invariance (the freshness subsystem's oracle
    contract, tests/test_freshness.py): with `fold_in_params`, user u's
    row is BIT-identical whether u is folded alone or inside any batch —
    per-slot normal-equation blocks are row-independent batched matmuls,
    the sorted scatter sums u's slots in the same order regardless of
    batch mates, and `_solve_rows_invariant` runs one UNBATCHED Cholesky
    per row. Both the dense-id space (`n_users`) and the event count are
    padded to powers of two here, so a steady fold-in stream compiles
    O(log²) programs and then runs entirely out of the persistent
    compile cache (PR 4)."""
    nnz = len(values)
    if nnz == 0 or n_users <= 0:
        k = item_factors.shape[1]
        return jnp.zeros((max(n_users, 0), k), jnp.float32)
    u = np.ascontiguousarray(user_idx, dtype=np.int32)
    i = np.ascontiguousarray(item_idx, dtype=np.int32)
    v = np.ascontiguousarray(values, dtype=np.float32)
    n_bucket = pow2_bucket(n_users)
    pad = pow2_bucket(nnz) - nnz
    if pad:
        # padding rides the user-side sentinel (u = n_bucket): the slot
        # layout drops those entries entirely, so item id 0 / value 0
        # never reach a real row's system
        u = np.concatenate([u, np.full(pad, n_bucket, np.int32)])
        i = np.concatenate([i, np.zeros(pad, np.int32)])
        v = np.concatenate([v, np.zeros(pad, np.float32)])
    rows = _fold_in_jit(u, i, v, jnp.asarray(item_factors), n_bucket,
                        fold_in_params(params))
    return rows[:n_users]


# ---------------------------------------------------------------------------
# prediction / scoring
# ---------------------------------------------------------------------------

@jax.jit
def predict_pairs(model: ALSModel, user_idx, item_idx) -> jax.Array:
    return jnp.einsum(
        "nk,nk->n",
        model.user_factors[user_idx],
        model.item_factors[item_idx],
    )


@partial(jax.jit, static_argnames=("k",))
def _topk_jit(model: ALSModel, user_idx, k: int):
    scores = model.user_factors[user_idx] @ model.item_factors.T  # (B, I)
    return jax.lax.top_k(scores, k)


def recommend_topk(model: ALSModel, user_idx, k: int):
    """Top-k items for a batch of users: one (B,k)x(k,I) matmul + lax.top_k
    (the MXU path serving /queries.json).

    Both k AND the batch dim are bucketed to the next power of two before
    jit, so per-query k values (e.g. num + len(blackList)) and the varying
    batch sizes the serving micro-batcher produces compile O(log) XLA
    programs instead of one per size; the exact trim happens on host."""
    n_items = model.item_factors.shape[0]
    k = max(1, min(int(k), n_items))
    k_bucket = pow2_bucket(k, cap=n_items)
    user_idx = np.asarray(user_idx)
    b = len(user_idx)
    b_bucket = pow2_bucket(b)
    if b_bucket != b:
        user_idx = np.concatenate(
            [user_idx, np.zeros(b_bucket - b, user_idx.dtype)]
        )
    scores, idx = _topk_jit(model, user_idx, k_bucket)
    return scores[:b, :k], idx[:b, :k]


def rmse(model: ALSModel, user_idx, item_idx, values) -> float:
    pred = predict_pairs(
        model, jnp.asarray(user_idx), jnp.asarray(item_idx)
    )
    return float(jnp.sqrt(jnp.mean((pred - jnp.asarray(values)) ** 2)))
