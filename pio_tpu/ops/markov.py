"""Markov chain transition model.

Replaces reference e2/.../engine/MarkovChain.scala:8-53: from a sparse count
matrix of state transitions, keep the top-N outgoing probabilities per state
(row-normalized). The reference builds a Spark CoordinateMatrix and maps
rows; here the counts accumulate into a dense (S, S) numpy matrix (states
are item/page vocabularies — fits host memory) and the top-N trim runs as
one jnp.top_k.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import jax.numpy as jnp
import numpy as np


@dataclass
class MarkovChainModel:
    """Top-N transitions per state (reference MarkovChainModel)."""

    indices: np.ndarray        # (S, N) target state per slot (-1 = empty)
    probs: np.ndarray          # (S, N) row-normalized transition prob
    n_states: int

    def transition_probs(self, state: int) -> dict[int, float]:
        out = {}
        for j, p in zip(self.indices[state], self.probs[state]):
            if j >= 0 and p > 0:
                out[int(j)] = float(p)
        return out

    def predict(self, state: int) -> int | None:
        """Most likely next state, None if the state was never seen."""
        if self.probs[state].sum() <= 0:
            return None
        return int(self.indices[state][np.argmax(self.probs[state])])


def markov_chain_train(
    transitions: Sequence[tuple[int, int]] | np.ndarray,
    n_states: int,
    top_n: int = 10,
) -> MarkovChainModel:
    """transitions: [(from_state, to_state)] counts-of-one (duplicates
    accumulate). Reference MarkovChain.train(matrix, topN)."""
    counts = np.zeros((n_states, n_states), np.float32)
    t = np.asarray(transitions, dtype=np.int64)
    if t.size:
        np.add.at(counts, (t[:, 0], t[:, 1]), 1.0)
    row_sums = counts.sum(axis=1, keepdims=True)
    probs = np.divide(
        counts, row_sums, out=np.zeros_like(counts), where=row_sums > 0
    )
    top_n = min(top_n, n_states)
    import jax

    top_p, top_i = jax.lax.top_k(jnp.asarray(probs), top_n)
    top_p = np.asarray(top_p)
    top_i = np.where(top_p > 0, np.asarray(top_i), -1)
    return MarkovChainModel(indices=top_i, probs=top_p, n_states=n_states)
