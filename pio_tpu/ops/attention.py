"""Attention kernels: Pallas flash attention + sequence-parallel variants.

The reference has no sequence models at all (SURVEY.md section 5
"Long-context / sequence parallelism: absent"), so this module is the
framework's net-new long-context capability, built TPU-first:

 * `flash_attention` — blockwise attention with online softmax as a Pallas
   TPU kernel: q blocks stream through VMEM, k/v live in VMEM per
   (batch, head) program, the (block_q, block_k) score tile hits the MXU,
   and softmax renormalization state (m, l) stays in registers/VMEM so the
   (S, S) score matrix is never materialized in HBM.
 * `ring_attention` — sequence parallelism over a mesh axis: each device
   holds a contiguous sequence shard of q/k/v; k/v shards rotate around the
   ring via `jax.lax.ppermute` (ICI neighbor exchange) while each device
   accumulates its q-shard's online softmax. Compute for step i overlaps
   the DMA of step i+1's shard (XLA pipelines the ppermute); memory per
   device is O(S/n), enabling sequences n x longer than one chip's HBM.
 * `ulysses_attention` — the all-to-all alternative: resharding
   (B, S/n, H, D) -> (B, S, H/n, D) with `lax.all_to_all`, full attention
   per head group, then the inverse all-to-all. Two collectives total;
   preferable when H >= n_seq and the mesh axis rides fast ICI.

All three compute the same math as `attention_reference` (tested against
it); masks are additive-big-negative with explicit zeroing so fully-masked
rows (causal prefixes) produce zeros, not NaNs.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from pio_tpu.utils.jaxcompat import ensure_jax_compat

ensure_jax_compat()  # jax<0.5: install the jax.shard_map forwarding wrapper

NEG_INF = -1e30


def attention_reference(q, k, v, causal: bool = False, scale: float | None = None):
    """Plain softmax attention; the correctness oracle for the kernels.

    q: (B, Sq, H, D); k/v: (B, Sk, H, D) -> (B, Sq, H, D).
    """
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


# ---------------------------------------------------------------------------
# Pallas flash attention (single device)
# ---------------------------------------------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
                  *, block_k: int, causal: bool, scale: float,
                  q_block_offset: bool, kv_len: int | None):
    """One (batch*head, q-block, kv-segment) program: k/v stream through
    VMEM one SEGMENT at a time (grid dim 2, innermost/sequential), and
    the online-softmax state (o, m, l) carries across segments in VMEM
    scratch — so total K/V length is HBM-bound, not VMEM-bound (the
    previous whole-K/V-resident design hit the 16 MB scoped limit at
    seq 32768). Within a segment, k blocks stream in `block_k` slices.
    kv_len masks right-padded key positions (None = no key padding)."""
    seg = pl.program_id(2)
    n_seg = pl.num_programs(2)
    q = q_ref[0].astype(jnp.float32) * scale          # (bq, d)
    bq, d = q.shape
    seg_len = k_ref.shape[1]
    nk = seg_len // block_k
    seg_off = seg * seg_len

    @pl.when(seg == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    q_pos = jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
    if q_block_offset:
        q_pos = q_pos + pl.program_id(1) * bq

    def body(j, carry):
        o, m, l = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = q @ k_blk.T                                # (bq, bk) on the MXU
        keep = None
        k_pos = (
            jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
            + seg_off + j * block_k
        )
        if causal:
            keep = q_pos >= k_pos                      # (bq, bk)
        if kv_len is not None:
            pad_keep = k_pos < kv_len
            keep = pad_keep if keep is None else keep & pad_keep
        if keep is not None:
            s = jnp.where(keep, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if keep is not None:
            p = jnp.where(keep, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        o_new = o * alpha + p @ v_blk
        return o_new, m_new, l_new

    if causal and q_block_offset:
        # skip k blocks entirely above the diagonal: this q block's highest
        # position is (pid+1)*bq - 1; blocks of THIS SEGMENT starting past
        # it are fully masked (a segment wholly above gets hi <= 0 and the
        # loop body never runs)
        q_hi = (pl.program_id(1) + 1) * bq
        hi = jnp.clip((q_hi - seg_off + block_k - 1) // block_k, 0, nk)
    else:
        hi = nk
    o, m, l = jax.lax.fori_loop(
        0, hi, body, (acc_ref[...], m_ref[...], l_ref[...]))
    acc_ref[...] = o
    m_ref[...] = m
    l_ref[...] = l

    @pl.when(seg == n_seg - 1)
    def _emit():
        o_ref[0] = (acc_ref[...] / jnp.maximum(l_ref[...], 1e-30)
                    ).astype(o_ref.dtype)


def _pad_len(n: int, block: int) -> int:
    return (block - n % block) % block


def flash_attention(
    q, k, v,
    causal: bool = False,
    scale: float | None = None,
    block_q: int = 256,
    block_k: int = 512,
    max_seg_bytes: int = 2 * 2**20,
    interpret: bool | None = None,
):
    """Blockwise (flash) attention as a Pallas TPU kernel.

    q: (B, Sq, H, D); k/v: (B, Sk, H, D) -> (B, Sq, H, D). Sequences are
    padded to the block size internally; padded key positions are excluded
    in-kernel via a key-length mask (applied only when padding exists, for
    both the causal and non-causal paths). Causal programs skip k blocks
    entirely above the diagonal. `interpret=True` runs the kernel in
    interpreter mode (used on CPU in tests; auto-detected when None).

    Default blocks 256x512, tuned on v5e at seq 8192 (b4 h8 d64, causal,
    bf16): 128x128 ran at 0.0262 s — 2.2x SLOWER than XLA's naive
    attention — while 256x512 runs 0.0057 s, 2.1x faster than naive;
    512x1024 ties it and 1024x1024 fails to compile. The inner k-loop's
    per-iteration overhead dominates at small blocks
    (eval/NEURAL_THROUGHPUT.json).
    """
    from jax.experimental import pallas as pl

    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = jax.devices()[0].platform == "cpu"

    block_q = min(block_q, max(sq, 8))
    block_k = min(block_k, max(sk, 8))
    pad_q, pad_k = _pad_len(sq, block_q), _pad_len(sk, block_k)
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
    sqp, skp = sq + pad_q, sk + pad_k

    # (B, S, H, D) -> (B*H, S, D): one program per (batch, head, q block)
    def bhsd(x):
        return x.transpose(0, 2, 1, 3).reshape(-1, x.shape[1], d)

    qt, kt, vt = bhsd(q), bhsd(k), bhsd(v)
    nq = sqp // block_q
    kv_len_arg = sk if pad_k else None

    # VMEM-budget the k/v residency: one SEGMENT (2 arrays, double-
    # buffered by the pipeline) stays under ~4 MB; the online-softmax
    # scratch carries across segments, so sequence length is unbounded
    # by VMEM (32k+ works single-chip; the previous whole-K/V design
    # overflowed the 16 MB scoped limit there)
    # max_seg_bytes is a knob mostly for tests (forcing n_seg > 1 at
    # small shapes); the default keeps one double-buffered k/v segment
    # pair under ~8 MB of the 16 MB scoped VMEM
    max_seg = max(block_k, max_seg_bytes // (2 * d * kt.dtype.itemsize))
    seg_len = min(skp, max_seg - max_seg % block_k)
    pad_seg = _pad_len(skp, seg_len)
    if pad_seg:
        # pad to a whole number of segments; in-kernel kv_len masking
        # already drops the padded keys
        kt = jnp.pad(kt, ((0, 0), (0, pad_seg), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, pad_seg), (0, 0)))
        if kv_len_arg is None:
            kv_len_arg = sk
    n_seg = (skp + pad_seg) // seg_len

    kernel = partial(
        _flash_kernel, block_k=block_k, causal=causal, scale=scale,
        q_block_offset=True, kv_len=kv_len_arg,
    )
    from jax.experimental.pallas import tpu as pltpu

    out = pl.pallas_call(
        kernel,
        grid=(qt.shape[0], nq, n_seg),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, sg: (bh, i, 0)),
            pl.BlockSpec((1, seg_len, d), lambda bh, i, sg: (bh, sg, 0)),
            pl.BlockSpec((1, seg_len, d), lambda bh, i, sg: (bh, sg, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i, sg: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    out = out.reshape(b, h, sqp, d).transpose(0, 2, 1, 3)
    return out[:, :sq]


# ---------------------------------------------------------------------------
# Chunked (memory-efficient) attention — the DIFFERENTIABLE long-context
# path for single-device training
# ---------------------------------------------------------------------------

def chunked_attention(
    q, k, v,
    causal: bool = False,
    scale: float | None = None,
    chunk: int = 1024,
):
    """Online-softmax attention as a lax.scan over key/value chunks —
    pure XLA, so it is reverse-differentiable (the Pallas flash kernel
    has no backward and stays the serving/forward-only fast path). Peak
    logits memory is O(B*H*Sq*chunk) instead of O(B*H*Sq*Sk), and
    jax.checkpoint on the per-chunk stats recomputes them in the
    backward pass instead of storing one residual per chunk — the same
    memory shape that lets ring_attention train across devices, applied
    within one device. q: (B, Sq, H, D); k/v: (B, Sk, H, D)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    chunk = min(chunk, sk)
    pad = _pad_len(sk, chunk)
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    n_ch = (sk + pad) // chunk
    ks = k.reshape(b, n_ch, chunk, h, d).transpose(1, 0, 2, 3, 4)
    vs = v.reshape(b, n_ch, chunk, h, d).transpose(1, 0, 2, 3, 4)
    offs = jnp.arange(n_ch) * chunk

    @jax.checkpoint
    def stats(k_c, v_c, off):
        s_ = jnp.einsum("bqhd,bkhd->bhqk", q, k_c) * scale
        q_pos = jnp.arange(sq)
        k_pos = off + jnp.arange(chunk)
        keep = k_pos[None, :] < sk                  # padded keys drop
        if causal:
            keep = keep & (q_pos[:, None] >= k_pos[None, :])
        s_ = jnp.where(keep[None, None], s_, NEG_INF)
        m_ = jnp.max(s_, axis=-1, keepdims=True)
        p_ = jnp.where(keep[None, None], jnp.exp(s_ - m_), 0.0)
        l_ = jnp.sum(p_, axis=-1, keepdims=True)
        o_ = jnp.einsum("bhqk,bkhd->bqhd", p_, v_c)
        return o_, m_, l_

    def step(carry, xs):
        o, m, l = carry
        k_c, v_c, off = xs
        o_i, m_i, l_i = stats(k_c, v_c, off)
        m_new = jnp.maximum(m, m_i)
        a_prev = jnp.exp(m - m_new)
        a_i = jnp.exp(m_i - m_new)
        l_new = l * a_prev + l_i * a_i
        o_new = (o * a_prev.transpose(0, 2, 1, 3)
                 + o_i * a_i.transpose(0, 2, 1, 3))
        return (o_new, m_new, l_new), None

    o0 = jnp.zeros((b, sq, h, d), jnp.float32)
    m0 = jnp.full((b, h, sq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq, 1), jnp.float32)
    (o, m, l), _ = jax.lax.scan(step, (o0, m0, l0), (ks, vs, offs))
    o = o / jnp.maximum(l.transpose(0, 2, 1, 3), 1e-30)
    return o.astype(q.dtype)


# ---------------------------------------------------------------------------
# Trainable flash attention: Pallas forward + chunked-XLA backward
# ---------------------------------------------------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention_trainable(q, k, v, causal: bool = False,
                              scale: float | None = None,
                              chunk: int = 1024):
    """flash_attention with gradients: the forward pass runs the Pallas
    kernel (1.7-1.9x the naive attention at 2k/8k on v5e, length
    HBM-bound), and the backward differentiates chunked_attention at the
    same primal point — mathematically the same function, so the
    cotangents are exact up to the forward kernels' mutual rounding
    (pinned by tests). This sidesteps hand-writing a flash backward
    kernel while keeping training forward passes on the fast path;
    memory stays O(S*chunk) in both directions."""
    return flash_attention(q, k, v, causal=causal, scale=scale)


def _fat_fwd(q, k, v, causal, scale, chunk):
    return flash_attention(q, k, v, causal=causal, scale=scale), (q, k, v)


def _fat_bwd(causal, scale, chunk, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: chunked_attention(
            q_, k_, v_, causal=causal, scale=scale, chunk=chunk
        ),
        q, k, v,
    )
    return vjp(g)


flash_attention_trainable.defvjp(_fat_fwd, _fat_bwd)


# ---------------------------------------------------------------------------
# Ring attention (sequence parallelism over a mesh axis)
# ---------------------------------------------------------------------------

def _block_attn_stats(q, k, v, scale, q_offset, k_offset, causal):
    """Un-normalized blockwise attention + softmax stats for one k/v shard.

    q: (B, Sq, H, D) local queries at global offset q_offset;
    k/v: (B, Sk, H, D) the currently-held shard at global offset k_offset.
    Returns (o, m, l): o = sum_j exp(s_j - m) v_j  (B, Sq, H, D),
    m = rowmax (B, H, Sq, 1), l = sum exp (B, H, Sq, 1).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        k_pos = k_offset + jnp.arange(k.shape[1])
        keep = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(keep[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)                  # (B,H,Sq,1)
    m_safe = jnp.maximum(m, NEG_INF)  # rows fully masked stay at NEG_INF
    p = jnp.exp(s - m_safe)
    if causal:
        p = jnp.where(keep[None, None], p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return o, m_safe, l


def ring_attention(
    q, k, v,
    axis_name: str,
    causal: bool = False,
    scale: float | None = None,
):
    """Sequence-parallel attention; call INSIDE shard_map/pjit with q/k/v
    sharded on their sequence axis over `axis_name`.

    Each device starts with its own k/v shard and rotates the shards one
    neighbor per step with `lax.ppermute` (n-1 ICI hops total), folding each
    visiting shard into its q-shard's online softmax (same m/l accumulation
    as the flash kernel, across devices instead of VMEM blocks). The k/v
    rotation for step i+1 overlaps step i's matmuls — XLA schedules the
    ppermute DMA concurrently with compute on TPU.
    """
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    # axis size is static mesh structure — safe to use for Python loops
    n = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    s_local = q.shape[1]
    perm = [(i, (i + 1) % n) for i in range(n)]

    q_offset = my * s_local
    # the shard held at step i originated at device (my - i) % n
    k_offsets = jnp.mod(my - jnp.arange(n), n) * s_local

    def step(carry, k_offset):
        o, m, l, k_cur, v_cur = carry
        o_i, m_i, l_i = _block_attn_stats(
            q, k_cur, v_cur, scale, q_offset, k_offset, causal
        )
        m_new = jnp.maximum(m, m_i)
        a_prev = jnp.exp(m - m_new)
        a_i = jnp.exp(m_i - m_new)
        l_new = l * a_prev + l_i * a_i
        o_new = (
            o * a_prev.transpose(0, 2, 1, 3)
            + o_i * a_i.transpose(0, 2, 1, 3)
        )
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (o_new, m_new, l_new, k_nxt, v_nxt), None

    b, sq, h, d = q.shape
    o0 = jnp.zeros((b, sq, h, d), jnp.float32)
    m0 = jnp.full((b, h, sq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq, 1), jnp.float32)
    # scan (not fori_loop) so the whole ring is reverse-differentiable —
    # the sequence model trains through this
    (o, m, l, _, _), _ = jax.lax.scan(step, (o0, m0, l0, k, v), k_offsets)
    o = o / jnp.maximum(l.transpose(0, 2, 1, 3), 1e-30)
    return o.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, axis_name: str,
                           causal: bool = False):
    """Host-facing wrapper: shard (B, S, H, D) on the sequence axis over
    `axis_name` and run ring_attention under shard_map. Batch stays
    replicated across the seq axis here; compose with a data axis via the
    caller's outer shard_map/pjit (see models/sequence.py)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = P(None, axis_name, None, None)

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    def run(ql, kl, vl):
        return ring_attention(ql, kl, vl, axis_name, causal=causal)

    sharding = NamedSharding(mesh, spec)
    return run(
        jax.device_put(q, sharding),
        jax.device_put(k, sharding),
        jax.device_put(v, sharding),
    )


# ---------------------------------------------------------------------------
# Ulysses-style all-to-all sequence parallelism
# ---------------------------------------------------------------------------

def ulysses_attention(
    q, k, v,
    axis_name: str,
    causal: bool = False,
    scale: float | None = None,
):
    """All-to-all sequence parallelism; call INSIDE shard_map with q/k/v
    sequence-sharded over `axis_name` and H divisible by the axis size.

    all_to_all flips the sharded dim from sequence to heads (each device
    gets the FULL sequence for H/n heads), full attention runs locally,
    and a second all_to_all flips back. Two collectives per layer vs the
    ring's n-1 hops — the better trade when heads are plentiful.
    """
    n = jax.lax.axis_size(axis_name)  # noqa: F841 — documents the contract
    # (B, S/n, H, D) -> (B, S, H/n, D)
    qh = jax.lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1,
                            tiled=True)
    kh = jax.lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1,
                            tiled=True)
    vh = jax.lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1,
                            tiled=True)
    o = attention_reference(qh, kh, vh, causal=causal, scale=scale)
    # (B, S, H/n, D) -> (B, S/n, H, D)
    return jax.lax.all_to_all(o, axis_name, split_axis=1, concat_axis=2,
                              tiled=True).astype(q.dtype)
