"""Shared compile-cache bucketing.

Dynamic sizes (serving batch dims, per-query k) hitting a jitted
function compile one XLA program per distinct value; padding to the next
power of two bounds the cache at O(log) programs. One helper so the
rule has one spelling (used by ops/als.py, ops/similarity.py, and the
model batch_predict paths).
"""

from __future__ import annotations


def pow2_bucket(n: int, cap: int | None = None) -> int:
    """Smallest power of two >= max(n, 1), optionally capped at `cap`."""
    b = 1 << (max(int(n), 1) - 1).bit_length()
    return min(b, cap) if cap is not None else b
