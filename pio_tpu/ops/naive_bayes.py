"""Naive Bayes kernels.

Two variants, replacing the reference's two NB paths:
 * `CategoricalNB` — string-categorical features, replacing
   e2/.../engine/CategoricalNaiveBayes.scala:6-176 (combineByKey
   log-likelihoods -> here: one one-hot scatter + vectorized log ops);
 * `MultinomialNB` — count/one-hot vectors, replacing MLlib NaiveBayes as
   used by the classification template
   (examples/scala-parallel-classification/.../NaiveBayesAlgorithm.scala:15-27).

Scoring is a single (B,D)x(D,L) matmul + argmax on the MXU.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from pio_tpu.data.bimap import BiMap


# ---------------------------------------------------------------------------
# multinomial NB over vectors
# ---------------------------------------------------------------------------

@jax.tree_util.register_pytree_node_class
@dataclass
class MultinomialNBModel:
    log_prior: jax.Array      # (L,)
    log_theta: jax.Array      # (L, D)

    def tree_flatten(self):
        return (self.log_prior, self.log_theta), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def multinomial_nb_train(
    x: np.ndarray, y: np.ndarray, n_classes: int, smoothing: float = 1.0
) -> MultinomialNBModel:
    """x: (N, D) non-negative counts; y: (N,) int labels."""
    x = jnp.asarray(x, dtype=jnp.float32)
    y = jnp.asarray(y, dtype=jnp.int32)
    one_hot = jax.nn.one_hot(y, n_classes, dtype=jnp.float32)  # (N, L)
    class_count = one_hot.sum(axis=0)                          # (L,)
    feat_count = one_hot.T @ x                                 # (L, D)
    log_prior = jnp.log(class_count + smoothing) - jnp.log(
        class_count.sum() + smoothing * n_classes
    )
    smoothed = feat_count + smoothing
    log_theta = jnp.log(smoothed) - jnp.log(
        smoothed.sum(axis=1, keepdims=True)
    )
    return MultinomialNBModel(log_prior, log_theta)


@jax.jit
def multinomial_nb_scores(model: MultinomialNBModel, x) -> jax.Array:
    """(B, D) -> (B, L) joint log-likelihoods."""
    return x @ model.log_theta.T + model.log_prior[None, :]


def multinomial_nb_predict(model: MultinomialNBModel, x: np.ndarray) -> np.ndarray:
    return np.asarray(
        jnp.argmax(multinomial_nb_scores(model, jnp.asarray(x, jnp.float32)), axis=1)
    )


# ---------------------------------------------------------------------------
# categorical NB over string features (e2 parity)
# ---------------------------------------------------------------------------

@dataclass
class CategoricalNBModel:
    """Reference CategoricalNaiveBayes.Model: priors + per-position
    log-likelihoods, with a smoothed floor for unseen categories."""

    labels: BiMap                     # label -> index
    categories: list[BiMap]           # per position: value -> index
    log_prior: np.ndarray             # (L,)
    log_likelihood: np.ndarray        # (L, P, Cmax)
    log_floor: np.ndarray             # (L, P) score for unseen values

    def _encode(self, features: Sequence[str]) -> tuple[np.ndarray, np.ndarray]:
        idx = np.zeros(len(features), np.int32)
        seen = np.zeros(len(features), bool)
        for p, v in enumerate(features):
            j = self.categories[p].get(v, -1) if p < len(self.categories) else -1
            if j is not None and j >= 0:
                idx[p] = j
                seen[p] = True
        return idx, seen

    def log_score(self, features: Sequence[str], label: str) -> float | None:
        """Reference Model.logScore: None when the label is unknown; unseen
        feature values use the smoothed floor."""
        if label not in self.labels:
            return None
        li = self.labels[label]
        idx, seen = self._encode(features)
        pos = np.arange(len(features))
        ll = np.where(
            seen, self.log_likelihood[li, pos, idx], self.log_floor[li, pos]
        )
        return float(self.log_prior[li] + ll.sum())

    def predict(self, features: Sequence[str]) -> str:
        """Reference Model.predict: argmax over labels."""
        idx, seen = self._encode(features)
        pos = np.arange(len(features))
        ll = np.where(
            seen[None, :],
            self.log_likelihood[:, pos, idx],
            self.log_floor[:, pos],
        ).sum(axis=1)
        scores = self.log_prior + ll
        return self.labels.inverse()[int(np.argmax(scores))]


def categorical_nb_train(
    labeled_points: Sequence[tuple[str, Sequence[str]]],
    smoothing: float = 1.0,
) -> CategoricalNBModel:
    """labeled_points: [(label, [feature values...])] — the reference's
    LabeledPoint shape (CategoricalNaiveBayes.scala LabeledPoint)."""
    if not labeled_points:
        raise ValueError("categorical_nb_train needs at least one point")
    n_pos = len(labeled_points[0][1])
    for lbl, feats in labeled_points:
        if len(feats) != n_pos:
            raise ValueError("all points must have the same feature count")
    labels = BiMap.string_int(lbl for lbl, _ in labeled_points)
    categories = [
        BiMap.string_int(f[p] for _, f in labeled_points)
        for p in range(n_pos)
    ]
    L = len(labels)
    cmax = max((len(c) for c in categories), default=1)
    counts = np.zeros((L, n_pos, cmax), np.float64)
    label_counts = np.zeros(L, np.float64)
    for lbl, feats in labeled_points:
        li = labels[lbl]
        label_counts[li] += 1
        for p, v in enumerate(feats):
            counts[li, p, categories[p][v]] += 1
    log_prior = np.log(label_counts) - np.log(label_counts.sum())
    denom = label_counts[:, None, None] + smoothing * np.array(
        [len(c) for c in categories]
    )[None, :, None]
    log_likelihood = np.log(counts + smoothing) - np.log(denom)
    log_floor = (np.log(smoothing) - np.log(denom))[:, :, 0]
    return CategoricalNBModel(
        labels=labels,
        categories=categories,
        log_prior=log_prior,
        log_likelihood=log_likelihood,
        log_floor=log_floor,
    )
