"""Admin server — REST wrapper over app/key commands.

Reference tools/.../admin/AdminAPI.scala:35-156 + CommandClient.scala on
:7071: /, /cmd/app (list/create/delete), /cmd/app/<name>/data.
"""

from __future__ import annotations

from pio_tpu.data.dao import AccessKey, App
from pio_tpu.data.storage import Storage, get_storage
from pio_tpu.server.http import HttpApp, HttpServer, Request


def build_admin_app(storage: Storage | None = None) -> HttpApp:
    storage = storage or get_storage()
    app = HttpApp("admin")

    @app.route("GET", r"/")
    def root(req: Request):
        return 200, {"status": "alive"}

    @app.route("GET", r"/cmd/app")
    def list_apps(req: Request):
        apps = storage.get_metadata_apps().get_all()
        return 200, {
            "status": 1,
            "apps": [
                {"name": a.name, "id": a.id, "description": a.description}
                for a in sorted(apps, key=lambda a: a.id)
            ],
        }

    @app.route("POST", r"/cmd/app")
    def create_app(req: Request):
        body = req.json() or {}
        name = body.get("name", "")
        if not name:
            return 400, {"message": "app name is required"}
        apps_dao = storage.get_metadata_apps()
        app_id = apps_dao.insert(App(0, name, body.get("description")))
        if app_id is None:
            return 409, {"message": f"App {name} already exists."}
        storage.get_events().init(app_id)
        key = storage.get_metadata_access_keys().insert(AccessKey("", app_id, ()))
        return 200, {
            "status": 1,
            "message": f"App {name} created.",
            "id": app_id,
            "name": name,
            "accessKey": key,
        }

    @app.route("DELETE", r"/cmd/app/([^/]+)")
    def delete_app(req: Request):
        name = req.path_args[0]
        apps_dao = storage.get_metadata_apps()
        a = apps_dao.get_by_name(name)
        if a is None:
            return 404, {"message": f"App {name} does not exist."}
        keys = storage.get_metadata_access_keys()
        for k in keys.get_by_appid(a.id):
            keys.delete(k.key)
        for ch in storage.get_metadata_channels().get_by_appid(a.id):
            storage.get_events().remove(a.id, ch.id)
            storage.get_metadata_channels().delete(ch.id)
        storage.get_events().remove(a.id)
        apps_dao.delete(a.id)
        return 200, {"status": 1, "message": f"App {name} deleted."}

    @app.route("DELETE", r"/cmd/app/([^/]+)/data")
    def delete_app_data(req: Request):
        name = req.path_args[0]
        a = storage.get_metadata_apps().get_by_name(name)
        if a is None:
            return 404, {"message": f"App {name} does not exist."}
        storage.get_events().remove(a.id)
        storage.get_events().init(a.id)
        return 200, {"status": 1, "message": f"App {name} data deleted."}

    return app


def create_admin_server(
    storage: Storage | None = None, ip: str = "127.0.0.1", port: int = 7071
) -> HttpServer:
    return HttpServer(build_admin_app(storage), host=ip, port=port)
