"""Admin server — REST wrapper over app/key commands.

Reference tools/.../admin/AdminAPI.scala:35-156 + CommandClient.scala on
:7071: /, /cmd/app (list/create/delete), /cmd/app/<name>/data.
"""

from __future__ import annotations

from pio_tpu.data.storage import Storage, get_storage
from pio_tpu.server.http import HttpApp, HttpServer, Request
from pio_tpu.tools import appops


def build_admin_app(storage: Storage | None = None) -> HttpApp:
    from pio_tpu.resilience.health import breaker_checks, install_health_routes

    storage = storage or get_storage()
    app = HttpApp("admin")
    install_health_routes(app, lambda: breaker_checks(storage))

    @app.route("GET", r"/")
    def root(req: Request):
        return 200, {"status": "alive"}

    @app.route("GET", r"/cmd/app")
    def list_apps(req: Request):
        apps = storage.get_metadata_apps().get_all()
        return 200, {
            "status": 1,
            "apps": [
                {"name": a.name, "id": a.id, "description": a.description}
                for a in sorted(apps, key=lambda a: a.id)
            ],
        }

    @app.route("POST", r"/cmd/app")
    def create_app(req: Request):
        body = req.json() or {}
        name = body.get("name", "")
        if not name:
            return 400, {"message": "app name is required"}
        created = appops.create_app(storage, name, body.get("description"))
        if created is None:
            return 409, {"message": f"App {name} already exists."}
        app_id, key = created
        return 200, {
            "status": 1,
            "message": f"App {name} created.",
            "id": app_id,
            "name": name,
            "accessKey": key,
        }

    @app.route("DELETE", r"/cmd/app/([^/]+)")
    def delete_app(req: Request):
        name = req.path_args[0]
        a = storage.get_metadata_apps().get_by_name(name)
        if a is None:
            return 404, {"message": f"App {name} does not exist."}
        appops.delete_app(storage, a)
        return 200, {"status": 1, "message": f"App {name} deleted."}

    @app.route("DELETE", r"/cmd/app/([^/]+)/data")
    def delete_app_data(req: Request):
        name = req.path_args[0]
        a = storage.get_metadata_apps().get_by_name(name)
        if a is None:
            return 404, {"message": f"App {name} does not exist."}
        appops.delete_app_data(storage, a)
        return 200, {"status": 1, "message": f"App {name} data deleted."}

    return app


def create_admin_server(
    storage: Storage | None = None, ip: str = "127.0.0.1", port: int = 7071,
    certfile: str | None = None, keyfile: str | None = None,
) -> HttpServer:
    from pio_tpu.server.security import server_ssl_context

    return HttpServer(
        build_admin_app(storage), host=ip, port=port,
        ssl_context=server_ssl_context(certfile, keyfile),
    )
