"""Event export/import — JSON-lines files <-> event store.

Reference tools/.../export/EventsToFile.scala (PEvents -> JSON/Parquet) and
imprt/FileToEvents.scala (JSON lines -> PEvents.write). JSON-lines format
matches the Event Server wire format, so exports replay through
`pio import` or the batch API.
"""

from __future__ import annotations

import json
from typing import TextIO

from pio_tpu.data.event import Event, validate_event
from pio_tpu.data.storage import Storage


def export_events(
    storage: Storage,
    app_id: int,
    out: TextIO,
    channel_id: int | None = None,
) -> int:
    """Write all events of an app/channel as JSON lines; returns count."""
    n = 0
    for event in storage.get_events().find(app_id, channel_id=channel_id, limit=-1):
        out.write(json.dumps(event.to_api_dict(), sort_keys=True) + "\n")
        n += 1
    return n


def import_events(
    storage: Storage,
    app_id: int,
    infile: TextIO,
    channel_id: int | None = None,
) -> tuple[int, int]:
    """Read JSON lines into the event store; returns (imported, failed)."""
    dao = storage.get_events()
    dao.init(app_id, channel_id)
    ok = failed = 0
    for line in infile:
        line = line.strip()
        if not line:
            continue
        try:
            event = Event.from_api_dict(json.loads(line))
            validate_event(event)
            dao.insert(event, app_id, channel_id)
            ok += 1
        except Exception:  # noqa: BLE001 - count+continue like the reference
            failed += 1
    return ok, failed
