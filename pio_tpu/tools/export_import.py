"""Event export/import — JSON-lines and Parquet files <-> event store.

Reference tools/.../export/EventsToFile.scala:39 (PEvents -> JSON/Parquet
via Spark DataFrames) and imprt/FileToEvents.scala (JSON lines ->
PEvents.write). JSON-lines format matches the Event Server wire format, so
exports replay through `pio import` or the batch API. The Parquet path is
columnar (one column per Event field, properties as a JSON string column —
they are schemaless by design) and streams in record batches, so exports of
millions of events never hold them all in memory.
"""

from __future__ import annotations

import json
from typing import TextIO

from pio_tpu.data.event import Event, validate_event
from pio_tpu.data.storage import Storage


def export_events(
    storage: Storage,
    app_id: int,
    out: TextIO,
    channel_id: int | None = None,
) -> int:
    """Write all events of an app/channel as JSON lines; returns count."""
    n = 0
    for event in storage.get_events().find(app_id, channel_id=channel_id, limit=-1):
        out.write(json.dumps(event.to_api_dict(), sort_keys=True) + "\n")
        n += 1
    return n


IMPORT_BATCH = 500


def import_events(
    storage: Storage,
    app_id: int,
    infile: TextIO,
    channel_id: int | None = None,
) -> tuple[int, int]:
    """Read JSON lines into the event store; returns (imported, failed).

    Inserts in IMPORT_BATCH bulk writes — over the storage server that
    is one RPC per batch instead of one per event (the difference
    between ~1k/s and wire speed on a remote store). Per-line fault
    isolation is preserved: parse/validation failures never enter a
    batch, and a failed bulk write retries its events singly so exactly
    the bad ones count as failures (the reference's count+continue)."""
    from pio_tpu.data.backends.common import new_event_id

    dao = storage.get_events()
    dao.init(app_id, channel_id)
    ok = failed = 0
    batch: list[Event] = []

    def flush():
        nonlocal ok, failed
        if not batch:
            return
        try:
            dao.insert_batch(batch, app_id, channel_id)
            ok += len(batch)
        except Exception:  # noqa: BLE001 - isolate: retry one by one.
            # A bulk write can fail PARTWAY (the default insert_batch is
            # a per-event loop; a remote RPC can time out after the
            # server committed) — ids were minted client-side above
            # precisely so this retry can skip what already landed
            # instead of duplicating it.
            for ev in batch:
                try:
                    if dao.get(ev.event_id, app_id, channel_id) is None:
                        dao.insert(ev, app_id, channel_id)
                    ok += 1
                except Exception:  # noqa: BLE001
                    failed += 1
        batch.clear()

    for line in infile:
        line = line.strip()
        if not line:
            continue
        try:
            event = Event.from_api_dict(json.loads(line))
            validate_event(event)
        except Exception:  # noqa: BLE001 - count+continue like the reference
            failed += 1
            continue
        if event.event_id is None:
            # client-side id minting makes the batch retry idempotent
            event = event.with_id(new_event_id())
        batch.append(event)
        if len(batch) >= IMPORT_BATCH:
            flush()
    flush()
    return ok, failed


# ---------------------------------------------------------------------------
# Parquet (columnar) path — reference EventsToFile.scala:39 "parquet" format
# ---------------------------------------------------------------------------

_PARQUET_BATCH = 65536


def _parquet_schema():
    import pyarrow as pa

    return pa.schema(
        [
            ("eventId", pa.string()),
            ("event", pa.string()),
            ("entityType", pa.string()),
            ("entityId", pa.string()),
            ("targetEntityType", pa.string()),
            ("targetEntityId", pa.string()),
            ("properties", pa.string()),  # schemaless JSON, one doc per row
            ("eventTime", pa.timestamp("us", tz="UTC")),
            ("tags", pa.list_(pa.string())),
            ("prId", pa.string()),
            ("creationTime", pa.timestamp("us", tz="UTC")),
        ]
    )


def _events_to_batch(events: list[Event], schema):
    import pyarrow as pa

    cols = {
        "eventId": [e.event_id for e in events],
        "event": [e.event for e in events],
        "entityType": [e.entity_type for e in events],
        "entityId": [e.entity_id for e in events],
        "targetEntityType": [e.target_entity_type for e in events],
        "targetEntityId": [e.target_entity_id for e in events],
        "properties": [
            json.dumps(dict(e.properties.fields), sort_keys=True)
            if e.properties.fields else None
            for e in events
        ],
        "eventTime": [e.event_time for e in events],
        "tags": [list(e.tags) if e.tags else None for e in events],
        "prId": [e.pr_id for e in events],
        "creationTime": [e.creation_time for e in events],
    }
    return pa.record_batch(
        [pa.array(cols[f.name], type=f.type) for f in schema], schema=schema
    )


def export_events_parquet(
    storage: Storage,
    app_id: int,
    path: str,
    channel_id: int | None = None,
) -> int:
    """Write all events of an app/channel to one Parquet file; returns count."""
    import pyarrow.parquet as pq

    schema = _parquet_schema()
    n = 0
    with pq.ParquetWriter(path, schema, compression="zstd") as writer:
        batch: list[Event] = []
        for event in storage.get_events().find(
            app_id, channel_id=channel_id, limit=-1
        ):
            batch.append(event)
            if len(batch) >= _PARQUET_BATCH:
                writer.write_batch(_events_to_batch(batch, schema))
                n += len(batch)
                batch = []
        if batch:
            writer.write_batch(_events_to_batch(batch, schema))
            n += len(batch)
    return n


def import_events_parquet(
    storage: Storage,
    app_id: int,
    path: str,
    channel_id: int | None = None,
) -> tuple[int, int]:
    """Read a Parquet export into the event store; returns (imported, failed)."""
    import pyarrow.parquet as pq

    dao = storage.get_events()
    dao.init(app_id, channel_id)
    ok = failed = 0
    pf = pq.ParquetFile(path)
    for rb in pf.iter_batches(batch_size=_PARQUET_BATCH):
        rows = rb.to_pylist()
        good: list[Event] = []
        for row in rows:
            try:
                props = json.loads(row["properties"]) if row["properties"] else {}
                event = Event(
                    event=row["event"],
                    entity_type=row["entityType"],
                    entity_id=row["entityId"],
                    target_entity_type=row["targetEntityType"],
                    target_entity_id=row["targetEntityId"],
                    properties=props,
                    event_time=row["eventTime"],
                    tags=tuple(row["tags"] or ()),
                    pr_id=row["prId"],
                    event_id=row["eventId"],
                    creation_time=row["creationTime"],
                )
                validate_event(event)
                good.append(event)
            except Exception:  # noqa: BLE001 - count+continue like the reference
                failed += 1
        if good:
            dao.insert_batch(good, app_id, channel_id)
            ok += len(good)
    return ok, failed
