"""Dashboard — browse completed evaluation instances.

Reference tools/.../dashboard/Dashboard.scala:59-156 (+ Twirl
index.scala.html) on :9000: an HTML list of completed evaluations with
links to each instance's detailed HTML report.
"""

from __future__ import annotations

import html

from pio_tpu.data.storage import Storage, get_storage
from pio_tpu.server.http import HttpApp, HttpServer, Request
from pio_tpu.utils.time import format_time


def build_dashboard_app(storage: Storage | None = None) -> HttpApp:
    from pio_tpu.resilience.health import breaker_checks, install_health_routes

    storage = storage or get_storage()
    app = HttpApp("dashboard")
    install_health_routes(app, lambda: breaker_checks(storage))

    @app.route("GET", r"/")
    def index(req: Request):
        instances = storage.get_metadata_evaluation_instances().get_completed()
        rows = "".join(
            "<tr>"
            f"<td><a href='/engine_instances/{html.escape(i.id)}"
            f"/evaluator_results.html'>{html.escape(i.id)}</a></td>"
            f"<td>{html.escape(i.evaluation_class)}</td>"
            f"<td>{html.escape(i.engine_params_generator_class)}</td>"
            f"<td>{html.escape(format_time(i.start_time))}</td>"
            f"<td>{html.escape(format_time(i.end_time))}</td>"
            f"<td><pre>{html.escape(i.evaluator_results)}</pre></td>"
            "</tr>"
            for i in instances
        )
        page = (
            "<!doctype html><html><head><title>pio-tpu dashboard</title>"
            "</head><body><h1>Completed evaluations</h1>"
            "<table border='1'><tr><th>ID</th><th>Evaluation</th>"
            "<th>Params generator</th><th>Start</th><th>End</th>"
            "<th>Result</th></tr>"
            f"{rows}</table></body></html>"
        )
        return 200, page

    @app.route("GET", r"/engine_instances/([^/]+)/evaluator_results\.html")
    def results_html(req: Request):
        i = storage.get_metadata_evaluation_instances().get(req.path_args[0])
        if i is None:
            return 404, {"message": "Not Found"}
        return 200, (
            "<!doctype html><html><body>"
            + (i.evaluator_results_html or "<p>(no results)</p>")
            + "</body></html>"
        )

    @app.route("GET", r"/engine_instances/([^/]+)/evaluator_results\.json")
    def results_json(req: Request):
        i = storage.get_metadata_evaluation_instances().get(req.path_args[0])
        if i is None:
            return 404, {"message": "Not Found"}
        import json

        return 200, json.loads(i.evaluator_results_json or "{}")

    return app


def create_dashboard(
    storage: Storage | None = None, ip: str = "127.0.0.1", port: int = 9000,
    certfile: str | None = None, keyfile: str | None = None,
) -> HttpServer:
    from pio_tpu.server.security import server_ssl_context

    return HttpServer(
        build_dashboard_app(storage), host=ip, port=port,
        ssl_context=server_ssl_context(certfile, keyfile),
    )
