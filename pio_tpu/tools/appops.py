"""App create/delete orchestration shared by the CLI and the admin server.

Parity target: reference tools/.../console/App.scala (create: app + default
event namespace + first access key; delete: cascading key/channel/event
cleanup) and admin/CommandClient.scala, which both drive the same sequence.
"""

from __future__ import annotations

from pio_tpu.data.dao import AccessKey, App, Channel
from pio_tpu.data.storage import Storage, StorageError


def create_app(
    storage: Storage,
    name: str,
    description: str | None = None,
    app_id: int = 0,
    access_key: str = "",
) -> tuple[int, str] | None:
    """Create an app, init its event namespace, mint its first access key.
    Returns (app_id, key), or None if the name is taken."""
    new_id = storage.get_metadata_apps().insert(App(app_id, name, description))
    if new_id is None:
        return None
    storage.get_events().init(new_id)
    key = storage.get_metadata_access_keys().insert(
        AccessKey(access_key, new_id, ())
    )
    return new_id, key


def delete_app(storage: Storage, app: App) -> None:
    """Cascading delete: access keys, per-channel event data + channels,
    default-channel event data, then the app record."""
    keys = storage.get_metadata_access_keys()
    channels = storage.get_metadata_channels()
    for k in keys.get_by_appid(app.id):
        keys.delete(k.key)
    for ch in channels.get_by_appid(app.id):
        storage.get_events().remove(app.id, ch.id)
        channels.delete(ch.id)
    storage.get_events().remove(app.id)
    storage.get_metadata_apps().delete(app.id)


def delete_app_data(
    storage: Storage, app: App, channel_id: int | None = None
) -> None:
    """Wipe and re-init event data for one channel (or the default)."""
    storage.get_events().remove(app.id, channel_id)
    storage.get_events().init(app.id, channel_id)


def _namespaces(
    channels_dao, app_id: int, channel_name: str | None
) -> list[tuple[str, int | None]]:
    """[(label, channel_id)] for an app: the default namespace plus every
    registered channel. Labels stay unique even if a user names a channel
    literally "default" (the default NAMESPACE is channel_id None; such a
    channel is a distinct namespace and must not be skipped)."""
    chans = channels_dao.get_by_appid(app_id)
    if channel_name is not None:
        match = [c for c in chans if c.name == channel_name]
        if not match:
            raise ValueError(f"Channel {channel_name} does not exist.")
        return [(channel_name, match[0].id)]
    out: list[tuple[str, int | None]] = [("default", None)]
    for c in sorted(chans, key=lambda c: c.name):
        label = c.name if c.name != "default" else f"default (channel {c.id})"
        out.append((label, c.id))
    return out


def trim_copy(
    storage: Storage,
    src_app: App,
    dst_app: App,
    start_time=None,
    until_time=None,
    channel_name: str | None = None,
) -> dict[str, int]:
    """Copy src app's events within [start_time, until_time) into dst app —
    the reference trim-app workflow (examples/experimental/
    scala-parallel-trim-app/src/main/scala/DataSource.scala:31-51: windowed
    PEvents.find -> write into a destination app that MUST be empty, so a
    botched window can never destroy the only copy).

    With channel_name=None every namespace is copied (the default one plus
    each named channel, which is created in dst under the same name —
    channel ids are app-scoped, so the destination always gets its OWN
    channels). With a channel_name only that channel is copied. Either
    way the destination app must be ENTIRELY empty first. Returns
    {namespace_label: events_copied}."""
    ev = storage.get_events()
    channels = storage.get_metadata_channels()

    # whole-app emptiness guard: default namespace + every dst channel
    for ch in [None] + [c.id for c in channels.get_by_appid(dst_app.id)]:
        try:
            probe = next(
                iter(ev.find(dst_app.id, channel_id=ch, limit=1)), None)
        except StorageError:  # uninitialized namespace = empty
            continue
        if probe is not None:
            raise ValueError(
                f"destination app {dst_app.name!r} is not empty; trim "
                "refuses to mix into existing data (reference TrimApp "
                "contract)"
            )

    pairs = _namespaces(channels, src_app.id, channel_name)

    counts: dict[str, int] = {}
    for name, src_ch in pairs:
        if src_ch is None:
            dst_ch = None
        else:
            existing = {c.name: c.id
                        for c in channels.get_by_appid(dst_app.id)}
            dst_ch = existing.get(name)
            if dst_ch is None:
                dst_ch = channels.insert(Channel(0, name, dst_app.id))
        ev.init(dst_app.id, dst_ch)
        n = 0
        try:
            found = ev.find(
                src_app.id, channel_id=src_ch,
                start_time=start_time, until_time=until_time, limit=-1,
            )
        except StorageError:  # src namespace never initialized
            found = []
        for event in found:
            ev.insert(event, dst_app.id, dst_ch)
            n += 1
        counts[name] = n
    return counts


def cleanup_events(
    storage: Storage,
    app: App,
    until_time,
    channel_name: str | None = None,
) -> dict[str, int]:
    """Delete events with event_time < until_time IN PLACE — the reference
    cleanup-app workflow (examples/experimental/scala-cleanup-app/src/main/
    scala/DataSource.scala:31-66: windowed PEvents.find -> per-event
    LEvents.futureDelete). With channel_name=None every namespace is
    cleaned. Returns {namespace_label: events_deleted}."""
    if until_time is None:
        raise ValueError("cleanup requires an --until cutoff time")
    ev = storage.get_events()
    channels = storage.get_metadata_channels()
    pairs = _namespaces(channels, app.id, channel_name)
    counts: dict[str, int] = {}
    for name, ch in pairs:
        try:
            doomed = [
                e.event_id
                for e in ev.find(app.id, channel_id=ch,
                                 until_time=until_time, limit=-1)
                if e.event_id
            ]
        except StorageError:  # uninitialized namespace: nothing to clean
            counts[name] = 0
            continue
        # a backend failure (e.g. remote store unreachable) must RAISE,
        # not report a successful no-op — retention crons trust this count
        counts[name] = ev.delete_many(doomed, app.id, ch)
    return counts
