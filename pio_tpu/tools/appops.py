"""App create/delete orchestration shared by the CLI and the admin server.

Parity target: reference tools/.../console/App.scala (create: app + default
event namespace + first access key; delete: cascading key/channel/event
cleanup) and admin/CommandClient.scala, which both drive the same sequence.
"""

from __future__ import annotations

from pio_tpu.data.dao import AccessKey, App, Channel
from pio_tpu.data.storage import Storage


def create_app(
    storage: Storage,
    name: str,
    description: str | None = None,
    app_id: int = 0,
    access_key: str = "",
) -> tuple[int, str] | None:
    """Create an app, init its event namespace, mint its first access key.
    Returns (app_id, key), or None if the name is taken."""
    new_id = storage.get_metadata_apps().insert(App(app_id, name, description))
    if new_id is None:
        return None
    storage.get_events().init(new_id)
    key = storage.get_metadata_access_keys().insert(
        AccessKey(access_key, new_id, ())
    )
    return new_id, key


def delete_app(storage: Storage, app: App) -> None:
    """Cascading delete: access keys, per-channel event data + channels,
    default-channel event data, then the app record."""
    keys = storage.get_metadata_access_keys()
    channels = storage.get_metadata_channels()
    for k in keys.get_by_appid(app.id):
        keys.delete(k.key)
    for ch in channels.get_by_appid(app.id):
        storage.get_events().remove(app.id, ch.id)
        channels.delete(ch.id)
    storage.get_events().remove(app.id)
    storage.get_metadata_apps().delete(app.id)


def delete_app_data(
    storage: Storage, app: App, channel_id: int | None = None
) -> None:
    """Wipe and re-init event data for one channel (or the default)."""
    storage.get_events().remove(app.id, channel_id)
    storage.get_events().init(app.id, channel_id)


def trim_copy(
    storage: Storage,
    src_app: App,
    dst_app: App,
    start_time=None,
    until_time=None,
    channel_name: str | None = None,
) -> dict[str, int]:
    """Copy src app's events within [start_time, until_time) into dst app —
    the reference trim-app workflow (examples/experimental/
    scala-parallel-trim-app/src/main/scala/DataSource.scala:31-51: windowed
    PEvents.find -> write into a destination app that MUST be empty, so a
    botched window can never destroy the only copy).

    With channel_name=None every namespace is copied (the default one plus
    each named channel, which is created in dst under the same name —
    channel ids are app-scoped, so the destination always gets its OWN
    channels). With a channel_name only that channel is copied. Either
    way the destination app must be ENTIRELY empty first. Returns
    {namespace_label: events_copied}."""
    ev = storage.get_events()
    channels = storage.get_metadata_channels()

    # whole-app emptiness guard: default namespace + every dst channel
    for ch in [None] + [c.id for c in channels.get_by_appid(dst_app.id)]:
        try:
            probe = next(
                iter(ev.find(dst_app.id, channel_id=ch, limit=1)), None)
        except Exception:  # noqa: BLE001 - uninitialized namespace = empty
            continue
        if probe is not None:
            raise ValueError(
                f"destination app {dst_app.name!r} is not empty; trim "
                "refuses to mix into existing data (reference TrimApp "
                "contract)"
            )

    src_channels = {c.name: c.id for c in channels.get_by_appid(src_app.id)}
    if channel_name is not None:
        if channel_name not in src_channels:
            raise ValueError(f"Channel {channel_name} does not exist.")
        pairs = [(channel_name, src_channels[channel_name])]
    else:
        pairs = [("default", None)] + sorted(
            (n, cid) for n, cid in src_channels.items() if n != "default"
        )

    counts: dict[str, int] = {}
    for name, src_ch in pairs:
        if src_ch is None:
            dst_ch = None
        else:
            existing = {c.name: c.id
                        for c in channels.get_by_appid(dst_app.id)}
            dst_ch = existing.get(name)
            if dst_ch is None:
                dst_ch = channels.insert(Channel(0, name, dst_app.id))
        ev.init(dst_app.id, dst_ch)
        n = 0
        try:
            found = ev.find(
                src_app.id, channel_id=src_ch,
                start_time=start_time, until_time=until_time, limit=-1,
            )
        except Exception:  # noqa: BLE001 - src namespace never initialized
            found = []
        for event in found:
            ev.insert(event, dst_app.id, dst_ch)
            n += 1
        counts[name] = n
    return counts
