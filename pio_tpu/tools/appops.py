"""App create/delete orchestration shared by the CLI and the admin server.

Parity target: reference tools/.../console/App.scala (create: app + default
event namespace + first access key; delete: cascading key/channel/event
cleanup) and admin/CommandClient.scala, which both drive the same sequence.
"""

from __future__ import annotations

from pio_tpu.data.dao import AccessKey, App
from pio_tpu.data.storage import Storage


def create_app(
    storage: Storage,
    name: str,
    description: str | None = None,
    app_id: int = 0,
    access_key: str = "",
) -> tuple[int, str] | None:
    """Create an app, init its event namespace, mint its first access key.
    Returns (app_id, key), or None if the name is taken."""
    new_id = storage.get_metadata_apps().insert(App(app_id, name, description))
    if new_id is None:
        return None
    storage.get_events().init(new_id)
    key = storage.get_metadata_access_keys().insert(
        AccessKey(access_key, new_id, ())
    )
    return new_id, key


def delete_app(storage: Storage, app: App) -> None:
    """Cascading delete: access keys, per-channel event data + channels,
    default-channel event data, then the app record."""
    keys = storage.get_metadata_access_keys()
    channels = storage.get_metadata_channels()
    for k in keys.get_by_appid(app.id):
        keys.delete(k.key)
    for ch in channels.get_by_appid(app.id):
        storage.get_events().remove(app.id, ch.id)
        channels.delete(ch.id)
    storage.get_events().remove(app.id)
    storage.get_metadata_apps().delete(app.id)


def delete_app_data(
    storage: Storage, app: App, channel_id: int | None = None
) -> None:
    """Wipe and re-init event data for one channel (or the default)."""
    storage.get_events().remove(app.id, channel_id)
    storage.get_events().init(app.id, channel_id)


def trim_copy(
    storage: Storage,
    src_app: App,
    dst_app: App,
    start_time=None,
    until_time=None,
    channel_id: int | None = None,
) -> int:
    """Copy src app's events within [start_time, until_time) into dst app —
    the reference trim-app workflow (examples/experimental/
    scala-parallel-trim-app/src/main/scala/DataSource.scala:31-51: windowed
    PEvents.find -> write into a destination app that MUST be empty, so a
    botched window can never destroy the only copy). Returns events copied.
    """
    ev = storage.get_events()
    ev.init(dst_app.id, channel_id)
    if next(iter(ev.find(dst_app.id, channel_id=channel_id, limit=1)), None) \
            is not None:
        raise ValueError(
            f"destination app {dst_app.name!r} is not empty; trim refuses "
            "to mix into existing data (reference TrimApp contract)"
        )
    n = 0
    for event in ev.find(
        src_app.id, channel_id=channel_id,
        start_time=start_time, until_time=until_time, limit=-1,
    ):
        ev.insert(event, dst_app.id, channel_id)
        n += 1
    return n
