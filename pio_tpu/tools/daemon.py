"""One-command bring-up/teardown of the serving stack.

Reference bin/pio-start-all / bin/pio-stop-all boot the storage services +
Event Server with nohup and pkill them by name. Here each service is a
detached `python -m pio_tpu.tools.cli <verb>` child (own session, log file,
pidfile under --pid-dir), so `pio start-all` / `pio stop-all` manage the
whole stack: event server, admin server, dashboard, and optionally the
shared storage server (the HBase/Postgres stand-in other hosts mount via
the `remote` backend).

Storage configuration (PIO_STORAGE_*) is inherited from the calling
environment, like the reference's conf/pio-env.sh sourcing.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass

def default_pid_dir() -> str:
    return os.environ.get(
        "PIO_TPU_PID_DIR", os.path.expanduser("~/.pio_tpu/run")
    )


@dataclass(frozen=True)
class Service:
    name: str
    argv: list[str]          # cli args after `python -m pio_tpu.tools.cli`
    port: int
    # /healthz: the uniform liveness endpoint every surface now serves
    # (resilience/health.py) — pure process-up, no storage round trips
    health_path: str = "/healthz"


def stack_services(args) -> list[Service]:
    services = []
    if getattr(args, "with_storageserver", False):
        argv = ["storageserver", "--ip", args.ip,
                "--port", str(args.storageserver_port)]
        if getattr(args, "server_key", None):
            # required for non-loopback binds (storageserver refuses them
            # keyless: the RPC surface includes access keys + model blobs)
            argv += ["--server-key", args.server_key]
        services.append(Service(
            "storageserver", argv, args.storageserver_port,
        ))
    services.append(Service(
        "eventserver",
        ["eventserver", "--ip", args.ip, "--port", str(args.eventserver_port)],
        args.eventserver_port,
    ))
    services.append(Service(
        "adminserver",
        ["adminserver", "--ip", args.ip, "--port", str(args.adminserver_port)],
        args.adminserver_port,
    ))
    services.append(Service(
        "dashboard",
        ["dashboard", "--ip", args.ip, "--port", str(args.dashboard_port)],
        args.dashboard_port,
    ))
    return services


def _pidfile(pid_dir: str, name: str) -> str:
    return os.path.join(pid_dir, f"{name}.pid")


def _read_pid(path: str) -> int:
    """0 = unreadable/corrupt (treated as stale everywhere)."""
    try:
        with open(path) as f:
            return int(f.read().strip() or 0)
    except (OSError, ValueError):
        return 0


def _alive(pid: int) -> bool:
    """True only if pid exists AND is one of our CLI daemons — guards the
    pidfile against pid reuse (e.g. after a reboot) so stop-all never
    signals an innocent process."""
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except (ProcessLookupError, PermissionError):
        return False
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            return b"pio_tpu" in f.read()
    except OSError:
        return True  # no /proc: fall back to existence only


def _healthy(service: Service, ip: str, timeout_s: float = 20.0,
             child: subprocess.Popen | None = None) -> bool:
    from pio_tpu.utils.httpclient import HttpClientError, JsonHttpClient

    host = "127.0.0.1" if ip in ("0.0.0.0", "") else ip
    client = JsonHttpClient(f"http://{host}:{service.port}", timeout=2)
    deadline = time.monotonic() + timeout_s
    # pio: lint-ok[bare-retry] deadline-paced startup-readiness poll at a
    # fixed cadence, not an I/O retry — backoff/jitter would only delay
    # the "up" verdict
    while time.monotonic() < deadline:
        if child is not None and child.poll() is not None:
            return False  # died at startup: fail now, not after the timeout
        try:
            client.request("GET", service.health_path)
            return True
        except HttpClientError as e:
            if e.status:
                return True  # listening; 4xx (e.g. auth) still means "up"
            time.sleep(0.3)   # status 0: transport-level, not up yet
    return False


def _terminate(pid: int, grace_s: float | None = None) -> None:
    """SIGTERM the process group (children lead their own sessions), wait,
    escalate to SIGKILL.

    SIGTERM is a *graceful preemption* for training children: the
    lifecycle handler (workflow/lifecycle.py) force-saves a checkpoint
    at the next step boundary and exits resumable, so the grace window
    must cover a checkpoint write — tune with PIO_TPU_STOP_GRACE_S
    (default 10s) for large models or slow blob stores. Only after the
    grace expires does SIGKILL make the run a zombie (still resumable:
    the sweep marks it FAILED and its last cadence checkpoint survives).
    """
    if grace_s is None:
        try:
            grace_s = float(os.environ.get("PIO_TPU_STOP_GRACE_S", "10"))
        except ValueError:
            grace_s = 10.0
    try:
        os.killpg(pid, signal.SIGTERM)
    except (ProcessLookupError, PermissionError, OSError):
        try:
            os.kill(pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            return
    deadline = time.monotonic() + grace_s
    while _alive(pid) and time.monotonic() < deadline:
        time.sleep(0.2)
    if _alive(pid):
        try:
            os.killpg(pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            pass


def start_all(args) -> int:
    pid_dir = os.path.expanduser(args.pid_dir)
    os.makedirs(pid_dir, exist_ok=True)
    started, failed = [], []
    for svc in stack_services(args):
        pf = _pidfile(pid_dir, svc.name)
        if os.path.exists(pf):
            old = _read_pid(pf)
            if _alive(old):
                print(f"{svc.name}: already running (pid {old})")
                continue
            os.unlink(pf)  # stale
        log_path = os.path.join(pid_dir, f"{svc.name}.log")
        with open(log_path, "ab") as logf:
            proc = subprocess.Popen(
                [sys.executable, "-m", "pio_tpu.tools.cli", *svc.argv],
                stdout=logf, stderr=logf, stdin=subprocess.DEVNULL,
                start_new_session=True,   # survives this CLI exiting
            )
        with open(pf, "w") as f:
            f.write(str(proc.pid))
        if _healthy(svc, args.ip, child=proc):
            print(f"{svc.name}: started (pid {proc.pid}, port {svc.port}, "
                  f"log {log_path})")
            started.append(svc.name)
        else:
            tail = ""
            try:
                with open(log_path, "rb") as lf:
                    tail = lf.read()[-400:].decode(errors="replace").strip()
            except OSError:
                pass
            print(f"{svc.name}: FAILED to come up on port {svc.port} "
                  f"(see {log_path})"
                  + (f"\n  {tail.splitlines()[-1]}" if tail else ""),
                  file=sys.stderr)
            # a slow-to-bind child may still be alive: kill it before
            # dropping the pidfile, or it becomes an unmanaged orphan
            _terminate(proc.pid)
            os.unlink(pf)
            failed.append(svc.name)
    if failed:
        return 1
    if started:
        print(f"Stack up: {', '.join(started)}. Stop with: pio stop-all")
    return 0


def stop_all(args) -> int:
    pid_dir = os.path.expanduser(args.pid_dir)
    if not os.path.isdir(pid_dir):
        print("Nothing to stop.")
        return 0
    stopped = 0
    for fn in sorted(os.listdir(pid_dir)):
        if not fn.endswith(".pid"):
            continue
        name = fn[:-4]
        pf = os.path.join(pid_dir, fn)
        pid = _read_pid(pf)
        if _alive(pid):
            _terminate(pid)
            print(f"{name}: stopped (pid {pid})")
            stopped += 1
        else:
            print(f"{name}: not running (stale pidfile removed)")
        os.unlink(pf)
    if not stopped:
        print("Nothing to stop.")
    return 0


def status_all(pid_dir: str | None = None) -> dict:
    """-> {service: {"pid": int, "alive": bool}} for `pio status`."""
    out = {}
    pid_dir = os.path.expanduser(pid_dir or default_pid_dir())
    if not os.path.isdir(pid_dir):
        return out
    for fn in sorted(os.listdir(pid_dir)):
        if fn.endswith(".pid"):
            pid = _read_pid(os.path.join(pid_dir, fn))
            out[fn[:-4]] = {"pid": pid, "alive": _alive(pid)}
    return out
