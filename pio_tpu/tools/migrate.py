"""Storage migration — copy apps/events between storage backends.

The reference ships `pio upgrade` with HBase 0.8->0.9 format migration tools
(data/.../storage/hbase/upgrade/, Console.scala upgrade verb); the TPU
build's equivalent is backend-generic: read every event from one configured
storage and write it into another (e.g. sqlite -> the native eventlog, or
dev memory -> durable sqlite), preserving event ids, times, and channels.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

from pio_tpu.data.dao import Channel
from pio_tpu.data.storage import Storage, StorageError

log = logging.getLogger("pio_tpu.tools")


@dataclass
class MigrationReport:
    apps: int = 0
    channels: int = 0
    access_keys: int = 0
    events: int = 0

    def one_liner(self) -> str:
        return (
            f"migrated {self.apps} apps, {self.channels} channels, "
            f"{self.access_keys} access keys, {self.events} events"
        )


def migrate_events(
    src: Storage,
    dst: Storage,
    app_ids: list[int] | None = None,
    copy_metadata: bool = True,
    batch_size: int = 1000,
) -> MigrationReport:
    """Copy events (and by default app/channel/key metadata) src -> dst.

    Events keep their ids, so re-running is idempotent on id-keyed backends
    and the eventlog backend dedups nothing — migrate into an empty target.
    """
    report = MigrationReport()
    src_apps = src.get_metadata_apps()
    apps = [
        a for a in src_apps.get_all()
        if app_ids is None or a.id in app_ids
    ]
    src_events = src.get_events()
    dst_events = dst.get_events()

    for app in apps:
        if copy_metadata:
            dst_apps = dst.get_metadata_apps()
            existing_app = dst_apps.get(app.id)
            if existing_app is None:
                if dst_apps.insert(app) is None:
                    raise StorageError(
                        f"cannot migrate app {app.id} ({app.name!r}): "
                        "target has a conflicting app with the same name"
                    )
                report.apps += 1
            elif existing_app.name != app.name:
                raise StorageError(
                    f"target app id {app.id} is {existing_app.name!r}, "
                    f"source is {app.name!r}; refusing to merge"
                )
            dst_keys = dst.get_metadata_access_keys()
            for key in src.get_metadata_access_keys().get_by_appid(app.id):
                existing_key = dst_keys.get(key.key)
                if existing_key is None:
                    if dst_keys.insert(key) is None:
                        raise StorageError(
                            f"cannot migrate access key for app {app.id}"
                        )
                    report.access_keys += 1
                elif existing_key.appid != key.appid:
                    # clients authenticating with this key on the target
                    # would write into a DIFFERENT app — refuse
                    raise StorageError(
                        f"access key of app {app.id} already exists on the "
                        f"target bound to app {existing_key.appid}"
                    )

        # Channel ids may differ on the target (same-name match, or a fresh
        # id when the source id is already taken), so build a src->dst
        # channel-id map from insert return values and copy events under
        # the TARGET ids.
        channels = src.get_metadata_channels().get_by_appid(app.id)
        dst_channels = dst.get_metadata_channels()
        existing_by_name = {
            c.name: c.id for c in dst_channels.get_by_appid(app.id)
        }
        channel_map: dict[int, int] = {}
        for ch in channels:
            if ch.name in existing_by_name:
                channel_map[ch.id] = existing_by_name[ch.name]
                continue
            if not copy_metadata:
                channel_map[ch.id] = ch.id
                continue
            new_id = dst_channels.insert(ch)
            if new_id is None:
                # source id taken by an unrelated channel: take a fresh id
                # and rely on the remap below
                new_id = dst_channels.insert(Channel(0, ch.name, ch.appid))
            if new_id is None:
                raise StorageError(
                    f"cannot migrate channel {ch.name!r} of app {app.id}"
                )
            channel_map[ch.id] = new_id
            report.channels += 1

        namespaces = [(None, None)] + [
            (c.id, channel_map[c.id]) for c in channels
        ]
        for src_cid, dst_cid in namespaces:
            try:
                events = src_events.find(
                    app_id=app.id, channel_id=src_cid, limit=-1
                )
            except StorageError:
                continue  # namespace never initialized on the source
            dst_events.init(app.id, dst_cid)
            batch = []
            for e in events:
                batch.append(e)
                if len(batch) >= batch_size:
                    dst_events.insert_batch(batch, app.id, dst_cid)
                    report.events += len(batch)
                    batch = []
            if batch:
                dst_events.insert_batch(batch, app.id, dst_cid)
                report.events += len(batch)
        log.info("migrated app %s (%s)", app.id, app.name)
    return report
