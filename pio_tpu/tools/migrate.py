"""Storage migration — copy apps/events between storage backends.

The reference ships `pio upgrade` with HBase 0.8->0.9 format migration tools
(data/.../storage/hbase/upgrade/, Console.scala upgrade verb); the TPU
build's equivalent is backend-generic: read every event from one configured
storage and write it into another (e.g. sqlite -> the native eventlog, or
dev memory -> durable sqlite), preserving event ids, times, and channels.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

from pio_tpu.data.storage import Storage

log = logging.getLogger("pio_tpu.tools")


@dataclass
class MigrationReport:
    apps: int = 0
    channels: int = 0
    access_keys: int = 0
    events: int = 0

    def one_liner(self) -> str:
        return (
            f"migrated {self.apps} apps, {self.channels} channels, "
            f"{self.access_keys} access keys, {self.events} events"
        )


def migrate_events(
    src: Storage,
    dst: Storage,
    app_ids: list[int] | None = None,
    copy_metadata: bool = True,
    batch_size: int = 1000,
) -> MigrationReport:
    """Copy events (and by default app/channel/key metadata) src -> dst.

    Events keep their ids, so re-running is idempotent on id-keyed backends
    and the eventlog backend dedups nothing — migrate into an empty target.
    """
    report = MigrationReport()
    src_apps = src.get_metadata_apps()
    apps = [
        a for a in src_apps.get_all()
        if app_ids is None or a.id in app_ids
    ]
    src_events = src.get_events()
    dst_events = dst.get_events()

    for app in apps:
        if copy_metadata:
            dst_apps = dst.get_metadata_apps()
            if dst_apps.get(app.id) is None:
                dst_apps.insert(app)
                report.apps += 1
            for key in src.get_metadata_access_keys().get_by_appid(app.id):
                if dst.get_metadata_access_keys().get(key.key) is None:
                    dst.get_metadata_access_keys().insert(key)
                    report.access_keys += 1

        channels = src.get_metadata_channels().get_by_appid(app.id)
        if copy_metadata:
            dst_channels = dst.get_metadata_channels()
            existing = {c.id for c in dst_channels.get_by_appid(app.id)}
            for ch in channels:
                if ch.id not in existing:
                    dst_channels.insert(ch)
                    report.channels += 1

        for channel_id in [None] + [c.id for c in channels]:
            try:
                events = src_events.find(
                    app_id=app.id, channel_id=channel_id, limit=-1
                )
            except Exception:  # noqa: BLE001 - namespace may not exist
                continue
            dst_events.init(app.id, channel_id)
            batch = []
            for e in events:
                batch.append(e)
                if len(batch) >= batch_size:
                    dst_events.insert_batch(batch, app.id, channel_id)
                    report.events += len(batch)
                    batch = []
            if batch:
                dst_events.insert_batch(batch, app.id, channel_id)
                report.events += len(batch)
        log.info("migrated app %s (%s)", app.id, app.name)
    return report
