"""`pio` command-line interface.

Verb parity with reference tools/.../console/Console.scala:186-677:
  version status
  app {new,list,show,delete,data-delete,trim,cleanup,channel-new,channel-delete}
  accesskey {new,list,delete}
  build train deploy undeploy eval
  eventserver adminserver dashboard
  export import template-new

Differences by design (single-controller runtime, SURVEY.md section 7): no
spark-submit hop — train/eval/deploy run in-process on the JAX mesh; `build`
is a syntax check of the engine dir instead of an sbt assembly.

Run as `python -m pio_tpu.tools.cli <verb>` (or `python -m pio_tpu`).
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import sys
import time

from pio_tpu import __version__
from pio_tpu.data.dao import AccessKey, Channel
from pio_tpu.data.storage import get_storage
from pio_tpu.tools import appops


def _fail(msg: str) -> int:
    print(f"[ERROR] {msg}", file=sys.stderr)
    return 1


def _load_variant(engine_dir: str) -> dict:
    path = os.path.join(engine_dir, "engine.json")
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"{path} not found. Run inside an engine directory or pass "
            "--engine-dir."
        )
    with open(path) as f:
        return json.load(f)


def _load_factory(class_path: str, engine_dir: str | None = None):
    """'pkg.module.ClassName' -> class (reference WorkflowUtils.getEngine
    reflective load). With engine_dir, the directory joins sys.path first so
    user-code engines (`engine.MyEngine` next to engine.json — the template
    layout, reference examples/*/src/main/scala/Engine.scala) resolve."""
    module_name, _, cls_name = class_path.rpartition(".")
    if not module_name:
        raise ValueError(f"invalid class path {class_path!r}")
    if engine_dir:
        d = os.path.abspath(engine_dir)
        if d not in sys.path:
            # stays on sys.path: the user module may lazily import more of
            # its directory at predict time, long after this returns
            sys.path.insert(0, d)
    mod = importlib.import_module(module_name)
    return getattr(mod, cls_name)


def _engine_from_variant(variant: dict, engine_dir: str | None = None):
    factory = _load_factory(variant["engineFactory"], engine_dir)
    engine = factory.apply()
    ep = engine.engine_params_from_variant(variant)
    if engine_dir:
        ep = _absolutize_param_paths(ep, engine_dir)
    return engine, ep


def _retrieval_block(ep) -> dict | None:
    """The engine's two-stage retrieval block (ops/retrieval.py). Fleet
    shards score partitions themselves rather than through the algorithm
    instance, so `pio deploy --shards` must lift the block out of the
    algorithm params and hand it to the shard servers explicitly —
    otherwise an engine.json that asks for clustered retrieval would
    silently serve exact in fleet mode."""
    for _name, p in (ep.algorithms or []):
        block = p.get("retrieval") if isinstance(p, dict) \
            else getattr(p, "retrieval", None)
        if block:
            return block
    return None


def _absolutize_param_paths(ep, engine_dir: str):
    """Engine-dir-relative paths in params become absolute at load time, so
    `pio train --engine-dir X` behaves the same from any cwd. Any Params
    subclass opts in by declaring `path_fields = ("field", ...)` (e.g. the
    external-engine bridge's workdir)."""
    import dataclasses

    base = os.path.abspath(engine_dir)

    def fix(p):
        fields = getattr(p, "path_fields", ())
        if not fields:
            return p, False
        updates = {
            f: os.path.join(base, v)
            for f in fields
            if (v := getattr(p, f, "")) and not os.path.isabs(v)
        }
        return (dataclasses.replace(p, **updates), True) if updates \
            else (p, False)

    changed = False

    def fix_stage(stage):
        nonlocal changed
        if stage is None:
            return stage
        name, p = stage
        p2, did = fix(p) if p is not None else (p, False)
        changed |= did
        return (name, p2)

    algos = [fix_stage(s) for s in (ep.algorithms or [])]
    out = dataclasses.replace(
        ep,
        datasource=fix_stage(ep.datasource),
        preparator=fix_stage(ep.preparator),
        algorithms=algos,
        serving=fix_stage(ep.serving),
    )
    return out if changed else ep


def _engine_ids(variant: dict, engine_dir: str) -> tuple[str, str, str]:
    engine_id = variant.get("id") or os.path.basename(
        os.path.abspath(engine_dir)
    )
    return engine_id, variant.get("engineVersion", "1"), "default"


# ---------------------------------------------------------------------------
# verbs
# ---------------------------------------------------------------------------

def cmd_version(args) -> int:
    print(__version__)
    return 0


def cmd_status(args) -> int:
    """Environment doctor (reference Console.status:1035-1107)."""
    import jax

    print(f"pio-tpu {__version__}")
    print(f"Python {sys.version.split()[0]}, jax {jax.__version__}")
    devices = jax.devices()
    print(f"devices: {len(devices)} x {devices[0].platform}"
          f" ({devices[0].device_kind})")
    storage = get_storage()
    print("storage sources:")
    for name, spec in storage.sources.items():
        print(f"  {name}: type={spec.type} {spec.properties}")
    print("repositories:")
    for repo, src in storage.repositories.items():
        print(f"  {repo} -> {src}")
    errors = storage.verify_all()
    if errors:
        for e in errors:
            print(f"  [ERROR] {e}")
        return 1
    from pio_tpu.tools.daemon import status_all

    daemons = status_all(getattr(args, "pid_dir", None))
    if daemons:
        print("daemons:")
        for name, info in daemons.items():
            state = "up" if info["alive"] else "DOWN"
            print(f"  {name}: {state} (pid {info['pid']})")
    print("(sanity check passed)")
    return 0


def _doctor_fleet_tenants(args, fleet: dict, router_url: str) -> int:
    """`pio doctor --fleet` against a MULTI-TENANT router: one row per
    tenant — placement (instance, bytes, per-shard spread), quota
    consumption (admitted/shed/inflight), and per-tenant shard health.
    A tenant is AFFECTED (exit 1) when any of its shard groups has zero
    routable replicas or a shard serves a different instance than the
    placement recorded (last-good degradation after a corrupt blob).
    `--tenant KEY` scopes the exit code to that one tenant, so a page
    about tenant A does not fail a check run on healthy tenant B."""
    from pio_tpu.utils.httpclient import JsonHttpClient

    tenants = fleet.get("tenants", {})
    if args.tenant and args.tenant not in tenants:
        return _fail(f"tenant {args.tenant!r} is not on fleet "
                     f"{fleet.get('fleet')!r} "
                     f"(tenants: {sorted(tenants)})")
    rows = []
    for key, t in sorted(tenants.items()):
        placement = t.get("placement") or {}
        status = t.get("status") or {}
        quota = t.get("quota") or {}
        shards = status.get("shards", {})
        routable = sum(1 for g in shards.values() if g.get("ok"))
        # the router prober fills engineInstanceId asynchronously; probe
        # each replica ourselves (tenant-stamped) so doctor is accurate
        # even right after deploy
        served = set()
        for g in shards.values():
            for rep in g.get("replicas", ()):
                iid = rep.get("engineInstanceId")
                if not iid and rep.get("url"):
                    try:
                        info = JsonHttpClient(
                            rep["url"], timeout=args.timeout,
                        ).request("GET", "/shard/info",
                                  headers={"X-Pio-Tenant": key})
                        iid = info.get("engineInstanceId")
                    except Exception:
                        pass
                if iid:
                    served.add(str(iid))
        served = sorted(served)
        placed = placement.get("instanceId")
        last_good = bool(served and placed
                         and any(s != str(placed) for s in served))
        affected = routable < len(shards) or last_good
        rows.append({
            "tenant": key,
            "instanceId": placed,
            "servedInstances": served,
            "lastGoodFallback": last_good,
            "shardsRoutable": f"{routable}/{len(shards)}",
            "partitionBytes": placement.get("partitionBytes"),
            "shardBytes": placement.get("shardBytes"),
            "quotaQps": quota.get("quotaQps"),
            "admitted": quota.get("admitted"),
            "shed": quota.get("shedTotal"),
            "inflight": quota.get("inflight"),
            "instanceSkew": status.get("instanceSkew", False),
            "degradedResponses": status.get("degradedResponses", 0),
            "affected": affected,
        })
    if args.tenant:
        exit_code = int(any(r["affected"] for r in rows
                            if r["tenant"] == args.tenant))
    else:
        exit_code = int(any(r["affected"] for r in rows))
    if args.json:
        print(json.dumps({
            "router": router_url,
            "fleet": fleet.get("fleet"),
            "multiTenant": True,
            "nShards": fleet.get("nShards"),
            "nReplicas": fleet.get("nReplicas"),
            "memoryBudgetBytes": fleet.get("memoryBudgetBytes"),
            "shardLoads": fleet.get("shardLoads"),
            "tenants": rows,
        }, indent=2))
        return exit_code
    print(f"multi-tenant fleet {fleet.get('fleet')!r} at {router_url}: "
          f"{len(rows)} tenant(s) on {fleet.get('nShards')} shards x "
          f"{fleet.get('nReplicas')} replicas")
    print(f"  pool loads (bytes/shard): {fleet.get('shardLoads')}"
          + (f"  budget: {fleet.get('memoryBudgetBytes')}"
             if fleet.get("memoryBudgetBytes") else ""))
    print(f"{'tenant':<28} {'instance':<12} {'shards':>6} "
          f"{'bytes':>10} {'quota':>7} {'admitted':>8} {'shed':>6} "
          "state")
    for r in rows:
        qps = r["quotaQps"]
        state = []
        if r["lastGoodFallback"]:
            state.append(f"LAST-GOOD (serving {r['servedInstances']})")
        if r["shardsRoutable"].split("/")[0] == "0":
            state.append("DOWN")
        elif r["affected"] and not r["lastGoodFallback"]:
            state.append("DEGRADED")
        if r["instanceSkew"]:
            state.append("skew")
        if r["degradedResponses"]:
            state.append(f"degraded={r['degradedResponses']}")
        print(f"{r['tenant']:<28} {str(r['instanceId']):<12} "
              f"{r['shardsRoutable']:>6} "
              f"{r['partitionBytes'] or 0:>10} "
              f"{'-' if not qps else f'{qps:g}/s':>7} "
              f"{r['admitted'] or 0:>8} {r['shed'] or 0:>6} "
              f"{' '.join(state) or 'ok'}")
    affected = [r["tenant"] for r in rows if r["affected"]]
    if affected:
        print(f"[WARN] affected tenant(s): {', '.join(affected)} — "
              "co-resident tenants above report ok and keep serving")
    return exit_code


def _doctor_fleet(args) -> int:
    """`pio doctor --fleet`: one table over the whole serving fleet —
    shard plan, every shard/replica's /healthz + /readyz + serving
    instance, replication status per shard group, and open breakers as
    the router sees them. Endpoints come from the router's /fleet.json,
    so the only address the operator needs is the router's."""
    from pio_tpu.utils.httpclient import HttpClientError, JsonHttpClient

    router_url = args.router_url or f"http://{args.ip}:{args.serving_port}"
    client = JsonHttpClient(router_url, timeout=args.timeout)
    try:
        fleet = client.request("GET", "/fleet.json")
    except HttpClientError as e:
        return _fail(f"fleet router at {router_url} unreachable: "
                     f"{e.message}")
    if fleet.get("multiTenant"):
        return _doctor_fleet_tenants(args, fleet, router_url)
    plan = fleet.get("plan", {})
    rollout = fleet.get("rollout")
    rows = []
    foldin_lag: dict[str, dict] = {}
    candidate_coverage: dict[str, dict] = {}
    exit_code = 0
    for s, group in sorted(fleet.get("shards", {}).items(),
                           key=lambda kv: int(kv[0])):
        group_ready = 0
        group_stale: list[float] = []
        group_applied: list[int] = []
        group_candidates: list = []
        for rep in group["replicas"]:
            probe = JsonHttpClient(rep["url"], timeout=args.timeout)
            live = ready = False
            instance = rep.get("engineInstanceId")
            candidate = rep.get("candidateInstanceId")
            foldin = None
            retrieval = None
            plan_version = rep.get("planVersion")
            try:
                probe.request("GET", "/healthz")
                live = True
                probe.request("GET", "/readyz")
                ready = True
                info = probe.request("GET", "/shard/info")
                instance = info.get("engineInstanceId", instance)
                candidate = info.get("candidateInstanceId", candidate)
                foldin = info.get("foldin")
                retrieval = info.get("retrieval")
                plan_version = info.get("planVersion", plan_version)
            except HttpClientError:
                pass
            group_ready += ready
            group_candidates.append(candidate)
            if foldin:
                group_applied.append(int(foldin.get("appliedUsers") or 0))
                if foldin.get("stalenessSeconds") is not None:
                    group_stale.append(float(foldin["stalenessSeconds"]))
            rows.append({
                "shard": int(s), "replica": rep["replica"],
                "url": rep["url"], "live": live, "ready": ready,
                "breaker": rep["breaker"], "instance": instance,
                "candidate": candidate,
                "foldin": foldin,
                "retrieval": retrieval,
                "planVersion": plan_version,
                # internal RPC plane (docs/performance.md): the
                # router's client-side connection-reuse ratio toward
                # this replica and the negotiated wire — a 0% reuse
                # replica under steady traffic means every RPC
                # re-dialed (keep-alive-stripping proxy, idle timeout
                # below the query cadence): a latency page in the
                # making, visible here first
                "connReuse": rep.get("connReuse"),
                "binaryWire": rep.get("binaryWire"),
            })
        # per-group candidate coverage (guarded rollout): how many
        # replicas have the canary candidate staged — a group at 0/N
        # cannot serve its slice of the candidate's partition
        candidate_coverage[s] = {
            "staged": sum(1 for c in group_candidates if c),
            "total": len(group_candidates),
            "instances": sorted({c for c in group_candidates if c}),
        }
        # per-group fold-in lag: MAX staleness any replica recorded at
        # its last apply, plus replica skew (a replica that missed
        # upserts — e.g. it was down during a fold — serves older rows
        # than its group mates until the next fold or /reload)
        foldin_lag[s] = {
            "maxStalenessSeconds": max(group_stale) if group_stale
            else None,
            "appliedUsers": group_applied,
            "replicaSkew": len(set(group_applied)) > 1,
            "overBudget": bool(group_stale
                               and max(group_stale)
                               > args.staleness_budget),
        }
        # fail on the router's breaker view OR the doctor's own probes:
        # on an IDLE fleet breakers never trip (they only open on failed
        # calls), so a dead group still reports routable until traffic
        # starts failing — the direct /readyz probe catches it now
        if not group["ok"] or group_ready == 0:
            exit_code = 1
    # plan-version agreement (live elastic resharding): every replica
    # should serve the router's plan version; a straggler answers
    # old-topology fans correctly (retired arm) but marks a replica
    # that missed the activate fan and is waiting on /reload
    router_pv = plan.get("planVersion")
    stale_plan = [f"shard{r['shard']}/replica{r['replica']}"
                  f"(v{r['planVersion']})"
                  for r in rows
                  if r["planVersion"] is not None
                  and router_pv is not None
                  and int(r["planVersion"]) != int(router_pv)]
    reshard = fleet.get("reshard")
    open_breakers = [f"shard{r['shard']}/replica{r['replica']}"
                     for r in rows if r["breaker"] == "open"]
    replication = {
        s: f"{g['routable']}/{len(g['replicas'])}"
        for s, g in sorted(fleet.get("shards", {}).items(),
                           key=lambda kv: int(kv[0]))
    }
    # two-stage retrieval (ops/retrieval.py): per-group mode/dtype/
    # nprobe, quantized sidecar vs f32 bytes, items headroom under the
    # budget. Replicas of one group MUST agree on mode — a replica
    # quietly serving exact while its group mates serve clustered
    # changes failover semantics (and latency) silently on the next
    # replica scan, so disagreement is an operator page, not a detail
    retr_by_group: dict[int, list] = {}
    for r in rows:
        if r.get("retrieval"):
            retr_by_group.setdefault(r["shard"], []).append(r["retrieval"])
    retr_cells: list[str] = []
    retr_disagree: list[str] = []
    for s, infos in sorted(retr_by_group.items()):
        modes = sorted({str(i.get("mode")) for i in infos})
        if len(modes) > 1:
            retr_disagree.append(f"shard {s}: {'/'.join(modes)}")
        i0 = infos[0]
        cell = f"shard {s}: {i0.get('mode')}"
        if i0.get("mode") == "clustered":
            hd = i0.get("itemsHeadroom")
            cell += (f"/{i0.get('dtype')} nprobe={i0.get('nprobe')} "
                     f"quantized {i0.get('quantizedBytes')}B vs f32 "
                     f"{i0.get('f32ItemBytes')}B headroom "
                     f"{'-' if hd is None else hd}")
        retr_cells.append(cell)
    batching = fleet.get("batching") or {"enabled": False}
    if args.json:
        print(json.dumps({
            "router": router_url, "plan": plan, "replicas": rows,
            "replication": replication, "openBreakers": open_breakers,
            "instanceSkew": fleet.get("instanceSkew", False),
            "degradedResponses": fleet.get("degradedResponses", 0),
            "foldinLag": foldin_lag,
            "stalenessBudgetSeconds": args.staleness_budget,
            "rollout": rollout,
            "candidateCoverage": candidate_coverage,
            "planVersion": router_pv,
            "stalePlanReplicas": stale_plan,
            "reshard": reshard,
            "retrievalModeDisagreement": retr_disagree,
            "batching": batching,
        }, indent=2))
        return exit_code
    print(f"fleet router {router_url}: instance {plan.get('instanceId')} "
          f"plan {plan.get('planHash')} v{plan.get('planVersion')} "
          f"({plan.get('nShards')} shards x {plan.get('nReplicas')} "
          "replicas)")
    print(f"  users/shard: {plan.get('userCounts')}  "
          f"items/shard: {plan.get('itemCounts')}")
    print(f"{'shard':>5} {'rep':>3} {'live':<5} {'ready':<5} "
          f"{'breaker':<9} {'instance':<12} {'wire':<6} {'reuse':>6} url")
    for r in rows:
        reuse = r.get("connReuse")
        wire = r.get("binaryWire")
        print(f"{r['shard']:>5} {r['replica']:>3} "
              f"{'up' if r['live'] else 'DOWN':<5} "
              f"{'yes' if r['ready'] else 'NO':<5} "
              f"{r['breaker']:<9} {str(r['instance']):<12} "
              f"{'binary' if wire else ('json' if wire is False else '-'):<6} "
              f"{'-' if reuse is None else f'{reuse:.0%}':>6} {r['url']}")
    print("replication (routable/total): "
          + ", ".join(f"shard {s}: {v}" for s, v in replication.items()))
    zero_reuse = [f"shard{r['shard']}/replica{r['replica']}"
                  for r in rows if r.get("connReuse") == 0.0]
    if zero_reuse:
        print("[WARN] 0% connection reuse toward: "
              + ", ".join(zero_reuse)
              + " — every RPC re-dials (a keep-alive-stripping proxy or "
              "an idle timeout below the query cadence?)")
    lag_cells = []
    for s, lag in sorted(foldin_lag.items(), key=lambda kv: int(kv[0])):
        ms = lag["maxStalenessSeconds"]
        cell = f"shard {s}: {'-' if ms is None else f'{ms:.1f}s'}"
        if lag["replicaSkew"]:
            cell += " (replica skew)"
        lag_cells.append(cell)
    if lag_cells:
        print("fold-in lag (max staleness at last apply): "
              + ", ".join(lag_cells))
    if retr_cells:
        print("retrieval: " + ", ".join(retr_cells))
    # continuous batching (docs/serving.md): coalescer health. Mean
    # occupancy pinned at ~1.0 means every window fills to max_batch —
    # arrivals are queuing behind full dispatches, so p99 is climbing;
    # widen --coalesce-window-ms gains nothing at that point (the batch
    # is already full): raise max batch or add replicas
    if batching.get("enabled"):
        occ = batching.get("meanOccupancy")
        wait = (batching.get("coalesceWaitMs") or {}).get("p50")
        print(f"batching: window {batching.get('windowMs')}ms "
              f"max {batching.get('maxBatch')} — "
              f"{batching.get('coalescedQueries', 0)} queries over "
              f"{batching.get('coalescedCalls', 0)} batched dispatches, "
              f"occupancy {'-' if occ is None else f'{occ:.2f}'} mean, "
              f"coalesce wait p50 "
              f"{'-' if wait is None else f'{wait:.2f}ms'}")
        if occ is not None and occ >= 0.95:
            print("[WARN] batch occupancy ~1.0: every coalesce window "
                  "fills to max batch — queries queue behind full "
                  "dispatches. Raise the max batch or add replicas; a "
                  "wider window will not help")
    if retr_disagree:
        print("[WARN] retrieval mode disagreement within shard "
              "group(s): " + "; ".join(retr_disagree)
              + " — replicas of one group must serve the same candidate "
              "tier (check --retrieval-* flags / the engine's retrieval "
              "block on the odd replica out)")
    over = sorted((s for s, lag in foldin_lag.items()
                   if lag["overBudget"]), key=int)
    if over:
        print(f"[WARN] fold-in staleness over the "
              f"{args.staleness_budget:.0f}s budget in shard group(s): "
              f"{', '.join(over)}")
    if rollout and rollout.get("candidateInstanceId"):
        state = rollout.get("verdict") or f"{rollout.get('stagePct')}%"
        print(f"rollout: candidate {rollout['candidateInstanceId']} "
              f"[{state}] {rollout.get('timeInStageSeconds', 0):.0f}s "
              "in stage")
        cov_cells = [
            f"shard {s}: {c['staged']}/{c['total']}"
            for s, c in sorted(candidate_coverage.items(),
                               key=lambda kv: int(kv[0]))
        ]
        print("candidate coverage (staged/total): " + ", ".join(cov_cells))
        under = [s for s, c in candidate_coverage.items()
                 if rollout.get("verdict") is None
                 and c["staged"] < c["total"]]
        if under:
            print(f"[WARN] candidate not staged on every replica of "
                  f"shard group(s): {', '.join(sorted(under, key=int))}")
    if reshard and reshard.get("inFlight"):
        print(f"reshard: {reshard.get('nShardsOld')} -> "
              f"{reshard.get('nShardsNew')} shard(s) in flight — "
              f"{reshard.get('partitionsStaged', 0)}/"
              f"{reshard.get('partitionsMoving', 0)} partition(s) "
              f"staged (plan v{reshard.get('planVersionOld')} -> "
              f"v{reshard.get('planVersionNew')})")
    elif reshard and reshard.get("verdict"):
        print(f"reshard: last migration {reshard['verdict']} "
              f"({reshard.get('reason') or 'no reason recorded'})")
    if stale_plan:
        print("[WARN] plan-version disagreement: router serves "
              f"plan v{router_pv} but {', '.join(stale_plan)} "
              "answer(s) an older version — replica(s) missed the "
              "reshard activate fan; a /reload (or `pio reshard "
              "--status` until convergence) clears it")
    if open_breakers:
        print(f"[WARN] open breakers: {', '.join(open_breakers)}")
    if fleet.get("instanceSkew"):
        print("[WARN] instance skew: shards serve different engine "
              "instances (a corrupt partition fell back last-good; "
              "retrain or repartition to converge)")
    if fleet.get("degradedResponses"):
        print(f"degraded responses served: {fleet['degradedResponses']}")
    return exit_code


def _doctor_storage(args) -> int:
    """`pio doctor --storage`: the replicated event store's health in
    one table — per-replica live/breaker/hint-depth/oldest-hint-age,
    quorum status (exit 1 on lost quorum: fewer live replicas than the
    write quorum means acked writes would start failing), the last
    scrub record, and a LIVE read-only convergence check (per-app
    bucket-digest comparison; `--scrub` repairs divergent buckets in
    the same pass). Reads THIS process's PIO_STORAGE_* config, like
    `pio status` — run it where the event tier runs so it sees the
    same replica set and hint directory."""
    storage = get_storage()
    try:
        dao = storage.get_events()
    except Exception as e:  # noqa: BLE001 - doctor reports, never dies
        return _fail(f"could not open the EVENTDATA source: {e}")
    status_fn = getattr(dao, "replication_status", None)
    if status_fn is None:
        return _fail(
            "the EVENTDATA source is not replicated — `doctor --storage` "
            "inspects a `replicated` backend (docs/storage.md)")
    st = status_fn(probe=True)
    live = st.get("liveReplicas",
                  sum(1 for r in st["replicas"] if r["live"]))
    # the sharded composition's verdict is per GROUP (every group must
    # hold its own quorum); the flat live>=W test covers single-group
    quorum_ok = st.get("quorumOk", live >= st["writeQuorum"])

    # live convergence check across every known namespace (apps +
    # channels from the metadata source); --scrub repairs in-pass
    scrub_results: list[dict] = []
    scrub_error = ""
    try:
        apps = storage.get_metadata_apps().get_all()
        channels = storage.get_metadata_channels()
        for app in apps:
            namespaces: list[int | None] = [None]
            namespaces += [c.id for c in channels.get_by_appid(app.id)]
            for ch in namespaces:
                try:
                    scrub_results.append(dao.scrub(
                        app.id, ch, repair=bool(args.scrub)))
                except Exception as e:  # noqa: BLE001 - a namespace
                    # that cannot be read is reported, not fatal
                    scrub_results.append({
                        "appId": app.id, "channelId": ch,
                        "error": f"{type(e).__name__}: {e}"})
    except Exception as e:  # noqa: BLE001 - doctor reports, never dies
        scrub_error = f"{type(e).__name__}: {e}"
    divergent = sum(r.get("divergentBuckets", 0) for r in scrub_results)
    repaired = sum(r.get("repairedEvents", 0) for r in scrub_results)

    if args.json:
        print(json.dumps({
            "replication": st,
            "liveReplicas": live,
            "quorumOk": quorum_ok,
            "convergence": scrub_results,
            "divergentBuckets": divergent,
            "repairedEvents": repaired,
            **({"scrubError": scrub_error} if scrub_error else {}),
        }, indent=2))
        return 0 if quorum_ok else 1

    print(f"replicated event store: {st['n']} replicas, write quorum "
          f"{st['writeQuorum']}, {live} live")
    for g in st.get("groups", ()):
        ok = ("ok" if g.get("quorumOk", True) else "QUORUM LOST")
        print(f"  shard group {g['shard']}: "
              f"{g.get('liveReplicas', '?')}/{g['n']} live, "
              f"quorum {g['writeQuorum']} — {ok}")
    print(f"{'replica':>7} {'live':<5} {'breaker':<9} {'hints':>6} "
          f"{'oldest':>8} {'corrupt':>7}")
    for r in st["replicas"]:
        age = r["hintOldestAgeSeconds"]
        print(f"{r['replica']:>7} {'up' if r['live'] else 'DOWN':<5} "
              f"{r['breaker']:<9} {r['hintDepth']:>6} "
              f"{'-' if age is None else f'{age:.0f}s':>8} "
              f"{r['hintsCorrupt']:>7}")
    c = st["counters"]
    print(f"lifetime: hinted {c['hinted']}, drained {c['drained']}, "
          f"dropped {c['hintsDropped']}, read-repairs {c['readRepairs']}")
    last = (st.get("scrub") or {})
    if last.get("lastScrubTs"):
        import datetime as _dt

        when = _dt.datetime.fromtimestamp(last["lastScrubTs"])
        res = last.get("lastResult") or {}
        print(f"last scrub: {when:%Y-%m-%d %H:%M:%S} — "
              f"{res.get('divergentBuckets', '?')} divergent bucket(s), "
              f"{res.get('repairedEvents', '?')} event(s) repaired")
    else:
        print("last scrub: never")
    verb = "repair" if args.scrub else "check"
    print(f"convergence {verb}: {len(scrub_results)} namespace(s), "
          f"{divergent} divergent bucket(s)"
          + (f", {repaired} event(s) repaired" if args.scrub else ""))
    if scrub_error:
        print(f"[WARN] convergence check failed: {scrub_error}")
    for r in scrub_results:
        if r.get("error"):
            print(f"[WARN] app {r['appId']} channel {r['channelId']}: "
                  f"{r['error']}")
    if not quorum_ok:
        print(f"[FAIL] write quorum LOST: {live} live < "
              f"{st['writeQuorum']} required — acked writes will fail "
              "until a replica rejoins")
    return 0 if quorum_ok else 1


def cmd_doctor(args) -> int:
    """Resilience doctor: poll every server surface's /healthz (liveness)
    + /readyz (readiness) and print the per-check detail — storage
    circuit-breaker states, load-shedder queue depth, eventserver spill
    backlog, the serving model's instance. The aggregate view `pio
    status` cannot give: status inspects THIS process's storage config;
    doctor inspects the RUNNING stack's health surfaces. With --fleet,
    inspects a sharded serving fleet through its router; with
    --storage, the replicated event store's replicas/hints/convergence."""
    from pio_tpu.utils.httpclient import HttpClientError, JsonHttpClient

    if getattr(args, "fleet", False):
        return _doctor_fleet(args)
    if getattr(args, "storage", False):
        return _doctor_storage(args)

    surfaces = {
        "eventserver": args.eventserver_port,
        "serving": args.serving_port,
        "adminserver": args.adminserver_port,
        "storageserver": args.storageserver_port,
        "dashboard": args.dashboard_port,
        # the freshness row: the fold-in worker's /healthz carries
        # staleness_seconds + queue depth, its /readyz flips past the
        # staleness budget (docs/freshness.md)
        "foldin": args.foldin_port,
    }
    report: dict[str, dict] = {}
    exit_code = 0
    for name, port in surfaces.items():
        url = f"http://{args.ip}:{port}"
        client = JsonHttpClient(url, timeout=args.timeout)
        entry: dict = {"url": url}
        try:
            client.request("GET", "/healthz")
            entry["live"] = True
        except HttpClientError as e:
            entry["live"] = False
            entry["error"] = e.message
            report[name] = entry
            continue  # down surfaces are reported, not failed: doctor
            # judges the health of what IS running
        try:
            ready = client.request("GET", "/readyz")
        except HttpClientError as e:
            # 503 carries the readiness payload in its message body;
            # surface the raw state either way
            entry["ready"] = False
            entry["detail"] = e.message
            exit_code = 1
            report[name] = entry
            continue
        entry["ready"] = bool(ready.get("ready"))
        entry["checks"] = ready.get("checks", {})
        if not entry["ready"]:
            exit_code = 1
        report[name] = entry

    # guarded rollout row: what (if anything) is canarying on the
    # serving surface — stage, verdict, per-arm guard stats
    rollout = None
    if report.get("serving", {}).get("live"):
        try:
            status = JsonHttpClient(
                report["serving"]["url"], timeout=args.timeout
            ).request("GET", "/rollout/status")
            if status and status.get("candidateInstanceId"):
                rollout = status
        except HttpClientError:
            pass

    # training-lifecycle sweep: kill -9'd runs leave INIT/TRAINING
    # instances whose heartbeat went stale; report them (and, with
    # --sweep-zombies, transition them to FAILED so they become
    # explicitly resumable and can never starve deploy's
    # get_latest_completed contract)
    zombies: list[dict] = []
    sweep_error = ""
    try:
        from pio_tpu.workflow.lifecycle import stale_instances, sweep_zombies

        storage = get_storage()
        stale_s = getattr(args, "zombie_stale_s", 600.0)
        if getattr(args, "sweep_zombies", False):
            found = sweep_zombies(storage, stale_after_s=stale_s)
            action = "swept"
        else:
            found = stale_instances(storage, stale_after_s=stale_s)
            action = "stale"
        zombies = [
            {"id": i.id, "status": i.status, "action": action,
             "lastStep": (i.progress or {}).get("step"),
             "heartbeat": (i.progress or {}).get("heartbeat")}
            for i in found
        ]
    except Exception as e:  # noqa: BLE001 - doctor reports, never dies
        sweep_error = f"{type(e).__name__}: {e}"

    # eval/tuning row: the last completed sweep's verdict, and whether
    # production actually serves the winning params — a COMPLETED
    # instance batch-tagged `from-eval:<id>` was trained by
    # `pio train --from-eval` from that sweep's best_params record
    eval_row = None
    eval_error = ""
    try:
        from pio_tpu.tuning.records import latest_best_params

        storage = get_storage()
        found = latest_best_params(storage)
        if found is not None:
            inst, payload = found
            completed = [
                i for i in
                storage.get_metadata_engine_instances().get_all()
                if i.status == "COMPLETED"
            ]
            if payload.get("engineId"):
                # NO fallback to other engines' instances: a sweep for
                # an engine that was never trained must report "not
                # trained yet", not point at an unrelated engine
                completed = [i for i in completed
                             if i.engine_id == payload["engineId"]]
            completed.sort(key=lambda i: i.start_time, reverse=True)
            prod = completed[0] if completed else None
            marker = f"from-eval:{inst.id}"
            eval_row = {
                "evaluationInstanceId": inst.id,
                "completedAt": inst.end_time.isoformat(),
                "metric": payload.get("metric"),
                "bestScore": payload.get("score"),
                "productionInstanceId": prod.id if prod else None,
                "productionBatch": prod.batch if prod else None,
                # substring match: `pio train --from-eval --batch X`
                # appends the marker to the operator's label
                "productionHasBestParams": bool(
                    prod and marker in (prod.batch or "")),
            }
    except Exception as e:  # noqa: BLE001 - doctor reports, never dies
        eval_error = f"{type(e).__name__}: {e}"

    chaos_spec = os.environ.get("PIO_TPU_CHAOS", "")
    if args.json:
        out = {"surfaces": report, "zombies": zombies}
        if rollout is not None:
            out["rollout"] = rollout
        if eval_row is not None:
            out["eval"] = eval_row
        if eval_error:
            out["evalError"] = eval_error
        if sweep_error:
            out["zombieSweepError"] = sweep_error
        if chaos_spec:
            out["chaos"] = chaos_spec
        print(json.dumps(out, indent=2))
        return exit_code

    if chaos_spec:
        print(f"[WARN] chaos injection active: PIO_TPU_CHAOS={chaos_spec}")
    for name, entry in report.items():
        if not entry["live"]:
            print(f"{name:14s} DOWN    {entry['url']}  ({entry['error']})")
            continue
        state = "ready" if entry.get("ready") else "NOT READY"
        print(f"{name:14s} up      {entry['url']}  {state}")
        for check, detail in sorted(entry.get("checks", {}).items()):
            ok = "ok " if detail.get("ok") else "FAIL"
            rest = {k: v for k, v in detail.items() if k != "ok"}
            print(f"  [{ok}] {check}: {rest}")
        if not entry.get("ready") and "detail" in entry:
            print(f"  detail: {entry['detail']}")
    if rollout is not None:
        state = rollout.get("verdict") or f"{rollout.get('stagePct')}%"
        arms = rollout.get("arms", {})
        cells = ", ".join(
            f"{arm}: {s.get('requests', 0)} req / {s.get('errors', 0)} err "
            f"/ {s.get('empty', 0)} empty"
            for arm, s in sorted(arms.items()))
        print(f"rollout        candidate {rollout.get('candidateInstanceId')}"
              f" [{state}] {rollout.get('timeInStageSeconds', 0):.0f}s "
              f"in stage — {cells}")
        div = (rollout.get("shadow") or {}).get("meanDivergence")
        if div is not None:
            print(f"  shadow divergence: {div} over "
                  f"{rollout['shadow'].get('samples', 0)} sample(s)")
    if eval_row is not None:
        score = eval_row["bestScore"]
        score_s = "nan" if score is None else f"{score:.4f}"
        print(f"eval           last sweep {eval_row['evaluationInstanceId']}"
              f" best {eval_row['metric']}={score_s}")
        if eval_row["productionInstanceId"] is None:
            print("  production: no COMPLETED engine instance yet — "
                  f"pio train --from-eval "
                  f"{eval_row['evaluationInstanceId']}")
        elif eval_row["productionHasBestParams"]:
            print(f"  production: instance "
                  f"{eval_row['productionInstanceId']} trained from "
                  "this sweep (best-known params in production)")
        else:
            print(f"  [WARN] production instance "
                  f"{eval_row['productionInstanceId']} was NOT trained "
                  "from the winning params — pio train --from-eval "
                  f"{eval_row['evaluationInstanceId']}")
    if eval_error:
        print(f"[WARN] eval check failed: {eval_error}")
    if sweep_error:
        print(f"[WARN] zombie check failed: {sweep_error}")
    for z in zombies:
        verb = ("swept to FAILED (resumable)" if z["action"] == "swept"
                else "stale (run doctor --sweep-zombies to mark FAILED)")
        print(f"zombie instance {z['id']} [{z['status']}] last step "
              f"{z['lastStep']} heartbeat {z['heartbeat']}: {verb}")
    return exit_code


def cmd_run(args) -> int:
    """Run a user script in the workflow environment (reference
    Console.scala `run` verb: arbitrary main class on the configured
    cluster; here: in-process with storage + mesh config active)."""
    import runpy

    script = args.script
    if not os.path.exists(script):
        return _fail(f"script {script} not found")
    get_storage()  # fail fast on storage misconfiguration
    saved_argv, saved_path = sys.argv, list(sys.path)
    sys.argv = [script] + list(args.args or [])
    sys.path.insert(0, os.path.dirname(os.path.abspath(script)) or ".")
    try:
        runpy.run_path(script, run_name="__main__")
    finally:
        sys.argv, sys.path[:] = saved_argv, saved_path
    return 0


def cmd_shell(args) -> int:
    """Interactive REPL with the storage + event store preloaded
    (reference bin/pio-shell: spark-shell with the assembly on the
    classpath)."""
    import code

    from pio_tpu.data.eventstore import EventStore

    storage = get_storage()
    ns = {
        "storage": storage,
        "events": storage.get_events(),
        "apps": storage.get_metadata_apps(),
        "event_store": EventStore(storage),
    }
    banner = (
        f"pio-tpu {__version__} shell\n"
        "preloaded: storage, events, apps, event_store"
    )
    code.interact(banner=banner, local=ns)
    return 0


def cmd_app(args) -> int:
    storage = get_storage()
    apps = storage.get_metadata_apps()
    keys = storage.get_metadata_access_keys()
    channels = storage.get_metadata_channels()
    sub = args.subcommand
    if sub == "new":
        created = appops.create_app(
            storage, args.name, args.description,
            app_id=args.id or 0, access_key=args.access_key or "",
        )
        if created is None:
            return _fail(f"App {args.name} already exists.")
        app_id, key = created
        print(f"App '{args.name}' created (id {app_id}).")
        print(f"Access key: {key}")
        return 0
    if sub == "list":
        for a in sorted(apps.get_all(), key=lambda a: a.id):
            ks = keys.get_by_appid(a.id)
            print(f"{a.id:>6}  {a.name:<24} keys={len(ks)}")
        return 0
    if sub == "show":
        a = apps.get_by_name(args.name)
        if a is None:
            return _fail(f"App {args.name} does not exist.")
        print(f"App: {a.name} (id {a.id})")
        print(f"Description: {a.description or ''}")
        for k in keys.get_by_appid(a.id):
            events = ",".join(k.events) or "(all)"
            print(f"  key {k.key} events={events}")
        for c in channels.get_by_appid(a.id):
            print(f"  channel {c.id}: {c.name}")
        return 0
    if sub == "delete":
        a = apps.get_by_name(args.name)
        if a is None:
            return _fail(f"App {args.name} does not exist.")
        appops.delete_app(storage, a)
        print(f"App '{args.name}' deleted.")
        return 0
    if sub == "data-delete":
        a = apps.get_by_name(args.name)
        if a is None:
            return _fail(f"App {args.name} does not exist.")
        channel_id = None
        if args.channel:
            ch = next((c for c in channels.get_by_appid(a.id)
                       if c.name == args.channel), None)
            if ch is None:
                return _fail(f"Channel {args.channel} does not exist.")
            channel_id = ch.id
        appops.delete_app_data(storage, a, channel_id)
        print(f"Data of app '{args.name}' deleted.")
        return 0
    if sub == "trim":
        from pio_tpu.utils.time import parse_time

        a = apps.get_by_name(args.name)
        if a is None:
            return _fail(f"App {args.name} does not exist.")
        dst = apps.get_by_name(args.dst)
        if dst is None:
            return _fail(f"Destination app {args.dst} does not exist "
                         "(create it with `pio app new` first).")
        try:
            counts = appops.trim_copy(
                storage, a, dst,
                start_time=parse_time(args.start) if args.start else None,
                until_time=parse_time(args.until) if args.until else None,
                channel_name=args.channel or None,
            )
        except ValueError as e:
            return _fail(str(e))
        total = sum(counts.values())
        detail = ", ".join(f"{k}: {v}" for k, v in counts.items())
        print(f"Copied {total} events from '{a.name}' to '{dst.name}' "
              f"({detail}).")
        return 0
    if sub == "cleanup":
        from pio_tpu.utils.time import parse_time

        a = apps.get_by_name(args.name)
        if a is None:
            return _fail(f"App {args.name} does not exist.")
        try:
            counts = appops.cleanup_events(
                storage, a,
                until_time=parse_time(args.until),  # --until is required
                channel_name=args.channel or None,
            )
        except ValueError as e:
            return _fail(str(e))
        total = sum(counts.values())
        detail = ", ".join(f"{k}: {v}" for k, v in counts.items())
        print(f"Deleted {total} events from '{a.name}' ({detail}).")
        return 0
    if sub == "channel-new":
        a = apps.get_by_name(args.name)
        if a is None:
            return _fail(f"App {args.name} does not exist.")
        if not Channel.is_valid_name(args.channel):
            return _fail(
                f"Channel name {args.channel} is invalid "
                "(1-16 alphanumeric/dash characters)."
            )
        cid = channels.insert(Channel(0, args.channel, a.id))
        if cid is None:
            return _fail(f"Channel {args.channel} could not be created.")
        storage.get_events().init(a.id, cid)
        print(f"Channel '{args.channel}' (id {cid}) created for app "
              f"'{args.name}'.")
        return 0
    if sub == "channel-delete":
        a = apps.get_by_name(args.name)
        if a is None:
            return _fail(f"App {args.name} does not exist.")
        ch = next((c for c in channels.get_by_appid(a.id)
                   if c.name == args.channel), None)
        if ch is None:
            return _fail(f"Channel {args.channel} does not exist.")
        storage.get_events().remove(a.id, ch.id)
        channels.delete(ch.id)
        print(f"Channel '{args.channel}' deleted.")
        return 0
    return _fail(f"unknown app subcommand {sub}")


def cmd_accesskey(args) -> int:
    storage = get_storage()
    keys = storage.get_metadata_access_keys()
    if args.subcommand == "new":
        a = storage.get_metadata_apps().get_by_name(args.app_name)
        if a is None:
            return _fail(f"App {args.app_name} does not exist.")
        key = keys.insert(
            AccessKey("", a.id, tuple(args.event or ()))
        )
        print(f"Access key: {key}")
        return 0
    if args.subcommand == "list":
        app_filter = None
        if args.app_name:
            a = storage.get_metadata_apps().get_by_name(args.app_name)
            if a is None:
                return _fail(f"App {args.app_name} does not exist.")
            app_filter = a.id
        for k in keys.get_all():
            if app_filter is not None and k.appid != app_filter:
                continue
            events = ",".join(k.events) or "(all)"
            print(f"{k.key} app={k.appid} events={events}")
        return 0
    if args.subcommand == "delete":
        keys.delete(args.key)
        print(f"Access key {args.key} deleted.")
        return 0
    return _fail(f"unknown accesskey subcommand {args.subcommand}")


def cmd_build(args) -> int:
    """Check the engine dir: engine.json parses + factory imports
    (replaces the reference's sbt package, Console.compile:933-997)."""
    variant = _load_variant(args.engine_dir)
    engine, ep = _engine_from_variant(variant, args.engine_dir)
    print(f"Engine factory {variant['engineFactory']} loads; "
          f"{len(ep.algorithms)} algorithm(s) configured.")
    return 0


def cmd_train(args) -> int:
    from pio_tpu.workflow.context import create_workflow_context
    from pio_tpu.workflow.lifecycle import EXIT_PREEMPTED, TrainingPreempted
    from pio_tpu.workflow.train import run_train

    if args.resume and args.auto_resume:
        return _fail("--resume and --auto-resume are mutually exclusive")
    variant = _load_variant(args.engine_dir)
    engine, ep = _engine_from_variant(variant, args.engine_dir)
    engine_id, engine_version, engine_variant = _engine_ids(
        variant, args.engine_dir
    )
    from pio_tpu.controller.base import TrainingInterruption

    storage = get_storage()
    batch = args.batch or ""
    if getattr(args, "from_eval", ""):
        ep, eval_id = _apply_from_eval(engine, ep, storage,
                                       args.from_eval)
        # the batch marker is how `pio doctor` knows production runs
        # the sweep's winner (docs/evaluation.md "Close the loop") —
        # APPENDED to an operator-supplied batch label, never displaced
        # by it (doctor matches by substring)
        marker = f"from-eval:{eval_id}"
        batch = f"{batch} {marker}".strip()
        print(f"Training with best params from evaluation {eval_id}")
    ctx = create_workflow_context(storage, use_mesh=not args.no_mesh)
    try:
        instance_id = run_train(
            engine, ep, storage,
            engine_id=engine_id, engine_version=engine_version,
            engine_variant=engine_variant,
            engine_factory=variant["engineFactory"],
            batch=batch,
            ctx=ctx,
            stop_after_read=args.stop_after_read,
            stop_after_prepare=args.stop_after_prepare,
            resume_instance_id=args.resume or None,
            auto_resume=args.auto_resume,
            checkpoint_root=args.checkpoint_root or None,
        )
    except TrainingPreempted as e:
        # preemption honored: checkpoint on disk, instance INTERRUPTED.
        # EXIT_PREEMPTED (75, EX_TEMPFAIL) tells supervisors this run
        # wants `pio train --resume` (or --auto-resume), not a bug report.
        print(f"Training preempted ({e}); resume with: "
              "pio train --auto-resume")
        return EXIT_PREEMPTED
    except TrainingInterruption as e:
        # controlled debug stop (reference --stop-after-read/-prepare)
        print(f"Training interrupted: {e}")
        return 0
    print(f"Training completed. Engine instance: {instance_id}")
    return 0


def cmd_eval(args) -> int:
    if args.sweep:
        return _eval_sweep(args)
    if not args.evaluation_class or not args.params_generator_class:
        return _fail("pio eval takes either --sweep (grid mode) or "
                     "<EvaluationClass> <ParamsGeneratorClass>")
    from pio_tpu.workflow.evaluate import run_evaluation_class

    evaluation = _load_factory(args.evaluation_class, args.engine_dir)
    generator = _load_factory(args.params_generator_class, args.engine_dir)
    instance_id, result = run_evaluation_class(
        evaluation, generator, get_storage(),
        output_path=args.output or None,
        workers=args.workers,
    )
    print(f"Evaluation completed. Instance: {instance_id}")
    print(f"Best score: [{result.best_score.score}]")
    print(f"Best params: {result.best_engine_params.to_json()}")
    return 0


def _sweep_candidates(engine, base_ep, args) -> list:
    """The candidate grid: either an EngineParamsGenerator class (full
    EngineParams control) or a --grid JSON over the FIRST algorithm's
    params — {"lambda_": [0.01, 0.1], "rank": [8, 16]} expands to the
    cartesian product, each candidate overriding engine.json's params."""
    import dataclasses
    import itertools

    if args.params_generator:
        gen = _load_factory(args.params_generator, args.engine_dir)
        return gen.params_list()
    if not args.grid:
        raise ValueError(
            "--sweep needs --grid '{\"param\": [values...]}' (or "
            "@file.json) or --params-generator pkg.Class")
    spec = args.grid
    if spec.startswith("@"):
        with open(spec[1:]) as f:
            grid = json.load(f)
    else:
        grid = json.loads(spec)
    if not isinstance(grid, dict) or not grid:
        raise ValueError("--grid must be a non-empty JSON object of "
                         "param name -> list of values")
    base_algos = base_ep.algorithms or [("", None)]
    algo_name, algo_params = base_algos[0]
    keys = sorted(grid)           # deterministic candidate order
    values = []
    for k in keys:
        v = grid[k]
        values.append(v if isinstance(v, list) else [v])
    candidates = []
    for combo in itertools.product(*values):
        overrides = dict(zip(keys, combo))
        if dataclasses.is_dataclass(algo_params):
            try:
                p = dataclasses.replace(algo_params, **overrides)
            except TypeError:
                valid = sorted(
                    f.name for f in dataclasses.fields(algo_params))
                bad = sorted(set(overrides) - set(valid))
                raise ValueError(
                    f"--grid key(s) {bad} are not params of "
                    f"{type(algo_params).__name__} (valid: "
                    f"{', '.join(valid)})") from None
        else:
            p = {**(algo_params or {}), **overrides}
        # vary ONLY the first algorithm; a multi-algo engine keeps its
        # trailing algorithms in every candidate (and in the persisted
        # winner --from-eval deploys)
        candidates.append(dataclasses.replace(
            base_ep, algorithms=[(algo_name, p), *base_algos[1:]]))
    return candidates


def _eval_sweep(args) -> int:
    """`pio eval --sweep` — the batched hyperparameter sweep
    (docs/evaluation.md): grid/generator candidates over deterministic
    k-fold or event-time splits, shape-compatible candidates trained as
    ONE stacked device program, per-fold results checkpointed durably
    (resume with --resume-eval), winner persisted as
    `<eval-iid>:best_params` for `pio train/deploy --from-eval`."""
    from pio_tpu.obs import make_recorder
    from pio_tpu.tuning import SweepConfig, parse_metric
    from pio_tpu.utils.tracing import Tracer
    from pio_tpu.workflow.context import create_workflow_context
    from pio_tpu.workflow.evaluate import run_sweep_evaluation

    engine_dir = args.engine_dir or "."
    variant = _load_variant(engine_dir)
    engine, ep = _engine_from_variant(variant, engine_dir)
    engine_id, engine_version, engine_variant = _engine_ids(
        variant, engine_dir)
    try:
        candidates = _sweep_candidates(engine, ep, args)
        metric = parse_metric(args.metric)
        others = [parse_metric(s)
                  for s in (args.other_metrics or "").split(",")
                  if s.strip()]
    except (ValueError, OSError) as e:
        # OSError: --grid @file.json that does not exist/read — the
        # same one-line error every other argument mistake gets
        return _fail(str(e))
    config = SweepConfig(
        metric=metric, other_metrics=others,
        split=args.split, folds=args.folds, seed=args.seed,
    )
    storage = get_storage()
    ctx = create_workflow_context(storage, use_mesh=not args.no_mesh)
    recorder = make_recorder("eval")
    tracer = Tracer(recorder=recorder)
    http = status = None
    if args.metrics_port is not None:
        from pio_tpu.tuning.server import EvalStatus, create_eval_server

        status = EvalStatus(tracer, recorder)
        http = create_eval_server(
            status, ip=args.ip, port=args.metrics_port,
            server_key=args.server_key
            or os.environ.get("PIO_SERVER_KEY", ""))
        http.start()
        print(f"sweep metrics on http://{args.ip}:{http.port} "
              "(/metrics, /debug/traces.json; watch with `pio top "
              f"--url http://{args.ip}:{http.port}`)")
    try:
        instance_id, result = run_sweep_evaluation(
            engine, candidates, storage, config,
            engine_id=engine_id, engine_version=engine_version,
            engine_variant=engine_variant,
            batch=args.batch or "",
            output_path=args.output or None,
            resume_eval_id=args.resume_eval or None,
            ctx=ctx, tracer=tracer,
            status=status,
        )
    finally:
        if http is not None:
            http.stop()
    print(f"Sweep completed. Evaluation instance: {instance_id} "
          f"({len(candidates)} candidate(s), {args.split} x "
          f"{args.folds})")
    print(f"Best {result.metric_header}: [{result.best_score.score}] "
          f"(candidate #{result.best_idx})")
    print(f"Best params: {result.best_engine_params.to_json()}")
    print(f"Deploy the winner: pio train --from-eval {instance_id} "
          f"&& pio deploy --from-eval {instance_id}")
    return 0


def _apply_from_eval(engine, ep, storage, from_eval: str):
    """Merge a sweep's winning ALGORITHM params into engine.json's
    EngineParams (datasource/preparator/serving stay the operator's —
    the sweep tuned the model, not the read). -> (merged ep, eval id)."""
    import dataclasses

    from pio_tpu.tuning.records import resolve_from_eval

    eval_id, payload = resolve_from_eval(storage, from_eval)
    tuned = engine.engine_params_from_variant(
        {"algorithms": payload["variant"]["algorithms"]})
    return dataclasses.replace(ep, algorithms=tuned.algorithms), eval_id


def cmd_deploy(args) -> int:
    from pio_tpu.workflow.context import create_workflow_context
    from pio_tpu.workflow.serve import ServingConfig, create_query_server

    if args.canary:
        # canary mode is a CLIENT verb: it tells the ALREADY-RUNNING
        # serving process (single-host server or fleet router — same
        # /rollout surface) to stage a candidate, rather than booting a
        # new one (docs/serving.md "Guarded rollout")
        if getattr(args, "from_eval", ""):
            return _fail("--from-eval does not combine with --canary: "
                         "the canary stages an already-TRAINED "
                         "instance — run `pio train --from-eval` "
                         "first, then canary that instance")
        return _deploy_canary_cmd(args)
    if args.fleet:
        # multi-tenant pool boot: everything comes from the recorded
        # FleetPlan (tenants, packing, pool shape) — no engine dir
        if args.fleet_join:
            return _fail("--fleet boots a pool from its recorded plan; "
                         "--fleet-join adds THIS engine to a plan — "
                         "run them as separate commands")
        return _deploy_fleet_pool_cmd(args)
    variant = _load_variant(args.engine_dir)
    engine, ep = _engine_from_variant(variant, args.engine_dir)
    engine_id, engine_version, engine_variant = _engine_ids(
        variant, args.engine_dir
    )
    storage = get_storage()
    if getattr(args, "from_eval", ""):
        if args.shards > 0:
            return _fail("--from-eval is not supported with --shards "
                         "yet: fleet shards serve already-partitioned "
                         "model blobs; train the winner "
                         "(`pio train --from-eval`) and fleet-deploy "
                         "that instance")
        ep, eval_id = _apply_from_eval(engine, ep, storage,
                                       args.from_eval)
        print(f"Deploying with best params from evaluation {eval_id}")
    if args.fleet_join:
        return _deploy_fleet_join_cmd(args, storage, engine_id,
                                      engine_version, engine_variant)
    if args.shards > 0:
        # fleet path: partition the persisted model at deploy time, boot
        # N x R shard servers + the router front-end (serving_fleet/)
        return _deploy_fleet_cmd(args, storage, engine_id, engine_version,
                                 engine_variant,
                                 retrieval=_retrieval_block(ep))
    ctx = create_workflow_context(storage, use_mesh=not args.no_mesh)
    config = ServingConfig(
        ip=args.ip, port=args.port,
        engine_id=engine_id, engine_version=engine_version,
        engine_variant=engine_variant,
        feedback=args.feedback,
        feedback_app_name=args.feedback_app or "",
        server_key=args.server_key or os.environ.get("PIO_SERVER_KEY", ""),
        warm_query=json.loads(args.warm_query) if args.warm_query else None,
        certfile=args.cert, keyfile=args.key,
        backend=args.server_backend,
        batch_window_ms=args.batch_window_ms,
        coalesce_window_ms=args.coalesce_window_ms,
    )
    http, qs = create_query_server(
        engine, ep, storage, config, ctx=ctx,
        instance_id=args.engine_instance_id,
    )
    http.start()  # bind first: with --port 0 the real port is only known now
    scheme = "https" if http.tls else "http"
    print(f"Engine instance {qs.instance.id} deployed on "
          f"{scheme}://{args.ip}:{http.port}")
    import threading

    def watch_stop():
        qs._stop_requested.wait()
        http.stop()

    # pio: lint-ok[context-loss] deliberate detach: shutdown watcher
    # waits for /stop for the process lifetime; no request context
    threading.Thread(target=watch_stop, daemon=True).start()
    try:
        http.wait()
    except KeyboardInterrupt:
        http.stop()
    qs.close()
    print("Server stopped.")
    return 0


def _deploy_fleet_cmd(args, storage, engine_id: str, engine_version: str,
                      engine_variant: str,
                      retrieval: dict | None = None) -> int:
    """`pio deploy --shards N [--replicas R]`: sharded, replicated
    serving (docs/serving.md "Sharded fleet"). The router binds
    --ip/--port; shard servers take ephemeral ports (printed, and always
    discoverable via the router's /fleet.json)."""
    from pio_tpu.serving_fleet.fleet import deploy_fleet
    from pio_tpu.serving_fleet.router import RouterConfig

    # fail loudly on single-host-only options rather than silently
    # ignoring them — --cert/--key especially: an operator asking for
    # TLS must never get plaintext without an error
    if args.cert or args.key:
        return _fail("TLS termination is not supported in fleet mode yet "
                     "(--shards with --cert/--key); front the router with "
                     "a TLS-terminating proxy instead")
    unsupported = [flag for flag, on in (
        ("--feedback", args.feedback),
        ("--warm-query", bool(args.warm_query)),
        ("--batch-window-ms", args.batch_window_ms > 0),
    ) if on]
    if unsupported:
        return _fail(f"{', '.join(unsupported)} not supported in fleet "
                     "mode (--shards); they configure the single-host "
                     "QueryServer")
    if args.replicas < 1:
        return _fail("--replicas must be >= 1")

    # shard endpoints must be dialable by the router, so a wildcard bind
    # resolves to loopback for the in-process fleet shape
    ip = args.ip if args.ip != "0.0.0.0" else "127.0.0.1"
    handle = deploy_fleet(
        storage,
        engine_id=engine_id, engine_version=engine_version,
        engine_variant=engine_variant,
        n_shards=args.shards, n_replicas=args.replicas,
        ip=ip,
        router_port=args.port,
        instance_id=args.engine_instance_id,
        server_key=args.server_key or os.environ.get("PIO_SERVER_KEY", ""),
        memory_budget_bytes=args.shard_memory_budget_mb * 1024 * 1024,
        shard_backend=args.server_backend,
        retrieval=retrieval,
        # continuous batching: coalesce concurrent fan-outs per shard
        # group into one batched binary frame (docs/serving.md)
        router_config=(RouterConfig(
            coalesce_window_ms=args.coalesce_window_ms)
            if args.coalesce_window_ms > 0 else None),
    )
    mode = (retrieval or {}).get("mode", "exact")
    print(f"Fleet router for instance {handle.plan.instance_id} on "
          f"http://{ip}:{handle.router_http.port} "
          f"({args.shards} shards x {args.replicas} replicas, "
          f"retrieval: {mode})")
    for s, urls in enumerate(handle.endpoints):
        print(f"  shard {s}: {' '.join(urls)}")
    import threading

    def watch_stop():
        handle.router._stop_requested.wait()
        handle.router_http.stop()

    # pio: lint-ok[context-loss] deliberate detach: shutdown watcher
    # waits for /stop for the process lifetime; no request context
    threading.Thread(target=watch_stop, daemon=True).start()
    try:
        handle.wait()
    except KeyboardInterrupt:
        pass
    handle.close()
    print("Fleet stopped.")
    return 0


def _deploy_fleet_join_cmd(args, storage, engine_id: str,
                           engine_version: str,
                           engine_variant: str) -> int:
    """`pio deploy --fleet-join NAME`: pack THIS engine's partitions
    into the named pool's remaining capacity (residents never move),
    persist the placement, and — when a multi-tenant router is already
    running at --ip/--port — fan the live attach so the tenant starts
    serving with zero pool downtime (docs/serving.md "Multi-tenant
    fleet")."""
    from pio_tpu.serving_fleet.tenancy import (
        FleetCapacityError, TenantSpec, join_fleet_plan,
    )
    from pio_tpu.utils.httpclient import JsonHttpClient

    spec = TenantSpec(
        engine_id=engine_id, engine_version=engine_version,
        engine_variant=engine_variant,
        instance_id=args.engine_instance_id or "",
        quota_qps=args.tenant_quota_qps,
        quota_burst=args.tenant_quota_burst,
        weight=args.tenant_weight,
        max_concurrency=args.tenant_max_concurrency,
    )
    try:
        plan, placement = join_fleet_plan(
            storage, args.fleet_join, spec,
            n_shards=args.shards if args.shards > 0 else 2,
            n_replicas=args.replicas,
            memory_budget_bytes=args.shard_memory_budget_mb
            * 1024 * 1024,
        )
    except FleetCapacityError as e:
        return _fail(str(e))
    except ValueError as e:
        return _fail(f"fleet join failed: {e}")
    print(f"Tenant {spec.key} joined fleet {plan.name!r}: instance "
          f"{placement.instance_id}, {placement.total_bytes()} bytes "
          f"over shard(s) {sorted(set(placement.owners))} "
          f"(pool loads: {plan.shard_loads()})")
    # best-effort live attach: a pool that is not running yet is fine —
    # the recorded placement serves on the next `pio deploy --fleet`
    ip = args.ip if args.ip != "0.0.0.0" else "127.0.0.1"
    key = args.server_key or os.environ.get("PIO_SERVER_KEY", "")
    try:
        out = JsonHttpClient(f"http://{ip}:{args.port}",
                             timeout=30).request(
            "POST", "/fleet/attach_tenant", {"tenant": spec.key},
            params={"accessKey": key} if key else None)
        print(f"live attach: {json.dumps(out)}")
    except Exception as e:  # noqa: BLE001 - attach is best-effort
        print(f"no live router attached at http://{ip}:{args.port} "
              f"({e}); placement is recorded — `pio deploy --fleet "
              f"{plan.name}` serves it")
    return 0


def _deploy_fleet_pool_cmd(args) -> int:
    """`pio deploy --fleet NAME`: boot the whole multi-tenant pool —
    tenant-mux shard hosts + the multi-tenant router — from the
    recorded FleetPlan."""
    from pio_tpu.serving_fleet.tenancy import deploy_multi_fleet

    storage = get_storage()
    ip = args.ip if args.ip != "0.0.0.0" else "127.0.0.1"
    try:
        handle = deploy_multi_fleet(
            storage, name=args.fleet, ip=ip, router_port=args.port,
            server_key=args.server_key
            or os.environ.get("PIO_SERVER_KEY", ""),
            router_backend=args.server_backend,
        )
    except ValueError as e:
        return _fail(str(e))
    plan = handle.fleet_plan
    print(f"Multi-tenant fleet {plan.name!r} on "
          f"http://{ip}:{handle.router_http.port} "
          f"({plan.n_shards} shards x {plan.n_replicas} replicas, "
          f"{len(plan.tenants)} tenants)")
    for t in plan.tenants:
        print(f"  tenant {t.tenant}: instance {t.instance_id}, "
              f"{t.total_bytes()} bytes over shard(s) "
              f"{sorted(set(t.owners))}")
    for s, urls in enumerate(handle.endpoints):
        print(f"  shard host {s}: {' '.join(urls)}")
    import threading

    def watch_stop():
        handle.router._stop_requested.wait()
        handle.router_http.stop()

    # pio: lint-ok[context-loss] deliberate detach: shutdown watcher
    # waits for /stop for the process lifetime; no request context
    threading.Thread(target=watch_stop, daemon=True).start()
    try:
        handle.wait()
    except KeyboardInterrupt:
        pass
    handle.close()
    print("Fleet stopped.")
    return 0


def _rollout_call(args, method: str, path: str, body=None) -> int:
    """Shared client for the rollout verbs: POST to the running serving
    process's /rollout surface (single-host server and fleet router
    expose the identical routes), print the JSON answer."""
    from pio_tpu.utils.httpclient import HttpClientError, JsonHttpClient

    ip = args.ip if args.ip != "0.0.0.0" else "127.0.0.1"
    url = f"http://{ip}:{args.port}"
    key = args.server_key or os.environ.get("PIO_SERVER_KEY", "")
    client = JsonHttpClient(url, timeout=getattr(args, "timeout", 30.0))
    try:
        out = client.request(method, path, body,
                             params={"accessKey": key} if key else None)
    except HttpClientError as e:
        if e.status == 0:
            return _fail(f"no serving process at {url}: {e.message}")
        return _fail(f"{path} answered HTTP {e.status}: {e.message}")
    print(json.dumps(out, indent=2))
    return 0


def _deploy_canary_cmd(args) -> int:
    """`pio deploy --canary <pct|auto>` — begin a guarded rollout of the
    latest eligible COMPLETED instance (or --engine-instance-id) on the
    running server. `auto` ramps 1% -> 5% -> 25% -> 100% while guards
    stay green; a fixed pct holds there until `pio promote` /
    `pio rollback`."""
    spec = args.canary.strip().lower()
    body: dict = {}
    if spec == "auto":
        body["auto"] = True
    else:
        try:
            body["pct"] = int(spec)
        except ValueError:
            return _fail(f"--canary takes a percentage or 'auto', "
                         f"got {args.canary!r}")
    if args.engine_instance_id:
        body["instanceId"] = args.engine_instance_id
    if args.canary_min_stage_seconds is not None:
        body["minStageSeconds"] = args.canary_min_stage_seconds
    if args.canary_min_stage_samples is not None:
        body["minStageSamples"] = args.canary_min_stage_samples
    return _rollout_call(args, "POST", "/rollout/deploy", body)


def cmd_promote(args) -> int:
    """`pio promote` — conclude a green canary: the candidate becomes
    the active instance at 100% and the PROMOTED verdict is persisted
    (it survives restarts; docs/serving.md "Guarded rollout")."""
    return _rollout_call(args, "POST", "/rollout/promote", {})


def cmd_rollback(args) -> int:
    """`pio rollback` — one-command instant rollback: 100% of traffic
    reverts to the last-good instance atomically and the ROLLED_BACK
    verdict is persisted, so no reload ever auto-advances onto the
    rejected instance again."""
    return _rollout_call(args, "POST", "/rollout/rollback",
                         {"reason": args.reason or "operator rollback"})


def cmd_reshard(args) -> int:
    """`pio reshard --shards N'` — live elastic resharding: grow or
    shrink the RUNNING fleet to N' shard groups with zero downtime
    (docs/serving.md "Elastic resharding"). The router streams moved
    partitions to their new owners, double-routes affected partitions
    during the move, and flips the durable plan atomically; `--status`
    follows an in-flight migration, `--abort` restores the old plan
    bit-identical."""
    from pio_tpu.utils.httpclient import HttpClientError, JsonHttpClient

    ip = args.ip if args.ip != "0.0.0.0" else "127.0.0.1"
    url = f"http://{ip}:{args.port}"
    key = args.server_key or os.environ.get("PIO_SERVER_KEY", "")
    params = {"accessKey": key} if key else None
    client = JsonHttpClient(url, timeout=args.timeout)

    def call(method, path, body=None):
        return client.request(method, path, body, params=params)

    try:
        if args.status:
            print(json.dumps(call("GET", "/reshard/status"), indent=2))
            return 0
        if args.abort:
            out = call("POST", "/reshard/abort")
            print(json.dumps(out, indent=2))
            return 0 if out.get("verdict") == "ABORTED" else 1
        if args.shards is None or args.shards < 1:
            return _fail("pio reshard needs --shards N' (or --status / "
                         "--abort)")
        body: dict = {"nShards": args.shards}
        if args.endpoint:
            # each --endpoint is ONE new shard group; commas separate
            # its replicas: --endpoint http://h1:9107,http://h2:9107
            body["endpoints"] = [
                [u.strip() for u in e.split(",") if u.strip()]
                for e in args.endpoint]
        out = call("POST", "/reshard/begin", body)
        if out.get("noop"):
            print(out.get("message", "nothing to do"))
            return 0
        print(f"resharding {out.get('nShardsOld')} -> "
              f"{out.get('nShardsNew')} shard(s): "
              f"{out.get('partitionsMoving')} partition(s) to move "
              f"(plan v{out.get('planVersionOld')} -> "
              f"v{out.get('planVersionNew')})")
        if args.no_wait:
            print("migration running; follow with `pio reshard "
                  "--status`")
            return 0
        last = -1
        while True:
            st = call("GET", "/reshard/status")
            staged = st.get("partitionsStaged", 0)
            if staged != last:
                print(f"  staged {staged}/"
                      f"{st.get('partitionsMoving', 0)} partition(s)")
                last = staged
            if not st.get("inFlight"):
                verdict = st.get("verdict")
                print(f"reshard {verdict}: "
                      f"{st.get('reason') or 'no reason recorded'}")
                return 0 if verdict == "COMMITTED" else 1
            time.sleep(0.2)
    except HttpClientError as e:
        if e.status == 0:
            return _fail(f"no fleet router at {url}: {e.message}")
        return _fail(f"HTTP {e.status}: {e.message}")


def _obs_urls(args) -> list[str]:
    """The surfaces `pio trace` / `pio top` poll: explicit --url flags,
    plus (given --router-url) the router AND every shard replica it
    knows from /fleet.json — one address covers the whole fleet."""
    from pio_tpu.obs.assemble import discover_fleet_urls

    urls = [u.rstrip("/") for u in (args.url or [])]
    if args.router_url:
        for u in discover_fleet_urls(args.router_url,
                                     timeout=args.timeout):
            if u not in urls:
                urls.append(u)
    if not urls:
        urls = [f"http://127.0.0.1:{args.port}"]
    return urls


def cmd_trace(args) -> int:
    """`pio trace <trace_id>` — collect span records from every surface
    (router, its shard replicas, serving, storage, folder) and print the
    MERGED span tree with per-hop self-time (docs/observability.md).
    Get a trace id from a response's X-Pio-Trace-Id echo header (send
    `X-Pio-Trace: 1`), from /metrics.json exemplars, or from a
    surface's /debug/traces.json listing."""
    from pio_tpu.obs.assemble import collect_trace, render_tree

    urls = _obs_urls(args)
    spans, misses = collect_trace(urls, args.trace_id,
                                  server_key=args.server_key or "",
                                  timeout=args.timeout)
    if args.json:
        print(json.dumps({
            "traceId": args.trace_id,
            "spans": [s.to_dict() for s in spans],
            "misses": misses,
        }, indent=2))
        return 0 if spans else 1
    print(render_tree(args.trace_id, spans, misses))
    return 0 if spans else 1


def cmd_top(args) -> int:
    """`pio top` — the live span table across surfaces: rate, p50, p99,
    error% per span per arm over each recorder's recent window. One
    shot by default; --watch N refreshes every N seconds."""
    import time as _time

    from pio_tpu.obs.assemble import collect_span_tables, render_span_table

    urls = _obs_urls(args)
    while True:
        rows, errors = collect_span_tables(
            urls, server_key=args.server_key or "", timeout=args.timeout)
        if args.json:
            print(json.dumps({"spans": rows, "errors": errors}))
        else:
            print(render_span_table(rows, errors))
        if not args.watch:
            return 0 if rows or not errors else 1
        try:
            _time.sleep(args.watch)
            if not args.json:
                print()
        except KeyboardInterrupt:
            return 0


def cmd_foldin(args) -> int:
    """`pio foldin` — the streaming fold-in worker (docs/freshness.md):
    tail the event stream, solve refreshed user rows against the
    deployed model's item factors, and hot-swap them into serving
    (single host or fleet router). Training-read semantics and solver
    params come from the SAME engine.json train/deploy read, so they
    cannot drift from the model being refreshed."""
    import threading

    from pio_tpu.freshness import (
        FoldInConfig, FoldInWorker, RouterFleetApplier, ServingHttpApplier,
        create_foldin_server,
    )
    from pio_tpu.freshness.tail import HttpEventSource
    from pio_tpu.ops import als

    variant = _load_variant(args.engine_dir)
    engine, ep = _engine_from_variant(variant, args.engine_dir)
    engine_id, engine_version, engine_variant = _engine_ids(
        variant, args.engine_dir
    )
    _, ds = ep.datasource
    _, ap = (ep.algorithms or [(None, None)])[0]
    rank = getattr(ap, "rank", None)
    if rank is None:
        return _fail(
            "fold-in needs a factor-model engine (algorithm params with "
            f"rank/lambda_/alpha/implicit_prefs); got {type(ap).__name__}")
    als_params = als.ALSParams(
        rank=rank,
        reg=getattr(ap, "lambda_", 0.1),
        alpha=getattr(ap, "alpha", 1.0),
        implicit=getattr(ap, "implicit_prefs", False),
    )
    app_name = getattr(ds, "app_name", "")
    if not app_name:
        return _fail("engine.json datasource params carry no appName")
    state_path = args.state_path or os.path.join(
        os.path.expanduser(os.environ.get("PIO_TPU_HOME", "~/.pio_tpu")),
        "foldin", f"{engine_id}-{engine_variant}.cursor")
    key = args.server_key or os.environ.get("PIO_SERVER_KEY", "")
    config = FoldInConfig(
        app_name=app_name,
        channel_name=getattr(ds, "channel_name", None),
        engine_id=engine_id, engine_version=engine_version,
        engine_variant=engine_variant,
        event_names=tuple(getattr(ds, "event_names", ("rate", "buy"))),
        value_event=getattr(ds, "rating_event", "rate"),
        default_value=getattr(ds, "implicit_value", 4.0),
        als_params=als_params,
        state_path=state_path,
        replay=args.replay,
        poll_interval_s=args.interval,
        max_batch_users=args.max_batch_users,
        staleness_budget_s=args.staleness_budget,
        ip=args.ip, port=args.port,
        # the same key that authenticates the applies guards the
        # folder's own /debug trace routes (traces carry request paths
        # + user-batch timing)
        server_key=key,
    )
    if args.router_url:
        applier = RouterFleetApplier(args.router_url, key)
        target = args.router_url
    else:
        applier = ServingHttpApplier(args.serving_url, key)
        target = args.serving_url
    source = None
    if args.event_server_url:
        source = HttpEventSource(
            args.event_server_url, args.access_key,
            channel_name=config.channel_name,
            event_names=config.event_names,
            wait_s=args.tail_wait,
        )
    storage = get_storage()
    worker = FoldInWorker(storage, config, applier, source=source)
    if args.once:
        try:
            stats = worker.run_once()
        except Exception as e:  # noqa: BLE001 - --once reports, not loops
            print(json.dumps({"error": f"{type(e).__name__}: {e}",
                              **worker.snapshot()}))
            return 1
        print(json.dumps({**stats, **worker.snapshot()}))
        return 0
    http = create_foldin_server(worker)
    http.start()
    worker.start()
    print(f"fold-in worker for engine {engine_id} -> {target} "
          f"(health on http://{args.ip}:{http.port}, cursor {state_path})")

    stop = threading.Event()
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    worker.stop()
    http.stop()
    print("fold-in worker stopped.")
    return 0


def cmd_batchpredict(args) -> int:
    """Offline bulk scoring through the full serving composition
    (workflow/batchpredict.py); no HTTP server involved."""
    import contextlib
    import sys as _sys

    from pio_tpu.workflow.batchpredict import run_batch_predict
    from pio_tpu.workflow.context import create_workflow_context

    variant = _load_variant(args.engine_dir)
    engine, ep = _engine_from_variant(variant, args.engine_dir)
    engine_id, engine_version, engine_variant = _engine_ids(
        variant, args.engine_dir
    )
    storage = get_storage()
    ctx = create_workflow_context(storage, use_mesh=not args.no_mesh)
    with contextlib.ExitStack() as stack:
        inp = (_sys.stdin if args.input == "-"
               else stack.enter_context(open(args.input)))
        out = (_sys.stdout if args.output == "-"
               else stack.enter_context(open(args.output, "w")))
        report = run_batch_predict(
            engine, ep, storage, inp, out,
            engine_id=engine_id, engine_version=engine_version,
            engine_variant=engine_variant,
            instance_id=args.engine_instance_id,
            batch_size=args.batch_size, ctx=ctx,
        )
    print(f"Batch predict done: {report.n_queries} queries"
          + (f", {report.n_errors} failed (malformed or engine-rejected; "
             "see the output's error records)" if report.n_errors else ""),
          file=_sys.stderr)
    return 0


def cmd_undeploy(args) -> int:
    """POST /stop to a running deploy server (reference Console.undeploy).
    Rides utils/httpclient like every other outbound call (the obs:
    raw-http contract — raw urllib would drop trace/deadline context).
    With --tenant: remove ONE tenant from a multi-tenant fleet (plan
    record + best-effort live detach) and leave the pool serving the
    rest."""
    from pio_tpu.utils.httpclient import JsonHttpClient

    key = args.server_key or os.environ.get("PIO_SERVER_KEY", "")
    if args.tenant:
        from pio_tpu.serving_fleet.tenancy import remove_tenant

        try:
            plan = remove_tenant(get_storage(), args.fleet, args.tenant)
        except ValueError as e:
            return _fail(str(e))
        print(f"Tenant {args.tenant} removed from fleet {plan.name!r} "
              f"({len(plan.tenants)} tenant(s) remain)")
        try:
            out = JsonHttpClient(f"http://{args.ip}:{args.port}",
                                 timeout=30).request(
                "POST", "/fleet/detach_tenant",
                {"tenant": args.tenant},
                params={"accessKey": key} if key else None)
            print(f"live detach: {json.dumps(out)}")
        except Exception as e:  # noqa: BLE001 - detach is best-effort
            print(f"no live router detached at "
                  f"http://{args.ip}:{args.port} ({e}); the plan "
                  f"record is updated")
        return 0
    try:
        out = JsonHttpClient(f"http://{args.ip}:{args.port}",
                             timeout=10).request(
            "POST", "/stop", params={"accessKey": key} if key else None)
        print(json.dumps(out) if out is not None else "")
        return 0
    except Exception as e:  # noqa: BLE001
        return _fail(f"undeploy failed: {e}")


def cmd_compilecache(args) -> int:
    """Inspect or clear the persistent XLA compile cache (the thing that
    makes the SECOND `pio train`/`pio deploy` skip cold-start XLA; see
    docs/performance.md). Shows the serving bucket registries too."""
    from pio_tpu.utils.compilecache import (
        cache_disabled, cache_stats, clear_cache, default_cache_dir,
    )

    d = args.dir or default_cache_dir()
    if args.clear:
        n = clear_cache(d)
        print(f"removed {n} file(s) from {d}")
        return 0
    stats = cache_stats(d)
    registries = sorted(
        f for f in (os.listdir(d) if os.path.isdir(d) else [])
        if f.startswith("buckets__") and f.endswith(".json")
    )
    if args.json:
        print(json.dumps({**stats, "disabled": cache_disabled(),
                          "bucket_registries": registries}))
        return 0
    state = "DISABLED (PIO_TPU_COMPILE_CACHE=off)" if cache_disabled() \
        else "enabled"
    print(f"compile cache: {state}")
    print(f"  dir:     {stats['dir']}")
    print(f"  entries: {stats['entries']}"
          f" ({stats['bytes'] / 1e6:.1f} MB)")
    for r in registries:
        with open(os.path.join(d, r), encoding="utf-8") as f:
            buckets = json.load(f).get("buckets", [])
        print(f"  buckets: {r[len('buckets__'):-len('.json')]} -> {buckets}")
    return 0


def cmd_start_all(args) -> int:
    from pio_tpu.tools.daemon import default_pid_dir, start_all

    if args.pid_dir is None:
        args.pid_dir = default_pid_dir()
    return start_all(args)


def cmd_stop_all(args) -> int:
    from pio_tpu.tools.daemon import default_pid_dir, stop_all

    if args.pid_dir is None:
        args.pid_dir = default_pid_dir()
    return stop_all(args)


def cmd_eventserver(args) -> int:
    from pio_tpu.server.eventserver import EventServerConfig, create_event_server

    srv = create_event_server(
        get_storage(),
        EventServerConfig(ip=args.ip, port=args.port, stats=args.stats,
                          metrics_key=args.metrics_key or "",
                          certfile=args.cert, keyfile=args.key,
                          backend=args.server_backend),
    )
    srv.start()  # bind first: with --port 0 the real port is only known now
    scheme = "https" if srv.tls else "http"
    print(f"Event Server on {scheme}://{args.ip}:{srv.port}")
    try:
        srv.wait()
    except KeyboardInterrupt:
        srv.stop()
    return 0


def cmd_storageserver(args) -> int:
    """Serve this host's configured storage to other hosts (the networked
    shared store; reference analogue: pointing every host's PIO_STORAGE_*
    at one Postgres/HBase — here one host owns the store and the rest mount
    it with the `remote` backend)."""
    from pio_tpu.server.storageserver import (
        StorageServerConfig, create_storage_server,
    )

    srv = create_storage_server(
        get_storage(),
        StorageServerConfig(ip=args.ip, port=args.port,
                            server_key=args.server_key or "",
                            certfile=args.cert, keyfile=args.key),
    )
    scheme = "https" if srv.tls else "http"
    print(f"Storage Server on {scheme}://{args.ip}:{srv.port}")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


def cmd_adminserver(args) -> int:
    from pio_tpu.tools.admin import create_admin_server

    srv = create_admin_server(get_storage(), ip=args.ip, port=args.port)
    print(f"Admin Server on http://{args.ip}:{srv.port}")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


def cmd_dashboard(args) -> int:
    from pio_tpu.tools.dashboard import create_dashboard

    srv = create_dashboard(get_storage(), ip=args.ip, port=args.port)
    print(f"Dashboard on http://{args.ip}:{srv.port}")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


def _io_format(explicit: str | None, path: str) -> str:
    if explicit:
        return explicit
    return "parquet" if path.endswith(".parquet") else "json"


def cmd_export(args) -> int:
    from pio_tpu.tools.export_import import export_events, export_events_parquet

    storage = get_storage()
    a = storage.get_metadata_apps().get(args.appid)
    if a is None:
        return _fail(f"App id {args.appid} does not exist.")
    channel_id = None
    if args.channel:
        ch = next((c for c in storage.get_metadata_channels()
                   .get_by_appid(a.id) if c.name == args.channel), None)
        if ch is None:
            return _fail(f"Channel {args.channel} does not exist.")
        channel_id = ch.id
    if _io_format(getattr(args, "format", None), args.output) == "parquet":
        n = export_events_parquet(
            storage, args.appid, args.output, channel_id=channel_id
        )
    else:
        with _open_text(args.output, "wt") as f:
            n = export_events(storage, args.appid, f, channel_id=channel_id)
    print(f"Exported {n} events to {args.output}")
    return 0


def _open_text(path: str, mode: str):
    """open() with transparent .gz (committed datasets ship gzipped)."""
    if path.endswith(".gz"):
        import gzip

        return gzip.open(path, mode, encoding="utf-8")
    return open(path, mode.rstrip("t"), encoding="utf-8")


def cmd_import(args) -> int:
    from pio_tpu.tools.export_import import import_events, import_events_parquet

    if _io_format(getattr(args, "format", None), args.input) == "parquet":
        ok, failed = import_events_parquet(get_storage(), args.appid, args.input)
    else:
        with _open_text(args.input, "rt") as f:
            ok, failed = import_events(get_storage(), args.appid, f)
    print(f"Imported {ok} events ({failed} failed).")
    return 0 if failed == 0 else 1


def cmd_upgrade(args) -> int:
    """Migrate events + app metadata between storage backends (the
    reference's `pio upgrade` generalized: any source -> any target)."""
    from pio_tpu.data.storage import Storage
    from pio_tpu.tools.migrate import migrate_events

    def load_env(path: str) -> dict:
        with open(path) as f:
            return json.load(f)

    src = Storage(env=load_env(args.from_env))
    dst = Storage(env=load_env(args.to_env))
    try:
        report = migrate_events(
            src, dst,
            app_ids=[args.appid] if args.appid is not None else None,
            copy_metadata=not args.no_metadata,
        )
    finally:
        src.close()
        dst.close()
    print(report.one_liner())
    return 0


def cmd_lint(args) -> int:
    """Static trace-safety & concurrency analysis (pio_tpu/analysis/):
    the compile-time net the reference gets from Scala's type system.
    Exits 0 when no error/warning findings survive suppressions (INFO
    findings are advisory). `--deep` switches to the whole-program tier
    (lock-order cycles, blocking-under-lock, context-loss,
    route-contract drift) with its committed baseline. See docs/lint.md
    for both rule catalogues."""
    select = {s for s in (args.select or "").split(",") if s}
    ignore = {s for s in (args.ignore or "").split(",") if s}
    paths = args.paths
    if args.deep:
        from pio_tpu.analysis.deep import run_deep_lint

        # `pio lint --deep` from the repo root means the package, not
        # the tree of tests/fixtures around it
        if paths == ["."] and os.path.isdir("pio_tpu"):
            paths = ["pio_tpu"]
        report = run_deep_lint(
            paths, select=select or None, ignore=ignore or None,
            baseline_path=args.baseline,
            update_baseline=args.update_baseline,
            use_baseline=not args.no_baseline)
    else:
        from pio_tpu.analysis import run_lint

        report = run_lint(paths, select=select or None,
                          ignore=ignore or None)
    exit_code = report.exit_code
    if args.deep and args.max_seconds and report.elapsed_s > args.max_seconds:
        exit_code = exit_code or 1
    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_dict() for f in report.findings],
            "baselined": [f.to_dict() for f in report.baselined],
            "suppressed": len(report.suppressed),
            "files": report.n_files,
            "elapsed_s": round(report.elapsed_s, 3),
            "deep": bool(args.deep),
        }, indent=2))
        return exit_code
    shown = [f for f in report.findings
             if args.show_info or f.severity.label() != "info"]
    for f in shown:
        print(f.format())
    print(report.summary())
    if args.deep:
        print(f"deep analysis took {report.elapsed_s:.2f}s"
              + (f" (budget {args.max_seconds:.0f}s"
                 + (" EXCEEDED)" if report.elapsed_s > args.max_seconds
                    else " ok)")
                 if args.max_seconds else ""))
    return exit_code


def cmd_template(args) -> int:
    """Scaffold a new engine directory from the template gallery
    (reference console/Template.scala). The built-in gallery is the local
    model zoo; `--gallery-url` (or PIO_TEMPLATE_GALLERY_URL) additionally
    lists/fetches an organization-hosted remote gallery."""
    from pio_tpu.tools.templates import (
        GALLERY_ENV, TEMPLATES, GalleryError, fetch_gallery, readme_for,
        scaffold_remote,
    )

    explicit_url = getattr(args, "gallery_url", None)
    gallery_url = explicit_url or os.environ.get(GALLERY_ENV)
    # a builtin scaffold must never need the network: fetch the remote
    # index only when the command actually involves it (list, or a non-
    # builtin name); an env-var-configured gallery that is down degrades
    # to a warning instead of blocking local work
    need_remote = gallery_url and (
        args.subcommand == "list"
        or (args.subcommand == "new" and args.template not in TEMPLATES)
    )
    remote = {}
    if need_remote:
        try:
            remote = fetch_gallery(gallery_url)
        except GalleryError as e:
            if explicit_url or args.subcommand == "new":
                return _fail(str(e))
            print(f"[WARN] {e} (continuing with the builtin gallery)",
                  file=sys.stderr)
        # builtin names are trusted: a remote entry cannot shadow one
        for clash in set(remote) & set(TEMPLATES):
            print(f"[WARN] remote template {clash!r} shadows a builtin "
                  "and is ignored", file=sys.stderr)
            del remote[clash]

    if args.subcommand == "list":
        for spec in TEMPLATES.values():
            print(f"{spec.name:16} {spec.description}")
        for rspec in remote.values():
            print(f"{rspec.name:16} {rspec.description} [remote]")
        return 0
    if args.subcommand != "new":
        return _fail("use 'template new <dir> [--template NAME]' or "
                     "'template list'")
    spec = TEMPLATES.get(args.template)
    if spec is None and args.template not in remote:
        choices = list(TEMPLATES) + list(remote)
        return _fail(
            f"unknown template {args.template!r}; "
            f"choose from: {', '.join(choices)}"
        )
    target = args.directory
    if os.path.exists(target) and (
        not os.path.isdir(target) or os.listdir(target)
    ):
        return _fail(f"{target} exists and is not an empty directory")
    os.makedirs(target, exist_ok=True)
    if spec is None:              # remote template
        try:
            scaffold_remote(remote[args.template], gallery_url, target)
        except GalleryError as e:
            return _fail(str(e))
        print(f"Engine template '{args.template}' (remote) created at "
              f"{target}")
        return 0
    name = os.path.basename(os.path.abspath(target))
    variant = dict(spec.engine_json, id=name)
    with open(os.path.join(target, "engine.json"), "w") as f:
        json.dump(variant, f, indent=2)
    if spec.engine_py is not None:
        with open(os.path.join(target, "engine.py"), "w") as f:
            f.write(spec.engine_py)
    for rel, content in spec.data_files.items():
        path = os.path.join(target, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(content)
    with open(os.path.join(target, "README.md"), "w") as f:
        f.write(readme_for(spec, name))
    print(f"Engine template '{spec.name}' created at {target}")
    return 0


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pio", description="pio-tpu command line interface"
    )
    sub = p.add_subparsers(dest="command", required=True)

    sub.add_parser("version").set_defaults(fn=cmd_version)
    x = sub.add_parser("status")
    x.add_argument("--pid-dir", default=None,
                   help="where start-all wrote pidfiles (default "
                        "$PIO_TPU_PID_DIR or ~/.pio_tpu/run)")
    x.set_defaults(fn=cmd_status)

    x = sub.add_parser(
        "doctor",
        help="poll every server surface's /healthz + /readyz: breaker "
             "states, shed queue depth, spill backlog, serving model",
    )
    x.add_argument("--ip", default="127.0.0.1")
    x.add_argument("--eventserver-port", type=int, default=7070)
    x.add_argument("--serving-port", type=int, default=8000)
    x.add_argument("--adminserver-port", type=int, default=7071)
    x.add_argument("--storageserver-port", type=int, default=7072)
    x.add_argument("--dashboard-port", type=int, default=9000)
    x.add_argument("--timeout", type=float, default=3.0)
    x.add_argument("--json", action="store_true")
    x.add_argument("--sweep-zombies", action="store_true",
                   help="transition INIT/TRAINING instances with stale "
                        "heartbeats to FAILED (resumable) instead of "
                        "just reporting them")
    x.add_argument("--zombie-stale-s", type=float, default=600.0,
                   help="heartbeat age (seconds) after which an "
                        "in-flight instance counts as a zombie")
    x.add_argument("--fleet", action="store_true",
                   help="inspect a sharded serving fleet via its router: "
                        "shard plan, per-replica health, replication "
                        "status, open breakers in one table")
    x.add_argument("--storage", action="store_true",
                   help="inspect the replicated event store (this "
                        "process's PIO_STORAGE_* config): per-replica "
                        "live/breaker/hint-depth/last-scrub + a live "
                        "convergence check; exit 1 on lost write quorum")
    x.add_argument("--scrub", action="store_true",
                   help="with --storage: repair divergent buckets during "
                        "the convergence pass instead of only reporting")
    x.add_argument("--router-url", default="",
                   help="fleet router base URL (default "
                        "http://<ip>:<serving-port>)")
    x.add_argument("--foldin-port", type=int, default=8100,
                   help="fold-in worker health port (the freshness row; "
                        "reported down when no folder is running)")
    x.add_argument("--staleness-budget", type=float, default=60.0,
                   help="fold-in staleness warn threshold (seconds) for "
                        "--fleet's per-group lag column")
    x.add_argument("--tenant", default="", metavar="KEY",
                   help="with --fleet against a multi-tenant router: "
                        "scope the exit code to this tenant — a page "
                        "about a noisy/broken co-tenant must not fail "
                        "a healthy tenant's check run")
    x.set_defaults(fn=cmd_doctor)

    x = sub.add_parser("run")
    x.add_argument("script")
    x.add_argument("args", nargs="*")
    x.set_defaults(fn=cmd_run)

    sub.add_parser("shell").set_defaults(fn=cmd_shell)

    pa = sub.add_parser("app")
    pas = pa.add_subparsers(dest="subcommand", required=True)
    x = pas.add_parser("new")
    x.add_argument("name")
    x.add_argument("--id", type=int, default=0)
    x.add_argument("--description")
    x.add_argument("--access-key", default="")
    pas.add_parser("list")
    x = pas.add_parser("show")
    x.add_argument("name")
    x = pas.add_parser("delete")
    x.add_argument("name")
    x = pas.add_parser(
        "trim", help="copy a time window of events into an EMPTY "
        "destination app (reference experimental trim-app)")
    x.add_argument("name")
    x.add_argument("dst")
    x.add_argument("--start", default="", help="ISO-8601 inclusive start")
    x.add_argument("--until", default="", help="ISO-8601 exclusive end")
    x.add_argument("--channel", default="",
                   help="copy only this named channel (all namespaces — "
                        "default + every channel — are copied otherwise)")
    x.set_defaults(fn=cmd_app, subcommand="trim")

    x = pas.add_parser(
        "cleanup", help="delete events OLDER than --until in place "
        "(reference experimental cleanup-app)")
    x.add_argument("name")
    x.add_argument("--until", required=True,
                   help="ISO-8601 exclusive cutoff: events before it go")
    x.add_argument("--channel", default="",
                   help="clean only this channel (all namespaces otherwise)")
    x.set_defaults(fn=cmd_app, subcommand="cleanup")

    x = pas.add_parser("data-delete")
    x.add_argument("name")
    x.add_argument("--channel")
    x = pas.add_parser("channel-new")
    x.add_argument("name")
    x.add_argument("channel")
    x = pas.add_parser("channel-delete")
    x.add_argument("name")
    x.add_argument("channel")
    pa.set_defaults(fn=cmd_app)

    pk = sub.add_parser("accesskey")
    pks = pk.add_subparsers(dest="subcommand", required=True)
    x = pks.add_parser("new")
    x.add_argument("app_name")
    x.add_argument("--event", action="append")
    x = pks.add_parser("list")
    x.add_argument("app_name", nargs="?")
    x = pks.add_parser("delete")
    x.add_argument("key")
    pk.set_defaults(fn=cmd_accesskey)

    def engine_dir_arg(q):
        q.add_argument("--engine-dir", default=".")

    x = sub.add_parser("build")
    engine_dir_arg(x)
    x.set_defaults(fn=cmd_build)

    x = sub.add_parser("train")
    engine_dir_arg(x)
    x.add_argument("--batch", default="")
    x.add_argument("--no-mesh", action="store_true")
    x.add_argument("--stop-after-read", action="store_true")
    x.add_argument("--stop-after-prepare", action="store_true")
    x.add_argument("--resume", default="", metavar="INSTANCE_ID",
                   help="resume an INTERRUPTED/FAILED engine instance "
                        "from its step checkpoints")
    x.add_argument("--auto-resume", action="store_true",
                   help="resume the most recent resumable instance of "
                        "this engine (fresh run when none has "
                        "checkpoints)")
    x.add_argument("--checkpoint-root", default="",
                   help="root for per-instance step-checkpoint dirs "
                        "(default $PIO_TPU_CKPT_ROOT or "
                        "$PIO_TPU_HOME/checkpoints)")
    x.add_argument("--from-eval", default="", metavar="EVAL_ID|latest",
                   help="train with the winning algorithm params a "
                        "`pio eval --sweep` persisted (the "
                        "<eval-iid>:best_params record); the instance "
                        "is batch-tagged from-eval:<id> so doctor can "
                        "tell production runs the best-known params")
    x.set_defaults(fn=cmd_train)

    x = sub.add_parser("eval")
    x.add_argument("evaluation_class", nargs="?", default="")
    x.add_argument("params_generator_class", nargs="?", default="")
    x.add_argument("--engine-dir", default=None,
                   help="directory holding the user-code engine.py the "
                        "classes live in (joins sys.path); with --sweep "
                        "also where engine.json lives")
    x.add_argument("--output", default="best.json")
    x.add_argument("--workers", type=int, default=1,
                   help="params-grid parallelism (reference runs .par)")
    x.add_argument("--sweep", action="store_true",
                   help="batched hyperparameter sweep over engine.json's "
                        "engine (docs/evaluation.md): shape-compatible "
                        "candidates train as ONE stacked device "
                        "program; per-fold results persist durably and "
                        "the winner lands in <eval-iid>:best_params "
                        "for `pio train/deploy --from-eval`")
    x.add_argument("--grid", default="",
                   help="with --sweep: JSON object (or @file.json) of "
                        "algorithm-param name -> list of values; the "
                        "cartesian product is the candidate grid, e.g. "
                        "'{\"lambda_\": [0.01, 0.1], \"rank\": [8, 16]}'")
    x.add_argument("--params-generator", default="",
                   help="with --sweep: EngineParamsGenerator class path "
                        "instead of --grid (full EngineParams control)")
    x.add_argument("--metric", default="map@10",
                   help="primary metric: map@K, ndcg@K, precision@K, "
                        "or auc (batched path only)")
    x.add_argument("--other-metrics", default="",
                   help="comma-separated supplementary metric columns")
    x.add_argument("--split", choices=["kfold", "time"], default="kfold",
                   help="kfold: seeded balanced folds over deduped "
                        "interactions; time: event-time rolling splits "
                        "(train on the past, test on the next window)")
    x.add_argument("--folds", type=int, default=3)
    x.add_argument("--seed", type=int, default=42,
                   help="kfold assignment seed (bit-reproducible)")
    x.add_argument("--resume-eval", default="", metavar="EVAL_ID",
                   help="resume a killed/failed sweep: completed folds "
                        "are read from the durable record, only the "
                        "remaining units run (result identical to an "
                        "uninterrupted sweep)")
    x.add_argument("--batch", default="",
                   help="batch label recorded on the EvaluationInstance")
    x.add_argument("--no-mesh", action="store_true")
    x.add_argument("--metrics-port", type=int, default=None,
                   help="with --sweep: serve /healthz /metrics "
                        "/debug/traces.json during the sweep (0 = "
                        "ephemeral port) so `pio top`/`pio trace` cover "
                        "it like every other surface")
    x.add_argument("--ip", default="127.0.0.1",
                   help="bind address for --metrics-port")
    x.add_argument("--server-key", default="",
                   help="guards the sweep's /debug trace routes")
    x.set_defaults(fn=cmd_eval)

    x = sub.add_parser("deploy")
    engine_dir_arg(x)
    x.add_argument("--ip", default="0.0.0.0")
    x.add_argument("--port", type=int, default=8000)
    x.add_argument("--engine-instance-id")
    x.add_argument("--feedback", action="store_true")
    x.add_argument("--feedback-app")
    x.add_argument("--server-key")
    x.add_argument("--warm-query")
    x.add_argument("--no-mesh", action="store_true")
    x.add_argument("--cert", help="TLS certificate (PEM) -> serve HTTPS")
    x.add_argument("--key", help="TLS private key (PEM)")
    x.add_argument("--server-backend", choices=["async", "threaded"],
                   default="async")
    x.add_argument("--batch-window-ms", type=float, default=0.0,
                   help="micro-batching: > 0 coalesces concurrent queries "
                        "within this fixed window (ms); < 0 = adaptive "
                        "continuous batching (no added wait; batch = "
                        "whatever queued during the previous execution); "
                        "0 = off")
    x.add_argument("--coalesce-window-ms", type=float, default=0.0,
                   help="continuous batching: > 0 admits queries through "
                        "a coalescing stage that merges concurrent "
                        "requests into one device dispatch (single-host) "
                        "or one batched shard RPC per group (fleet); "
                        "~2 ms is the recommended starting window. "
                        "Deadline-doomed requests dispatch solo or shed "
                        "503. 0 = off")
    x.add_argument("--shards", type=int, default=0,
                   help="> 0 deploys a SHARDED fleet: partition the "
                        "model's factor tables across this many shard "
                        "servers behind a top-k-merging router "
                        "(docs/serving.md); 0 = single-host serve")
    x.add_argument("--replicas", type=int, default=2,
                   help="replicas per shard (fleet mode; >= 2 gives warm "
                        "failover)")
    x.add_argument("--shard-memory-budget-mb", type=int, default=0,
                   help="hard cap (MB) each shard may hold; a partition "
                        "over budget fails deploy instead of lying about "
                        "capacity. 0 = unlimited")
    x.add_argument("--canary", default="", metavar="PCT|auto",
                   help="guarded rollout: tell the RUNNING serving "
                        "process at --ip/--port to stage the latest "
                        "eligible instance (or --engine-instance-id) as "
                        "a canary at PCT percent of traffic, or 'auto' "
                        "to ramp 1->5->25->100 while live guards stay "
                        "green (docs/serving.md). Conclude with `pio "
                        "promote` / `pio rollback`")
    x.add_argument("--canary-min-stage-seconds", type=float, default=None,
                   help="with --canary auto: minimum seconds per stage")
    x.add_argument("--canary-min-stage-samples", type=int, default=None,
                   help="with --canary auto: minimum candidate-arm "
                        "requests per stage")
    x.add_argument("--from-eval", default="", metavar="EVAL_ID|latest",
                   help="serve with the winning algorithm params a "
                        "`pio eval --sweep` persisted (single-host "
                        "mode; pair with `pio train --from-eval` so "
                        "the served instance was trained with them)")
    x.add_argument("--fleet", default="", metavar="NAME",
                   help="boot a MULTI-TENANT pool from the named "
                        "recorded FleetPlan (tenant-mux shard hosts + "
                        "multi-tenant router; no engine dir needed) — "
                        "join tenants first with --fleet-join "
                        "(docs/serving.md \"Multi-tenant fleet\")")
    x.add_argument("--fleet-join", default="", metavar="NAME",
                   help="bin-pack THIS engine's partitions into the "
                        "named fleet's remaining capacity (resident "
                        "tenants never move), record the placement, "
                        "and live-attach to a running router at "
                        "--ip/--port when one answers; pool shape for "
                        "a NEW fleet comes from --shards/--replicas/"
                        "--shard-memory-budget-mb")
    x.add_argument("--tenant-quota-qps", type=float, default=0.0,
                   help="with --fleet-join: this tenant's admitted "
                        "query rate; floods past it answer per-tenant "
                        "429 + Retry-After while co-tenants keep their "
                        "p99. 0 = unlimited")
    x.add_argument("--tenant-quota-burst", type=float, default=0.0,
                   help="with --fleet-join: token-bucket burst "
                        "capacity; 0 = max(rate, 1)")
    x.add_argument("--tenant-weight", type=float, default=1.0,
                   help="with --fleet-join: weighted-fair share under "
                        "admission pressure")
    x.add_argument("--tenant-max-concurrency", type=int, default=0,
                   help="with --fleet-join: cap on this tenant's "
                        "in-flight queries; 0 = unlimited")
    x.set_defaults(fn=cmd_deploy)

    for verb, fn, descr in (
        ("promote", cmd_promote,
         "conclude a green canary: candidate becomes the active "
         "instance at 100% (verdict persisted; survives restart)"),
        ("rollback", cmd_rollback,
         "instant rollback: revert 100% of traffic to the last-good "
         "instance and persist ROLLED_BACK (reloads never auto-advance "
         "onto it again)"),
    ):
        x = sub.add_parser(verb, help=descr)
        x.add_argument("--ip", default="127.0.0.1")
        x.add_argument("--port", type=int, default=8000,
                       help="serving server or fleet router port")
        x.add_argument("--server-key")
        if verb == "rollback":
            x.add_argument("--reason", default="",
                           help="recorded on the rollout verdict")
        x.set_defaults(fn=fn)

    x = sub.add_parser(
        "reshard",
        help="live elastic resharding: grow/shrink the RUNNING fleet "
             "to --shards N' with zero downtime (streams moved "
             "partitions, double-routes during the move, flips the "
             "plan atomically; docs/serving.md)")
    x.add_argument("--shards", type=int, default=None, metavar="N",
                   help="target shard-group count (1..32 virtual "
                        "partitions bound the range)")
    x.add_argument("--endpoint", action="append", default=None,
                   metavar="URL[,URL...]",
                   help="one NEW shard group per flag (repeatable), "
                        "commas separating its replica URLs — required "
                        "when growing past the groups the router "
                        "already knows")
    x.add_argument("--status", action="store_true",
                   help="report the in-flight (or last) migration and "
                        "exit")
    x.add_argument("--abort", action="store_true",
                   help="abort the in-flight migration: the old plan "
                        "was never touched, serving reverts "
                        "bit-identical")
    x.add_argument("--no-wait", action="store_true",
                   help="start the migration and return immediately "
                        "instead of following progress")
    x.add_argument("--ip", default="127.0.0.1")
    x.add_argument("--port", type=int, default=8000,
                   help="fleet router port")
    x.add_argument("--server-key")
    x.add_argument("--timeout", type=float, default=30.0)
    x.set_defaults(fn=cmd_reshard)

    def obs_args(q):
        q.add_argument("--url", action="append", default=None,
                       help="surface base URL to poll (repeatable: "
                            "serving, event server, storage server, "
                            "folder, shard)")
        q.add_argument("--router-url", default="",
                       help="fleet router base URL; its /fleet.json "
                            "auto-discovers every shard replica")
        q.add_argument("--port", type=int, default=8000,
                       help="default single-host serving port when no "
                            "--url/--router-url is given")
        q.add_argument("--server-key", default="",
                       help="accessKey for the /debug trace routes")
        q.add_argument("--timeout", type=float, default=5.0)
        q.add_argument("--json", action="store_true")

    x = sub.add_parser(
        "trace",
        help="print one request's merged span tree (router + shards + "
             "serving/storage/folder) with per-hop self-time",
    )
    x.add_argument("trace_id", help="32-hex trace id (from the "
                                    "X-Pio-Trace-Id echo header, "
                                    "/metrics.json exemplars, or "
                                    "/debug/traces.json)")
    obs_args(x)
    x.set_defaults(fn=cmd_trace)

    x = sub.add_parser(
        "top",
        help="live span table across surfaces: rate/p50/p99/error% per "
             "span, per arm",
    )
    obs_args(x)
    x.add_argument("--watch", type=float, default=0.0,
                   help="refresh every N seconds (0 = print once)")
    x.set_defaults(fn=cmd_top)

    x = sub.add_parser(
        "foldin",
        help="streaming fold-in worker: tail the event stream, solve "
             "refreshed user rows against the deployed item factors, "
             "hot-swap them into serving (docs/freshness.md)")
    engine_dir_arg(x)
    x.add_argument("--serving-url", default="http://127.0.0.1:8000",
                   help="single-host deploy server to apply rows to")
    x.add_argument("--router-url", default="",
                   help="fleet router base URL — apply rows through the "
                        "sharded fleet instead of --serving-url")
    x.add_argument("--event-server-url", default="",
                   help="tail a remote event server's GET /tail/events.json"
                        " (default: read the event store directly)")
    x.add_argument("--access-key", default="",
                   help="event-server app access key "
                        "(with --event-server-url)")
    x.add_argument("--server-key", default="",
                   help="serving/router server key (or PIO_SERVER_KEY)")
    x.add_argument("--state-path", default="",
                   help="durable cursor file (default $PIO_TPU_HOME/foldin/"
                        "<engine>-<variant>.cursor)")
    x.add_argument("--replay", action="store_true",
                   help="a FRESH cursor replays the whole event log "
                        "(re-fold every historical user) instead of "
                        "starting at now")
    x.add_argument("--interval", type=float, default=0.5,
                   help="tail poll interval (seconds)")
    x.add_argument("--tail-wait", type=float, default=10.0,
                   help="with --event-server-url: long-poll push "
                        "subscription — an idle tail blocks server-side "
                        "this many seconds for new events before "
                        "answering (0 = plain polling; pre-long-poll "
                        "servers degrade to polling automatically)")
    x.add_argument("--max-batch-users", type=int, default=1024,
                   help="fold batch cap per cycle")
    x.add_argument("--staleness-budget", type=float, default=60.0,
                   help="the folder's /readyz flips once event->servable "
                        "staleness exceeds this many seconds")
    x.add_argument("--once", action="store_true",
                   help="run exactly one tail->solve->apply cycle, print "
                        "its stats as JSON, and exit (cron-style fold-in)")
    x.add_argument("--ip", default="127.0.0.1")
    x.add_argument("--port", type=int, default=8100,
                   help="health port (/healthz /readyz /metrics.json)")
    x.set_defaults(fn=cmd_foldin)

    x = sub.add_parser(
        "batchpredict",
        help="offline bulk scoring: JSON-lines queries in, "
             "{query, prediction} JSON-lines out (0.13-era verb; device "
             "batches amortize the per-query dispatch)")
    engine_dir_arg(x)
    x.add_argument("--input", required=True,
                   help="queries file, one JSON object per line "
                        "('-' = stdin)")
    x.add_argument("--output", required=True,
                   help="predictions file ('-' = stdout)")
    x.add_argument("--engine-instance-id")
    x.add_argument("--batch-size", type=int, default=256,
                   help="queries per device batch")
    x.add_argument("--no-mesh", action="store_true")
    x.set_defaults(fn=cmd_batchpredict)

    x = sub.add_parser("undeploy")
    x.add_argument("--ip", default="127.0.0.1")
    x.add_argument("--port", type=int, default=8000)
    x.add_argument("--server-key")
    x.add_argument("--tenant", default="", metavar="KEY",
                   help="remove ONE tenant (engine triple key, e.g. "
                        "rec/1/default) from a multi-tenant fleet: "
                        "plan record + best-effort live detach at "
                        "--ip/--port; the pool keeps serving the rest")
    x.add_argument("--fleet", default="default", metavar="NAME",
                   help="with --tenant: the fleet plan to update")
    x.set_defaults(fn=cmd_undeploy)

    x = sub.add_parser("eventserver")
    x.add_argument("--ip", default="0.0.0.0")
    x.add_argument("--port", type=int, default=7070)
    x.add_argument("--stats", action="store_true")
    x.add_argument("--metrics-key",
                   help="with --stats: enable GET /metrics (Prometheus "
                        "ingest counters, cross-app) guarded by this key")
    x.add_argument("--cert", help="TLS certificate (PEM) -> serve HTTPS")
    x.add_argument("--key", help="TLS private key (PEM)")
    x.add_argument("--server-backend", choices=["async", "threaded"],
                   default="async")
    x.set_defaults(fn=cmd_eventserver)

    x = sub.add_parser("start-all", help="daemon-start the full stack "
                       "(reference bin/pio-start-all)")
    x.add_argument("--ip", default="127.0.0.1")
    x.add_argument("--eventserver-port", type=int, default=7070)
    x.add_argument("--adminserver-port", type=int, default=7071)
    x.add_argument("--dashboard-port", type=int, default=9000)
    x.add_argument("--with-storageserver", action="store_true")
    x.add_argument("--storageserver-port", type=int, default=7072)
    x.add_argument("--server-key",
                   help="storage-server shared secret (required for a "
                        "non-loopback --ip)")
    x.add_argument("--pid-dir", default=None)
    x.set_defaults(fn=cmd_start_all)

    x = sub.add_parser("stop-all", help="stop everything start-all started "
                       "(reference bin/pio-stop-all)")
    x.add_argument("--pid-dir", default=None)
    x.set_defaults(fn=cmd_stop_all)

    x = sub.add_parser("storageserver")
    # loopback default: a non-loopback bind requires --server-key (the RPC
    # surface includes access keys and model blobs)
    x.add_argument("--ip", default="127.0.0.1")
    x.add_argument("--port", type=int, default=7072)
    x.add_argument("--server-key", help="shared secret required on every call")
    x.add_argument("--cert", help="TLS certificate (PEM) -> serve HTTPS")
    x.add_argument("--key", help="TLS private key (PEM)")
    x.set_defaults(fn=cmd_storageserver)

    x = sub.add_parser("adminserver")
    x.add_argument("--ip", default="127.0.0.1")
    x.add_argument("--port", type=int, default=7071)
    x.set_defaults(fn=cmd_adminserver)

    x = sub.add_parser("dashboard")
    x.add_argument("--ip", default="127.0.0.1")
    x.add_argument("--port", type=int, default=9000)
    x.set_defaults(fn=cmd_dashboard)

    x = sub.add_parser("export")
    x.add_argument("--appid", type=int, required=True)
    x.add_argument("--output", required=True)
    x.add_argument("--channel")
    x.add_argument("--format", choices=["json", "parquet"],
                   help="default: by --output extension (.parquet), else json")
    x.set_defaults(fn=cmd_export)

    x = sub.add_parser("import")
    x.add_argument("--appid", type=int, required=True)
    x.add_argument("--input", required=True)
    x.add_argument("--format", choices=["json", "parquet"],
                   help="default: by --input extension (.parquet), else json")
    x.set_defaults(fn=cmd_import)

    x = sub.add_parser("upgrade")
    x.add_argument("--from-env", required=True,
                   help="JSON file of PIO_STORAGE_* vars for the source")
    x.add_argument("--to-env", required=True,
                   help="JSON file of PIO_STORAGE_* vars for the target")
    x.add_argument("--appid", type=int)
    x.add_argument("--no-metadata", action="store_true")
    x.set_defaults(fn=cmd_upgrade)

    x = sub.add_parser(
        "compilecache",
        help="persistent XLA compile cache: show size/location, prune, "
             "or clear (docs/performance.md)")
    x.add_argument("--dir", default=None,
                   help="cache directory (default $PIO_TPU_COMPILE_CACHE "
                        "or $PIO_TPU_HOME/compile_cache)")
    x.add_argument("--clear", action="store_true",
                   help="delete every cached executable and bucket "
                        "registry (next train/deploy recompiles)")
    x.add_argument("--json", action="store_true")
    x.set_defaults(fn=cmd_compilecache)

    x = sub.add_parser(
        "lint",
        help="static trace-safety/concurrency analysis (docs/lint.md)")
    x.add_argument("paths", nargs="*", default=["."],
                   help="files or directories to lint (default: .)")
    x.add_argument("--format", choices=["text", "json"], default="text")
    x.add_argument("--select", default="",
                   help="comma-separated rule-id prefixes to run "
                        "(e.g. trace,bench)")
    x.add_argument("--ignore", default="",
                   help="comma-separated rule-id prefixes to skip")
    x.add_argument("--show-info", action="store_true",
                   help="print INFO-level (advisory) findings too")
    x.add_argument("--deep", action="store_true",
                   help="whole-program tier: lock-order cycles, "
                        "blocking-under-lock, context-loss, "
                        "route-contract drift (docs/lint.md)")
    x.add_argument("--baseline", default=None,
                   help="baseline JSON for --deep (default: the "
                        "committed pio_tpu/analysis/deep_baseline.json)")
    x.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline to accept every current "
                        "deep finding (ratchet after review)")
    x.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline and report everything "
                        "(the CI self-check mode)")
    x.add_argument("--max-seconds", type=float, default=0.0,
                   help="fail if the deep analysis wall-clock exceeds "
                        "this budget (CI uses 30)")
    x.set_defaults(fn=cmd_lint)

    x = sub.add_parser("template")
    xs = x.add_subparsers(dest="subcommand", required=True)
    t = xs.add_parser("new")
    t.add_argument("directory")
    t.add_argument("--template", default="custom",
                   help="engine shape (see `pio template list`)")
    t.add_argument("--gallery-url",
                   help="remote gallery base URL (or "
                        "PIO_TEMPLATE_GALLERY_URL)")
    t.set_defaults(fn=cmd_template)
    t = xs.add_parser("list")
    t.add_argument("--gallery-url",
                   help="remote gallery base URL (or "
                        "PIO_TEMPLATE_GALLERY_URL)")
    t.set_defaults(fn=cmd_template)
    x.set_defaults(fn=cmd_template)

    return p


def main(argv: list[str] | None = None) -> int:
    # Platform override for CPU-only hosts / CI. Must use the config API:
    # some deployments (including this project's own test image) pin
    # JAX_PLATFORMS at interpreter startup, so the plain env var is
    # snapshotted before user code runs.
    platform = os.environ.get("PIO_TPU_PLATFORM")
    n_cpu = os.environ.get("PIO_TPU_CPU_DEVICES")
    if platform or n_cpu:
        import jax

        if platform:
            jax.config.update("jax_platforms", platform)
        if n_cpu:
            from pio_tpu.utils.jaxcompat import set_cpu_device_count

            try:
                set_cpu_device_count(int(n_cpu))
            except ValueError:
                return _fail(f"PIO_TPU_CPU_DEVICES={n_cpu!r} is not an int")
    # engine dirs put engine.py on the path (factory "engine.MyEngine")
    if "" not in sys.path and "." not in sys.path:
        sys.path.insert(0, os.getcwd())
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except FileNotFoundError as e:
        return _fail(str(e))
    except (ValueError, KeyError) as e:
        return _fail(f"{type(e).__name__}: {e}")


if __name__ == "__main__":
    sys.exit(main())
