"""Deploy server — REST query serving with models resident in device HBM.

Mirrors reference core/.../workflow/CreateServer.scala:
  GET  /               -> engine status (instance info + latency stats,
                          reference :463-487)
  POST /queries.json   -> supplement -> per-algo predict -> serve
                          (+ optional feedback event, plugins, latency
                          bookkeeping; reference :492-615)
  POST /reload         -> hot-swap to the latest eligible COMPLETED
                          instance (reference MasterActor ReloadServer
                          :334-360; GET kept as a deprecated alias)
  POST /rollout/*      -> guarded canary deploy/promote/rollback
                          (pio_tpu/rollout/, docs/serving.md)
  POST /stop           -> shut down (server-key auth, reference
                          KeyAuthentication + :277-302)
  GET  /plugins.json   -> plugin listing; /plugins/<name>/* -> plugin REST

TPU-native differences: models restore straight from the model store into
HBM (no retrain-on-deploy); predict paths are jit-warmed at startup with a
sample query so first-request latency is compile-free.
"""

from __future__ import annotations

import contextvars
import logging
import queue
from contextlib import nullcontext
import threading
import time
from concurrent.futures import (
    FIRST_COMPLETED,
    Future,
    ThreadPoolExecutor,
    TimeoutError as FuturesTimeoutError,
    wait as futures_wait,
)
from dataclasses import dataclass, field
from typing import Any

from pio_tpu.controller.engine import Engine, EngineParams
from pio_tpu.data.dao import AccessKey
from pio_tpu.data.event import Event
from pio_tpu.data.storage import Storage
from pio_tpu.resilience import CircuitOpenError, Deadline, DeadlineExceeded
from pio_tpu.resilience.health import (
    breaker_checks, install_health_routes, shedder_check,
)
from pio_tpu.rollout import (
    ARM_ACTIVE, ARM_CANDIDATE, install_rollout_routes,
    is_auto_advance_eligible,
)
from pio_tpu.server.http import (
    AsyncHttpServer, HttpApp, HttpServer, Request, json_response,
    server_key_ok,
)
from pio_tpu.server.plugins import PluginContext
from pio_tpu.utils.durable import ModelIntegrityError
from pio_tpu.utils.time import format_time, utcnow
from pio_tpu.utils.tracing import Tracer
from pio_tpu.workflow.context import WorkflowContext, create_workflow_context
from pio_tpu.workflow.train import load_models

log = logging.getLogger("pio_tpu.serve")


@dataclass
class ServingConfig:
    ip: str = "0.0.0.0"
    port: int = 8000
    engine_id: str = ""
    engine_version: str = "1"
    engine_variant: str = "default"
    feedback: bool = False
    feedback_app_name: str = ""   # app receiving pio_pr predict events
    access_key: str = ""          # access key used for feedback inserts
    server_key: str = ""          # guards /stop and /reload (KeyAuthentication)
    warm_query: dict | None = None  # sample query to jit-warm at startup
    certfile: str | None = None   # TLS cert (PEM); with keyfile -> HTTPS
    keyfile: str | None = None
    backend: str = "async"        # HTTP transport: "async" | "threaded"
    # dynamic micro-batching: concurrent /queries.json requests arriving
    # within the window are executed as ONE batch_predict per algorithm.
    # batch_window_ms > 0: fixed collection window; < 0: ADAPTIVE
    # (continuous) batching — no artificial wait, each batch is whatever
    # queued while the previous one executed, so batch size self-tunes to
    # arrival-rate x device-roundtrip (the right mode when dispatch is
    # RTT-dominated, e.g. a remote/tunneled TPU) —
    # the TPU-native answer to CreateServer.scala:516's "TODO: Parallelize"
    # (one big matmul beats many small ones on the MXU). 0 = off.
    batch_window_ms: float = 0.0
    batch_max: int = 64
    # batches concurrently in flight. 0 = AUTO from the measured dispatch
    # RTT: 2 on a local device (double buffering — the collection window
    # overlaps the in-flight batch; depth 1 idles the device through
    # every window and deeper pipelines convoy, the round-2 "357 ms p99"
    # artifact), 4 over a high-RTT link where in-flight batches hide the
    # round trip. Medians over repeated runs in eval/SERVING_TAIL.md.
    batch_pipeline: int = 0
    # tail hedging for the predict dispatch: if a device dispatch has not
    # returned after hedge_after x the rolling predict-stage MEDIAN, issue
    # a duplicate dispatch and take whichever finishes first. predict is a
    # pure function of (model, queries), so the duplicate is safe; it only
    # costs device time on the rare stall. Motivated by measured transport
    # hiccups on a tunneled TPU (~1 in 2000 dispatches takes ~1.9 s vs a
    # 135 ms p50) that micro-batching amplifies into whole-batch p99
    # convoys (eval/SERVING_TAIL.md). 0 disables. Hedging arms only after
    # 20 recorded predict spans; warm-up calls record no spans at all
    # (record=False skips the histograms), so compiles never skew the
    # median the hedge timeout derives from.
    hedge_after: float = 3.0
    # per-request time budget (seconds) opened around each /queries.json
    # dispatch and propagated (resilience.Deadline contextvar) into the
    # storage DAO calls made on the REQUEST THREAD: retries stop
    # sleeping and I/O stops starting once the budget is spent, and the
    # request answers 503 instead of holding a connection past its
    # usefulness. Work executed on other pools (micro-batched execution,
    # hedged/multi-algo predict dispatch, background feedback) does not
    # inherit the contextvar — the batcher instead enforces the budget
    # at its result wait, and predict stages are bounded by their own
    # hedging. 0 = off.
    request_budget_s: float = 0.0
    # cross-request continuous batching (pio_tpu/serving/batcher.py):
    # > 0 puts a ContinuousBatcher in front of the device program —
    # concurrent /queries.json requests coalesce into ONE batched
    # einsum+top_k whenever a pipeline slot frees OR this window (ms)
    # elapses, whichever comes first (2 ms is the recommended default
    # when enabling; docs/serving.md "Continuous batching"). Unlike
    # batch_window_ms it is Deadline-aware: a query whose budget cannot
    # survive the window dispatches solo or sheds 503 instead of
    # parking. Takes precedence over batch_window_ms. 0 = off.
    coalesce_window_ms: float = 0.0


@dataclass
class _CandidateArm:
    """The second model slot a guarded rollout serves its canary from
    (pio_tpu/rollout/): a fully-restored instance living BEHIND the
    same swap lock as the active one, so promote is one pointer move
    and rollback is one pointer drop — never a reload."""

    instance: Any
    models: list
    algorithms: list
    serving: Any


class QueryServer:
    """Serving runtime: engine + params + restored models (reference
    ServerActor state, CreateServer.scala:407-431)."""

    def __init__(
        self,
        engine: Engine,
        engine_params: EngineParams,
        storage: Storage,
        config: ServingConfig,
        ctx: WorkflowContext | None = None,
        plugin_context: PluginContext | None = None,
        instance_id: str | None = None,
    ):
        self.engine = engine
        self.engine_params = engine_params
        self.storage = storage
        self.config = config
        self.ctx = ctx or create_workflow_context(storage)
        self.plugins = plugin_context or PluginContext()
        self._lock = threading.RLock()
        # per-stage latency histograms (replaces the reference's rolling
        # average, CreateServer.scala:420-422; SURVEY.md §5 real tracing)
        # + distributed span records (pio_tpu/obs/): every span under an
        # active trace context lands in the recorder, and the HTTP edge
        # (dispatch_safe) opens that context per request
        from pio_tpu.obs import make_recorder

        self.recorder = make_recorder("serving")
        self.tracer = Tracer(recorder=self.recorder)
        self.start_time = utcnow()
        self._stop_requested = threading.Event()
        self._predict_pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="predict"
        )
        # separate pool for hedged device dispatches: _hedged may be
        # CALLED from a _predict_pool worker (multi-algo path), so its
        # inner submissions must not compete for the same workers or a
        # full pool deadlocks on its own children
        self._hedge_pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="hedge"
        )
        self.hedged_dispatches = 0
        self.last_reload_error: str | None = None
        # streaming fold-in accounting (foldin_upsert): how many user
        # rows the freshness subsystem has hot-swapped in, and the last
        # batch's measured event-ingest -> servable staleness
        self.foldin_applied_users = 0
        self.foldin_applied_items = 0
        self.foldin_last_time = None
        self.foldin_last_staleness_s: float | None = None
        # guarded rollout (pio_tpu/rollout/): the candidate arm and the
        # controller splitting traffic onto it. Both live behind the
        # existing locks — queries snapshot whichever arm serves them
        # exactly like they snapshot the active model today.
        self.rollout = None                       # RolloutController
        self.candidate: _CandidateArm | None = None
        # fold-in rows that could not land on the candidate arm yet
        # (arm mid-swap, rank mismatch): queued and retried on the next
        # apply so freshness never silently diverges the experiment
        self._candidate_foldin_pending: dict = {}
        self._candidate_item_pending: dict = {}
        # serializes whole reloads (resolve + restore + swap) end to end
        # WITHOUT blocking queries: queries snapshot state under
        # self._lock, which a reload only takes for the final swap.
        # Without this, two concurrent /reloads could resolve different
        # "latest" instances and swap in restore-completion order,
        # leaving the older one serving.
        self._load_lock = threading.Lock()
        self._load(instance_id)
        # admission stage in front of the device program: the continuous
        # batcher (deadline-aware, slot-OR-window drain) takes precedence
        # over the window-only micro-batcher; both expose the same
        # .query()/.close() so the serving edge and the readiness
        # "buckets" gate treat them interchangeably
        if config.coalesce_window_ms > 0:
            from pio_tpu.serving.batcher import ContinuousBatcher

            self.batcher = ContinuousBatcher(
                self, config.coalesce_window_ms / 1e3, config.batch_max,
                pipeline_depth=config.batch_pipeline
                or _auto_pipeline_depth())
        elif config.batch_window_ms != 0:
            self.batcher = QueryBatcher(
                self, config.batch_window_ms / 1e3, config.batch_max,
                pipeline_depth=config.batch_pipeline
                or _auto_pipeline_depth())
        else:
            self.batcher = None
        # persistent XLA compile cache: a re-deploy deserializes the
        # predict/bucket executables the last deployment compiled instead
        # of re-running XLA (utils/compilecache.py); the bucket registry
        # remembers WHICH buckets that deployment actually served so the
        # warm sweep compiles exactly that set
        from pio_tpu.utils.compilecache import BucketRegistry, enable_compile_cache

        cache_dir = enable_compile_cache()
        self.bucket_registry = (
            BucketRegistry(config.engine_id, config.engine_version,
                           config.engine_variant, cache_dir=cache_dir)
            if cache_dir is not None else None
        )
        self._buckets_warmed = False
        self._warm_once = threading.Lock()
        # /readyz gate (resilience/health.py "buckets" check): starts
        # NOT-ready only when a warm sweep is owed at startup (batching on
        # + a warm query to run it with); set once the sweep completes so
        # a load balancer never routes traffic into a bucket-miss compile.
        # Without a warm query the first real request triggers the
        # background sweep — gating then would deadlock readiness on the
        # traffic it gates, so the server reports ready and the gate only
        # drops while that background warm is in flight.
        self._buckets_ready = threading.Event()
        if self.batcher is None or config.warm_query is None:
            self._buckets_ready.set()
        self._warm()

    # -- model lifecycle ----------------------------------------------------
    def _load(self, instance_id: str | None = None) -> None:
        """Restore an instance's models and swap them in ATOMICALLY: every
        failable step (metadata lookup, model restore, doer construction)
        runs before the swap, so a failed load leaves the previous
        instance/models/algorithms fully intact — the last-good model
        keeps serving through a broken /reload (reference MasterActor
        keeps its old ServerActor when ReloadServer fails). Whole loads
        (resolve + restore + swap) are serialized by _load_lock so
        concurrent reloads cannot swap in restore-completion order;
        queries are NOT blocked — they contend only on the final swap."""
        with self._load_lock:
            self._load_locked(instance_id)

    def _load_locked(self, instance_id: str | None) -> None:
        c = self.config
        instances = self.storage.get_metadata_engine_instances()
        if instance_id is None:
            candidates = instances.get_completed(
                c.engine_id, c.engine_version, c.engine_variant
            )
            # rollout verdicts gate AUTO-advancement: an instance the
            # guards ROLLED_BACK (or whose canary is still in flight)
            # is skipped, so no reload/restart quietly re-serves a
            # rejected model. Operators can still pin one explicitly.
            candidates = [
                cand for cand in candidates
                if is_auto_advance_eligible(self.storage, cand.id)
            ]
            if not candidates:
                raise ValueError(
                    f"No COMPLETED engine instance eligible for engine "
                    f"{c.engine_id} {c.engine_version} "
                    f"{c.engine_variant} (rolled-back canaries are "
                    "skipped). Run train first."
                )
        else:
            instance = instances.get(instance_id)
            if instance is None:
                raise ValueError(f"Engine instance {instance_id} not found")
            candidates = [instance]
        # restore OUTSIDE the lock: queries keep serving the old model
        # while the new one loads (restore can take seconds on big models).
        # A corrupt blob (CRC32C mismatch — torn write, bit rot) on the
        # latest instance falls back to the previous COMPLETED one:
        # integrity failures are permanent for that blob, and an older
        # good model beats no model. Explicit instance_ids do not fall
        # back — the operator asked for THAT instance.
        models = instance = None
        last_integrity_error: ModelIntegrityError | None = None
        for candidate in candidates:
            try:
                models = load_models(
                    self.storage, self.engine, self.engine_params,
                    candidate.id, ctx=self.ctx,
                )
                instance = candidate
                break
            except ModelIntegrityError as e:
                log.error(
                    "model blob for instance %s is corrupt (%s); trying "
                    "the previous COMPLETED instance", candidate.id, e,
                )
                last_integrity_error = e
        if models is None:
            raise last_integrity_error
        _, _, algorithms, serving = self.engine._doers(self.engine_params)
        with self._lock:
            # hot-swap: retire the outgoing doers' resources (e.g. an
            # external engine's child process) — but on a delay: queries
            # that snapshotted the old algorithms may still be mid-predict,
            # and closing under them would kill their child mid-call
            self._retire_algorithms(getattr(self, "algorithms", []))
            self.instance = instance
            self.models = models
            self.algorithms = algorithms
            self.serving = serving
        log.info("deployed engine instance %s", instance.id)

    def reload(self) -> str:
        """Hot-swap to the latest completed instance; returns its id. On
        failure the exception propagates and the last-good model keeps
        serving (the /reload route maps it to 503 + the serving id)."""
        try:
            self._load(None)
        except Exception as e:
            self.last_reload_error = f"{type(e).__name__}: {e}"
            raise
        self.last_reload_error = None
        return self.instance.id

    # -- guarded rollout arms (pio_tpu/rollout/) -----------------------------
    def rollout_active_instance_id(self) -> str:
        with self._lock:
            return self.instance.id

    def load_candidate(self, instance_id: str) -> None:
        """Restore `instance_id` into the CANDIDATE slot alongside the
        active model. Every failable step runs before the slot is set
        (same atomicity contract as _load); no last-good fallback — a
        canary candidate is THAT instance or nothing."""
        with self._load_lock:
            instance = self.storage.get_metadata_engine_instances().get(
                instance_id)
            if instance is None:
                raise ValueError(f"Engine instance {instance_id} not found")
            if instance.status != "COMPLETED":
                raise ValueError(
                    f"candidate instance {instance_id} is "
                    f"{instance.status}, not COMPLETED")
            models = load_models(
                self.storage, self.engine, self.engine_params,
                instance.id, ctx=self.ctx,
            )
            _, _, algorithms, serving = self.engine._doers(self.engine_params)
            with self._lock:
                self._retire_algorithms(
                    self.candidate.algorithms if self.candidate else [])
                self.candidate = _CandidateArm(
                    instance=instance, models=models,
                    algorithms=algorithms, serving=serving)
                self._candidate_foldin_pending = {}
                self._candidate_item_pending = {}
        log.info("candidate arm loaded: instance %s", instance_id)

    def drop_candidate(self) -> None:
        """Discard the candidate arm (rollback). The active arm is
        untouched — in-flight queries that snapshotted the candidate
        finish on their snapshot; new ones never see it."""
        with self._lock:
            cand, self.candidate = self.candidate, None
            self._candidate_foldin_pending = {}
            self._candidate_item_pending = {}
            if cand is not None:
                self._retire_algorithms(cand.algorithms)

    def promote_candidate(self) -> None:
        """The candidate becomes the active instance (100%): one
        pointer swap under the lock, the exact shape _load uses. The
        outgoing active arm's resources retire on the usual delay.
        Queued candidate fold-ins flush under ``_load_lock`` (an upsert
        landing between an unlocked flush and the swap would be
        silently discarded); anything STILL pending at the swap — rank
        mismatch, or an apply racing the swap itself — is logged, and
        the next fold-in cycle re-solves those users."""
        with self._load_lock:
            self._flush_candidate_foldin()
            with self._lock:
                cand = self.candidate
                if cand is None:
                    raise ValueError("no candidate arm to promote")
                dropped = (len(self._candidate_foldin_pending)
                           + len(self._candidate_item_pending))
                if dropped:
                    log.warning(
                        "%d queued candidate fold-in row(s) could not "
                        "apply at promote and are dropped (next fold-in "
                        "cycle re-solves those users)", dropped)
                self._retire_algorithms(self.algorithms)
                self.instance = cand.instance
                self.models = cand.models
                self.algorithms = cand.algorithms
                self.serving = cand.serving
                self.candidate = None
                self._candidate_foldin_pending = {}
                self._candidate_item_pending = {}
        log.info("candidate promoted: instance %s now active",
                 self.instance.id)

    def _retire_algorithms(self, algorithms) -> None:
        """Close an arm's algorithm resources on a delay (see
        _load_locked: queries that snapshotted them may be mid-predict).
        Callers hold self._lock."""
        retired = [
            close for algo in algorithms
            if callable(close := getattr(algo, "close", None))
        ]
        if retired:
            # pio: lint-ok[context-loss] deliberate detach: the delayed
            # close must outlive the request (and its budget) that
            # triggered the reload
            t = threading.Timer(30.0, lambda: [c() for c in retired])
            t.daemon = True
            t.start()

    def _arm_snapshot(self, arm: str):
        """-> (models, algorithms, serving, instance_id) for the arm a
        query rides. A candidate request that races a just-finished
        rollback falls through to the active arm — a dropped arm is
        never served."""
        with self._lock:
            if arm == ARM_CANDIDATE and self.candidate is not None:
                c = self.candidate
                return c.models, c.algorithms, c.serving, c.instance.id
            return (self.models, self.algorithms, self.serving,
                    self.instance.id)

    def shadow_predict(self, q: dict, arm: str) -> Any:
        """Score `q` on one arm without stats, feedback, or plugins —
        the rollout controller's divergence sampler."""
        models, algorithms, serving, _ = self._arm_snapshot(arm)
        supplemented = serving.supplement(dict(q))
        predictions = [
            a.predict(m, supplemented) for a, m in zip(algorithms, models)
        ]
        return serving.serve(q, predictions)

    def close(self) -> None:
        """Release serving resources (predict pool, batcher thread, and any
        algorithm-held children such as external engine processes). The
        HTTP transport's stop() does not know about them."""
        if self.batcher is not None:
            self.batcher.close()
        if self.bucket_registry is not None:
            self.bucket_registry.flush()
        self._predict_pool.shutdown(wait=False)
        self._hedge_pool.shutdown(wait=False)
        if self.rollout is not None:
            self.rollout.close()
        arms = list(getattr(self, "algorithms", []))
        if self.candidate is not None:
            arms += self.candidate.algorithms
        for algo in arms:
            close = getattr(algo, "close", None)
            if callable(close):
                close()

    def _warm_bucket_set(self) -> list[int]:
        """The bucket sizes the warm sweep compiles: exactly what the
        LAST deployment of this engine served (bucket registry) when
        known, else the full power-of-two ladder up to batch_max."""
        recorded = (
            self.bucket_registry.buckets() if self.bucket_registry else []
        )
        recorded = [b for b in recorded if b <= self.config.batch_max]
        if recorded:
            return sorted(set(recorded) | {1})
        out = []
        b = 1
        while b <= self.config.batch_max:
            out.append(b)
            b *= 2
        return out

    def _warm(self) -> None:
        if self.config.warm_query is None:
            return
        try:
            # record=False: warm-up neither counts toward stats nor
            # generates feedback events
            self.query(dict(self.config.warm_query), record=False)
        except Exception:  # noqa: BLE001 - warmup is best-effort
            log.warning("warm query failed", exc_info=True)
        if self.batcher is None:
            return
        try:
            # compile the registry's bucket set (or the power-of-two
            # ladder) up front so the micro-batcher's varying batch sizes
            # never pay jit in traffic; with the persistent compile cache
            # each of these is a deserialize, not an XLA run
            for b in self._warm_bucket_set():
                self.query_batch(
                    [dict(self.config.warm_query)] * b, record=False
                )
            self._buckets_warmed = True
        except Exception:  # noqa: BLE001 - warmup is best-effort
            log.warning("warm batch failed", exc_info=True)
        finally:
            # ready either way: a failed warm means traffic pays the
            # compile, which beats a permanently not-ready instance
            self._buckets_ready.set()

    # -- query path (reference CreateServer.scala:492-615) ------------------
    def _auto_warm_buckets(self, sample: dict) -> None:
        """Compile every micro-batch bucket in the background using a clone
        of the first real query, so bucket-miss jit never lands mid-traffic
        (a fresh bucket costs a full XLA compile — tens of seconds through
        a remote tunnel, i.e. client-timeout territory). Explicit
        ServingConfig.warm_query still does this up-front at startup."""
        # atomic test-and-set: concurrent batch executions must not spawn
        # duplicate warm threads (each runs a full compile sweep)
        if self.batcher is None:
            return
        with self._warm_once:
            if self._buckets_warmed:
                return
            self._buckets_warmed = True
        # pio: lint-ok[attr-no-lock] threading.Event is internally locked
        self._buckets_ready.clear()  # /readyz drops while the sweep runs

        def go():
            try:
                for b in self._warm_bucket_set():
                    self.query_batch([dict(sample)] * b, record=False)
            except Exception:  # noqa: BLE001 - warmup is best-effort
                log.warning("background bucket warm failed", exc_info=True)
            finally:
                self._buckets_ready.set()

        # pio: lint-ok[context-loss] deliberate detach: bucket warm-up
        # is best-effort background compile priming, not on the
        # triggering request's clock or trace
        threading.Thread(
            target=go, name="bucket-warm", daemon=True
        ).start()

    def query(self, q: dict, record: bool = True) -> Any:
        t0 = time.monotonic()
        tr = self.tracer
        # guarded rollout: the controller picks the arm (sticky crc32c
        # user split); warm-ups (record=False) always ride active
        rollout = self.rollout if record else None
        arm = rollout.arm_for(q) if rollout is not None else ARM_ACTIVE
        # warm-up calls (record=False) must not enter the stage
        # histograms: their compile-heavy spans would pollute dashboard
        # quantiles AND the hedge-arming median (_hedge_timeout)
        span = tr.span if record else (lambda _n, **_kw: nullcontext())
        models, algorithms, serving, instance_id = self._arm_snapshot(arm)
        try:
            with span("supplement", arm=arm):
                supplemented = serving.supplement(q)
            with span("predict", arm=arm):
                if len(algorithms) > 1:
                    # concurrent per-algo predict (the parallelization
                    # the reference left as TODO, CreateServer.scala:516);
                    # device dispatch releases the GIL so the algos
                    # genuinely overlap. copy_context: predict runs ON
                    # the request path — the Deadline budget and trace
                    # must follow it onto the pool worker
                    futures = [
                        self._predict_pool.submit(
                            contextvars.copy_context().run,
                            a.predict, m, supplemented)
                        for a, m in zip(algorithms, models)
                    ]
                    predictions = [f.result() for f in futures]
                else:
                    predictions = [
                        algorithms[0].predict(models[0], supplemented)]
            with span("serve", arm=arm):
                prediction = serving.serve(q, predictions)
        except Exception:
            if rollout is not None:
                rollout.observe(arm, q, None, time.monotonic() - t0,
                                error=True)
            raise
        if rollout is not None:
            rollout.observe(arm, q, prediction, time.monotonic() - t0)
        if record:
            self._auto_warm_buckets(q)
        return self._postprocess(q, prediction, instance_id, record, t0)

    def _hedge_timeout(self) -> float | None:
        """Seconds after which a predict dispatch gets a duplicate, or
        None when hedging is off / not yet armed (needs 20 recorded spans
        so warm-up compiles never count as stalls)."""
        if self.config.hedge_after <= 0:
            return None
        h = self.tracer.histogram("predict")
        if h.count < 20:
            return None
        p50 = h.quantiles((0.5,))["p50"]
        if p50 <= 0:
            return None
        return max(0.05, self.config.hedge_after * p50)

    def _hedged(self, fn, *args):
        """Run fn on the predict pool; if it outlives the hedge timeout,
        race a duplicate and return whichever finishes first. fn must be
        pure (device predict is), so the loser is discarded harmlessly.

        The hedge clock starts when the task actually STARTS on a pool
        worker, not at submit: under >pool-width concurrent dispatches,
        queue wait would otherwise read as a "stall" and fire spurious
        duplicates into the already-saturated pool (round-3 advisor
        finding). If the task hasn't even started within the timeout,
        the pool is saturated — a duplicate could only queue behind the
        original, so hedging is skipped entirely."""
        timeout = self._hedge_timeout()
        if timeout is None:
            return fn(*args)
        started = threading.Event()
        t_start: list[float] = []

        def wrapped(*a):
            t_start.append(time.monotonic())
            started.set()
            return fn(*a)

        # copy_context on both attempts: the hedged dispatch is the
        # request's own predict — it must see the Deadline budget and
        # parent its spans into the request trace
        futs = [self._hedge_pool.submit(
            contextvars.copy_context().run, wrapped, *args)]
        if not started.wait(timeout):
            # saturated pool: no worker picked the task up within the
            # hedge window — duplicates add load without cutting latency
            return futs[0].result()
        try:
            remaining = t_start[0] + timeout - time.monotonic()
            return futs[0].result(timeout=max(0.0, remaining))
        except FuturesTimeoutError:
            with self._lock:
                self.hedged_dispatches += 1
            futs.append(self._hedge_pool.submit(
                contextvars.copy_context().run, fn, *args))
        # first SUCCESS wins; an attempt's exception propagates only once
        # every attempt has failed (a tunnel reset may fail the stalled
        # original while the duplicate is still inbound with the answer)
        pending = set(futs)
        first_exc: BaseException | None = None
        while pending:
            done, pending = futures_wait(
                pending, timeout=60.0, return_when=FIRST_COMPLETED
            )
            for f in done:
                exc = f.exception()
                if exc is None:
                    for loser in pending:
                        loser.cancel()  # free not-yet-started duplicates
                    return f.result()
                first_exc = first_exc or exc
        raise first_exc

    def query_batch(self, queries: list[dict], record: bool = True,
                    observe_batch_errors: bool = True) -> list:
        """Serve several queries as one batch_predict per algorithm (the
        micro-batching execution path; also the bulk path behind
        /batch/queries.json). With a rollout in flight the batch is
        partitioned by arm — each sub-batch executes against its own
        arm's models, results reassemble in request order.

        observe_batch_errors=False is for callers that retry each query
        SOLO after a batch failure (QueryBatcher/ContinuousBatcher): the
        solo retries record per-arm rollout stats themselves, so the
        batch-level error observation here would double-count every
        member and skew the latency-ratio guard. Double-count audit:
        `query(record=False)` takes rollout=None (no stats at all), and
        `_hedged` duplicates run the bare predict fn — neither path ever
        re-records a request's per-arm stats; this flag closes the one
        path that did."""
        t0 = time.monotonic()
        rollout = self.rollout if record else None
        if rollout is not None:
            arms = [rollout.arm_for(q) for q in queries]
            if ARM_CANDIDATE in arms:
                out: list = [None] * len(queries)
                for arm in (ARM_ACTIVE, ARM_CANDIDATE):
                    idx = [i for i, a in enumerate(arms) if a == arm]
                    if not idx:
                        continue
                    sub = self._query_batch_arm(
                        [queries[i] for i in idx], arm, record, t0,
                        rollout, observe_batch_errors)
                    for i, r in zip(idx, sub):
                        out[i] = r
                return out
        return self._query_batch_arm(queries, ARM_ACTIVE, record, t0,
                                     rollout, observe_batch_errors)

    def _query_batch_arm(self, queries: list[dict], arm: str, record: bool,
                         t0: float, rollout,
                         observe_batch_errors: bool = True) -> list:
        tr = self.tracer
        # see query(): warm-up spans stay out of the histograms
        span = tr.span if record else (lambda _n, **_kw: nullcontext())
        # per-ARM clock for the rollout stats (t0 stays the whole-batch
        # clock for _postprocess bookkeeping): the arms execute
        # sequentially, so charging candidate observations from the
        # whole-batch start would bill the active sub-batch's time to
        # the candidate and trip the latency-ratio guard on perfectly
        # healthy canaries
        arm_t0 = time.monotonic()
        models, algorithms, serving, instance_id = self._arm_snapshot(arm)
        try:
            return self._query_batch_body(
                queries, arm, record, t0, arm_t0, rollout, span, models,
                algorithms, serving, instance_id)
        except Exception:
            if rollout is not None and observe_batch_errors:
                # per-QUERY time (sub-batch wall / size): whole-batch
                # time would make each arm's mean scale with its share
                # of the split — at 25% the candidate would look 3x
                # faster (slow canary promoted) and at 80% 4x slower
                # (healthy canary rolled back)
                dt = (time.monotonic() - arm_t0) / max(1, len(queries))
                for q in queries:
                    rollout.observe(arm, q, None, dt, error=True)
            raise

    def _query_batch_body(self, queries, arm, record, t0, arm_t0, rollout,
                          span, models, algorithms, serving, instance_id):
        with span("supplement", arm=arm):
            supplemented = [serving.supplement(q) for q in queries]
        with span("predict", arm=arm):
            if len(algorithms) > 1:
                futures = [
                    self._predict_pool.submit(
                        contextvars.copy_context().run,
                        self._hedged, a.batch_predict, m, supplemented)
                    for a, m in zip(algorithms, models)
                ]
                per_algo = [f.result() for f in futures]
            else:
                per_algo = [
                    self._hedged(
                        algorithms[0].batch_predict, models[0], supplemented)
                ]
        if record and queries:
            # the batched path is the PRIMARY path when the batcher is on
            # (query() is bypassed), so auto-warm must hook here too; the
            # warm calls themselves pass record=False and cannot recurse
            self._auto_warm_buckets(queries[0])
            if self.bucket_registry is not None:
                # remember the pow2 bucket this batch landed in so the
                # NEXT deployment's warm sweep compiles exactly the set
                # this one served
                self.bucket_registry.record(
                    min(1 << (len(queries) - 1).bit_length(),
                        self.config.batch_max))
        with span("serve", arm=arm):
            predictions = [
                serving.serve(q, [algo_out[i] for algo_out in per_algo])
                for i, q in enumerate(queries)
            ]
        if rollout is not None:
            # per-query time, not whole-sub-batch time — see the error
            # path above
            dt = (time.monotonic() - arm_t0) / max(1, len(queries))
            for q, p in zip(queries, predictions):
                rollout.observe(arm, q, p, dt)
        return [
            self._postprocess(q, p, instance_id, record, t0)
            for q, p in zip(queries, predictions)
        ]

    def _postprocess(self, q, prediction, instance_id, record, t0):
        if record and self.config.feedback:
            prediction = self._feedback(q, prediction, instance_id)
        for blocker in self.plugins.output_blockers:
            prediction = blocker.process(
                q, prediction, {"engineInstanceId": instance_id}
            )
        if record:
            self.tracer.record("query", time.monotonic() - t0)
        return prediction

    def _feedback(self, query: dict, prediction: Any, instance_id: str):
        """Record the prediction as a pio_pr 'predict' event
        (reference CreateServer.scala:536-598). In-process insert — there is
        no separate event-server JVM to POST across."""
        import secrets

        pr_id = None
        if isinstance(prediction, dict):
            pr_id = prediction.get("prId") or None
        new_pr_id = pr_id or secrets.token_urlsafe(48)[:64]
        event = Event(
            event="predict",
            entity_type="pio_pr",
            entity_id=new_pr_id,
            properties={
                "engineInstanceId": instance_id,
                "query": query,
                "prediction": prediction,
            },
            pr_id=query.get("prId") if isinstance(query, dict) else None,
        )

        def send():
            try:
                app = self.storage.get_metadata_apps().get_by_name(
                    self.config.feedback_app_name
                )
                if app is None:
                    log.error(
                        "feedback app %r not found",
                        self.config.feedback_app_name,
                    )
                    return
                self.storage.get_events().insert(event, app.id)
            except Exception:  # noqa: BLE001 - feedback must not fail serving
                log.error("feedback event failed", exc_info=True)

        # pio: lint-ok[context-loss] deliberate detach (see
        # Deadline docstring): the feedback insert must not be
        # cancelled by the request's exhausted budget, and it runs
        # after the response is already decided
        threading.Thread(target=send, daemon=True).start()
        if isinstance(prediction, dict) and "prId" in prediction:
            prediction = dict(prediction, prId=new_pr_id)
        return prediction

    # -- streaming fold-in (pio_tpu/freshness/) ------------------------------
    def foldin_upsert(self, rows, staleness_s: float | None = None,
                      items=None) -> dict:
        """Hot-swap refreshed user factor rows into the serving model
        (the freshness subsystem's apply surface): existing users'
        rows are replaced in place, new users are APPENDED — id index
        and factor table extended together, so ``recommend_topk`` and
        the id decode stay aligned. Last-good semantics: the new model
        is built completely OUTSIDE the lock and swapped atomically; a
        failure anywhere leaves the previous model serving untouched.
        ``rows`` maps user id → (k,)-float sequence. With a rollout in
        flight the rows land on BOTH arms (or queue for the candidate),
        so streaming freshness never silently diverges the experiment.

        ``items`` maps item id → (k,)-float sequence and upserts
        EXISTING items' factor rows in the same atomic swap — including
        the two-stage retrieval sidecar (ops/retrieval.py): the cached
        quantized table and cluster assignments are re-encoded for
        exactly the touched rows, so an upserted item is retrievable
        through the candidate tier immediately after this call returns,
        not after a lazy rebuild. Unknown item ids are REJECTED (shard
        parity: appending an item needs a global dense index that only
        a retrain/repartition assigns)."""
        rows = rows or {}
        items = items or {}
        if not rows and not items:
            with self._lock:
                return {"applied": 0, "new": 0,
                        "engineInstanceId": self.instance.id}
        with self._lock:
            models = list(self.models)
            instance_id = self.instance.id
        mi, model, new_model, new_ids = _fold_rows_into(models, rows)
        items_applied, items_rejected = 0, []
        if items:
            new_model, items_applied, items_rejected = \
                _fold_item_rows_into(new_model, items)
        with self._lock:
            # the model may have moved while we built the new one: a
            # /reload (new instance — applying stale rows onto it would
            # mix factor spaces) or a CONCURRENT fold-in apply (swapping
            # over it would silently drop the other batch's rows, which
            # the folder then never refolds — its cursor advanced).
            # Object identity catches both; report instead of guessing
            if (self.instance.id != instance_id
                    or self.models[mi] is not model):
                raise ValueError(
                    f"serving model changed (instance {instance_id} -> "
                    f"{self.instance.id}, or a concurrent fold-in apply) "
                    "during fold-in apply; retry")
            models = list(self.models)
            models[mi] = new_model
            self.models = models
            self.foldin_applied_users += len(rows)
            self.foldin_applied_items += items_applied
            self.foldin_last_time = utcnow()
            if staleness_s is not None:
                self.foldin_last_staleness_s = float(staleness_s)
        out = {"applied": len(rows), "new": len(new_ids),
               "engineInstanceId": instance_id}
        if items:
            out["itemsApplied"] = items_applied
            out["itemsRejected"] = items_rejected
        # second arm: the ACTIVE apply above is the durable one (the
        # folder's cursor advances on it); the candidate apply is
        # best-effort-with-queue — a failure parks the rows in
        # _candidate_foldin_pending and retries on the next apply (and
        # at promote), never blocking freshness on the experiment
        with self._lock:
            has_candidate = self.candidate is not None
        if has_candidate:
            out["candidateQueued"] = self._apply_foldin_to_candidate(
                rows, items)
        return out

    def _apply_foldin_to_candidate(self, rows, items=None) -> int:
        """Apply `rows`/`items` (plus anything previously queued) to the
        candidate arm. Returns the queue depth left behind (0 = fully
        applied). Never raises: the active apply already succeeded and
        the folder must not re-solve the window for a canary hiccup."""
        with self._lock:
            cand = self.candidate
            if cand is None:
                self._candidate_foldin_pending = {}
                self._candidate_item_pending = {}
                return 0
            pending = dict(self._candidate_foldin_pending)
            pending.update(rows)
            pending_items = dict(self._candidate_item_pending)
            pending_items.update(items or {})
            models = list(cand.models)
        try:
            mi, model, new_model, _ = _fold_rows_into(models, pending)
            if pending_items:
                new_model, _, _ = _fold_item_rows_into(
                    new_model, pending_items)
        except ValueError as e:
            with self._lock:
                self._candidate_foldin_pending = pending
                self._candidate_item_pending = pending_items
            log.warning("fold-in rows queued for candidate arm (%d "
                        "users, %d items): %s", len(pending),
                        len(pending_items), e)
            return len(pending) + len(pending_items)
        with self._lock:
            cand = self.candidate
            if cand is None:
                self._candidate_foldin_pending = {}
                self._candidate_item_pending = {}
                return 0
            if cand.models[mi] is not model:
                # the arm moved mid-build (promote/drop/another apply):
                # queue and let the next apply land on the new arm
                self._candidate_foldin_pending = pending
                self._candidate_item_pending = pending_items
                return len(pending) + len(pending_items)
            cand_models = list(cand.models)
            cand_models[mi] = new_model
            self.candidate = _CandidateArm(
                instance=cand.instance, models=cand_models,
                algorithms=cand.algorithms, serving=cand.serving)
            self._candidate_foldin_pending = {}
            self._candidate_item_pending = {}
        return 0

    def _flush_candidate_foldin(self) -> None:
        """Drain queued candidate fold-ins (called before promote so
        the promoted arm is as fresh as the active one was)."""
        with self._lock:
            pending = dict(self._candidate_foldin_pending)
            pending_items = dict(self._candidate_item_pending)
        if pending or pending_items:
            self._apply_foldin_to_candidate(pending, pending_items)

    def foldin_status(self) -> dict:
        """Bounded-staleness accounting for /readyz + /metrics.json."""
        with self._lock:
            return {
                "appliedUsers": self.foldin_applied_users,
                "appliedItems": self.foldin_applied_items,
                "lastAppliedTime": (format_time(self.foldin_last_time)
                                    if self.foldin_last_time else None),
                "stalenessSeconds": self.foldin_last_staleness_s,
                "candidateQueued": (len(self._candidate_foldin_pending)
                                    + len(self._candidate_item_pending)),
            }

    # -- status -------------------------------------------------------------
    @property
    def request_count(self) -> int:
        return self.tracer.histogram("query").count

    @property
    def avg_serving_sec(self) -> float:
        h = self.tracer.histogram("query")
        return h.total / h.count if h.count else 0.0

    @property
    def last_serving_sec(self) -> float:
        return self.tracer.histogram("query").last

    def status(self) -> dict:
        with self._lock:
            return {
                "status": "alive",
                "engineInstance": {
                    "id": self.instance.id,
                    "engineId": self.instance.engine_id,
                    "engineVersion": self.instance.engine_version,
                    "engineVariant": self.instance.engine_variant,
                    "startTime": format_time(self.instance.start_time),
                },
                "startTime": format_time(self.start_time),
                "requestCount": self.request_count,
                "avgServingSec": round(self.avg_serving_sec, 6),
                "lastServingSec": round(self.last_serving_sec, 6),
            }

    def metrics(self) -> dict:
        """Per-stage latency histograms (p50/p90/p95/p99 over the recent
        window, all-time count/avg) — the serving observability surface.
        ``exemplars`` link each span's slowest recent occurrence to a
        trace id fetchable with ``pio trace <id>``."""
        out = {
            "startTime": format_time(self.start_time),
            "spans": self.tracer.snapshot(),
            "hedgedDispatches": self.hedged_dispatches,
            "foldin": self.foldin_status(),
        }
        if self.recorder is not None:
            out["exemplars"] = self.recorder.exemplars()
        return out


def _fold_rows_into(models: list, rows) -> tuple:
    """Build an updated factor-table model with `rows` upserted —
    existing users replaced in place, new users appended with the id
    index extended in lockstep. Pure with respect to serving state (the
    caller swaps under its lock): returns
    ``(model_index, old_model, new_model, new_ids)``. Raises ValueError
    when no deployed model has a factor table or a row's rank
    mismatches."""
    import dataclasses

    import jax.numpy as jnp
    import numpy as np

    for mi, model in enumerate(models):
        factors = getattr(model, "factors", None)
        if (getattr(factors, "user_factors", None) is not None
                and getattr(model, "users", None) is not None):
            break
    else:
        raise ValueError(
            "fold-in needs a factor-table model (factors.user_factors "
            "+ users index); none of the deployed models qualifies")
    uf = model.factors.user_factors
    k = int(uf.shape[1])
    users = model.users
    existing: list[tuple[int, list[float]]] = []
    new_ids: list = []
    new_rows: list = []
    for uid, row in rows.items():
        if len(row) != k:
            raise ValueError(
                f"fold-in row for {uid!r} has {len(row)} dims, model "
                f"rank is {k}")
        if uid in users:
            existing.append((users.index_of(uid), row))
        else:
            new_ids.append(uid)
            new_rows.append(row)
    new_uf = uf
    if existing:
        idx = np.fromiter((i for i, _ in existing), np.int32,
                          count=len(existing))
        vals = np.asarray([r for _, r in existing], np.float32)
        new_uf = new_uf.at[jnp.asarray(idx)].set(jnp.asarray(vals))
    if new_ids:
        new_uf = jnp.concatenate(
            [new_uf, jnp.asarray(np.asarray(new_rows, np.float32))])
    new_model = dataclasses.replace(
        model,
        factors=dataclasses.replace(model.factors, user_factors=new_uf),
        users=users.extended(new_ids) if new_ids else users,
    )
    # a user-only fold-in leaves item_factors the SAME array object, so
    # the retrieval sidecar cache (keyed by item-table identity in
    # models/recommendation.py) stays valid — carry it so a user upsert
    # never forces a k-means rebuild on the next clustered query
    cache = getattr(model, "_retrieval_cache", None)
    if cache is not None:
        new_model._retrieval_cache = cache
    return mi, model, new_model, new_ids


def _fold_item_rows_into(model, items) -> tuple:
    """Upsert EXISTING items' factor rows on `model` — the item-side
    half of streaming fold-in. Returns ``(new_model, applied,
    rejected_ids)``; unknown ids are rejected, not appended (appending
    an item needs the retrieval/partition tier's dense index space to
    grow, which only a retrain assigns — shard.upsert_item_rows makes
    the same call). When the model carries a two-stage retrieval cache
    for its current item table, the quantized rows and cluster
    assignments are re-encoded for the touched positions IN THIS BUILD,
    so the swap that publishes the f32 rows publishes the candidate
    tier's view of them too — never a stale quantized row serving
    beside a fresh f32 one. Raises ValueError on rank mismatch."""
    import dataclasses

    import jax.numpy as jnp
    import numpy as np

    itf = getattr(getattr(model, "factors", None), "item_factors", None)
    if itf is None or getattr(model, "items", None) is None:
        raise ValueError(
            "item fold-in needs a factor-table model (factors."
            "item_factors + items index); the deployed model "
            "does not qualify")
    k = int(itf.shape[1])
    positions: list[int] = []
    vals: list = []
    rejected: list = []
    for iid, row in items.items():
        if len(row) != k:
            raise ValueError(
                f"fold-in row for item {iid!r} has {len(row)} dims, "
                f"model rank is {k}")
        if iid in model.items:
            positions.append(model.items.index_of(iid))
            vals.append(row)
        else:
            rejected.append(iid)
    if not positions:
        return model, 0, rejected
    pos = np.fromiter(positions, np.int32, count=len(positions))
    rows_f32 = np.asarray(vals, np.float32)
    new_itf = itf.at[jnp.asarray(pos)].set(jnp.asarray(rows_f32))
    new_model = dataclasses.replace(
        model,
        factors=dataclasses.replace(model.factors, item_factors=new_itf),
    )
    cache = getattr(model, "_retrieval_cache", None)
    if cache is not None and cache[0] is itf:
        from pio_tpu.ops import retrieval as rt

        idx, _didx = cache[1]
        new_idx = idx.updated(pos, rows_f32)
        new_model._retrieval_cache = (
            new_itf, (new_idx, rt.build_device_index(new_idx)))
    return new_model, len(positions), rejected


def _depth_for_rtt(rtt_s: float) -> int:
    """Dispatch-RTT -> pipeline depth. High-RTT (remote/tunneled) devices
    want several batches in flight to hide the link; local devices get
    TWO. Evidence (eval/SERVING_TAIL.md, medians over repeated runs):
    depth 1 is unstable across sessions — median p99 anywhere from ~10
    to ~95 ms, because with one batch in flight any stall serializes the
    whole queue behind it — while depths 2 and 4 both hold p99 ~10-15 ms
    warm. 2 is the minimal depth that achieves that stability; it also
    bounds how deep a queue can build behind a stalled batch, the
    suspected mechanism of round-2's 357 ms p99 outlier (BENCH_r02
    async_batched ran depth 4; the committed medians could not reproduce
    that tail, so it is recorded as motivation, not proof). Note this
    sizes the pipeline GIVEN that the operator enabled batching; whether
    batching pays at all over a high-RTT link is a separate call
    (BASELINE.md: the tunnel pipelines per-query dispatches well enough
    that per-query serving won end-to-end)."""
    return 4 if rtt_s > 0.005 else 2


_auto_depth_cache: int | None = None
_auto_depth_lock = threading.Lock()


def _auto_pipeline_depth() -> int:
    """Resolve ServingConfig.batch_pipeline=0: measure the device dispatch
    round-trip once per process (cached — re-deploys and multi-engine
    processes skip the probe) and map it via _depth_for_rtt. Probe and
    cache write run under a lock: two engines deploying concurrently must
    not both pay the probe (found by `pio lint`, global-no-lock)."""
    global _auto_depth_cache
    with _auto_depth_lock:
        if _auto_depth_cache is not None:
            return _auto_depth_cache
        try:
            import jax
            import jax.numpy as jnp

            one = jnp.ones(())
            add = jax.jit(lambda x: x + 1)
            # pio: lint-ok[blocking-under-lock] one-time boot probe:
            # the lock exists to serialize exactly this measurement
            # (docstring above); steady state returns the cache
            jax.block_until_ready(add(one))  # compile, not measurement
            samples = []
            for _ in range(5):
                t0 = time.monotonic()
                jax.block_until_ready(add(one))
                samples.append(time.monotonic() - t0)
            depth = _depth_for_rtt(sorted(samples)[len(samples) // 2])
        except Exception:  # noqa: BLE001 - sizing must never fail boot
            depth = 2
        _auto_depth_cache = depth
        return depth


class QueryBatcher:
    """Dynamic micro-batching: requests enqueue, a collector thread drains
    up to `max_batch` of them within `window_s`, and each batch executes as
    one `query_batch` ON A POOL — so several batches stay in flight at once.
    One big top-k matmul replaces N small ones (the MXU-friendly shape) and
    the pipelining keeps throughput up even when a device dispatch is
    round-trip-dominated (remote/tunneled TPU); cost is up to window_s
    added latency, so it is off unless ServingConfig.batch_window_ms is
    set.

    window_s < 0 selects ADAPTIVE batching: the collector never waits —
    it drains everything already queued and hands it off, so while a
    batch executes the next one accumulates. Batch size then self-tunes
    to arrival_rate x execution_time with ZERO added latency at low
    load; a fixed window can only lose against it when execution is
    RTT-dominated. NOTE the measured inversion on a TUNNELED device
    (BASELINE.md): the tunnel pipelines per-query dispatches so well that
    batching only adds coordination — batch when co-located with the
    accelerator, serve per-query over high-RTT links."""

    def __init__(self, server: QueryServer, window_s: float, max_batch: int,
                 pipeline_depth: int):
        self.server = server
        self.window_s = window_s
        self.max_batch = max_batch
        self._q: queue.Queue[tuple[dict, Future]] = queue.Queue()
        self._closed = False
        self._pool = ThreadPoolExecutor(
            max_workers=pipeline_depth, thread_name_prefix="batch-exec"
        )
        # backpressure: ThreadPoolExecutor.submit never blocks, so without
        # this bound the collector shreds the queue into 1-sized batches
        # that pile up in the executor's unbounded queue — no batch ever
        # forms and latency becomes queue wait (measured on the tunneled
        # v5e: 27 qps / p50 490ms without it). Acquired BEFORE draining,
        # so requests accumulate while all pipeline slots are busy and
        # each freed slot takes a real batch (CPU co-located, 16 clients:
        # batched 6.6ms p50 / 1499 qps vs unbatched async 12.6ms / 1242).
        self._slots = threading.BoundedSemaphore(pipeline_depth)
        self._thread = threading.Thread(
            target=self._run, name="query-batcher", daemon=True
        )
        self._thread.start()

    def query(self, q: dict) -> Any:
        fut: Future = Future()
        self._q.put((q, fut))
        # batch execution runs on the batcher pool, which does not
        # inherit the caller's Deadline contextvar — enforce the budget
        # here, at the wait (the batch result lands harmlessly later)
        timeout = Deadline.remaining()
        try:
            return fut.result(timeout=timeout)
        except FuturesTimeoutError:
            raise DeadlineExceeded(
                "request budget exhausted waiting for batch execution"
            ) from None

    def _run(self):
        while not self._closed:
            try:
                first = self._q.get(timeout=0.5)
            except queue.Empty:
                continue
            self._slots.acquire()  # wait for a pipeline slot FIRST
            batch = [first]
            if self.window_s < 0:  # adaptive: take what's there, no wait
                while len(batch) < self.max_batch:
                    try:
                        batch.append(self._q.get_nowait())
                    except queue.Empty:
                        break
            else:
                deadline = time.monotonic() + self.window_s
                while len(batch) < self.max_batch:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    try:
                        batch.append(self._q.get(timeout=remaining))
                    except queue.Empty:
                        break
            # hand off and go straight back to collecting the next batch
            try:
                self._pool.submit(self._execute, batch)
            except RuntimeError as e:
                self._slots.release()
                # close() raced the collection: fail the batch's waiters
                # rather than stranding them on never-set futures
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(e)
                return

    def _execute(self, batch: list[tuple[dict, Future]]):
        queries = [q for q, _ in batch]
        try:
            self._do_execute(batch, queries)
        finally:
            self._slots.release()

    def _do_execute(self, batch, queries):
        try:
            # observe_batch_errors=False: the per-query retry below
            # records each member's rollout stats exactly once on a
            # batch failure (see query_batch's double-count audit)
            results = self.server.query_batch(
                queries, observe_batch_errors=False)
            for (_, fut), res in zip(batch, results):
                fut.set_result(res)
        except Exception:  # noqa: BLE001 - isolate the bad query
            # one malformed query must not fail its batch-mates: retry
            # each one alone so only the offender sees the error
            for q, fut in batch:
                if fut.done():
                    continue
                try:
                    fut.set_result(self.server.query(q))
                except Exception as e:  # noqa: BLE001
                    fut.set_exception(e)

    def close(self):
        self._closed = True
        self._pool.shutdown(wait=False)


def build_serving_app(server: QueryServer) -> HttpApp:
    app = HttpApp("serving")
    config = server.config

    def check_server_key(req: Request) -> bool:
        return server_key_ok(req, config.server_key)

    @app.route("GET", r"/")
    def root(req: Request):
        return 200, server.status()

    def _budgeted(fn):
        """Run one query dispatch under the per-request Deadline budget
        (ServingConfig.request_budget_s); exhausted budgets and tripped
        storage breakers surface as 503 + Retry-After instead of a 500
        or a connection held past its usefulness."""
        try:
            if config.request_budget_s > 0:
                with Deadline.budget(config.request_budget_s):
                    return 200, fn()
            return 200, fn()
        except KeyError as e:
            return 400, {"message": f"query missing field {e}"}
        except DeadlineExceeded as e:
            return 503, json_response(
                {"message": f"request budget exhausted: {e}"},
                {"Retry-After": "1"},
            )
        except CircuitOpenError as e:
            return 503, json_response(
                {"message": str(e)},
                {"Retry-After": f"{max(1, round(e.retry_after_s))}"},
            )

    @app.route("POST", r"/queries\.json")
    def queries(req: Request):
        try:
            q = req.json()
        except Exception as e:  # noqa: BLE001 - malformed body
            return 400, {"message": f"Invalid query: {e}"}
        if not isinstance(q, dict):
            return 400, {"message": "query must be a JSON object"}
        if server.batcher is not None:
            return _budgeted(lambda: server.batcher.query(q))
        return _budgeted(lambda: server.query(q))

    @app.route("POST", r"/batch/queries\.json")
    def batch_queries(req: Request):
        """Bulk endpoint: a JSON array of queries answered by one
        batch_predict per algorithm (no reference analogue; the event
        server's /batch/events.json shape applied to serving)."""
        try:
            qs = req.json()
        except Exception as e:  # noqa: BLE001 - malformed body
            return 400, {"message": f"Invalid query batch: {e}"}
        if not isinstance(qs, list) or not all(isinstance(q, dict) for q in qs):
            return 400, {"message": "body must be a JSON array of objects"}
        if not qs:
            return 200, []
        return _budgeted(lambda: server.query_batch(qs))

    @app.route("POST", r"/model/upsert_users")
    def upsert_users(req: Request):
        """Streaming fold-in apply surface (pio_tpu/freshness/): body
        ``{"users": {id: [row]}, "items"?: {id: [row]},
        "stalenessSeconds"?: s}``. Item rows upsert existing items AND
        their two-stage retrieval sidecar in the same swap. Guarded
        like /reload — it mutates the serving model."""
        if not check_server_key(req):
            return 401, {"message": "Invalid accessKey."}
        try:
            body = req.json()
        except Exception as e:  # noqa: BLE001 - malformed body
            return 400, {"message": f"Invalid body: {e}"}
        users = body.get("users") if isinstance(body, dict) else None
        items = body.get("items") if isinstance(body, dict) else None
        if not isinstance(users, dict) and not isinstance(items, dict):
            return 400, {"message": "body must be {\"users\": {id: [row]}}"
                                    " and/or {\"items\": {id: [row]}}"}
        try:
            out = server.foldin_upsert(
                users if isinstance(users, dict) else {},
                body.get("stalenessSeconds"),
                items=items if isinstance(items, dict) else {})
        except ValueError as e:
            return 400, {"message": str(e)}
        return 200, out

    @app.route("POST", r"/reload")
    @app.route("GET", r"/reload")  # deprecated alias: reload MUTATES
    # serving state, so POST is the canonical route (docs/serving.md);
    # GET remains for pre-PR-8 clients and scripts
    def reload(req: Request):
        if not check_server_key(req):
            return 401, {"message": "Invalid accessKey."}
        try:
            instance_id = server.reload()
        except Exception as e:  # noqa: BLE001 - degrade, don't die
            # last-good serving: the failed load left the previous
            # instance fully in place (see QueryServer._load), so report
            # the failure AND what is still serving
            with server._lock:
                still = server.instance.id
            return 503, json_response(
                {"message": f"Reload failed ({type(e).__name__}: {e}); "
                            "still serving last-good model",
                 "engineInstanceId": still},
                {"Retry-After": "1"},
            )
        return 200, {"message": "Reloaded", "engineInstanceId": instance_id}

    @app.route("POST", r"/stop")
    def stop(req: Request):
        if not check_server_key(req):
            return 401, {"message": "Invalid accessKey."}
        server._stop_requested.set()
        return 200, {"message": "Shutting down."}

    @app.route("GET", r"/metrics\.json")
    def metrics(req: Request):
        return 200, server.metrics()

    @app.route("GET", r"/metrics")
    def metrics_prometheus(req: Request):
        """Prometheus text exposition of the same data as /metrics.json
        (span latency summaries + counters) for scrape-based stacks —
        through the shared renderer with the uniform `surface` label
        (docs/observability.md)."""
        from pio_tpu.server.http import RawResponse
        from pio_tpu.utils.httpclient import pool_counters
        from pio_tpu.utils.tracing import (
            PROMETHEUS_CONTENT_TYPE, prometheus_text,
        )

        counters = {
            "hedged_dispatches_total": float(server.hedged_dispatches),
            "foldin_applied_users_total":
                float(server.foldin_applied_users),
            "uptime_seconds":
                (utcnow() - server.start_time).total_seconds(),
        }
        # outbound keep-alive pool (docs/performance.md "Internal RPC
        # plane"): the serving process's storage DAO RPCs ride it
        counters.update(pool_counters())
        text = prometheus_text(server.tracer.snapshot(), counters,
                               labels={"surface": "serving"})
        batcher = server.batcher
        if batcher is not None and hasattr(batcher, "occupancy_exposition"):
            # continuous batching: batch-occupancy distribution (fraction
            # of batch_max per coalesced dispatch) as a real histogram
            # family — the occupancy-pinned-at-1.0 saturation signal
            # docs/observability.md documents
            from pio_tpu.utils.tracing import prometheus_histogram

            buckets, counts, total, occ_sum = batcher.occupancy_exposition()
            text += "\n".join(prometheus_histogram(
                "serving_batch_occupancy", buckets, counts, total, occ_sum,
                labels={"surface": "serving"})) + "\n"
        return 200, RawResponse(text, PROMETHEUS_CONTENT_TYPE)

    @app.route("GET", r"/batcher\.json")
    def batcher_status(req: Request):
        """Admission-stage visibility: which batcher fronts the device
        program (continuous / micro / none) and its live counters —
        dispatches, coalesced queries, occupancy, coalesce wait, solo
        bypasses and deadline sheds (docs/serving.md "Continuous
        batching")."""
        batcher = server.batcher
        if batcher is None:
            return 200, {"mode": None, "enabled": False}
        if hasattr(batcher, "stats"):
            return 200, {"enabled": True, **batcher.stats()}
        return 200, {
            "enabled": True, "mode": "micro",
            "windowMs": config.batch_window_ms,
            "maxBatch": config.batch_max,
        }

    @app.route("POST", r"/batcher/window")
    def batcher_window(req: Request):
        """Live coalesce-window retune (server-key guarded, like /reload):
        the occupancy runbook's knob — widen a window whose batches run
        near-empty, narrow one pinned at occupancy 1.0 — without a
        redeploy. Continuous batcher only."""
        if not check_server_key(req):
            return 401, {"message": "Invalid accessKey."}
        batcher = server.batcher
        if batcher is None or not hasattr(batcher, "set_window"):
            return 409, {"message": "continuous batching is not enabled "
                                    "(ServingConfig.coalesce_window_ms)"}
        try:
            body = req.json()
            window_ms = float(body["windowMs"])
        except Exception as e:  # noqa: BLE001 - malformed body
            return 400, {"message": f"body must be {{\"windowMs\": ms}}: "
                                    f"{e}"}
        if not (0 < window_ms <= 1000):
            return 400, {"message": "windowMs must be in (0, 1000]"}
        batcher.set_window(window_ms / 1e3)
        return 200, {"message": "window updated", **batcher.stats()}

    @app.route("POST", r"/profile/start")
    def profile_start(req: Request):
        """Capture a device (XLA/TPU) trace while serving — the TPU
        equivalent of attaching the Spark UI. Guarded like /stop."""
        if not check_server_key(req):
            return 401, {"message": "Invalid accessKey."}
        from pio_tpu.utils.tracing import start_device_profile

        logdir = req.params.get("logdir", "/tmp/pio_tpu_profile")
        if not start_device_profile(logdir):
            return 409, {"message": "profile already running"}
        return 200, {"message": "profiling", "logdir": logdir}

    @app.route("POST", r"/profile/stop")
    def profile_stop(req: Request):
        if not check_server_key(req):
            return 401, {"message": "Invalid accessKey."}
        from pio_tpu.utils.tracing import stop_device_profile

        logdir = stop_device_profile()
        if logdir is None:
            return 409, {"message": "no profile running"}
        return 200, {"message": "profile written", "logdir": logdir}

    def readiness() -> dict:
        """model loaded + storage breakers not open + async-transport
        queue under its shed watermark (resilience/health.py contract)."""
        checks = breaker_checks(server.storage)
        with server._lock:
            inst = getattr(server, "instance", None)
        checks["model"] = {
            "ok": inst is not None,
            "engineInstanceId": inst.id if inst is not None else None,
            "lastReloadError": server.last_reload_error,
        }
        # fold-in visibility, NEVER a readiness gate: a stale/absent
        # folder means batch-stale serving (degraded freshness), and
        # flipping serving readyz for it would turn that degradation
        # into the outage the freshness contract forbids
        checks["freshness"] = {"ok": True, **server.foldin_status()}
        # bucket-warm gate: NOT ready while a micro-batch warm sweep is
        # owed or in flight — a balancer that routes on /readyz never
        # lands traffic in a bucket-miss XLA compile (BENCH_r05's 187 ms
        # async_batched cold-start p99). Always-true when batching is off
        # or no warm query is configured (the sweep then rides the first
        # real request, which readiness must not deadlock on).
        if server.batcher is not None:
            checks["buckets"] = {
                "ok": server._buckets_ready.is_set(),
                "warmed": server._buckets_warmed,
                "registry": (server.bucket_registry.buckets()
                             if server.bucket_registry else None),
            }
        # rollout visibility, never a readiness gate: a breached canary
        # auto-rolls-back to the active arm — the server stays ready
        # throughout (that atomic revert is the whole point)
        rollout = server.rollout
        if rollout is not None:
            st = rollout.status()
            checks["rollout"] = {
                "ok": True,
                "stagePct": st["stagePct"],
                "verdict": st["verdict"],
                "candidateInstanceId": st["candidateInstanceId"],
            }
        checks.update(shedder_check(getattr(app, "transport", None)))
        return checks

    install_health_routes(app, readiness)
    # distributed tracing (pio_tpu/obs/): /debug/traces.json +
    # /debug/spans.json, and app.recorder switches the dispatch edge
    # into traced mode; app.tracer feeds the per-surface `request`
    # histogram
    from pio_tpu.obs.http import install_trace_routes

    app.tracer = server.tracer
    install_trace_routes(app, server.recorder, check_server_key)
    # guarded rollout verbs (pio_tpu/rollout/): /rollout/deploy,
    # /rollout/promote, /rollout/rollback (server-key guarded) +
    # /rollout/status
    install_rollout_routes(app, server, server.storage, check_server_key)

    @app.route("GET", r"/plugins\.json")
    def plugins_list(req: Request):
        return 200, {
            "plugins": {
                p.plugin_name: {"type": p.plugin_type}
                for p in server.plugins.plugins
            }
        }

    @app.route("GET", r"/plugins/([^/]+)(/.*)?")
    def plugin_rest(req: Request):
        name = req.path_args[0]
        plugin = server.plugins.get(name)
        if plugin is None:
            return 404, {"message": f"plugin {name} not found"}
        return 200, plugin.handle_rest(req.path_args[1] or "/", req.params)

    return app


def create_query_server(
    engine: Engine,
    engine_params: EngineParams,
    storage: Storage,
    config: ServingConfig,
    ctx: WorkflowContext | None = None,
    plugin_context: PluginContext | None = None,
    instance_id: str | None = None,
) -> tuple[HttpServer, QueryServer]:
    qs = QueryServer(
        engine, engine_params, storage, config,
        ctx=ctx, plugin_context=plugin_context, instance_id=instance_id,
    )
    from pio_tpu.server.security import server_ssl_context

    app = build_serving_app(qs)
    ssl_ctx = server_ssl_context(config.certfile, config.keyfile)
    if config.backend == "async":
        kwargs = {}
        if config.coalesce_window_ms > 0:
            # admission sized for coalescing: parked waiters are the
            # mechanism, not the overload — admit what one full batch per
            # pipeline slot (plus one forming) can absorb before the
            # LoadShedder starts answering 503 (SLO shedding rides the
            # same watermark as before, just sized to batch capacity)
            depth = config.batch_pipeline or _auto_pipeline_depth()
            kwargs["shed_watermark"] = max(
                128, config.batch_max * (depth + 1))
        http = AsyncHttpServer(
            app, host=config.ip, port=config.port, ssl_context=ssl_ctx,
            **kwargs)
    else:
        http = HttpServer(
            app, host=config.ip, port=config.port, ssl_context=ssl_ctx)
    return http, qs
