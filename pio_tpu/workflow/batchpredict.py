"""Offline bulk scoring: queries in, predictions out, no HTTP server.

PredictionIO grew `pio batchpredict` in 0.13 (after the incubator
version this framework re-implements) because deploy-server round trips
are the wrong shape for backfills; users migrating from the reference
expect it, and it is the MOST TPU-congenial serving mode — large
batched predicts amortize the device dispatch that dominates
single-query latency (eval/SERVING_DECOMP.md).

Runs each input line through the engine's full serving composition
(supplement -> [algo.batch_predict ...] -> serve) via
QueryServer.query_batch — the same code path as /batch/queries.json —
against the latest COMPLETED engine instance's restored model (no
retrain, like deploy). Input: one JSON query per line. Output: one JSON
object per line, `{"query": ..., "prediction": ...}` (the 0.13 wire
shape). Order is preserved; a malformed line becomes
`{"query": <raw>, "error": ...}` without aborting the run.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import IO, Iterator

from pio_tpu.controller.engine import Engine, EngineParams
from pio_tpu.data.storage import Storage
from pio_tpu.workflow.context import WorkflowContext
from pio_tpu.workflow.serve import QueryServer, ServingConfig


@dataclass
class BatchPredictReport:
    n_queries: int = 0
    n_errors: int = 0


def run_batch_predict(
    engine: Engine,
    engine_params: EngineParams,
    storage: Storage,
    inp: IO[str],
    out: IO[str],
    engine_id: str = "default",
    engine_version: str = "1",
    engine_variant: str = "default",
    instance_id: str | None = None,
    batch_size: int = 256,
    ctx: WorkflowContext | None = None,
) -> BatchPredictReport:
    """Stream `inp` (JSON-lines queries) to `out` (JSON-lines
    predictions) in `batch_size` device batches. Returns counts."""
    config = ServingConfig(
        engine_id=engine_id, engine_version=engine_version,
        engine_variant=engine_variant,
        batch_window_ms=0,          # no micro-batcher: batches are explicit
    )
    qs = QueryServer(engine, engine_params, storage, config,
                     ctx=ctx, instance_id=instance_id)
    report = BatchPredictReport()
    try:
        for chunk in _chunks(inp, batch_size):
            # parse first, predict the good ones as ONE device batch,
            # then emit every record in INPUT order (error lines
            # interleaved where their query appeared)
            parsed: list[tuple[str, dict | None, str | None]] = []
            for raw in chunk:
                try:
                    q = json.loads(raw)
                    if not isinstance(q, dict):
                        raise ValueError("query must be a JSON object")
                    parsed.append((raw, q, None))
                except ValueError as e:
                    parsed.append((raw, None, str(e)))
            good = [q for _, q, err in parsed if err is None]
            # record=False: a backfill must not pollute the serving
            # latency histograms or arm the hedge clock
            preds = iter(_predict_isolating(qs, good))
            for raw, q, err in parsed:
                if err is not None:
                    report.n_errors += 1
                    out.write(json.dumps(
                        {"query": raw.rstrip("\n"), "error": err}) + "\n")
                    continue
                p, perr = next(preds)
                if perr is not None:
                    report.n_errors += 1
                    out.write(json.dumps({"query": q, "error": perr}) + "\n")
                else:
                    report.n_queries += 1
                    out.write(json.dumps(
                        {"query": q, "prediction": p}) + "\n")
    finally:
        qs.close()
    return report


def _predict_isolating(qs: QueryServer, queries: list[dict]
                       ) -> list[tuple[object, str | None]]:
    """query_batch with the same per-query fault isolation the
    micro-batcher has (serve.py _do_execute): one engine-rejected query
    (bad key, unknown field) must fail ALONE as an error record, not
    abort the batch — let alone the whole backfill. Fast path: one
    batched device dispatch; on failure, each query retries singly."""
    if not queries:
        return []
    try:
        return [(p, None) for p in qs.query_batch(queries, record=False)]
    except Exception:  # noqa: BLE001 - isolate and re-run one by one
        out: list[tuple[object, str | None]] = []
        for q in queries:
            try:
                out.append((qs.query(q, record=False), None))
            except Exception as e:  # noqa: BLE001
                out.append((None, f"{type(e).__name__}: {e}"))
        return out


def _chunks(inp: IO[str], n: int) -> Iterator[list[str]]:
    buf: list[str] = []
    for line in inp:
        if not line.strip():
            continue
        buf.append(line)
        if len(buf) >= n:
            yield buf
            buf = []
    if buf:
        yield buf
