"""Training lifecycle supervision: preemption, heartbeats, zombie sweep.

The reference's CoreWorkflow has exactly two terminal transitions —
COMPLETED or FAILED — and a killed trainer restarts from scratch
(CoreWorkflow.scala:42-98; SURVEY §5 "No mid-train resume exists"). On
TPU slices that is not an edge case: preemption is routine, so the
training path gets the same lifecycle rigor PR 2 gave serving:

  * ``PreemptionHandler`` — SIGTERM/SIGINT become a *checkpoint request*
    observed at the next step boundary instead of an immediate death.
    The trainer force-saves, ``run_train`` marks the instance
    INTERRUPTED, and the CLI exits with ``EXIT_PREEMPTED`` (75,
    EX_TEMPFAIL) so supervisors can distinguish "resume me" from a real
    failure. ``pio stop-all``'s SIGTERM-then-SIGKILL escalation thereby
    becomes a graceful preemption for in-flight training children.
  * ``TrainLifecycle`` — the per-run supervision handle threaded through
    ``WorkflowContext.lifecycle`` into the iterative trainers: a
    throttled *heartbeat* (the instance's ``progress`` field gains
    {step, total_steps, heartbeat, pid, host}) plus the per-instance
    checkpoint directory the trainers hand to ``StepCheckpointer``.
    Heartbeats are best-effort: a down metadata store must never kill a
    healthy training run.
  * ``sweep_zombies`` — a kill -9'd run leaves an INIT/TRAINING instance
    forever; since deploy's ``get_latest_completed`` contract ignores
    them they are invisible until someone wonders why no model ever
    lands. The sweep transitions instances whose heartbeat went stale to
    FAILED (resumable — their checkpoints survive) and is run by
    ``run_train`` at startup and by ``pio doctor --sweep-zombies``.
  * ``find_resumable`` — resolves ``pio train --auto-resume``: the most
    recent INTERRUPTED/FAILED instance of the engine triple that still
    has a checkpoint on disk.

Resume correctness rests on the (seed, step)-keyed batch streams in the
trainers (models/twotower.py, models/sequence.py): a resumed run replays
the exact step sequence, so its final params are bit-identical to an
uninterrupted run (tested in tests/test_train_lifecycle.py).
"""

from __future__ import annotations

import logging
import os
import signal
import socket
import threading
import time
from dataclasses import replace
from typing import Any

from pio_tpu.controller.base import TrainingInterruption
from pio_tpu.data.dao import EngineInstance, EngineInstancesDAO
from pio_tpu.utils.time import format_time, parse_time, utcnow

log = logging.getLogger("pio_tpu.workflow")

#: sysexits EX_TEMPFAIL — the run was preempted with a checkpoint on
#: disk; `pio train --resume <id>` (or --auto-resume) continues it.
EXIT_PREEMPTED = 75

#: heartbeats older than this mark an INIT/TRAINING instance as a zombie
DEFAULT_STALE_S = 600.0

#: statuses a crashed/preempted run can be resumed from
RESUMABLE_STATUSES = ("INTERRUPTED", "FAILED")


class TrainingPreempted(TrainingInterruption):
    """A preemption signal was honored at a step boundary; the final
    checkpoint (if a checkpointer was active) is on disk."""

    def __init__(self, step: int | None = None):
        at = f"preemption at step {step}" if step is not None else "preemption"
        super().__init__(at)
        self.step = step


class PreemptionHandler:
    """Context manager turning SIGTERM/SIGINT into a cooperative stop
    request (``requested`` Event) for the dynamic extent of a training
    run. A second SIGINT restores Python's default KeyboardInterrupt so
    an operator can still insist. Signal handlers only install from the
    main thread; elsewhere (e.g. a test harness thread) the handler
    degrades to a manually settable Event."""

    def __init__(self) -> None:
        self.requested = threading.Event()
        self.signum: int | None = None
        self._previous: dict[int, Any] = {}

    def _handle(self, signum, frame) -> None:
        if signum == signal.SIGINT and self.requested.is_set():
            signal.signal(signal.SIGINT, signal.default_int_handler)
            raise KeyboardInterrupt
        self.signum = signum
        self.requested.set()
        log.warning(
            "received %s: requesting checkpoint + stop at the next step "
            "boundary (send SIGINT again to abort immediately)",
            signal.Signals(signum).name,
        )

    def __enter__(self) -> "PreemptionHandler":
        if threading.current_thread() is threading.main_thread():
            for signum in (signal.SIGTERM, signal.SIGINT):
                self._previous[signum] = signal.signal(signum, self._handle)
        return self

    def __exit__(self, *exc) -> None:
        for signum, prev in self._previous.items():
            try:
                signal.signal(signum, prev)
            except (ValueError, TypeError):
                pass
        # pio: lint-ok[attr-no-lock] enter/exit run on the one thread
        # that owns the training run; signal delivery only SETS an Event
        self._previous.clear()


class TrainLifecycle:
    """Per-run supervision handle (``WorkflowContext.lifecycle``).

    Trainers call ``heartbeat(step, total)`` and ``check_preemption(step)``
    at step/span boundaries; ``checkpoint_dir`` is the per-instance
    directory algorithms hand to ``StepCheckpointer`` when their params
    do not pin one explicitly.
    """

    def __init__(
        self,
        instances: EngineInstancesDAO,
        instance: EngineInstance,
        checkpoint_dir: str = "",
        heartbeat_every_steps: int = 10,
        heartbeat_min_interval_s: float = 2.0,
        preemption: PreemptionHandler | None = None,
        readonly: bool = False,
        liveness_interval_s: float = 60.0,
    ):
        self.instances = instances
        self.instance = instance
        self.checkpoint_dir = checkpoint_dir
        self.heartbeat_every_steps = max(1, heartbeat_every_steps)
        self.heartbeat_min_interval_s = heartbeat_min_interval_s
        self.preemption = preemption
        # multi-host: only process 0 writes metadata; the other hosts
        # still track progress locally and observe preemption requests
        self.readonly = readonly
        # wall-clock liveness floor: step heartbeats only fire at span
        # boundaries, which on big models can be further apart than the
        # zombie-stale threshold — a background thread re-stamps the
        # heartbeat so a healthy mid-span run is never swept. 0 = off.
        self.liveness_interval_s = liveness_interval_s
        self.last_step: int | None = None
        self._last_beat = 0.0
        self._last_written_step: int | None = None
        self._lock = threading.Lock()   # training thread vs beat thread
        self._stop_beat = threading.Event()
        self._beat_thread: threading.Thread | None = None

    # -- heartbeat -----------------------------------------------------------
    def heartbeat(self, step: int, total_steps: int | None = None,
                  force: bool = False) -> bool:
        """Record training progress on the instance. The local snapshot
        updates on every call (so the terminal COMPLETED/FAILED record
        carries the true last step); the STORE write is throttled by
        step cadence AND wall time, and is best-effort — losing a
        heartbeat must not lose the run."""
        with self._lock:
            self.last_step = step
            progress = dict(self.instance.progress)
            progress.update(
                step=step,
                heartbeat=format_time(utcnow()),
                pid=os.getpid(),
                host=socket.gethostname(),
            )
            if total_steps is not None:
                progress["total_steps"] = total_steps
            if self.checkpoint_dir:
                progress["checkpoint_dir"] = self.checkpoint_dir
            self.instance = replace(self.instance, progress=progress)
            now = time.monotonic()
            # throttle by steps SINCE THE LAST WRITTEN beat, not by step
            # modulo: trainers only call at span boundaries (checkpoint-
            # aligned), and a cadence that never lands on a modulo-of-N
            # step would starve the store of beats — a healthy run would
            # read as a zombie and get swept mid-flight
            if not force and (
                (self._last_written_step is not None
                 and step - self._last_written_step
                 < self.heartbeat_every_steps)
                or now - self._last_beat < self.heartbeat_min_interval_s
            ):
                return False
            if self.readonly:
                return False
            self._last_beat = now
            self._last_written_step = step
            snapshot = self.instance
        try:
            # pio: lint-ok[attr-no-lock] DAO call, not local mutation:
            # the store write runs outside _lock on purpose (no I/O
            # under the lock); DAOs are thread-safe, and last-writer-
            # wins between beats is harmless
            self.instances.update(snapshot)
        except Exception:  # noqa: BLE001 - heartbeat is best-effort
            log.warning("heartbeat for instance %s failed (store down?)",
                        snapshot.id, exc_info=True)
            return False
        return True

    # -- wall-clock liveness beat --------------------------------------------
    def start(self) -> None:
        """Start the background liveness thread (no-op when readonly or
        disabled): re-stamps the heartbeat timestamp every
        ``liveness_interval_s`` so the zombie sweep never mistakes a
        healthy run mid-long-span for a crash."""
        if self.readonly or self.liveness_interval_s <= 0:
            return
        self._beat_thread = threading.Thread(
            target=self._beat_loop, name="train-liveness", daemon=True
        )
        self._beat_thread.start()

    def stop(self) -> None:
        self._stop_beat.set()
        if self._beat_thread is not None:
            self._beat_thread.join(timeout=5.0)
            self._beat_thread = None

    def _beat_loop(self) -> None:
        while not self._stop_beat.wait(self.liveness_interval_s):
            with self._lock:
                progress = dict(self.instance.progress)
                progress["heartbeat"] = format_time(utcnow())
                self.instance = replace(self.instance, progress=progress)
                snapshot = self.instance
            try:
                # pio: lint-ok[attr-no-lock] DAO call outside _lock by
                # design (no I/O under the lock); see heartbeat()
                self.instances.update(snapshot)
            except Exception:  # noqa: BLE001 - liveness is best-effort
                log.warning("liveness beat for instance %s failed",
                            snapshot.id, exc_info=True)

    # -- preemption ----------------------------------------------------------
    def preempted(self) -> bool:
        return self.preemption is not None and self.preemption.requested.is_set()

    def check_preemption(self, step: int, force: bool = False) -> None:
        """Raise TrainingPreempted when a stop was requested. Trainers
        call this AFTER force-saving their checkpoint at the boundary.
        ``force`` carries a cross-host consensus (spans.after_span
        OR-reduces the flag): a host whose peer was signaled stops too,
        even though its own handler saw nothing."""
        if force or self.preempted():
            self.heartbeat(step, force=True)
            raise TrainingPreempted(step)


def checkpoint_dir_for(instance_id: str, root: str | None = None) -> str:
    """Per-instance step-checkpoint directory: keyed by EngineInstance.id
    so `--resume <id>` finds exactly its own run's steps. Root resolves
    `root` arg -> $PIO_TPU_CKPT_ROOT -> $PIO_TPU_HOME/checkpoints."""
    root = root or os.environ.get("PIO_TPU_CKPT_ROOT") or os.path.join(
        os.environ.get(
            "PIO_TPU_HOME", os.path.join(os.path.expanduser("~"), ".pio_tpu")
        ),
        "checkpoints",
    )
    return os.path.join(root, instance_id.replace("/", "_"))


def has_checkpoint(directory: str) -> bool:
    """True when `directory` holds at least one saved step (cheap listing
    check — avoids constructing an orbax manager just to probe)."""
    try:
        return any(
            name.isdigit() or name.startswith("ckpt")
            for name in os.listdir(directory)
        )
    except OSError:
        return False


def _heartbeat_age_s(instance: EngineInstance, now) -> float:
    """Seconds since the instance last proved liveness: its heartbeat
    stamp, else its start_time (pre-heartbeat instances and runs that
    died before the first beat)."""
    stamp = instance.progress.get("heartbeat") if instance.progress else None
    ts = None
    if stamp:
        try:
            ts = parse_time(stamp)
        except (ValueError, TypeError):
            ts = None
    if ts is None:
        ts = instance.start_time
    if ts is None:
        return float("inf")
    return (now - ts).total_seconds()


def sweep_zombies(
    storage,
    stale_after_s: float = DEFAULT_STALE_S,
    now=None,
) -> list[EngineInstance]:
    """Transition stale INIT/TRAINING instances to FAILED (resumable).

    A kill -9'd trainer leaves its instance in-flight forever; deploy's
    get_latest_completed ignores it, so nothing ever surfaces the loss.
    The sweep makes the crash explicit and the run resumable. Returns
    the instances it transitioned.
    """
    instances = storage.get_metadata_engine_instances()
    now = now or utcnow()
    swept: list[EngineInstance] = []
    for inst in instances.get_all():
        if inst.status not in ("INIT", "TRAINING"):
            continue
        age = _heartbeat_age_s(inst, now)
        if age < stale_after_s:
            continue
        progress = dict(inst.progress)
        progress.update(
            zombie=True,
            swept_at=format_time(now),
            stale_for_s=round(age, 1),
        )
        updated = replace(
            inst, status="FAILED", end_time=now, progress=progress
        )
        try:
            instances.update(updated)
        except Exception:  # noqa: BLE001 - sweep is advisory
            log.warning("zombie sweep could not update instance %s",
                        inst.id, exc_info=True)
            continue
        log.warning(
            "zombie sweep: instance %s (%s) heartbeat stale for %.0fs -> "
            "FAILED (resumable)", inst.id, inst.status, age,
        )
        swept.append(updated)
    return swept


def stale_instances(
    storage, stale_after_s: float = DEFAULT_STALE_S, now=None
) -> list[EngineInstance]:
    """Read-only zombie detection (what `pio doctor` reports without
    --sweep-zombies)."""
    instances = storage.get_metadata_engine_instances()
    now = now or utcnow()
    return [
        i for i in instances.get_all()
        if i.status in ("INIT", "TRAINING")
        and _heartbeat_age_s(i, now) >= stale_after_s
    ]


def find_resumable(
    instances: EngineInstancesDAO,
    engine_id: str,
    engine_version: str,
    engine_variant: str,
    checkpoint_root: str | None = None,
) -> EngineInstance | None:
    """The most recent INTERRUPTED/FAILED instance of the engine triple
    whose checkpoint directory still holds steps (for --auto-resume)."""
    candidates = [
        i for i in instances.get_all()
        if i.status in RESUMABLE_STATUSES
        and (i.engine_id, i.engine_version, i.engine_variant)
        == (engine_id, engine_version, engine_variant)
    ]
    candidates.sort(key=lambda i: i.start_time, reverse=True)
    for inst in candidates:
        ckpt_dir = (inst.progress or {}).get("checkpoint_dir") or \
            checkpoint_dir_for(inst.id, checkpoint_root)
        if has_checkpoint(ckpt_dir):
            return inst
    return None
