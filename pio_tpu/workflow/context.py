"""WorkflowContext — what every DASE stage receives.

The reference threads a SparkContext through every stage signature
(core/.../workflow/WorkflowContext.scala:11-28 creates it). The TPU-native
context carries the device mesh (the cluster), the storage facade (the event
store), and a PRNG key — the single-controller runtime state.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any

import jax

from pio_tpu.data.eventstore import EventStore
from pio_tpu.data.storage import Storage, get_storage
from pio_tpu.parallel.mesh import MeshConfig, create_mesh


@dataclass
class WorkflowContext:
    storage: Storage
    mesh: Any = None          # jax.sharding.Mesh | None (None = single device)
    seed: int = 0
    batch: str = ""
    params: dict = field(default_factory=dict)  # runtime conf (sparkConf slot)
    # training supervision handle (workflow/lifecycle.py TrainLifecycle):
    # heartbeats, preemption checks, and the per-instance checkpoint dir.
    # Set by run_train; None outside a supervised training run.
    lifecycle: Any = None

    @property
    def event_store(self) -> EventStore:
        return EventStore(self.storage)

    def rng(self) -> jax.Array:
        return jax.random.PRNGKey(self.seed)

    def with_seed(self, seed: int) -> "WorkflowContext":
        return replace(self, seed=seed)


def create_workflow_context(
    storage: Storage | None = None,
    mesh_config: MeshConfig | None = None,
    use_mesh: bool = True,
    seed: int = 0,
    batch: str = "",
    params: dict | None = None,
) -> WorkflowContext:
    """Reference WorkflowContext.scala: conf -> SparkContext; here conf ->
    Mesh over available devices (all of them by default). When
    PIO_TPU_COORDINATOR is set, the multi-host runtime is joined first so
    the mesh spans every host's devices (parallel/distributed.py)."""
    from pio_tpu.parallel.distributed import initialize_distributed

    initialize_distributed()  # no-op unless configured; must precede mesh
    storage = storage or get_storage()
    mesh = None
    if use_mesh:
        mesh = create_mesh(mesh_config)
    return WorkflowContext(
        storage=storage, mesh=mesh, seed=seed, batch=batch,
        params=dict(params or {}),
    )
