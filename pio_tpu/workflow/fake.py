"""Fake workflow — run an arbitrary function through the full evaluation
plumbing (test infrastructure).

Parity with reference core/.../workflow/FakeWorkflow.scala:14-71 (`FakeRun`
wraps a `SparkContext => Unit` in a fake engine/evaluator so tests exercise
the real EvaluationInstance lifecycle). Here the function receives the
WorkflowContext; everything else — instance INIT -> EVALCOMPLETED, result
persistence — is the production path in workflow/evaluate.py.
"""

from __future__ import annotations

from typing import Callable

from pio_tpu.controller.base import (
    DataSource,
    FirstServing,
    IdentityPreparator,
    LAlgorithm,
)
from pio_tpu.controller.engine import Engine, EngineParams
from pio_tpu.controller.evaluation import Metric
from pio_tpu.data.storage import Storage
from pio_tpu.workflow.context import WorkflowContext


class FakeEvalResult:
    """Marker eval-info (reference FakeEvalResult)."""

    def __repr__(self):
        return "FakeEvalResult()"


class _FakeDataSource(DataSource):
    def __init__(self, params=None):
        pass

    def read_training(self, ctx):
        return ()

    def read_eval(self, ctx):
        return [((), FakeEvalResult(), [])]


class _FakeAlgorithm(LAlgorithm):
    def __init__(self, params=None):
        pass

    def train(self, ctx, data):
        return ()

    def predict(self, model, query):
        return None


class _FakeEngine(Engine):
    """Engine whose eval() runs the wrapped function (reference FakeRunner)."""

    def __init__(self, fn: Callable[[WorkflowContext], None]):
        super().__init__(
            _FakeDataSource, IdentityPreparator,
            {"fake": _FakeAlgorithm}, FirstServing,
        )
        self.fn = fn

    def eval(self, ctx, engine_params):
        self.fn(ctx)
        return [(FakeEvalResult(), [])]


class _FakeMetric(Metric):
    def calculate(self, ctx, eval_data_set) -> float:
        return 0.0


def fake_run(
    fn: Callable[[WorkflowContext], None],
    storage: Storage,
    ctx: WorkflowContext | None = None,
) -> str:
    """Run `fn(ctx)` through the real evaluation workflow; returns the
    EvaluationInstance id (status EVALCOMPLETED on success)."""
    from pio_tpu.workflow.evaluate import run_evaluation

    instance_id, _ = run_evaluation(
        engine=_FakeEngine(fn),
        metric=_FakeMetric(),
        engine_params_list=[EngineParams(algorithms=[("fake", None)])],
        storage=storage,
        evaluation_class="FakeRun",
        ctx=ctx,
    )
    return instance_id
