"""Model persistence.

The reference Kryo-serializes trained models into the MODELDATA repository
(CoreWorkflow.scala:73-78, storage/Models.scala:30-48), with the PAlgorithm
escape hatch of persisting Unit and retraining at deploy
(PAlgorithm.makePersistentModel, Engine.prepareDeploy:208-230). Here every
model — including device-resident pytrees — serializes for real: jax.Arrays
are pulled to host numpy inside the pytree and pickled; restore optionally
`device_put`s back onto a serving mesh. No retrain-on-deploy.

Orbax-style sharded step checkpoints for large multi-host models live beside
this (see pio_tpu/workflow/orbax_ckpt.py once models outgrow a blob).
"""

from __future__ import annotations

import io
import pickle
from typing import Any

import jax
import numpy as np

from pio_tpu.utils.durable import ModelIntegrityError, frame, unframe

__all__ = [
    "ModelIntegrityError", "host_copy", "models_from_bytes",
    "models_to_bytes",
]


def _to_host(x: Any) -> Any:
    if isinstance(x, jax.Array):
        return np.asarray(x)
    return x


def host_copy(model: Any) -> Any:
    """Pytree-map jax.Array leaves to numpy; non-pytree objects untouched."""
    return jax.tree_util.tree_map(_to_host, model)


def models_to_bytes(models: list[Any]) -> bytes:
    """Pickle + CRC32C-frame (utils/durable.py): the checksum rides
    INSIDE the blob, so every backend — file, SQL BLOB, wire — hands
    `models_from_bytes` enough to detect truncation and bit-rot, not
    just the localfs path with its own file-level durability."""
    buf = io.BytesIO()
    pickle.dump([host_copy(m) for m in models], buf, protocol=5)
    return frame(buf.getvalue())


def models_from_bytes(data: bytes) -> list[Any]:
    """Verify + unpickle. Raises ModelIntegrityError (NOT a pickle error
    deep in a partial stream) when a framed blob fails its checksum;
    legacy unframed blobs from pre-durability stores unpickle as before."""
    return pickle.loads(unframe(data, source="model blob"))
