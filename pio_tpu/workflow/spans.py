"""Span scheduling for on-device training scans.

Both neural trainers (models/twotower.py, models/sequence.py) replace
their per-step host loops with `lax.scan` over SPANS of steps: one
compiled program per span instead of one dispatch + batch transfer per
step (the per-step loop is dispatch-bound on remote/tunneled devices).
The span boundaries have to respect two constraints:

 * bounded staging — a span's batch tensors are materialized host-side
   and transferred once, so spans are capped;
 * checkpoint cadence — orbax only accepts saves at steps that are
   multiples of save_every, and resume correctness requires hitting
   exactly the steps the original per-step loop hit (0, k, 2k, ...), so
   a span must END right after a save-eligible step.

This module owns that boundary math so the trainers share one tested
implementation.
"""

from __future__ import annotations

from typing import Iterator


def span_bounds(start: int, steps: int, save_every: int | None,
                cap: int = 512) -> Iterator[tuple[int, int, bool]]:
    """Yield (lo, hi, save_after) spans covering [start, steps).

    `save_after` is True when step hi-1 is save-eligible
    ((hi-1) % save_every == 0) — the caller then invokes
    checkpoint.maybe_save(hi-1, ...). With save_every=None no span ever
    asks for a save."""
    s = start
    while s < steps:
        e = min(steps, s + cap)
        if save_every is not None:
            m = s if s % save_every == 0 else (
                s // save_every + 1) * save_every
            if m < e:
                e = m + 1
        yield s, e, (
            save_every is not None and (e - 1) % save_every == 0
        )
        s = e
