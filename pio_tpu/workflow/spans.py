"""Span scheduling for on-device training scans.

Both neural trainers (models/twotower.py, models/sequence.py) replace
their per-step host loops with `lax.scan` over SPANS of steps: one
compiled program per span instead of one dispatch + batch transfer per
step (the per-step loop is dispatch-bound on remote/tunneled devices).
The span boundaries have to respect two constraints:

 * bounded staging — a span's batch tensors are materialized host-side
   and transferred once, so spans are capped;
 * checkpoint cadence — orbax only accepts saves at steps that are
   multiples of save_every, and resume correctness requires hitting
   exactly the steps the original per-step loop hit (0, k, 2k, ...), so
   a span must END right after a save-eligible step.

This module owns that boundary math so the trainers share one tested
implementation.
"""

from __future__ import annotations

from typing import Iterator


def span_bounds(start: int, steps: int, save_every: int | None,
                cap: int = 512) -> Iterator[tuple[int, int, bool]]:
    """Yield (lo, hi, save_after) spans covering [start, steps).

    `save_after` is True when step hi-1 is save-eligible
    ((hi-1) % save_every == 0) — the caller then invokes
    checkpoint.maybe_save(hi-1, ...). With save_every=None no span ever
    asks for a save."""
    s = start
    while s < steps:
        e = min(steps, s + cap)
        if save_every is not None:
            m = s if s % save_every == 0 else (
                s // save_every + 1) * save_every
            if m < e:
                e = m + 1
        yield s, e, (
            save_every is not None and (e - 1) % save_every == 0
        )
        s = e


def step_chaos_active() -> bool:
    """True when a `train.step` chaos spec is live: trainers then degrade
    their spans to single steps (cap=1) so a `train.step.<n>` fault fires
    at EXACTLY step n — deterministic kill-at-step for the resume tests.
    Zero-cost when chaos is off (one module-global read)."""
    from pio_tpu.resilience import chaos

    return chaos.watches("train.step")


def after_span(
    hi: int,
    total_steps: int,
    params,
    opt_state,
    *,
    checkpoint,
    lifecycle,
    save_after: bool,
    step_chaos: bool,
) -> None:
    """Shared span-boundary bookkeeping for the iterative trainers
    (models/twotower.py, models/sequence.py) — one implementation so the
    chaos/save/preemption ordering cannot drift between them:

      1. `train.step.<hi-1>` chaos point (the kill-at-step hook);
      2. cadence save (only save-eligible steps reach maybe_save — it
         device_gets the full state, which a declined save would waste);
      3. preemption: force-save the current step when it is off-cadence,
         then raise TrainingPreempted (via lifecycle.check_preemption).
         Multi-host, the flag is OR-reduced across processes FIRST — a
         SIGTERM often lands on one host only, and a lone force-saver
         would strand its peers at the save barrier;
      4. heartbeat.
    """
    if step_chaos:
        from pio_tpu.resilience import chaos

        chaos.maybe_inject(f"train.step.{hi - 1}")
    if save_after:
        checkpoint.maybe_save(hi - 1, params, opt_state)
    if lifecycle is not None:
        from pio_tpu.parallel.distributed import any_process

        if any_process(lifecycle.preempted()):
            if checkpoint is not None and not save_after:
                checkpoint.save(hi - 1, params, opt_state)
            lifecycle.check_preemption(hi - 1, force=True)  # raises
        lifecycle.heartbeat(hi - 1, total_steps)
