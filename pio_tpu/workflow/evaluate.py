"""Evaluation workflow — EvaluationInstance lifecycle around MetricEvaluator.

Mirrors reference CoreWorkflow.runEvaluation (core/.../CoreWorkflow.scala:100-157)
+ EvaluationWorkflow.scala:17-27: insert EvaluationInstance, run
engine.eval x params via the evaluator, persist one-liner/JSON/HTML results,
mark EVALCOMPLETED.
"""

from __future__ import annotations

import logging
import traceback
from dataclasses import replace
from typing import Sequence

from pio_tpu.controller.engine import Engine, EngineParams
from pio_tpu.controller.evaluation import (
    Evaluation,
    Metric,
    MetricEvaluator,
    MetricEvaluatorResult,
)
from pio_tpu.data.dao import EvaluationInstance
from pio_tpu.data.storage import Storage
from pio_tpu.utils.time import utcnow
from pio_tpu.workflow.context import WorkflowContext, create_workflow_context

log = logging.getLogger("pio_tpu.workflow")


def run_evaluation(
    engine: Engine,
    metric: Metric,
    engine_params_list: Sequence[EngineParams],
    storage: Storage,
    other_metrics: Sequence[Metric] = (),
    evaluation_class: str = "",
    params_generator_class: str = "",
    batch: str = "",
    output_path: str | None = None,
    ctx: WorkflowContext | None = None,
    workers: int = 1,
) -> tuple[str, MetricEvaluatorResult]:
    """Returns (evaluation instance id, result)."""
    ctx = ctx or create_workflow_context(storage)
    instances = storage.get_metadata_evaluation_instances()
    now = utcnow()
    instance_id = instances.insert(
        EvaluationInstance(
            id="",
            status="INIT",
            start_time=now,
            end_time=now,
            evaluation_class=evaluation_class,
            engine_params_generator_class=params_generator_class,
            batch=batch,
        )
    )
    instance = instances.get(instance_id)
    try:
        evaluator = MetricEvaluator(
            metric, other_metrics=other_metrics, output_path=output_path,
            workers=workers,
        )
        result = evaluator.evaluate_base(ctx, engine, engine_params_list)
        instances.update(
            replace(
                instance,
                status="EVALCOMPLETED",
                end_time=utcnow(),
                evaluator_results=result.one_liner(),
                evaluator_results_html=result.to_html(),
                evaluator_results_json=result.to_json(),
            )
        )
        log.info("evaluation %s EVALCOMPLETED best=%s",
                 instance_id, result.best_score.score)
        return instance_id, result
    except Exception:
        log.error("evaluation %s FAILED:\n%s", instance_id, traceback.format_exc())
        instances.update(
            replace(instance, status="EVALFAILED", end_time=utcnow())
        )
        raise


def run_evaluation_class(
    evaluation_class: type[Evaluation],
    generator_class,
    storage: Storage,
    **kwargs,
) -> tuple[str, MetricEvaluatorResult]:
    """Run an Evaluation subclass with an EngineParamsGenerator (the
    `pio eval Evaluation ParamsGenerator` entry shape)."""
    engine, metric = evaluation_class.engine_metric()
    return run_evaluation(
        engine=engine,
        metric=metric,
        engine_params_list=generator_class.params_list(),
        storage=storage,
        other_metrics=evaluation_class.other_metrics(),
        evaluation_class=evaluation_class.__name__,
        params_generator_class=generator_class.__name__,
        **kwargs,
    )
