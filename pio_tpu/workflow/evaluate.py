"""Evaluation workflow — EvaluationInstance lifecycle around MetricEvaluator.

Mirrors reference CoreWorkflow.runEvaluation (core/.../CoreWorkflow.scala:100-157)
+ EvaluationWorkflow.scala:17-27: insert EvaluationInstance, run
engine.eval x params via the evaluator, persist one-liner/JSON/HTML results,
mark EVALCOMPLETED.
"""

from __future__ import annotations

import logging
import traceback
from dataclasses import replace
from typing import Sequence

from pio_tpu.controller.engine import Engine, EngineParams
from pio_tpu.controller.evaluation import (
    Evaluation,
    Metric,
    MetricEvaluator,
    MetricEvaluatorResult,
)
from pio_tpu.data.dao import EvaluationInstance
from pio_tpu.data.storage import Storage
from pio_tpu.utils.time import utcnow
from pio_tpu.workflow.context import WorkflowContext, create_workflow_context

log = logging.getLogger("pio_tpu.workflow")


def run_evaluation(
    engine: Engine,
    metric: Metric,
    engine_params_list: Sequence[EngineParams],
    storage: Storage,
    other_metrics: Sequence[Metric] = (),
    evaluation_class: str = "",
    params_generator_class: str = "",
    batch: str = "",
    output_path: str | None = None,
    ctx: WorkflowContext | None = None,
    workers: int = 1,
) -> tuple[str, MetricEvaluatorResult]:
    """Returns (evaluation instance id, result)."""
    ctx = ctx or create_workflow_context(storage)
    instances = storage.get_metadata_evaluation_instances()
    now = utcnow()
    instance_id = instances.insert(
        EvaluationInstance(
            id="",
            status="INIT",
            start_time=now,
            end_time=now,
            evaluation_class=evaluation_class,
            engine_params_generator_class=params_generator_class,
            batch=batch,
        )
    )
    instance = instances.get(instance_id)
    try:
        evaluator = MetricEvaluator(
            metric, other_metrics=other_metrics, output_path=output_path,
            workers=workers,
        )
        result = evaluator.evaluate_base(ctx, engine, engine_params_list)
        instances.update(
            replace(
                instance,
                status="EVALCOMPLETED",
                end_time=utcnow(),
                evaluator_results=result.one_liner(),
                evaluator_results_html=result.to_html(),
                evaluator_results_json=result.to_json(),
            )
        )
        log.info("evaluation %s EVALCOMPLETED best=%s",
                 instance_id, result.best_score.score)
        return instance_id, result
    except Exception:
        log.error("evaluation %s FAILED:\n%s", instance_id, traceback.format_exc())
        instances.update(
            replace(instance, status="EVALFAILED", end_time=utcnow())
        )
        raise


def run_evaluation_class(
    evaluation_class: type[Evaluation],
    generator_class,
    storage: Storage,
    **kwargs,
) -> tuple[str, MetricEvaluatorResult]:
    """Run an Evaluation subclass with an EngineParamsGenerator (the
    `pio eval Evaluation ParamsGenerator` entry shape)."""
    engine, metric = evaluation_class.engine_metric()
    return run_evaluation(
        engine=engine,
        metric=metric,
        engine_params_list=generator_class.params_list(),
        storage=storage,
        other_metrics=evaluation_class.other_metrics(),
        evaluation_class=evaluation_class.__name__,
        params_generator_class=generator_class.__name__,
        **kwargs,
    )


def run_sweep_evaluation(
    engine: Engine,
    candidates,
    storage: Storage,
    sweep_config,
    engine_id: str = "",
    engine_version: str = "",
    engine_variant: str = "",
    batch: str = "",
    output_path: str | None = None,
    resume_eval_id: str | None = None,
    ctx: WorkflowContext | None = None,
    tracer=None,
    status=None,
) -> tuple[str, MetricEvaluatorResult]:
    """The batched-sweep twin of run_evaluation (pio eval --sweep):
    same EvaluationInstance lifecycle and result rendering, but the
    grid runs through tuning.sweep.SweepRunner — candidates sharing
    array shapes train as ONE stacked device program, per-unit results
    checkpoint into the durable ``<eval-iid>:sweep`` record (a killed
    sweep resumes via ``resume_eval_id`` and completes the remaining
    units with an identical final result), and the winner lands in
    ``<eval-iid>:best_params`` for ``pio train/deploy --from-eval``.

    Returns (evaluation instance id, result)."""
    from pio_tpu.tuning.records import save_best_params
    from pio_tpu.tuning.sweep import SweepRunner

    ctx = ctx or create_workflow_context(storage)
    instances = storage.get_metadata_evaluation_instances()
    now = utcnow()
    if resume_eval_id:
        instance = instances.get(resume_eval_id)
        if instance is None:
            raise ValueError(
                f"cannot resume: evaluation instance {resume_eval_id} "
                "not found")
        if instance.status == "EVALCOMPLETED":
            raise ValueError(
                f"evaluation {resume_eval_id} already completed; "
                "start a fresh sweep")
        instance_id = instance.id
    else:
        instance_id = instances.insert(
            EvaluationInstance(
                id="",
                status="INIT",
                start_time=now,
                end_time=now,
                evaluation_class="sweep",
                engine_params_generator_class="grid",
                batch=batch,
            )
        )
        instance = instances.get(instance_id)
    runner = SweepRunner(
        engine, candidates, storage, sweep_config,
        eval_id=instance_id, tracer=tracer,
    )
    if status is not None:
        status.update(phase="running", evalId=instance_id,
                      mode=runner.mode,
                      metric=sweep_config.metric.header)
        runner.on_unit = lambda done, total: status.update(
            unitsDone=done, unitsTotal=total)
    try:
        result = runner.run(ctx)
        if status is not None:
            status.update(
                phase="completed",
                bestScore=_finite_or_none(result.best_score.score))
            if runner.last_sweep_seconds is not None:
                status.observe_sweep_seconds(runner.last_sweep_seconds)
        save_best_params(
            storage, instance_id, result.best_engine_params,
            score=(result.best_score.score
                   if isinstance(result.best_score.score, float)
                   else float(result.best_score.score)),
            metric=result.metric_header,
            engine_id=engine_id, engine_version=engine_version,
            engine_variant=engine_variant,
            all_scores=[
                {"score": _finite_or_none(ms.score),
                 "otherScores": [_finite_or_none(s)
                                 for s in ms.other_scores]}
                for _, ms in result.engine_params_scores
            ],
        )
        instances.update(
            replace(
                instance,
                status="EVALCOMPLETED",
                end_time=utcnow(),
                evaluator_results=result.one_liner(),
                evaluator_results_html=result.to_html(),
                evaluator_results_json=result.to_json(),
            )
        )
        if output_path:
            # plain text like MetricEvaluator's best.json: this file is
            # the USER artifact (paste into engine.json); the durable
            # copy lives in the :best_params record
            with open(output_path, "w") as f:
                f.write(result.best_engine_params.to_json())
        log.info("sweep evaluation %s EVALCOMPLETED best=%s mode=%s "
                 "(%.2fs)", instance_id, result.best_score.score,
                 runner.mode, runner.last_sweep_seconds or 0.0)
        return instance_id, result
    except Exception:
        if status is not None:
            status.update(phase="failed")
        # advertise --resume-eval only when a sweep state record exists:
        # a usage/plan error raised before any unit ran would fail the
        # resume identically — the hint would just accrete junk rows
        from pio_tpu.tuning.records import load_sweep_state

        try:
            resumable = load_sweep_state(storage, instance_id) is not None
        except Exception:  # noqa: BLE001 - the hint is advisory
            resumable = False
        log.error("sweep evaluation %s FAILED%s:\n%s",
                  instance_id,
                  (f" (resumable with --resume-eval {instance_id})"
                   if resumable else ""),
                  traceback.format_exc())
        instances.update(
            replace(instance, status="EVALFAILED", end_time=utcnow())
        )
        raise


def _finite_or_none(x):
    try:
        x = float(x)
    except (TypeError, ValueError):
        return None
    return None if x != x else x
