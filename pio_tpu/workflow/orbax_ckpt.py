"""Mid-train step checkpoints (orbax) with resume.

The reference has NO mid-train resume — a failed Spark training job restarts
from scratch, and its only persistence is the post-train model blob
(CoreWorkflow.scala:73-78; SURVEY.md §5 "No mid-train resume exists — a TPU
build should do strictly better"). This module is that better story for the
iterative trainers (two-tower, sequence): an orbax CheckpointManager wraps
{params, opt_state, step}; training saves every `save_every` steps and, on
restart, resumes from the latest step with an identical batch stream (batch
sampling is keyed by (seed, step), so a resumed run reproduces the
uninterrupted one exactly).

Sharded restore: state is pulled to host before save; restore hands back
host arrays which the trainer re-device_puts with its mesh shardings — the
checkpoint is therefore portable across mesh shapes (train on 8 chips,
resume on 4).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any

import jax


@dataclass(frozen=True)
class StepCheckpointConfig:
    directory: str
    save_every: int = 100       # save cadence in steps
    max_to_keep: int = 3


class StepCheckpointer:
    """Orbax CheckpointManager wrapper for {params, opt_state} pytrees."""

    def __init__(self, config: StepCheckpointConfig):
        import orbax.checkpoint as ocp

        self.config = config
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(config.directory),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=config.max_to_keep,
                save_interval_steps=config.save_every,
            ),
        )

    def maybe_save(self, step: int, params: Any, opt_state: Any) -> bool:
        """Save if the cadence says so (orbax enforces save_interval_steps).
        Arrays are pulled to host so the checkpoint is mesh-portable."""
        return self._save(step, params, opt_state, force=False)

    def save(self, step: int, params: Any, opt_state: Any) -> bool:
        """Save unconditionally — the preemption path's final checkpoint
        at the interrupted step, regardless of cadence."""
        return self._save(step, params, opt_state, force=True)

    def _save(self, step: int, params: Any, opt_state: Any,
              force: bool) -> bool:
        import orbax.checkpoint as ocp

        from pio_tpu.resilience import chaos

        save_error: Exception | None = None
        saved = False
        try:
            # chaos point: a `train.checkpoint` spec simulates a
            # checkpoint-write fault (full disk, flaky blobstore) —
            # training must surface it, and a later resume must restore
            # the PREVIOUS step
            chaos.maybe_inject("train.checkpoint")
            state = jax.device_get(
                {"params": params, "opt_state": opt_state})
            saved = self._mgr.save(
                step, args=ocp.args.StandardSave(state), force=force
            )
            if saved and (force or jax.process_count() > 1):
                # forced (preemption) saves are followed by process exit,
                # and multi-host saves must not let any process run ahead
                # of its peers' shard writes — both demand the save be
                # durable NOW. Cadence saves on a single host stay async
                # (orbax overlaps them with the next span; close() drains
                # the tail).
                self._mgr.wait_until_finished()
        except Exception as e:  # noqa: BLE001 - re-raised after barrier
            save_error = e
        if (saved or save_error is not None) and (
                force or jax.process_count() > 1):
            # reached on success AND failure: a host whose save raised
            # must not strand its peers in sync_global_devices
            from pio_tpu.parallel.distributed import barrier

            barrier(f"ckpt-save-{step}")
        if save_error is not None:
            raise save_error
        return saved

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore(self, params_template: Any, opt_state_template: Any,
                step: int | None = None) -> tuple[Any, Any, int]:
        """-> (params, opt_state, step) as host arrays, structured like the
        templates (a freshly-initialized state works as the template)."""
        import orbax.checkpoint as ocp

        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            raise ValueError(f"no checkpoint in {self.config.directory}")
        template = jax.device_get(
            {"params": params_template, "opt_state": opt_state_template}
        )
        state = self._mgr.restore(step, args=ocp.args.StandardRestore(template))
        return state["params"], state["opt_state"], step

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def resume_or_init(
    ckpt: StepCheckpointer | None, params: Any, opt_state: Any
) -> tuple[Any, Any, int]:
    """Restore the latest step if a checkpointer with history is given,
    else pass through the fresh state at step 0."""
    if ckpt is not None and ckpt.latest_step() is not None:
        p, o, step = ckpt.restore(params, opt_state)
        return p, o, step + 1  # saved AFTER that step ran
    return params, opt_state, 0
