"""Train workflow — read -> prepare -> train -> persist -> record.

Mirrors reference CoreWorkflow.runTrain (core/.../workflow/CoreWorkflow.scala:42-98)
and CreateWorkflow's EngineInstance bookkeeping (CreateWorkflow.scala:133-273):
 * EngineInstance inserted with status INIT, updated COMPLETED/FAILED;
 * models serialized into the MODELDATA repository keyed by instance id;
 * deploy later picks getLatestCompleted — never a half-trained run.
"""

from __future__ import annotations

import logging
import traceback
from dataclasses import replace
from typing import Any

from pio_tpu.controller.base import TrainingInterruption
from pio_tpu.controller.engine import Engine, EngineParams
from pio_tpu.data.dao import EngineInstance, Model
from pio_tpu.data.storage import Storage
from pio_tpu.utils.time import utcnow
from pio_tpu.workflow.checkpoint import models_from_bytes, models_to_bytes
from pio_tpu.workflow.context import WorkflowContext, create_workflow_context

log = logging.getLogger("pio_tpu.workflow")


def run_train(
    engine: Engine,
    engine_params: EngineParams,
    storage: Storage,
    engine_id: str,
    engine_version: str = "1",
    engine_variant: str = "default",
    engine_factory: str = "",
    batch: str = "",
    ctx: WorkflowContext | None = None,
    stop_after_read: bool = False,
    stop_after_prepare: bool = False,
) -> str:
    """Returns the EngineInstance id (status COMPLETED on success)."""
    ctx = ctx or create_workflow_context(storage)
    instances = storage.get_metadata_engine_instances()
    now = utcnow()
    instance_id = instances.insert(
        EngineInstance(
            id="",
            status="INIT",
            start_time=now,
            end_time=now,
            engine_id=engine_id,
            engine_version=engine_version,
            engine_variant=engine_variant,
            engine_factory=engine_factory,
            batch=batch,
            datasource_params=f"{engine_params.datasource}",
            preparator_params=f"{engine_params.preparator}",
            algorithms_params=f"{engine_params.algorithms}",
            serving_params=f"{engine_params.serving}",
        )
    )
    instance = instances.get(instance_id)
    try:
        models = engine.train(
            ctx,
            engine_params,
            stop_after_read=stop_after_read,
            stop_after_prepare=stop_after_prepare,
        )
        blob = models_to_bytes(models)
        storage.get_model_data_models().insert(Model(instance_id, blob))
        instances.update(
            replace(instance, status="COMPLETED", end_time=utcnow())
        )
        log.info("training %s COMPLETED (%d bytes of models)",
                 instance_id, len(blob))
        return instance_id
    except TrainingInterruption:
        instances.update(replace(instance, status="INTERRUPTED", end_time=utcnow()))
        raise
    except Exception:
        log.error("training %s FAILED:\n%s", instance_id, traceback.format_exc())
        instances.update(replace(instance, status="FAILED", end_time=utcnow()))
        raise


def load_models(
    storage: Storage,
    engine: Engine,
    engine_params: EngineParams,
    instance_id: str,
    ctx: WorkflowContext | None = None,
) -> list[Any]:
    """Restore an instance's models and run per-algorithm deploy prep
    (reference Engine.prepareDeploy, Engine.scala:196-266 — minus the
    retrain-on-deploy hack: device models restore straight from bytes)."""
    ctx = ctx or create_workflow_context(storage)
    record = storage.get_model_data_models().get(instance_id)
    if record is None:
        raise ValueError(f"no models stored for engine instance {instance_id}")
    models = models_from_bytes(record.models)
    _, _, algos, _ = engine._doers(engine_params)
    if len(models) != len(algos):
        raise ValueError(
            f"instance {instance_id} has {len(models)} models but engine "
            f"params define {len(algos)} algorithms"
        )
    return [
        algo.prepare_model_for_deploy(ctx, m)
        for algo, m in zip(algos, models)
    ]
