"""Train workflow — read -> prepare -> train -> persist -> record.

Mirrors reference CoreWorkflow.runTrain (core/.../workflow/CoreWorkflow.scala:42-98)
and CreateWorkflow's EngineInstance bookkeeping (CreateWorkflow.scala:133-273):
 * EngineInstance inserted with status INIT, updated COMPLETED/FAILED;
 * models serialized into the MODELDATA repository keyed by instance id;
 * deploy later picks getLatestCompleted — never a half-trained run.

Beyond the reference, the run is *supervised* (workflow/lifecycle.py):

 * every run gets a per-instance step-checkpoint directory (keyed by
   EngineInstance.id) that the iterative trainers save into, so a killed
   run loses at most `checkpoint_every` steps;
 * SIGTERM/SIGINT request a final checkpoint at the next step boundary —
   the instance lands INTERRUPTED (resumable), not half-dead INIT;
 * heartbeats keep the instance's `progress` field fresh; stale
   INIT/TRAINING zombies from kill -9'd runs are swept to FAILED at the
   next train startup (and by `pio doctor --sweep-zombies`) so deploy's
   get_latest_completed contract is never starved silently;
 * `resume_instance_id` / `auto_resume` re-enter a resumable instance:
   the (seed, step)-keyed batch streams make the resumed run reproduce
   the uninterrupted one exactly;
 * multi-host: only process 0 writes metadata/models; all hosts barrier
   on checkpoint saves and on the final persist.
"""

from __future__ import annotations

import logging
import os
import traceback
from contextlib import nullcontext
from dataclasses import replace
from typing import Any

from pio_tpu.controller.base import TrainingInterruption
from pio_tpu.controller.engine import Engine, EngineParams
from pio_tpu.data.dao import EngineInstance, Model
from pio_tpu.data.storage import Storage
from pio_tpu.resilience import chaos
from pio_tpu.utils.time import format_time, utcnow
from pio_tpu.workflow.checkpoint import models_from_bytes, models_to_bytes
from pio_tpu.workflow.context import WorkflowContext, create_workflow_context
from pio_tpu.workflow.lifecycle import (
    RESUMABLE_STATUSES,
    PreemptionHandler,
    TrainingPreempted,
    TrainLifecycle,
    checkpoint_dir_for,
    find_resumable,
    sweep_zombies,
)

log = logging.getLogger("pio_tpu.workflow")


def _resolve_instance(
    instances,
    primary: bool,
    resume_instance_id: str | None,
    auto_resume: bool,
    engine_id: str,
    engine_version: str,
    engine_variant: str,
    engine_factory: str,
    batch: str,
    engine_params: EngineParams,
    checkpoint_root: str | None,
) -> EngineInstance:
    """Resume an existing resumable instance, or insert a fresh one."""
    now = utcnow()
    if resume_instance_id:
        instance = instances.get(resume_instance_id)
        if instance is None:
            raise ValueError(
                f"cannot resume: engine instance {resume_instance_id} "
                "not found"
            )
        if instance.status not in RESUMABLE_STATUSES:
            raise ValueError(
                f"cannot resume instance {resume_instance_id}: status is "
                f"{instance.status} (resumable: "
                f"{', '.join(RESUMABLE_STATUSES)})"
            )
        got = (instance.engine_id, instance.engine_version,
               instance.engine_variant)
        want = (engine_id, engine_version, engine_variant)
        if got != want:
            # resuming under the wrong engine would persist engine B's
            # model blob against engine A's instance — and deploy's
            # get_latest_completed would then serve it
            raise ValueError(
                f"cannot resume instance {resume_instance_id}: it belongs "
                f"to engine {got}, not {want} (wrong --engine-dir?)"
            )
        return instance
    if auto_resume:
        instance = find_resumable(
            instances, engine_id, engine_version, engine_variant,
            checkpoint_root,
        )
        if instance is not None:
            log.info("auto-resume: picking up instance %s (%s, last step "
                     "%s)", instance.id, instance.status,
                     instance.progress.get("step"))
            return instance
        log.info("auto-resume: no resumable instance with checkpoints "
                 "found; starting fresh")
    # multi-host: every process must agree on the instance id, and only
    # process 0 may insert — an explicit PIO_TPU_RUN_ID provides both
    run_id = os.environ.get("PIO_TPU_RUN_ID", "")
    fresh = EngineInstance(
        id=run_id,
        status="INIT",
        start_time=now,
        end_time=now,
        engine_id=engine_id,
        engine_version=engine_version,
        engine_variant=engine_variant,
        engine_factory=engine_factory,
        batch=batch,
        datasource_params=f"{engine_params.datasource}",
        preparator_params=f"{engine_params.preparator}",
        algorithms_params=f"{engine_params.algorithms}",
        serving_params=f"{engine_params.serving}",
    )
    if not primary:
        if not run_id:
            raise ValueError(
                "multi-host training needs PIO_TPU_RUN_ID set (identically "
                "on every host) so non-primary processes know the "
                "engine-instance id without writing metadata"
            )
        return fresh
    instance_id = instances.insert(fresh)
    return instances.get(instance_id)


def run_train(
    engine: Engine,
    engine_params: EngineParams,
    storage: Storage,
    engine_id: str,
    engine_version: str = "1",
    engine_variant: str = "default",
    engine_factory: str = "",
    batch: str = "",
    ctx: WorkflowContext | None = None,
    stop_after_read: bool = False,
    stop_after_prepare: bool = False,
    resume_instance_id: str | None = None,
    auto_resume: bool = False,
    checkpoint_root: str | None = None,
    supervise: bool = True,
    heartbeat_every_steps: int = 10,
    sweep_stale_s: float | None = None,
) -> str:
    """Returns the EngineInstance id (status COMPLETED on success).

    With ``supervise`` (the default) the run gets the full lifecycle:
    per-instance checkpoint dir, SIGTERM/SIGINT preemption handling
    (raises TrainingPreempted; instance INTERRUPTED), heartbeats, and a
    startup zombie sweep. ``resume_instance_id`` re-enters a resumable
    (INTERRUPTED/FAILED) instance; ``auto_resume`` picks the most recent
    one with checkpoints on disk.
    """
    # persistent XLA compile cache BEFORE any engine compile: the second
    # consecutive train of the same engine deserializes its executables
    # instead of re-running XLA (utils/compilecache.py; PIO_TPU_COMPILE_
    # CACHE=off disables)
    from pio_tpu.utils.compilecache import enable_compile_cache

    enable_compile_cache()
    ctx = ctx or create_workflow_context(storage)
    instances = storage.get_metadata_engine_instances()
    from pio_tpu.parallel.distributed import barrier, is_primary

    primary = is_primary()
    if supervise and primary:
        try:
            swept = sweep_zombies(
                storage,
                **({"stale_after_s": sweep_stale_s}
                   if sweep_stale_s is not None else {}),
            )
            if swept:
                log.warning("startup sweep transitioned %d zombie "
                            "instance(s) to FAILED: %s",
                            len(swept), [i.id for i in swept])
        except Exception:  # noqa: BLE001 - the sweep is advisory
            log.warning("startup zombie sweep failed", exc_info=True)

    instance = _resolve_instance(
        instances, primary, resume_instance_id, auto_resume,
        engine_id, engine_version, engine_variant, engine_factory, batch,
        engine_params, checkpoint_root,
    )
    resumed = instance.status in RESUMABLE_STATUSES
    instance_id = instance.id

    # a resumed run MUST read the directory the original run recorded —
    # recomputing from the current --checkpoint-root/env could point at
    # an empty dir and silently restart from step 0 (and --auto-resume's
    # has_checkpoint validation reads the recorded dir)
    ckpt_dir = (
        (instance.progress or {}).get("checkpoint_dir") if resumed else None
    ) or checkpoint_dir_for(instance_id, checkpoint_root)
    handler = PreemptionHandler() if supervise else None
    lifecycle = TrainLifecycle(
        instances,
        instance,
        checkpoint_dir=ckpt_dir,
        heartbeat_every_steps=heartbeat_every_steps,
        preemption=handler,
        readonly=not primary,
    )

    def record(status: str, **progress_extra) -> None:
        """Terminal status transition, keeping accumulated progress."""
        lifecycle.stop()  # the liveness beat must not race terminal writes
        if not primary:
            return
        progress = dict(lifecycle.instance.progress)
        progress.update(progress_extra)
        lifecycle.instance = replace(
            lifecycle.instance, status=status, end_time=utcnow(),
            progress=progress,
        )
        instances.update(lifecycle.instance)

    # mark the run live before training: TRAINING + an initial heartbeat
    # so a kill -9 from now on is detectable as a stale zombie
    progress = dict(instance.progress)
    if resumed:
        progress["resumed_at"] = format_time(utcnow())
    lifecycle.instance = replace(
        instance, status="TRAINING", progress=progress
    )
    if primary:
        instances.update(lifecycle.instance)
    lifecycle.heartbeat(progress.get("step", 0), force=True)
    lifecycle.start()  # wall-clock liveness beat (see TrainLifecycle)

    ctx.lifecycle = lifecycle
    try:
        with handler if handler is not None else nullcontext():
            models = engine.train(
                ctx,
                engine_params,
                stop_after_read=stop_after_read,
                stop_after_prepare=stop_after_prepare,
            )
            # chaos point: a `train.persist` spec simulates a storage
            # fault during the final model write — the run must land
            # FAILED (resumable from its last checkpoint), never
            # COMPLETED-without-a-blob. The barrier is reached on BOTH
            # outcomes: a host whose persist epoch failed must not leave
            # its peers blocked in sync_global_devices forever.
            persist_error: Exception | None = None
            try:
                chaos.maybe_inject("train.persist")
                blob = models_to_bytes(models)
                if primary:
                    storage.get_model_data_models().insert(
                        Model(instance_id, blob)
                    )
            except Exception as e:  # noqa: BLE001 - re-raised after barrier
                persist_error = e
            # the COMPLETED transition must not outrun any host's part of
            # the persist epoch
            barrier("train-persist")
            if persist_error is not None:
                raise persist_error
            record("COMPLETED")
            log.info("training %s COMPLETED (%d bytes of models)",
                     instance_id, len(blob))
            return instance_id
    except TrainingPreempted as preempted:
        try:
            record(
                "INTERRUPTED",
                preempted_at_step=preempted.step,
                resumable=True,
            )
        except Exception:  # noqa: BLE001 - preserve the preemption signal
            log.error("could not mark %s INTERRUPTED (status store down)",
                      instance_id, exc_info=True)
        log.warning(
            "training %s INTERRUPTED by preemption at step %s; resume "
            "with: pio train --resume %s",
            instance_id, preempted.step, instance_id,
        )
        raise
    except TrainingInterruption:
        record("INTERRUPTED")
        raise
    except Exception as train_error:
        log.error("training %s FAILED:\n%s",
                  instance_id, traceback.format_exc())
        try:
            record("FAILED")
        except Exception as update_error:
            # the status write failing (store down) must not MASK why
            # training died: surface the training error, chained to the
            # bookkeeping failure
            raise train_error from update_error
        raise
    finally:
        lifecycle.stop()
        ctx.lifecycle = None


def load_models(
    storage: Storage,
    engine: Engine,
    engine_params: EngineParams,
    instance_id: str,
    ctx: WorkflowContext | None = None,
) -> list[Any]:
    """Restore an instance's models and run per-algorithm deploy prep
    (reference Engine.prepareDeploy, Engine.scala:196-266 — minus the
    retrain-on-deploy hack: device models restore straight from bytes).

    Raises ModelIntegrityError (utils/durable.py) when the stored blob
    fails its CRC32C frame — a truncated or bit-rotted artifact never
    reaches the unpickler; serve falls back to the previous COMPLETED
    instance on that error."""
    ctx = ctx or create_workflow_context(storage)
    record = storage.get_model_data_models().get(instance_id)
    if record is None:
        raise ValueError(f"no models stored for engine instance {instance_id}")
    models = models_from_bytes(record.models)
    _, _, algos, _ = engine._doers(engine_params)
    if len(models) != len(algos):
        raise ValueError(
            f"instance {instance_id} has {len(models)} models but engine "
            f"params define {len(algos)} algorithms"
        )
    return [
        algo.prepare_model_for_deploy(ctx, m)
        for algo, m in zip(algos, models)
    ]
