"""Client SDK — the counterpart of the reference's PredictionIO-python-sdk.

Two clients, mirroring the SDK surface users of the reference already know
(predictionio.EventClient / predictionio.EngineClient):

    from pio_tpu.sdk import EventClient, EngineClient

    events = EventClient(access_key="...", url="http://localhost:7070")
    events.create_event(event="rate", entity_type="user", entity_id="u1",
                        target_entity_type="item", target_entity_id="i9",
                        properties={"rating": 5})
    events.create_events_batch([...])            # <= 50 per request

    engine = EngineClient(url="http://localhost:8000")
    engine.send_query({"user": "u1", "num": 10})
    engine.send_queries_batch([{...}, {...}])    # bulk endpoint

Stdlib-only (urllib), keep-alive not required — for high-volume ingest use
create_events_batch. Errors raise PIOError carrying the server's status
and message.
"""

from __future__ import annotations

import urllib.parse
from typing import Any, Sequence

from pio_tpu.utils.httpclient import HttpClientError, JsonHttpClient

BATCH_LIMIT = 50  # server-enforced (reference EventServer.scala:68)


class PIOError(HttpClientError):
    """SDK error: .status (0 = transport failure) + server message."""


class _Http(JsonHttpClient):
    def call(self, method: str, path: str, body: Any = None,
             **params) -> Any:
        try:
            return self.request(method, path, body, params)
        except HttpClientError as e:
            raise PIOError(e.status, e.message) from e


class EventClient:
    """Event Server client (reference python-sdk EventClient)."""

    def __init__(self, access_key: str, url: str = "http://localhost:7070",
                 channel: str | None = None, timeout: float = 30.0,
                 verify_tls: bool = True):
        self.access_key = access_key
        self.channel = channel
        self._http = _Http(url, timeout, verify_tls)

    # -- write --------------------------------------------------------------
    def create_event(self, event: str, entity_type: str, entity_id: str,
                     target_entity_type: str | None = None,
                     target_entity_id: str | None = None,
                     properties: dict | None = None,
                     event_time: str | None = None) -> str:
        """-> eventId. event_time: ISO-8601 string (server default: now)."""
        body: dict[str, Any] = {
            "event": event, "entityType": entity_type, "entityId": entity_id,
        }
        if target_entity_type:
            body["targetEntityType"] = target_entity_type
        if target_entity_id:
            body["targetEntityId"] = target_entity_id
        if properties:
            body["properties"] = properties
        if event_time:
            body["eventTime"] = event_time
        out = self._http.call(
            "POST", "/events.json", body,
            accessKey=self.access_key, channel=self.channel,
        )
        return out["eventId"]

    def create_events_batch(self, events: Sequence[dict]) -> list[dict]:
        """<= 50 events (server limit); returns per-item statuses."""
        if len(events) > BATCH_LIMIT:
            raise ValueError(
                f"batch limit is {BATCH_LIMIT} events per request"
            )
        return self._http.call(
            "POST", "/batch/events.json", list(events),
            accessKey=self.access_key, channel=self.channel,
        )

    # -- convenience entity ops (reference SDK set_user/set_item/record) ----
    def set_user(self, uid: str, properties: dict | None = None) -> str:
        return self.create_event("$set", "user", uid, properties=properties)

    def set_item(self, iid: str, properties: dict | None = None) -> str:
        return self.create_event("$set", "item", iid, properties=properties)

    def record_user_action_on_item(self, action: str, uid: str, iid: str,
                                   properties: dict | None = None) -> str:
        return self.create_event(
            action, "user", uid, target_entity_type="item",
            target_entity_id=iid, properties=properties,
        )

    # -- read ---------------------------------------------------------------
    def get_event(self, event_id: str) -> dict:
        return self._http.call(
            "GET", f"/events/{urllib.parse.quote(event_id)}.json",
            accessKey=self.access_key, channel=self.channel,
        )

    def find_events(self, **filters) -> list[dict]:
        """filters: startTime/untilTime/entityType/entityId/event/limit/
        reversed — the /events.json query params."""
        return self._http.call(
            "GET", "/events.json",
            accessKey=self.access_key, channel=self.channel, **filters,
        )

    def delete_event(self, event_id: str) -> None:
        self._http.call(
            "DELETE", f"/events/{urllib.parse.quote(event_id)}.json",
            accessKey=self.access_key, channel=self.channel,
        )


class EngineClient:
    """Deploy-server client (reference python-sdk EngineClient)."""

    def __init__(self, url: str = "http://localhost:8000",
                 timeout: float = 30.0, verify_tls: bool = True):
        self._http = _Http(url, timeout, verify_tls)

    def send_query(self, query: dict) -> Any:
        return self._http.call("POST", "/queries.json", query)

    def send_queries_batch(self, queries: Sequence[dict]) -> list:
        """Bulk endpoint: one batch_predict per algorithm server-side."""
        return self._http.call("POST", "/batch/queries.json", list(queries))

    def status(self) -> dict:
        return self._http.call("GET", "/")
