"""Client SDK — the counterpart of the reference's PredictionIO-python-sdk.

Two clients, mirroring the SDK surface users of the reference already know
(predictionio.EventClient / predictionio.EngineClient):

    from pio_tpu.sdk import EventClient, EngineClient

    events = EventClient(access_key="...", url="http://localhost:7070")
    events.create_event(event="rate", entity_type="user", entity_id="u1",
                        target_entity_type="item", target_entity_id="i9",
                        properties={"rating": 5})
    events.create_events_batch([...])            # <= 50 per request

    engine = EngineClient(url="http://localhost:8000")
    engine.send_query({"user": "u1", "num": 10})
    engine.send_queries_batch([{...}, {...}])    # bulk endpoint

Stdlib-only (urllib), keep-alive not required — for high-volume ingest use
create_events_batch. Errors raise PIOError carrying the server's status
and message.

Wire format: ``create_events_batch`` encodes the binary columnar frame
(``application/x-pio-columnar``, data/columnar.py — the server decodes
it by pointer-cast instead of per-event JSON) by default; pass
``wire="json"`` for pre-binary servers. Responses are identical either
way (per-event statuses, same verdicts/messages).

Backpressure: the event server answers 429 + Retry-After past its spill
high-water mark (whole-request on /events.json, per-slot inside a batch
response). The client absorbs both through its resilience RetryPolicy
(full jitter, deadline-capped, floored at the server's Retry-After
hint) instead of surfacing the 429 to callers; ``EventClient.stats``
counts shed/retried so load generators can report them.
"""

from __future__ import annotations

import time
import urllib.parse
from typing import Any, Sequence

from pio_tpu.data.columnar import COLUMNAR_CONTENT_TYPE, encode_api_batch
from pio_tpu.resilience import Deadline, RetryPolicy
from pio_tpu.utils.httpclient import HttpClientError, JsonHttpClient

BATCH_LIMIT = 50  # server-enforced (reference EventServer.scala:68)
# the binary columnar route's bulk ceiling (eventserver
# MAX_EVENTS_PER_BINARY_BATCH): the JSON limit is reference compat; the
# binary frame is built to amortize per-request cost over big batches
BINARY_BATCH_LIMIT = 10_000

# backpressure default: absorb short spill-queue saturation bursts (the
# server drains to its low-water mark in ~seconds) without hammering it
_DEFAULT_RETRY = RetryPolicy(attempts=4, base_delay_s=0.1, max_delay_s=2.0)


class PIOError(HttpClientError):
    """SDK error: .status (0 = transport failure) + server message."""


def _looks_pre_binary(e: PIOError) -> bool:
    """True when a 400 to a binary-frame POST reads like a pre-binary
    server JSON-parsing the frame bytes (see _post_batch)."""
    if e.status != 400:
        return False
    msg = e.message or ""
    return (msg == "Invalid JSON body"
            or "codec can't decode" in msg
            or msg.startswith("Expecting value")
            or msg.startswith("Extra data"))


class _Http(JsonHttpClient):
    def call(self, method: str, path: str, body: Any = None,
             raw: bytes | None = None, content_type: str | None = None,
             accept: str | None = None, **params) -> Any:
        try:
            return self.request(method, path, body, params, raw=raw,
                                content_type=content_type, accept=accept)
        except HttpClientError as e:
            raise PIOError(e.status, e.message,
                           retry_after=e.retry_after) from e


class EventClient:
    """Event Server client (reference python-sdk EventClient)."""

    def __init__(self, access_key: str, url: str = "http://localhost:7070",
                 channel: str | None = None, timeout: float = 30.0,
                 verify_tls: bool = True, wire: str = "binary",
                 retry: RetryPolicy | None = None):
        if wire not in ("binary", "json"):
            raise ValueError("wire must be 'binary' or 'json'")
        self.access_key = access_key
        self.channel = channel
        self.wire = wire
        self.retry = retry or _DEFAULT_RETRY
        # shed/retry accounting for load generators: `shed` counts 429
        # verdicts received (whole-request or per-slot), `retried` the
        # re-submissions this client performed on the caller's behalf
        self.stats = {"shed": 0, "retried": 0}
        self._sleep = time.sleep  # injectable for tests
        self._http = _Http(url, timeout, verify_tls)

    # -- backpressure ------------------------------------------------------
    def _call_absorbing_429(self, fn):
        """Run fn() under the RetryPolicy, retrying ONLY 429 (the spill
        high-water backpressure signal): backoff is full-jitter from the
        policy, floored at the server's Retry-After hint and capped by
        the ambient Deadline. Other failures surface unchanged."""
        state: dict[str, Any] = {"retry_after": None}

        def retry_if(e: BaseException) -> bool:
            if getattr(e, "status", None) != 429:
                return False
            state["retry_after"] = getattr(e, "retry_after", None)
            self.stats["shed"] += 1
            return True

        def sleep(d: float) -> None:
            hint = state["retry_after"]
            if hint:
                d = max(d, min(float(hint), self.retry.max_delay_s))
            rem = Deadline.remaining()
            if rem is not None:
                d = min(d, max(0.0, rem))
            self.stats["retried"] += 1
            self._sleep(d)

        return self.retry.call(fn, retry_if=retry_if, sleep=sleep)

    # -- write --------------------------------------------------------------
    def create_event(self, event: str, entity_type: str, entity_id: str,
                     target_entity_type: str | None = None,
                     target_entity_id: str | None = None,
                     properties: dict | None = None,
                     event_time: str | None = None) -> str:
        """-> eventId. event_time: ISO-8601 string (server default: now)."""
        body: dict[str, Any] = {
            "event": event, "entityType": entity_type, "entityId": entity_id,
        }
        if target_entity_type:
            body["targetEntityType"] = target_entity_type
        if target_entity_id:
            body["targetEntityId"] = target_entity_id
        if properties:
            body["properties"] = properties
        if event_time:
            body["eventTime"] = event_time
        out = self._call_absorbing_429(lambda: self._http.call(
            "POST", "/events.json", body,
            accessKey=self.access_key, channel=self.channel,
        ))
        return out["eventId"]

    def _post_batch(self, events: Sequence[dict]) -> list[dict]:
        if self.wire == "binary":
            # encode ONCE outside the retry closure: the bytes are
            # identical on every 429 re-attempt
            blob = encode_api_batch(list(events))
            try:
                return self._call_absorbing_429(lambda: self._http.call(
                    "POST", "/batch/events.json",
                    raw=blob,
                    content_type=COLUMNAR_CONTENT_TYPE,
                    accessKey=self.access_key, channel=self.channel,
                ))
            except PIOError as e:
                # a PRE-BINARY server ran req.json() on the frame:
                # depending on where the parse failed, its authed
                # wrapper answers 400 with a UnicodeDecodeError text
                # ("codec can't decode", the usual case — the frame's
                # CRC bytes are rarely valid UTF-8), a JSONDecodeError
                # text ("Expecting value"/"Extra data"), or the
                # dispatch-level "Invalid JSON body". A binary-capable
                # server decodes the frame BEFORE any JSON parse, so its
                # 400s on this route are WireFormatError/limit messages
                # that match none of these. Downgrade to the JSON wire
                # for this client's lifetime, like the read paths
                # degrade on 404/Accept.
                if not _looks_pre_binary(e):
                    raise
                self.wire = "json"
        batch = list(events)
        if len(batch) > BATCH_LIMIT:
            raise PIOError(
                400, f"server only speaks the JSON wire, whose batch "
                f"limit is {BATCH_LIMIT} events per request")
        return self._call_absorbing_429(lambda: self._http.call(
            "POST", "/batch/events.json", batch,
            accessKey=self.access_key, channel=self.channel,
        ))

    def create_events_batch(self, events: Sequence[dict]) -> list[dict]:
        """<= 50 events (server limit); returns per-item statuses.

        Slots the server shed with a per-event 429 (spill backpressure)
        are re-submitted on the RetryPolicy schedule — callers see 429
        only after the policy's attempts are exhausted. Statuses come
        back in input order either way. The binary wire accepts bulk
        frames up to BINARY_BATCH_LIMIT; the JSON wire keeps the
        reference's 50-event contract."""
        events = list(events)
        limit = (BINARY_BATCH_LIMIT if self.wire == "binary"
                 else BATCH_LIMIT)
        if len(events) > limit:
            raise ValueError(
                f"batch limit is {limit} events per request"
            )
        out = self._post_batch(events)
        pending = [i for i, r in enumerate(out)
                   if isinstance(r, dict) and r.get("status") == 429]
        # policy-driven resend of shed slots: .delays() is the schedule
        for d in self.retry.delays() if pending else ():
            self.stats["shed"] += len(pending)
            rem = Deadline.remaining()
            if rem is not None:
                if rem <= 0:
                    break
                d = min(d, rem)
            self._sleep(d)
            self.stats["retried"] += len(pending)
            try:
                resent = self._post_batch([events[i] for i in pending])
            except HttpClientError:
                # a failed RESEND must not discard the receipts already
                # in `out` — the caller keeps the accepted slots' ids
                # (re-posting the whole batch would duplicate them) and
                # sees the still-shed slots as honest per-slot 429s
                break
            for i, r in zip(pending, resent):
                out[i] = r
            pending = [i for i in pending
                       if isinstance(out[i], dict)
                       and out[i].get("status") == 429]
            if not pending:
                break
        return out

    # -- convenience entity ops (reference SDK set_user/set_item/record) ----
    def set_user(self, uid: str, properties: dict | None = None) -> str:
        return self.create_event("$set", "user", uid, properties=properties)

    def set_item(self, iid: str, properties: dict | None = None) -> str:
        return self.create_event("$set", "item", iid, properties=properties)

    def record_user_action_on_item(self, action: str, uid: str, iid: str,
                                   properties: dict | None = None) -> str:
        return self.create_event(
            action, "user", uid, target_entity_type="item",
            target_entity_id=iid, properties=properties,
        )

    # -- read ---------------------------------------------------------------
    def get_event(self, event_id: str) -> dict:
        return self._http.call(
            "GET", f"/events/{urllib.parse.quote(event_id)}.json",
            accessKey=self.access_key, channel=self.channel,
        )

    def find_events(self, **filters) -> list[dict]:
        """filters: startTime/untilTime/entityType/entityId/event/limit/
        reversed — the /events.json query params."""
        return self._http.call(
            "GET", "/events.json",
            accessKey=self.access_key, channel=self.channel, **filters,
        )

    def delete_event(self, event_id: str) -> None:
        self._http.call(
            "DELETE", f"/events/{urllib.parse.quote(event_id)}.json",
            accessKey=self.access_key, channel=self.channel,
        )


class EngineClient:
    """Deploy-server client (reference python-sdk EngineClient)."""

    def __init__(self, url: str = "http://localhost:8000",
                 timeout: float = 30.0, verify_tls: bool = True):
        self._http = _Http(url, timeout, verify_tls)

    def send_query(self, query: dict) -> Any:
        return self._http.call("POST", "/queries.json", query)

    def send_queries_batch(self, queries: Sequence[dict]) -> list:
        """Bulk endpoint: one batch_predict per algorithm server-side."""
        return self._http.call("POST", "/batch/queries.json", list(queries))

    def status(self) -> dict:
        return self._http.call("GET", "/")
