"""BinaryVectorizer — (property, value) one-hot encoding.

Reference e2/.../engine/BinaryVectorizer.scala:10-46: builds an index map
from distinct (field, value) pairs and emits MLlib SparseVectors; here the
map is host-side and `transform` emits dense numpy one-hot rows (XLA wants
dense static shapes; at typical categorical widths a dense row is the right
layout for the MXU anyway).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Mapping, Sequence

import numpy as np

from pio_tpu.data.bimap import BiMap


@dataclass
class BinaryVectorizer:
    index: BiMap  # (field, value) -> dim

    @property
    def n_features(self) -> int:
        return len(self.index)

    @staticmethod
    def fit(
        maps: Iterable[Mapping[str, str]], fields: Sequence[str]
    ) -> "BinaryVectorizer":
        """Reference BinaryVectorizer.apply(rdd, properties)."""
        pairs: dict[tuple[str, str], int] = {}
        for m in maps:
            for f in fields:
                if f in m:
                    key = (f, str(m[f]))
                    if key not in pairs:
                        pairs[key] = len(pairs)
        return BinaryVectorizer(BiMap(pairs))

    def transform(self, m: Mapping[str, str]) -> np.ndarray:
        """One map -> dense one-hot row (reference toBinaryVector)."""
        v = np.zeros(self.n_features, np.float32)
        for f, val in m.items():
            j = self.index.get((f, str(val)), -1)
            if j >= 0:
                v[j] = 1.0
        return v

    def transform_batch(self, maps: Sequence[Mapping[str, str]]) -> np.ndarray:
        out = np.zeros((len(maps), self.n_features), np.float32)
        for i, m in enumerate(maps):
            out[i] = self.transform(m)
        return out
