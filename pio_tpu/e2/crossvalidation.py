"""k-fold cross-validation splitters.

Reference e2/.../evaluation/CrossValidation.scala:9-39 `splitData`: fold i's
test set is every example whose index % k == i; train is the rest. Same
index-mod-k contract here, vectorized over numpy columns.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Sequence

import numpy as np

from pio_tpu.data.eventstore import Interactions


@dataclass(frozen=True)
class FoldInfo:
    fold: int
    k: int


def split_indices(n: int, k: int) -> list[tuple[np.ndarray, np.ndarray]]:
    """-> [(train_idx, test_idx)] per fold, index-mod-k."""
    idx = np.arange(n)
    return [((idx % k) != f, (idx % k) == f) for f in range(k)]


def split_data(
    rows: Sequence[Any], k: int
) -> list[tuple[list[Any], FoldInfo, list[Any]]]:
    """Generic splitter over a row list (reference splitData shape)."""
    out = []
    for f in range(k):
        train = [r for i, r in enumerate(rows) if i % k != f]
        test = [r for i, r in enumerate(rows) if i % k == f]
        out.append((train, FoldInfo(f, k), test))
    return out


def split_interactions(
    data: Interactions,
    k: int,
    num: int = 10,
) -> list[tuple[Interactions, FoldInfo, list[tuple[dict, Any]]]]:
    """Interactions -> k folds of (train, info, [(query, actual)]).

    Queries follow the recommendation template shape {"user", "num"}; the
    actual is the list of held-out item ids for that user (what the metric
    layer scores against, reference MetricEvaluator input shape)."""
    if k <= 1:
        return []
    folds = []
    n = len(data)
    for train_mask, test_mask in split_indices(n, k):
        train = Interactions(
            user_idx=data.user_idx[train_mask],
            item_idx=data.item_idx[train_mask],
            values=data.values[train_mask],
            users=data.users,
            items=data.items,
        )
        qa: list[tuple[dict, Any]] = []
        test_users = data.user_idx[test_mask]
        test_items = data.item_idx[test_mask]
        by_user: dict[int, list[int]] = {}
        for u, i in zip(test_users, test_items):
            by_user.setdefault(int(u), []).append(int(i))
        for u, item_list in sorted(by_user.items()):
            qa.append((
                {"user": data.users.id_of(u), "num": num},
                [data.items.id_of(i) for i in item_list],
            ))
        folds.append((train, FoldInfo(len(folds), k), qa))
    return folds
