"""k-fold cross-validation splitters.

Reference e2/.../evaluation/CrossValidation.scala:9-39 `splitData`: fold i's
test set is every example whose index % k == i; train is the rest. Same
index-mod-k contract here, vectorized over numpy columns.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Sequence

import numpy as np

from pio_tpu.data.eventstore import Interactions


@dataclass(frozen=True)
class FoldInfo:
    fold: int
    k: int


def split_indices(n: int, k: int) -> list[tuple[np.ndarray, np.ndarray]]:
    """-> [(train_idx, test_idx)] per fold, index-mod-k."""
    idx = np.arange(n)
    return [((idx % k) != f, (idx % k) == f) for f in range(k)]


def split_data(
    rows: Sequence[Any], k: int
) -> list[tuple[list[Any], FoldInfo, list[Any]]]:
    """Generic splitter over a row list (reference splitData shape)."""
    out = []
    for f in range(k):
        train = [r for i, r in enumerate(rows) if i % k != f]
        test = [r for i, r in enumerate(rows) if i % k == f]
        out.append((train, FoldInfo(f, k), test))
    return out


def split_interactions(
    data: Interactions,
    k: int,
    num: int = 10,
    exclude_seen: bool = True,
) -> list[tuple[Interactions, FoldInfo, list[tuple[dict, Any]]]]:
    """Interactions -> k folds of (train, info, [(query, actual)]).

    Queries follow the recommendation template shape {"user", "num"}; the
    actual is the list of held-out item ids for that user (what the metric
    layer scores against, reference MetricEvaluator input shape).

    exclude_seen (default): each query carries the user's TRAIN-fold items
    as blackList, and heldout actuals are deduped against that blackList
    (a blacklisted item is unhittable by construction — leaving it in the
    actuals would deflate every engine's score). Without the blacklist the
    metric mostly measures how much of the top-k an engine wastes on
    reconstruction (standard unseen-item evaluation; the reference's
    ecommerce template applies the same seen-filter at serve time)."""
    if k <= 1:
        return []
    n = len(data)
    # one numpy group-by over the FULL dataset (per-user row slices +
    # fold tags), instead of k Python passes over the train folds
    order = np.lexsort((data.item_idx, data.user_idx))
    u_sorted = data.user_idx[order]
    i_sorted = data.item_idx[order]
    f_sorted = (order % k).astype(np.int64)  # fold of each row
    bounds = np.flatnonzero(
        np.concatenate([[True], u_sorted[1:] != u_sorted[:-1], [True]])
    )
    folds: list[tuple[Interactions, FoldInfo, list[tuple[dict, Any]]]] = []
    for train_mask, test_mask in split_indices(n, k):
        f = len(folds)
        train = Interactions(
            user_idx=data.user_idx[train_mask],
            item_idx=data.item_idx[train_mask],
            values=data.values[train_mask],
            users=data.users,
            items=data.items,
        )
        qa: list[tuple[dict, Any]] = []
        for s, e in zip(bounds[:-1], bounds[1:]):
            in_test = f_sorted[s:e] == f
            test_items = i_sorted[s:e][in_test]
            if not len(test_items):
                continue
            u = int(u_sorted[s])
            q: dict = {"user": data.users.id_of(u), "num": num}
            if exclude_seen:
                seen = np.unique(i_sorted[s:e][~in_test])
                if len(seen):
                    q["blackList"] = data.items.decode(seen)
                    # actuals the blacklist makes unhittable are dropped
                    test_items = test_items[
                        ~np.isin(test_items, seen)]
                    if not len(test_items):
                        qa.append((q, []))  # metric scores this as None
                        continue
            qa.append((q, data.items.decode(test_items)))
        folds.append((train, FoldInfo(f, k), qa))
    return folds
