"""Ranking metrics for the recommendation templates.

The reference's similarproduct/ecommerce evaluation examples define
Precision@K-style metrics over PredictedResult.itemScores vs. actual item
sets; these are the shared vectorized implementations.
"""

from __future__ import annotations

from pio_tpu.controller.evaluation import (  # noqa: F401 (re-export)
    MeanSquareError,
    OptionAverageMetric,
)


def _predicted_items(prediction) -> list[str]:
    if isinstance(prediction, dict):
        return [s["item"] for s in prediction.get("itemScores", [])]
    return list(prediction or [])


class PrecisionAtK(OptionAverageMetric):
    """tp / min(k, |actual|) over the top-k predictions — the reference
    recommendation-template metric shape. Queries with no *actuals* score
    None (excluded); an engine returning few/no predictions is penalized,
    not excluded, so tuning cannot be gamed by under-predicting."""

    def __init__(self, k: int = 10):
        self.k = k

    @property
    def header(self) -> str:
        return f"Precision@{self.k}"

    def calculate_one(self, query, prediction, actual):
        actual_set = set(actual or [])
        if not actual_set:
            return None
        pred = _predicted_items(prediction)[: self.k]
        tp = sum(1 for p in pred if p in actual_set)
        return tp / min(self.k, len(actual_set))


class RecallAtK(OptionAverageMetric):
    def __init__(self, k: int = 10):
        self.k = k

    @property
    def header(self) -> str:
        return f"Recall@{self.k}"

    def calculate_one(self, query, prediction, actual):
        actual_set = set(actual or [])
        if not actual_set:
            return None
        pred = _predicted_items(prediction)[: self.k]
        return sum(1 for p in pred if p in actual_set) / len(actual_set)
