"""e2 engine-building helpers — parity naming for the reference e2 library.

Reference e2/src/main/scala/org/apache/predictionio/e2/engine/:
CategoricalNaiveBayes.scala, MarkovChain.scala, BinaryVectorizer.scala.
The implementations live in pio_tpu.ops / pio_tpu.e2.vectorizer; this module
re-exports them under the e2 names engine templates import.
"""

from pio_tpu.ops.naive_bayes import (
    CategoricalNBModel,
    categorical_nb_train,
)
from pio_tpu.ops.markov import MarkovChainModel, markov_chain_train
from pio_tpu.e2.vectorizer import BinaryVectorizer

__all__ = [
    "CategoricalNBModel",
    "categorical_nb_train",
    "MarkovChainModel",
    "markov_chain_train",
    "BinaryVectorizer",
]
