"""ctypes bindings + record codec for the native event log.

The C++ side (native/eventlog.cpp) owns framing, crc, filtered scans, and
the training columnarizer; this module packs/unpacks record payloads and
exposes a typed ``EventLog`` handle. Python re-verifies scan matches exactly
(`match_event`), so the C hash prefilter can never produce a wrong result —
collisions only cost a wasted decode.

Times are stored as exact integer microseconds since epoch plus the original
UTC-offset minutes, so ``Event`` round-trips losslessly (the reference keeps
joda DateTimes with zone, hbase/HBEventsUtil.scala:144-270).
"""

from __future__ import annotations

import ctypes as C
import json
import struct
from dataclasses import dataclass
from datetime import datetime, timedelta, timezone

import numpy as np

from pio_tpu.data.datamap import DataMap
from pio_tpu.data.event import Event
from pio_tpu.native import load_library

_EPOCH = datetime(1970, 1, 1, tzinfo=timezone.utc)
_US = timedelta(microseconds=1)

F_START = 1 << 0
F_UNTIL = 1 << 1
F_ETYPE = 1 << 2
F_EID = 1 << 3
F_EVENTS = 1 << 4
F_TETYPE_EQ = 1 << 5
F_TETYPE_ABSENT = 1 << 6
F_TEID_EQ = 1 << 7
F_TEID_ABSENT = 1 << 8
F_EVENTID = 1 << 9

DEDUP_NONE, DEDUP_LAST, DEDUP_SUM = 0, 1, 2


def _lib() -> C.CDLL:
    lib = load_library("eventlog")
    if getattr(lib, "_el_typed", False):
        return lib
    u8p = C.POINTER(C.c_uint8)
    u64p = C.POINTER(C.c_uint64)
    lib.el_open.restype = C.c_void_p
    lib.el_open.argtypes = [C.c_char_p, C.c_int]
    lib.el_close.argtypes = [C.c_void_p]
    lib.el_flush.restype = C.c_int
    lib.el_flush.argtypes = [C.c_void_p]
    lib.el_append.restype = C.c_int64
    lib.el_append.argtypes = [C.c_void_p, C.c_char_p, C.c_uint32]
    lib.el_stats.argtypes = [C.c_void_p, u64p, u64p]
    lib.el_hash.restype = C.c_uint64
    lib.el_hash.argtypes = [C.c_char_p, C.c_uint32]
    lib.el_free.argtypes = [C.c_void_p]
    lib.el_scan.restype = C.c_int64
    lib.el_scan.argtypes = [
        C.c_void_p, C.c_uint32, C.c_int64, C.c_int64, C.c_uint64, C.c_uint64,
        u64p, C.c_uint32, C.c_uint64, C.c_uint64, C.c_uint64,
        C.c_char_p, C.c_uint32, C.POINTER(C.POINTER(C.c_uint64)),
    ]
    lib.el_read.restype = C.c_int
    lib.el_read.argtypes = [
        C.c_void_p, C.c_uint64, C.POINTER(u8p), C.POINTER(C.c_uint32)
    ]
    lib.el_ingest_batch.restype = C.c_int64
    lib.el_ingest_batch.argtypes = [
        C.c_void_p, C.c_char_p, C.c_uint32, C.c_char_p, C.c_uint32,
        C.c_uint32, C.c_int64, C.c_int16, C.c_int, C.c_uint32,
        C.POINTER(u8p), u64p,
    ]
    lib.el_columnarize.restype = C.c_int64
    lib.el_columnarize.argtypes = [
        C.c_void_p, C.c_uint32, C.c_int64, C.c_int64, C.c_uint64,
        u64p, C.c_uint32, C.c_uint64, C.c_char_p, C.c_float, C.c_uint64,
        C.c_char_p, C.c_uint32, C.c_int,
        C.POINTER(C.POINTER(C.c_uint32)), C.POINTER(C.POINTER(C.c_uint32)),
        C.POINTER(C.POINTER(C.c_float)), C.POINTER(C.POINTER(C.c_int64)),
        C.POINTER(u8p), u64p, C.POINTER(C.c_uint32),
        C.POINTER(u8p), u64p, C.POINTER(C.c_uint32),
    ]
    lib._el_typed = True
    return lib


def el_hash(s: str) -> int:
    b = s.encode("utf-8")
    return _lib().el_hash(b, len(b))


def _micros(dt: datetime) -> int:
    return (dt - _EPOCH) // _US  # exact integer arithmetic


def _tz_minutes(dt: datetime) -> int:
    off = dt.utcoffset()
    return 0 if off is None else int(off.total_seconds() // 60)


def _restore_time(us: int, tz_min: int) -> datetime:
    dt = _EPOCH + timedelta(microseconds=us)
    return dt.astimezone(timezone(timedelta(minutes=tz_min)))


def _pack_str(s: str | None) -> bytes:
    b = (s or "").encode("utf-8")
    if len(b) > 0xFFFF:
        raise ValueError(f"string field too long ({len(b)} bytes)")
    return struct.pack("<H", len(b)) + b


def pack_event(e: Event) -> bytes:
    """Event -> record payload (layout documented in native/eventlog.cpp)."""
    if e.event_id is None:
        raise ValueError("event_id must be assigned before packing")
    h = el_hash
    has_target = e.target_entity_type is not None
    flags = (1 if has_target else 0) | (2 if e.pr_id is not None else 0)
    head = struct.pack(
        "<qhqh6QB",
        _micros(e.event_time), _tz_minutes(e.event_time),
        _micros(e.creation_time), _tz_minutes(e.creation_time),
        h(e.event), h(e.entity_type), h(e.entity_id),
        h(e.target_entity_type) if has_target else 0,
        h(e.target_entity_id) if has_target else 0,
        h(e.event_id), flags,
    )
    tags_json = json.dumps(list(e.tags)) if e.tags else ""
    props = e.properties.to_json().encode("utf-8")
    return (
        head
        + _pack_str(e.event) + _pack_str(e.entity_type) + _pack_str(e.entity_id)
        + _pack_str(e.target_entity_type) + _pack_str(e.target_entity_id)
        + _pack_str(e.event_id) + _pack_str(e.pr_id) + _pack_str(tags_json)
        + struct.pack("<I", len(props)) + props
    )


_HEAD = struct.Struct("<qhqh6QB")


def unpack_event(payload: bytes) -> Event:
    (t_us, t_tz, c_us, c_tz, _he, _het, _hei, _htt, _hti, _hid,
     flags) = _HEAD.unpack_from(payload, 0)
    pos = _HEAD.size
    strs = []
    for _ in range(8):
        (n,) = struct.unpack_from("<H", payload, pos)
        pos += 2
        strs.append(payload[pos:pos + n].decode("utf-8"))
        pos += n
    (props_len,) = struct.unpack_from("<I", payload, pos)
    pos += 4
    props = payload[pos:pos + props_len].decode("utf-8")
    event, etype, eid, tetype, teid, event_id, pr_id, tags_json = strs
    has_target = bool(flags & 1)
    return Event(
        event=event,
        entity_type=etype,
        entity_id=eid,
        target_entity_type=tetype if has_target else None,
        target_entity_id=teid if has_target else None,
        properties=DataMap.from_json(props),
        event_time=_restore_time(t_us, t_tz),
        tags=tuple(json.loads(tags_json)) if tags_json else (),
        pr_id=pr_id if flags & 2 else None,
        event_id=event_id,
        creation_time=_restore_time(c_us, c_tz),
    )


@dataclass
class ScanFilter:
    """Mirror of the C-side Filter; times are datetimes here."""

    start_time: datetime | None = None
    until_time: datetime | None = None
    entity_type: str | None = None
    entity_id: str | None = None
    event_names: list[str] | None = None
    target_entity_type: object = ...   # ... = don't care, None = absent
    target_entity_id: object = ...
    event_id: str | None = None

    def to_c(self):
        flags = 0
        start = until = 0
        if self.start_time is not None:
            flags |= F_START
            start = _micros(self.start_time)
        if self.until_time is not None:
            flags |= F_UNTIL
            until = _micros(self.until_time)
        h_etype = h_eid = h_tetype = h_teid = h_eventid = 0
        if self.entity_type is not None:
            flags |= F_ETYPE
            h_etype = el_hash(self.entity_type)
        if self.entity_id is not None:
            flags |= F_EID
            h_eid = el_hash(self.entity_id)
        events_arr = None
        n_events = 0
        if self.event_names is not None:
            flags |= F_EVENTS
            n_events = len(self.event_names)
            events_arr = (C.c_uint64 * max(n_events, 1))(
                *[el_hash(s) for s in self.event_names]
            )
        if self.target_entity_type is None:
            flags |= F_TETYPE_ABSENT
        elif self.target_entity_type is not ...:
            flags |= F_TETYPE_EQ
            h_tetype = el_hash(self.target_entity_type)
        if self.target_entity_id is None:
            flags |= F_TEID_ABSENT
        elif self.target_entity_id is not ...:
            flags |= F_TEID_EQ
            h_teid = el_hash(self.target_entity_id)
        if self.event_id is not None:
            flags |= F_EVENTID
            h_eventid = el_hash(self.event_id)
        return (flags, start, until, h_etype, h_eid, events_arr, n_events,
                h_tetype, h_teid, h_eventid)


def pack_tombstones(event_ids: list[str]) -> bytes:
    return b"".join(_pack_str(i) for i in event_ids)


@dataclass
class Columns:
    """Output of the native columnarizer (training fast path)."""

    user_idx: np.ndarray    # uint32 codes into `users`
    item_idx: np.ndarray
    values: np.ndarray      # float32
    times_us: np.ndarray    # int64 event-time microseconds
    users: list[str]        # code -> entity_id
    items: list[str]        # code -> target_entity_id


def _decode_table(ptr, total_len: int, count: int) -> list[str]:
    blob = C.string_at(ptr, total_len)
    out = []
    pos = 0
    for _ in range(count):
        (n,) = struct.unpack_from("<I", blob, pos)
        pos += 4
        out.append(blob[pos:pos + n].decode("utf-8"))
        pos += n
    return out


class BatchTooLarge(Exception):
    """Batch exceeded the server's max events per request."""


class EventLog:
    """One open log file (one per app/channel namespace)."""

    def __init__(self, path: str, create: bool = True):
        self._lib = _lib()
        self._h = self._lib.el_open(path.encode(), 1 if create else 0)
        if not self._h:
            raise OSError(f"cannot open event log at {path}")
        self.path = path

    def close(self) -> None:
        if self._h:
            self._lib.el_close(self._h)
            self._h = None

    def flush(self) -> None:
        self._lib.el_flush(self._h)

    def append(self, e: Event) -> int:
        payload = pack_event(e)
        off = self._lib.el_append(self._h, payload, len(payload))
        if off < 0:
            raise OSError(f"append failed on {self.path}")
        return off

    def ingest_batch(
        self,
        raw: bytes,
        allowed_events: list[str] | None,
        now: datetime,
        single: bool = False,
        max_events: int = 0,
    ) -> list[tuple[int, str, str, str]]:
        """Native parse+validate+append of a JSON request body.

        raw: the HTTP body (JSON array of events, or one object when
        `single`). Returns [(status, id_or_message, event, entity_type)]
        per event — status 0 = created, 1 = invalid (400), 2 = not allowed
        by the key's whitelist (403). Raises ValueError on a malformed
        body and BatchTooLarge when max_events is exceeded (matching the
        Python route semantics in server/eventserver.py)."""
        allow_blob = b"".join(
            struct.pack("<H", len(b)) + b
            for b in ((s.encode("utf-8") for s in allowed_events or ()))
        )
        n_allowed = len(allowed_events or ())
        out = C.POINTER(C.c_uint8)()
        out_len = C.c_uint64()
        n = self._lib.el_ingest_batch(
            self._h, raw, len(raw), allow_blob, len(allow_blob), n_allowed,
            _micros(now), _tz_minutes(now), 1 if single else 0,
            max_events, C.byref(out), C.byref(out_len),
        )
        if n == -2:
            raise BatchTooLarge()
        if n < 0:
            raise ValueError("request body is not well-formed JSON")
        try:
            buf = C.string_at(out, out_len.value)
        finally:
            self._lib.el_free(out)
        results = []
        pos = 0
        for _ in range(n):
            status = buf[pos]
            pos += 1
            fields = []
            for _ in range(3):
                (ln,) = struct.unpack_from("<H", buf, pos)
                pos += 2
                fields.append(buf[pos:pos + ln].decode("utf-8"))
                pos += ln
            results.append((status, *fields))
        return results

    def stats(self) -> tuple[int, int]:
        end = C.c_uint64()
        n = C.c_uint64()
        self._lib.el_stats(self._h, C.byref(end), C.byref(n))
        return end.value, n.value

    def scan(self, f: ScanFilter, tombstones: bytes = b"") -> list[Event]:
        """All matching events in file order (decoded; exact post-filter is
        the caller's job via match_event)."""
        (flags, start, until, h_etype, h_eid, events_arr, n_events,
         h_tetype, h_teid, h_eventid) = f.to_c()
        out = C.POINTER(C.c_uint64)()
        n = self._lib.el_scan(
            self._h, flags, start, until, h_etype, h_eid,
            events_arr, n_events, h_tetype, h_teid, h_eventid,
            tombstones, len(tombstones), C.byref(out),
        )
        if n < 0:
            raise OSError(f"scan failed on {self.path}")
        try:
            offsets = [out[i] for i in range(n)]
        finally:
            self._lib.el_free(out)
        events = []
        for off in offsets:
            buf = C.POINTER(C.c_uint8)()
            blen = C.c_uint32()
            if self._lib.el_read(self._h, off, C.byref(buf), C.byref(blen)) != 0:
                continue
            try:
                events.append(unpack_event(C.string_at(buf, blen.value)))
            finally:
                self._lib.el_free(buf)
        return events

    def columnarize(
        self,
        f: ScanFilter,
        value_key: str | None = "rating",
        default_value: float = 1.0,
        dedup: int = DEDUP_LAST,
        tombstones: bytes = b"",
        value_event: str | None = None,
    ) -> Columns:
        """One native sweep: filter + dict-encode + value extract + dedup.
        value_event restricts value_key extraction to that event name."""
        (flags, start, until, h_etype, _h_eid, events_arr, n_events,
         h_tetype, _h_teid, _h_eventid) = f.to_c()
        u8p = C.POINTER(C.c_uint8)
        uc = C.POINTER(C.c_uint32)()
        ic = C.POINTER(C.c_uint32)()
        vals = C.POINTER(C.c_float)()
        ts = C.POINTER(C.c_int64)()
        utab, itab = u8p(), u8p()
        ulen, ilen = C.c_uint64(), C.c_uint64()
        nu, ni = C.c_uint32(), C.c_uint32()
        n = self._lib.el_columnarize(
            self._h, flags, start, until, h_etype, events_arr, n_events,
            h_tetype,
            value_key.encode() if value_key else None,
            default_value,
            el_hash(value_event) if value_event else 0,
            tombstones, len(tombstones), dedup,
            C.byref(uc), C.byref(ic), C.byref(vals), C.byref(ts),
            C.byref(utab), C.byref(ulen), C.byref(nu),
            C.byref(itab), C.byref(ilen), C.byref(ni),
        )
        if n < 0:
            raise OSError(f"columnarize failed on {self.path}")
        try:
            cols = Columns(
                user_idx=np.ctypeslib.as_array(uc, shape=(n,)).copy()
                if n else np.zeros(0, np.uint32),
                item_idx=np.ctypeslib.as_array(ic, shape=(n,)).copy()
                if n else np.zeros(0, np.uint32),
                values=np.ctypeslib.as_array(vals, shape=(n,)).copy()
                if n else np.zeros(0, np.float32),
                times_us=np.ctypeslib.as_array(ts, shape=(n,)).copy()
                if n else np.zeros(0, np.int64),
                users=_decode_table(utab, ulen.value, nu.value),
                items=_decode_table(itab, ilen.value, ni.value),
            )
        finally:
            for p in (uc, ic, vals, ts, utab, itab):
                self._lib.el_free(p)
        return cols
