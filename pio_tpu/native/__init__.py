"""Native (C++) runtime components.

The reference delegates its native compute to Spark/MLlib's JVM+BLAS stack;
this package holds the TPU build's own native runtime pieces — currently the
append-only event log (native/eventlog.cpp), compiled on demand with g++ and
loaded via ctypes (no pybind11 in the image).

Build artifacts are cached under ``pio_tpu/native/_build/`` keyed by source
hash, so the first import pays one ~2s compile and subsequent imports load
the cached .so.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_BUILD_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_build")
_LOCK = threading.Lock()
_LIBS: dict[str, ctypes.CDLL] = {}


class NativeBuildError(RuntimeError):
    pass


def _source_path(name: str) -> str:
    return os.path.join(_REPO_ROOT, "native", f"{name}.cpp")


def build_library(name: str) -> str:
    """Compile native/<name>.cpp to a shared library; returns the .so path."""
    src = _source_path(name)
    with open(src, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    os.makedirs(_BUILD_DIR, exist_ok=True)
    so_path = os.path.join(_BUILD_DIR, f"{name}-{digest}.so")
    if os.path.exists(so_path):
        return so_path
    tmp = so_path + f".tmp{os.getpid()}"
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
        "-Wall", "-Werror", "-o", tmp, src,
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise NativeBuildError(
            f"g++ failed for {src}:\n{proc.stdout}\n{proc.stderr}"
        )
    os.replace(tmp, so_path)  # atomic: concurrent builders race benignly
    return so_path


def load_library(name: str) -> ctypes.CDLL:
    with _LOCK:
        if name not in _LIBS:
            # pio: lint-ok[blocking-under-lock] one-time g++ build per
            # process; the lock exists to serialize exactly this build
            # so concurrent importers don't compile twice
            _LIBS[name] = ctypes.CDLL(build_library(name))
        return _LIBS[name]


def native_available(name: str = "eventlog") -> bool:
    """True if the native library builds/loads on this machine."""
    try:
        load_library(name)
        return True
    except (NativeBuildError, OSError):
        return False
